# paddle_tpu developer entry points (documented in README §Tests / bench).
#
# `tier1` is the ROADMAP tier-1 verify lane; `tier1-budget` re-runs it with
# per-test durations and gates the ROADMAP 870 s budget through
# perf/check_tier1_budget.py (fails when cumulative runtime exceeds 90% of
# the budget — 97% on a single-core host, where quiet-run wall drifts
# ~±10% day to day — or any single non-slow test exceeds 20 s, so
# slow-marker demotions stop regressing silently).  A failing SUITE also fails the target (pipefail + propagated
# pytest status): a red run within budget must not exit green.
# `check-budget LOG=path` gates an EXISTING log without re-running the suite.
#
# Timing gates are only meaningful on a QUIET machine: this host's
# throughput varies ~2x under load, enough to push a ~10 s test past the
# 20 s single-test limit and fail the gate spuriously.  The suite runs
# under `timeout` at 2x budget so a hung test fails the gate instead of
# wedging it.

SHELL := /bin/bash
PY ?= python
T1_LOG ?= /tmp/_t1_durations.log
PYTEST_T1 = env JAX_PLATFORMS=cpu $(PY) -m pytest tests/ -q -m 'not slow' \
	--continue-on-collection-errors -p no:cacheprovider -p no:xdist \
	-p no:randomly

# `obs-check` is the observability gate (perf/check_obs.py, README
# §Observability): runs the serving trace with a --json artifact,
# schema-validates it (engine counters + metrics snapshot + SLO report
# with quantile fields), then runs the telemetry-overhead gate —
# telemetry ON must hold >= 0.97x the telemetry-off tokens/s (medians
# over interleaved rounds; same quiet-machine caveat as the timing
# gates above).
#
# `lint` runs graftlint (paddle_tpu/analysis — the trace-safety +
# distributed/dataflow static analyzer, README §Static analysis) over the
# package against the committed baseline of grandfathered findings:
# non-zero exit on any NEW finding (traced-value branch in a jitted fn,
# hot-path host sync, unbound collective axis, rank-dependent collective
# branch, use-after-donate, implicit dtype promotion, ...) AND on any
# STALE baseline entry (the fix landed — delete the entry).
# `make lint DIFF=BASE_REF` reports only findings in .py files changed
# (or untracked) vs the git ref — the full project is still parsed so
# the interprocedural rules keep their cross-module context.
# `lint-baseline` regenerates
# graftlint.baseline.json — fill in the one-line justification per entry
# before committing it.
#
# `check` is the aggregate local gate: lint (writing the JSON report
# artifact next to the BENCH jsons) -> tier1-budget -> obs-check ->
# proc-smoke (the ISSUE 17 cross-process SIGKILL drill).

GRAFTLINT = $(PY) -m paddle_tpu.analysis paddle_tpu \
	--baseline graftlint.baseline.json

LINT_ARTIFACT ?= GRAFTLINT_report.json

.PHONY: tier1 tier1-budget check-budget bench bench-trend lint \
	lint-baseline obs-check proc-smoke race-check check

# `bench-trend` reads every BENCH_r*.json driver artifact at the repo root
# and prints the headline tokens/s + serving TTFT-p95 + goodput trajectory
# across PRs; it exits non-zero on artifact schema drift (perf/bench_trend.py).
bench-trend:
	$(PY) perf/bench_trend.py

OBS_ARTIFACT ?= /tmp/_obs_serving.json
OBS_FRONTEND_ARTIFACT ?= /tmp/_obs_frontend.json
OBS_FAILOVER_ARTIFACT ?= /tmp/_obs_failover.json
OBS_FAILOVER_PERFETTO ?= /tmp/_obs_failover_perfetto.json
OBS_ELASTIC_ARTIFACT ?= /tmp/_obs_elastic.json
OBS_QUANT_ARTIFACT ?= /tmp/_obs_quant.json
OBS_DISAGG_ARTIFACT ?= /tmp/_obs_disagg.json

# obs-check additionally runs the ISSUE 11 frontend trace (AsyncFrontend
# bit-equality + zero-leak asserts, predictive-vs-depth admission A/B on
# bursty + diurnal traffic) and schema-gates its artifact — admission
# counters, fraction-sum, prediction-error stats, and the machine-aware
# goodput-under-SLO gate all live in perf/check_obs.py --trace frontend.
# Since ISSUE 12 it also runs the failover trace with the fleet-wide
# observability plane on: the artifact's `fleet` block must carry the
# bucket-wise MERGED replica histograms + per-replica telemetry, the
# `stitched` block must show the crashed request as ONE cross-component
# timeline (>= 3 tracks), and the stitched Perfetto JSON is written to
# $(OBS_FAILOVER_PERFETTO) for ui.perfetto.dev.  Since ISSUE 13 both
# traces run SENTINEL-ON and must carry the `attribution` section
# (per-request critical-path decomposition; exact_requests == requests
# is the gate) and the `alerts` section (aggregated health-sentinel
# report); the overhead gate's ON arm runs stitching + fleet
# aggregation + memory sampling + the health sentinel + tail capture +
# a live exporter scrape + the attribution report (<3% bar).
# Since ISSUE 14 it also runs the elastic trace (sentinel-driven
# autoscaling + prefix-affinity routing on a virtual-clock diurnal
# replay): zero-loss + bit-equal asserted across every scale event,
# elastic >= every fixed-N arm on goodput-per-replica-hour, and the
# affinity fleet's hit rate >= 0.9x the single engine's — all
# deterministic (perf/check_obs.py --trace elastic).
# Since ISSUE 15 it also runs the quant trace (the int8-KV + int8-weight
# serving plane): greedy exact-match >= 0.99 vs the f32 engine on the
# parity scenarios, >= 1.8x concurrent users at FIXED pool bytes,
# dequant-tax tokens/s >= 0.95x (best paired), and the failover/elastic/
# ladder drills re-run with quantized pages — zero-lost, bit-equal,
# ladder order preserved (perf/check_obs.py --trace quant).
# Since ISSUE 18 the serving trace runs with --tp 2 (XLA forced-host
# devices): the tensor-parallel engine must be greedy BIT-EXACT vs the
# single-chip engine with f32 collectives, the quantized-AllReduce arm
# must hold exact_match >= 0.99 on the parity scenarios, and the
# artifact's `tp` block (collective profile + rank skew + attribution
# decode_sync_frac) is schema-gated.  Forced-host TP time-slices one
# CPU, so tokens_per_sec_tp measures dispatch overhead, not speedup —
# the gate is on correctness + schema, never on the paired ratio.
# Since ISSUE 19 it also runs the disagg trace (prefill/decode on
# separate mp=2 submeshes, 4 forced-host chips): colocated-TP vs
# disaggregated arms replay the SAME prefill-heavy scenario at FIXED
# chip count on the shared virtual clock, greedy bit-exactness vs the
# single-chip engine is asserted in BOTH arms before anything is
# reported, and the artifact's TTFT win ratio, rank-local handoff
# telemetry, and EXACT kv_transfer attribution segment are schema-gated
# (perf/check_obs.py --trace disagg) — all deterministic.
obs-check:
	set -o pipefail; \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace serving --tp 2 \
		--json $(OBS_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_ARTIFACT) --trace serving --gate && \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace frontend \
		--json $(OBS_FRONTEND_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_FRONTEND_ARTIFACT) --trace frontend && \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace failover \
		--json $(OBS_FAILOVER_ARTIFACT) \
		--perfetto $(OBS_FAILOVER_PERFETTO) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_FAILOVER_ARTIFACT) --trace failover && \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace elastic \
		--json $(OBS_ELASTIC_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_ELASTIC_ARTIFACT) --trace elastic && \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace quant \
		--json $(OBS_QUANT_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_QUANT_ARTIFACT) --trace quant && \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace disagg \
		--json $(OBS_DISAGG_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_DISAGG_ARTIFACT) --trace disagg

# `proc-smoke` is the ISSUE 17 cross-process CI lane: spawn 2 REAL worker
# processes (each hosting a full ServingEngine behind the length-prefixed
# RPC), SIGKILL one mid-decode, and assert zero-loss + bit-equal recovery
# + a measured wall-clock failover + passing invariants reports for every
# spawned generation (the killed one vouched by its replacement) BEFORE
# the artifact is reported; perf/check_obs.py --proc then schema-gates it.
# The spawn-heavy pytest drills (tests/test_procfleet.py) stay in the slow
# lane — this target is the fast deterministic smoke that runs in `check`.
OBS_FAILOVER_PROC_ARTIFACT ?= /tmp/_obs_failover_proc.json

proc-smoke:
	set -o pipefail; \
	env JAX_PLATFORMS=cpu $(PY) bench.py --trace failover --proc \
		--json $(OBS_FAILOVER_PROC_ARTIFACT) && \
	env JAX_PLATFORMS=cpu $(PY) perf/check_obs.py \
		--artifact $(OBS_FAILOVER_PROC_ARTIFACT) --trace failover --proc

lint:
	$(GRAFTLINT) --fail-on-stale $(if $(DIFF),--diff $(DIFF))

lint-baseline:
	$(GRAFTLINT) --write-baseline

# `race-check` is the graftlint v3 runtime lane (README §Static analysis,
# ISSUE 20): the thread-heavy drills — fleet failover, the AsyncFrontend
# worker seam, and the sanitizer's own inversion/interleave fixtures —
# re-run with GRAFT_THREAD_SANITIZE=1, which wraps every test in
# thread_sanitize(): threading.Lock/RLock are instrumented, lock-order
# inversions raise LockOrderViolation with both stacks instead of
# deadlocking CI, and the seeded thread.interleave fault point makes the
# schedules reproducible.  The sanitizer is OFF everywhere timed
# (tier1-budget, obs-check overhead gates) — it is a test-lane tool, not
# a production tax.
race-check:
	env JAX_PLATFORMS=cpu GRAFT_THREAD_SANITIZE=1 timeout -k 10 600 \
		$(PY) -m pytest tests/test_thread_sanitize.py \
		tests/test_frontend.py tests/test_fleet.py tests/test_rpc.py \
		tests/test_procfleet.py \
		-q -m 'not slow' -p no:cacheprovider -p no:xdist -p no:randomly

check:
	$(GRAFTLINT) --fail-on-stale --json-artifact $(LINT_ARTIFACT)
	$(MAKE) tier1-budget
	$(MAKE) race-check
	$(MAKE) obs-check
	$(MAKE) proc-smoke

tier1:
	timeout -k 10 870 $(PYTEST_T1)

tier1-budget:
	set -o pipefail; \
	timeout -k 10 1740 $(PYTEST_T1) --durations=0 2>&1 | tee $(T1_LOG); rc=$$?; \
	$(PY) perf/check_tier1_budget.py $(T1_LOG) && exit $$rc

check-budget:
	$(PY) perf/check_tier1_budget.py $(LOG)

bench:
	$(PY) bench.py
