"""paddle.audio parity (reference: python/paddle/audio/ — spectral features)."""
from . import functional
from . import features

__all__ = ["functional", "features"]
