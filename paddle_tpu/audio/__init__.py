"""paddle.audio parity (reference: python/paddle/audio/ — spectral features)."""
from . import functional

__all__ = ["functional"]
