"""Audio feature layers (reference: python/paddle/audio/features/layers.py —
Spectrogram / MelSpectrogram / LogMelSpectrogram / MFCC), built on
`paddle_tpu.signal.stft` + the functional helpers; the heavy compute is the
framed rFFT on the TPU FFT op and two small matmuls."""
from __future__ import annotations

from functools import partial

from ..nn.layer import Layer
from ..core.tensor import Tensor
from .. import signal as _signal
from .functional import (get_window, compute_fbank_matrix, create_dct,
                         power_to_db)

__all__ = ["Spectrogram", "MelSpectrogram", "LogMelSpectrogram", "MFCC"]


class Spectrogram(Layer):
    """|stft|^power of waveforms [N, T] -> [N, n_fft//2+1, num_frames]."""

    def __init__(self, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=1.0, center=True, pad_mode="reflect",
                 dtype="float32"):
        super().__init__()
        if power <= 0:
            raise ValueError("Power of spectrogram must be > 0.")
        self.power = power
        if win_length is None:
            win_length = n_fft
        self.fft_window = get_window(window, win_length, fftbins=True,
                                     dtype=dtype)
        self._stft = partial(_signal.stft, n_fft=n_fft,
                             hop_length=hop_length, win_length=win_length,
                             window=self.fft_window, center=center,
                             pad_mode=pad_mode)
        self.register_buffer("fft_window", self.fft_window)

    def forward(self, x):
        spec = self._stft(x)
        return (spec.real() ** 2 + spec.imag() ** 2) ** (self.power / 2)


class MelSpectrogram(Layer):
    """fbank_matrix @ Spectrogram: [N, T] -> [N, n_mels, num_frames]."""

    def __init__(self, sr=22050, n_fft=2048, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 dtype="float32"):
        super().__init__()
        self._spectrogram = Spectrogram(n_fft=n_fft, hop_length=hop_length,
                                        win_length=win_length, window=window,
                                        power=power, center=center,
                                        pad_mode=pad_mode, dtype=dtype)
        self.fbank_matrix = compute_fbank_matrix(
            sr=sr, n_fft=n_fft, n_mels=n_mels, f_min=f_min, f_max=f_max,
            htk=htk, norm=norm, dtype=dtype)
        self.register_buffer("fbank_matrix", self.fbank_matrix)

    def forward(self, x):
        return self.fbank_matrix @ self._spectrogram(x)


class LogMelSpectrogram(Layer):
    """power_to_db(MelSpectrogram): [N, T] -> [N, n_mels, num_frames]."""

    def __init__(self, sr=22050, n_fft=512, hop_length=512, win_length=None,
                 window="hann", power=2.0, center=True, pad_mode="reflect",
                 n_mels=64, f_min=50.0, f_max=None, htk=False, norm="slaney",
                 ref_value=1.0, amin=1e-10, top_db=None, dtype="float32"):
        super().__init__()
        self._melspectrogram = MelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk, norm=norm,
            dtype=dtype)
        self.ref_value = ref_value
        self.amin = amin
        self.top_db = top_db

    def forward(self, x):
        return power_to_db(self._melspectrogram(x), ref_value=self.ref_value,
                           amin=self.amin, top_db=self.top_db)


class MFCC(Layer):
    """DCT-II of the log-mel spectrogram: [N, T] -> [N, n_mfcc, num_frames]."""

    def __init__(self, sr=22050, n_mfcc=40, norm="ortho", n_fft=512,
                 hop_length=512, win_length=None, window="hann", power=2.0,
                 center=True, pad_mode="reflect", n_mels=64, f_min=50.0,
                 f_max=None, htk=False, ref_value=1.0, amin=1e-10,
                 top_db=None, dtype="float32"):
        super().__init__()
        if n_mfcc > n_mels:
            raise ValueError("n_mfcc cannot be larger than n_mels")
        self._log_melspectrogram = LogMelSpectrogram(
            sr=sr, n_fft=n_fft, hop_length=hop_length, win_length=win_length,
            window=window, power=power, center=center, pad_mode=pad_mode,
            n_mels=n_mels, f_min=f_min, f_max=f_max, htk=htk,
            ref_value=ref_value, amin=amin, top_db=top_db, dtype=dtype)
        self.dct_matrix = create_dct(n_mfcc=n_mfcc, n_mels=n_mels, norm=norm,
                                     dtype=dtype)
        self.register_buffer("dct_matrix", self.dct_matrix)

    def forward(self, x):
        logmel = self._log_melspectrogram(x)          # [N, n_mels, L]
        return (logmel.transpose((0, 2, 1)) @ self.dct_matrix
                ).transpose((0, 2, 1))                # [N, n_mfcc, L]
