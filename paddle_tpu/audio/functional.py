"""Audio features (reference: python/paddle/audio/functional/ — window fns,
mel filterbank, spectrogram pieces) implemented over jnp FFT."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = ["get_window", "create_dct", "compute_fbank_matrix", "hz_to_mel",
           "mel_to_hz", "power_to_db"]


def get_window(window, win_length, fftbins=True, dtype="float64"):
    n = win_length
    if isinstance(window, tuple):
        window = window[0]
    if window in ("hann", "hanning"):
        w = np.hanning(n + 1)[:-1] if fftbins else np.hanning(n)
    elif window == "hamming":
        w = np.hamming(n + 1)[:-1] if fftbins else np.hamming(n)
    elif window == "blackman":
        w = np.blackman(n + 1)[:-1] if fftbins else np.blackman(n)
    elif window in ("rect", "boxcar", "rectangular"):
        w = np.ones(n)
    else:
        raise ValueError(f"unknown window {window}")
    return Tensor(jnp.asarray(w, dtype=jnp.float32))


def hz_to_mel(freq, htk=False):
    f = np.asarray(freq, dtype=np.float64)
    if htk:
        out = 2595.0 * np.log10(1.0 + f / 700.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        mels = (f - f_min) / f_sp
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        mels = np.where(f >= min_log_hz,
                        min_log_mel + np.log(np.maximum(f, 1e-10) / min_log_hz) / logstep,
                        mels)
        out = mels
    return float(out) if np.isscalar(freq) else Tensor(jnp.asarray(out, jnp.float32))


def mel_to_hz(mel, htk=False):
    m = np.asarray(mel, dtype=np.float64)
    if htk:
        out = 700.0 * (10.0 ** (m / 2595.0) - 1.0)
    else:
        f_min, f_sp = 0.0, 200.0 / 3
        freqs = f_min + f_sp * m
        min_log_hz = 1000.0
        min_log_mel = (min_log_hz - f_min) / f_sp
        logstep = np.log(6.4) / 27.0
        out = np.where(m >= min_log_mel,
                       min_log_hz * np.exp(logstep * (m - min_log_mel)), freqs)
    return float(out) if np.isscalar(mel) else Tensor(jnp.asarray(out, jnp.float32))


def compute_fbank_matrix(sr, n_fft, n_mels=64, f_min=0.0, f_max=None, htk=False,
                         norm="slaney", dtype="float32"):
    f_max = f_max or sr / 2.0
    n_freqs = n_fft // 2 + 1
    fft_freqs = np.linspace(0, sr / 2, n_freqs)
    mel_min = hz_to_mel(f_min, htk)
    mel_max = hz_to_mel(f_max, htk)
    mels = np.linspace(mel_min, mel_max, n_mels + 2)
    hz = np.array([mel_to_hz(float(m), htk) for m in mels])
    weights = np.zeros((n_mels, n_freqs))
    fdiff = np.diff(hz)
    ramps = hz[:, None] - fft_freqs[None, :]
    for i in range(n_mels):
        lower = -ramps[i] / fdiff[i]
        upper = ramps[i + 2] / fdiff[i + 1]
        weights[i] = np.maximum(0, np.minimum(lower, upper))
    if norm == "slaney":
        enorm = 2.0 / (hz[2:n_mels + 2] - hz[:n_mels])
        weights *= enorm[:, None]
    return Tensor(jnp.asarray(weights, jnp.float32))


def create_dct(n_mfcc, n_mels, norm="ortho", dtype="float32"):
    n = np.arange(n_mels)
    k = np.arange(n_mfcc)[:, None]
    dct = np.cos(np.pi / n_mels * (n + 0.5) * k)
    if norm == "ortho":
        dct[0] *= 1.0 / np.sqrt(2)
        dct *= np.sqrt(2.0 / n_mels)
    return Tensor(jnp.asarray(dct.T, jnp.float32))


def power_to_db(spect, ref_value=1.0, amin=1e-10, top_db=80.0):
    def impl(s):
        log_spec = 10.0 * jnp.log10(jnp.maximum(s, amin))
        log_spec = log_spec - 10.0 * jnp.log10(jnp.maximum(ref_value, amin))
        if top_db is not None:
            log_spec = jnp.maximum(log_spec, jnp.max(log_spec) - top_db)
        return log_spec
    return op_call("power_to_db", impl, spect)
