"""Discrete Fourier transforms (reference: python/paddle/fft.py — the
fft_c2c/fft_r2c/fft_c2r kernel trio behind the 22-function public API).

TPU-native design: everything lowers to `jnp.fft`, whose XLA FFT op runs on
TPU natively; gradients come from jax's fft JVP/transpose rules rather than
the reference's hand-written fft_grad kernels.  The Hermitian family members
jnp lacks (hfft2/ihfft2/hfftn/ihfftn) are built from the conjugation
identities  hfftn(x) = irfftn(conj(x), norm=swap)  and
ihfftn(x) = conj(rfftn(x, norm=swap))  (same contract as the reference's
fftn_c2r/fftn_r2c with forward flipped).

Every transform executes as a cached jitted program (keyed on the static
n/s/axis/norm arguments), not an eager op stream: some TPU transports (the
axon tunnel) mis-handle eager complex-dtype ops, and compiled programs are
also simply faster.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from .core.tensor import Tensor
from .core.dispatch import op_call

__all__ = [
    "fft", "ifft", "rfft", "irfft", "hfft", "ihfft",
    "fft2", "ifft2", "rfft2", "irfft2", "hfft2", "ihfft2",
    "fftn", "ifftn", "rfftn", "irfftn", "hfftn", "ihfftn",
    "fftfreq", "rfftfreq", "fftshift", "ifftshift",
]

_NORMS = ("backward", "ortho", "forward")


def _check_norm(norm):
    if norm not in _NORMS:
        raise ValueError(
            f"Unexpected norm: {norm!r}. Norm should be 'forward', "
            f"'backward' or 'ortho'")
    return norm


def _swap_norm(norm):
    """backward <-> forward (ortho is self-dual): the Hermitian-transform
    identities flip which direction carries the 1/n factor."""
    return {"backward": "forward", "forward": "backward",
            "ortho": "ortho"}[norm]


def _as_complex(v):
    if jnp.issubdtype(v.dtype, jnp.complexfloating):
        return v
    if v.dtype == jnp.float64:
        return v.astype(jnp.complex128)
    return v.astype(jnp.complex64)


def _as_real(v):
    if jnp.issubdtype(v.dtype, jnp.integer) or v.dtype == jnp.bool_:
        return v.astype(jnp.float32)
    return v


def _shape_of(x):
    return tuple(x.shape)


def _dtype_of(x):
    v = x._value if isinstance(x, Tensor) else x
    return jnp.result_type(v)


def _check_1d(x, axis, real_input=False):
    nd = len(_shape_of(x))
    if not isinstance(axis, int):
        raise ValueError(f"Invalid fft axis: {axis!r}")
    if not (-nd <= axis < nd):
        raise ValueError(f"axis {axis} out of range for rank {nd}")
    if real_input and jnp.issubdtype(_dtype_of(x), jnp.complexfloating):
        raise TypeError("Input must be real, but got a complex tensor")


def _check_nd(x, s, axes, real_input=False):
    if s is not None and axes is not None and len(s) != len(axes):
        raise ValueError(
            f"Length of s ({len(s)}) and axes ({len(axes)}) must match")
    if real_input and jnp.issubdtype(_dtype_of(x), jnp.complexfloating):
        raise TypeError("Input must be real, but got a complex tensor")


def _tup(v):
    if v is None or isinstance(v, int):
        return v
    return tuple(v)


@functools.lru_cache(maxsize=1024)
def _exec(kind, n_or_s, ax, norm):
    """Cached jitted executor for one (transform, static-args) combo."""
    def body(v):
        if kind == "fft":
            return jnp.fft.fft(_as_complex(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "ifft":
            return jnp.fft.ifft(_as_complex(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "rfft":
            return jnp.fft.rfft(_as_real(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "irfft":
            return jnp.fft.irfft(_as_complex(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "hfft":
            return jnp.fft.hfft(_as_complex(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "ihfft":
            return jnp.fft.ihfft(_as_real(v), n=n_or_s, axis=ax, norm=norm)
        if kind == "fftn":
            return jnp.fft.fftn(_as_complex(v), s=n_or_s, axes=ax, norm=norm)
        if kind == "ifftn":
            return jnp.fft.ifftn(_as_complex(v), s=n_or_s, axes=ax, norm=norm)
        if kind == "rfftn":
            return jnp.fft.rfftn(_as_real(v), s=n_or_s, axes=ax, norm=norm)
        if kind == "irfftn":
            return jnp.fft.irfftn(_as_complex(v), s=n_or_s, axes=ax,
                                  norm=norm)
        if kind == "hfftn":
            return jnp.fft.irfftn(jnp.conj(_as_complex(v)), s=n_or_s, axes=ax,
                                  norm=_swap_norm(norm))
        if kind == "ihfftn":
            return jnp.conj(jnp.fft.rfftn(_as_real(v), s=n_or_s, axes=ax,
                                          norm=_swap_norm(norm)))
        if kind == "fftshift":
            return jnp.fft.fftshift(v, axes=ax)
        if kind == "ifftshift":
            return jnp.fft.ifftshift(v, axes=ax)
        raise ValueError(kind)
    return jax.jit(body)


# --- 1d -------------------------------------------------------------------
def fft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis)
    return op_call("fft_c2c", _exec("fft", n, axis, norm), x)


def ifft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis)
    return op_call("fft_c2c", _exec("ifft", n, axis, norm), x)


def rfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis, real_input=True)
    return op_call("fft_r2c", _exec("rfft", n, axis, norm), x)


def irfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis)
    return op_call("fft_c2r", _exec("irfft", n, axis, norm), x)


def hfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis)
    return op_call("fft_c2r", _exec("hfft", n, axis, norm), x)


def ihfft(x, n=None, axis=-1, norm="backward", name=None):
    _check_norm(norm)
    _check_1d(x, axis, real_input=True)
    return op_call("fft_r2c", _exec("ihfft", n, axis, norm), x)


# --- nd -------------------------------------------------------------------
def fftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes)
    return op_call("fft_c2c", _exec("fftn", _tup(s), _tup(axes), norm), x)


def ifftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes)
    return op_call("fft_c2c", _exec("ifftn", _tup(s), _tup(axes), norm), x)


def rfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes, real_input=True)
    return op_call("fft_r2c", _exec("rfftn", _tup(s), _tup(axes), norm), x)


def irfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes)
    return op_call("fft_c2r", _exec("irfftn", _tup(s), _tup(axes), norm), x)


def hfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes)
    return op_call("fft_c2r", _exec("hfftn", _tup(s), _tup(axes), norm), x)


def ihfftn(x, s=None, axes=None, norm="backward", name=None):
    _check_norm(norm)
    _check_nd(x, s, axes, real_input=True)
    return op_call("fft_r2c", _exec("ihfftn", _tup(s), _tup(axes), norm), x)


# --- 2d (thin fixed-axes wrappers, same as the reference) -----------------
def _axes2(axes):
    if axes is None:
        return (-2, -1)
    if len(axes) != 2:
        raise ValueError(f"Invalid 2D fft axes: {axes!r}")
    return tuple(axes)


def fft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return fftn(x, s=s, axes=_axes2(axes), norm=norm)


def ifft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ifftn(x, s=s, axes=_axes2(axes), norm=norm)


def rfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return rfftn(x, s=s, axes=_axes2(axes), norm=norm)


def irfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return irfftn(x, s=s, axes=_axes2(axes), norm=norm)


def hfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return hfftn(x, s=s, axes=_axes2(axes), norm=norm)


def ihfft2(x, s=None, axes=(-2, -1), norm="backward", name=None):
    return ihfftn(x, s=s, axes=_axes2(axes), norm=norm)


# --- helpers --------------------------------------------------------------
def fftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.fftfreq(n, d=d)
    return Tensor(out.astype(jnp.dtype(dtype)) if dtype else out)


def rfftfreq(n, d=1.0, dtype=None, name=None):
    out = jnp.fft.rfftfreq(n, d=d)
    return Tensor(out.astype(jnp.dtype(dtype)) if dtype else out)


def fftshift(x, axes=None, name=None):
    return op_call("fftshift", _exec("fftshift", None, _tup(axes), None), x)


def ifftshift(x, axes=None, name=None):
    return op_call("fftshift", _exec("ifftshift", None, _tup(axes), None), x)