"""auto_cast context (reference: python/paddle/amp/auto_cast.py:1006 and
amp_lists.py per-op white/black lists).

Implementation: registers a hook in the op dispatch layer that casts the
jax-value inputs of white-list ops to the amp dtype and black-list ops to
float32 before the kernel runs — exactly where the reference's generated
AmpAutoCasts calls sit (eager_gen.py:645).
"""
from __future__ import annotations

import contextlib
import functools
from typing import Optional, Set

import jax.numpy as jnp

from ..core import dispatch
from ..core import dtype as dtype_mod

# Reference amp_lists.py: ops that are numerically safe & fast in low precision
WHITE_LIST: Set[str] = {
    "matmul", "linear", "conv1d", "conv2d", "conv3d", "conv1d_transpose",
    "conv2d_transpose", "conv3d_transpose", "bmm", "mm", "mv", "einsum",
    "flash_attention", "flash_attention_causal", "flash_attn_unpadded",
    "addmm",
}
# Ops that must run in fp32 (reductions / losses / norms / exp-family)
BLACK_LIST: Set[str] = {
    "exp", "square", "log", "log2", "log10", "log1p", "mean", "sum", "cos_sim",
    "softmax", "log_softmax", "cross_entropy", "bce", "bce_logits",
    "layer_norm", "batch_norm_train", "batch_norm_infer", "group_norm",
    "instance_norm", "rms_norm", "norm", "logsumexp", "cumsum", "prod",
    "l1_loss", "mse_loss", "nll_loss", "kl_div", "smooth_l1", "softmax_with_cross_entropy",
    "erf", "erfinv", "pow", "rsqrt", "sqrt", "std", "var", "dist", "sigmoid_focal",
}


def white_list():
    return set(WHITE_LIST)


def black_list():
    return set(BLACK_LIST)


class _AmpState:
    enabled = False
    dtype = None
    level = "O1"
    custom_white = set()
    custom_black = set()


def _amp_hook(op_name, vals, tensor_idx):
    if not _AmpState.enabled:
        return vals
    target = None
    if op_name in WHITE_LIST or op_name in _AmpState.custom_white:
        target = _AmpState.dtype
    elif op_name in BLACK_LIST or op_name in _AmpState.custom_black:
        target = jnp.float32
    elif _AmpState.level == "O2":
        target = _AmpState.dtype
    if target is None:
        return vals
    out = list(vals)
    for i in tensor_idx:
        v = out[i]
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.floating) \
                and v.dtype != jnp.dtype(target):
            out[i] = v.astype(target)
    return out


dispatch._set_amp_hook(_amp_hook)


@contextlib.contextmanager
def auto_cast(enable=True, custom_white_list=None, custom_black_list=None,
              level="O1", dtype="bfloat16", use_promote=True):
    """paddle.amp.auto_cast parity."""
    prev = (_AmpState.enabled, _AmpState.dtype, _AmpState.level,
            _AmpState.custom_white, _AmpState.custom_black)
    _AmpState.enabled = bool(enable)
    _AmpState.dtype = dtype_mod.convert_dtype(dtype)
    _AmpState.level = level
    _AmpState.custom_white = set(custom_white_list or ())
    _AmpState.custom_black = set(custom_black_list or ())
    try:
        yield
    finally:
        (_AmpState.enabled, _AmpState.dtype, _AmpState.level,
         _AmpState.custom_white, _AmpState.custom_black) = prev


amp_guard = auto_cast


def decorate(models, optimizers=None, level="O1", dtype="bfloat16",
             master_weight=None, save_dtype=None, master_grad=False,
             excluded_layers=None):
    """paddle.amp.decorate parity (auto_cast.py:1091): O2 casts model params
    to the amp dtype (norm layers kept fp32 via excluded_layers)."""
    from ..nn.layer import Layer
    single = isinstance(models, Layer)
    model_list = [models] if single else list(models)
    if level == "O2":
        d = dtype_mod.convert_dtype(dtype)
        from ..nn import norm as norm_layers
        excluded = tuple(excluded_layers) if excluded_layers else (
            norm_layers._BatchNormBase, norm_layers.LayerNorm, norm_layers.GroupNorm)
        for m in model_list:
            for sub in m.sublayers(include_self=True):
                if isinstance(sub, excluded):
                    continue
                for p in sub._parameters.values():
                    if p is not None and jnp.issubdtype(p._value.dtype, jnp.floating):
                        p._set_value(p._value.astype(d))
    if optimizers is None:
        return models if single else model_list
    return (models if single else model_list), optimizers


amp_decorate = decorate
