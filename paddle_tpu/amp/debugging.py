"""AMP numeric debugging (reference: python/paddle/amp/debugging.py —
TensorChecker, op precision compare). TPU analog: flag-driven NaN/Inf scan in
dispatch + jax.debug_nans under jit.
"""
from __future__ import annotations

import contextlib

from .. import flags

__all__ = ["enable_operator_stats_collection", "disable_operator_stats_collection",
           "collect_operator_stats", "enable_tensor_checker", "disable_tensor_checker",
           "check_numerics", "DebugMode"]


class DebugMode:
    CHECK_NAN_INF_AND_ABORT = 0
    CHECK_NAN_INF = 1
    CHECK_ALL = 2


_op_stats = {}


def enable_operator_stats_collection():
    _op_stats.clear()


def disable_operator_stats_collection():
    pass


@contextlib.contextmanager
def collect_operator_stats():
    enable_operator_stats_collection()
    yield
    disable_operator_stats_collection()


def enable_tensor_checker(checker_config=None):
    flags.set_flags({"check_nan_inf": True})


def disable_tensor_checker():
    flags.set_flags({"check_nan_inf": False})


def check_numerics(tensor, op_type="", var_name="", debug_mode=None):
    import jax.numpy as jnp
    import numpy as np
    v = tensor._value if hasattr(tensor, "_value") else tensor
    arr = np.asarray(v)
    if not np.all(np.isfinite(arr)):
        raise FloatingPointError(f"NaN/Inf in {op_type}:{var_name}")
    return tensor
