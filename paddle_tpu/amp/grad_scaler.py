"""GradScaler (reference: python/paddle/amp/grad_scaler.py:657).

Dynamic loss scaling for float16; with bfloat16 (TPU default) scaling is
mathematically unnecessary — enable_when needed for fp16 parity tests.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False
        # per-optimizer state machine (INIT -> UNSCALED -> STEPPED) so the
        # canonical `scaler.unscale_(opt); clip; scaler.step(opt)` pattern
        # does not divide gradients by the scale twice, including with
        # multiple optimizers per iteration (reference OptimizerState
        # tracking in python/paddle/amp/grad_scaler.py)
        self._opt_states: dict[int, str] = {}
        self._opt_found_inf: dict[int, bool] = {}

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        oid = id(optimizer)
        if self._opt_states.get(oid) == "UNSCALED":
            raise RuntimeError(
                "unscale_() has already been called on this optimizer since "
                "the last step()/update(); calling it twice would divide "
                "gradients by the loss scale twice.")
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad is None:
                continue
            g = p._grad._value * inv
            if not bool(jnp.all(jnp.isfinite(g.astype(jnp.float32)))):
                found = True
            p._grad._set_value(g)
        self._opt_found_inf[oid] = found
        self._found_inf = self._found_inf or found
        self._opt_states[oid] = "UNSCALED"

    def step(self, optimizer):
        """Unscale (if not already done) and apply the optimizer step.
        Like the reference, step() does NOT update the loss scale — call
        update() once per iteration after all optimizers have stepped."""
        if not self._enable:
            optimizer.step()
            return
        oid = id(optimizer)
        if self._opt_states.get(oid) != "UNSCALED":
            self.unscale_(optimizer)
        if not self._opt_found_inf.get(oid, False):
            optimizer.step()
        self._opt_states[oid] = "STEPPED"

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)
        self.update()

    def update(self):
        self._opt_states.clear()
        self._opt_found_inf.clear()
        if not (self._enable and self._dynamic):
            self._found_inf = False
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def notify_nonfinite(self):
        """Backoff hook for compiled train loops (TrainStep's non-finite
        sentinel): count a bad step and run the dynamic-loss-scale decay —
        the skipped-step analog of found_inf inside minimize()."""
        self._found_inf = True
        self.update()

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("incr_count", 0)
        self._bad_steps = sd.get("decr_count", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
