"""GradScaler (reference: python/paddle/amp/grad_scaler.py:657).

Dynamic loss scaling for float16; with bfloat16 (TPU default) scaling is
mathematically unnecessary — enable_when needed for fp16 parity tests.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor

__all__ = ["GradScaler", "AmpScaler"]


class GradScaler:
    def __init__(self, enable=True, init_loss_scaling=65536.0, incr_ratio=2.0,
                 decr_ratio=0.5, incr_every_n_steps=2000,
                 decr_every_n_nan_or_inf=1, use_dynamic_loss_scaling=True):
        self._enable = enable
        self._scale = float(init_loss_scaling)
        self._incr_ratio = incr_ratio
        self._decr_ratio = decr_ratio
        self._incr_every = incr_every_n_steps
        self._decr_every = decr_every_n_nan_or_inf
        self._dynamic = use_dynamic_loss_scaling
        self._good_steps = 0
        self._bad_steps = 0
        self._found_inf = False

    def is_enable(self):
        return self._enable

    def is_use_dynamic_loss_scaling(self):
        return self._dynamic

    def get_loss_scaling(self):
        return Tensor(jnp.asarray(self._scale))

    def scale(self, var):
        if not self._enable:
            return var
        return var * self._scale

    def unscale_(self, optimizer):
        if not self._enable:
            return
        inv = 1.0 / self._scale
        found = False
        for p in optimizer._parameter_list:
            if p._grad is None:
                continue
            g = p._grad._value * inv
            if not bool(jnp.all(jnp.isfinite(g.astype(jnp.float32)))):
                found = True
            p._grad._set_value(g)
        self._found_inf = found

    def step(self, optimizer):
        if not self._enable:
            optimizer.step()
            return
        self.unscale_(optimizer)
        if not self._found_inf:
            optimizer.step()
        self.update()

    def minimize(self, optimizer, scaled_loss):
        scaled_loss.backward()
        self.step(optimizer)

    def update(self):
        if not (self._enable and self._dynamic):
            return
        if self._found_inf:
            self._bad_steps += 1
            self._good_steps = 0
            if self._bad_steps >= self._decr_every:
                self._scale = max(self._scale * self._decr_ratio, 1.0)
                self._bad_steps = 0
        else:
            self._good_steps += 1
            self._bad_steps = 0
            if self._good_steps >= self._incr_every:
                self._scale *= self._incr_ratio
                self._good_steps = 0
        self._found_inf = False

    def state_dict(self):
        return {"scale": self._scale, "incr_ratio": self._incr_ratio,
                "decr_ratio": self._decr_ratio, "incr_count": self._good_steps,
                "decr_count": self._bad_steps,
                "use_dynamic_loss_scaling": self._dynamic}

    def load_state_dict(self, sd):
        self._scale = sd.get("scale", self._scale)
        self._good_steps = sd.get("incr_count", 0)
        self._bad_steps = sd.get("decr_count", 0)

    set_state_dict = load_state_dict


AmpScaler = GradScaler
