"""AMP (reference: python/paddle/amp/ — auto_cast.py:1006, grad_scaler.py:657).

On TPU the low-precision dtype is bfloat16 (MXU-native, same exponent range
as fp32), so GradScaler is a functional no-op by default (kept for parity and
for float16 experiments); auto_cast drives the per-op cast lists through the
dispatch-layer AMP hook (the eager_gen.py:645 AMP-cast analog).
"""
from __future__ import annotations

from .auto_cast import auto_cast, amp_guard, decorate, amp_decorate, white_list, black_list
from .grad_scaler import GradScaler, AmpScaler
from . import debugging

__all__ = ["auto_cast", "amp_guard", "decorate", "GradScaler", "AmpScaler",
           "debugging", "white_list", "black_list"]
