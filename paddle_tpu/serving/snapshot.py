"""Durable ServingEngine snapshots through the checkpoint commit protocol.

`ServingEngine.snapshot()` (inference/paged.py) serializes the engine's
complete state — in-flight requests with emitted tokens, seeded RNG key,
slot/page tables, PagePool refcounts, prefix-cache index, and (in
``full_kv`` mode) the raw referenced KV pages.  This module makes that
state DURABLE with exactly the discipline train checkpoints already have
(distributed/checkpoint/save_state_dict.py): staged ``<path>.tmp`` +
chunked fsync'd writes + per-file SHA-256 ``manifest.json`` + atomic
rename commit point.  A crash at any instant leaves the previous intact
snapshot; a torn or bit-rotted snapshot fails manifest verification and
``find_latest_complete()`` falls back to the previous intact one — the
same guarantee, now covering the serving plane.

Fault drills (resilience/faults.py catalog):

  * ``serve.snapshot`` — consulted once per :meth:`save_engine`.
    ``action="raise"`` kills the snapshot attempt before anything stages
    (the process died right as it decided to snapshot; the previous
    snapshot stays latest).  ``action="trigger"`` TEARS the freshly
    committed snapshot after the fact — one flipped byte in the data
    payload — modeling bit-rot or a storage layer that lied about
    durability: manifest verification must reject it.
  * ``ckpt.write`` / ``ckpt.commit`` — the staged writer's own fault
    points fire on this path too (engine snapshots go through the same
    writer), so mid-write and mid-commit crash windows are drilled by the
    existing checkpoint chaos machinery.
  * ``ckpt.dirsync`` — consulted just before the writer fsyncs the
    PARENT directory entry ahead of the atomic rename (ISSUE 17
    satellite): fsyncing the staging dir alone persists its contents but
    not its *name*, so a host crash in this window could lose a
    fully-written snapshot.  ``action="raise"`` kills the commit there;
    ``find_latest_complete()`` must fall back to the previous intact
    snapshot.
"""
from __future__ import annotations

import json
import os

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..distributed.checkpoint import load_state_dict, verify_checkpoint
from ..distributed.checkpoint.save_state_dict import save_state_dict
from ..resilience.checkpoint import CheckpointManager
from ..resilience.faults import fault_point

__all__ = ["EngineSnapshotManager", "load_engine_snapshot"]


def load_engine_snapshot(path) -> dict:
    """Read a committed engine-snapshot directory back into the flat state
    dict :meth:`ServingEngine.restore` consumes: tensors as numpy arrays,
    py-values (the ``meta`` JSON string) as-is.  The caller is responsible
    for verification (``verify_checkpoint`` /
    ``find_latest_complete``) — ``load_state_dict`` still rejects torn
    shards it actually reads."""
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    template: dict = {}
    state: dict = {}
    for name, entry in meta["tensors"].items():
        if entry.get("py"):
            state[name] = entry.get("value")
            continue
        template[name] = Tensor(
            jnp.zeros(tuple(entry["shape"]), dtype=jnp.dtype(entry["dtype"])))
    load_state_dict(template, path)
    for name, t in template.items():
        state[name] = np.asarray(jax.device_get(t._value))
    return state


def _tear(path):
    """serve.snapshot ``action="trigger"``: flip one byte mid-file in the
    committed snapshot's data payload.  The manifest now lies about the
    content, so verification MUST reject the whole snapshot and discovery
    must fall back to the previous intact one."""
    fn = os.path.join(path, "rank0.data")
    size = os.path.getsize(fn)
    with open(fn, "r+b") as f:
        f.seek(size // 2)
        b = f.read(1) or b"\x00"
        f.seek(size // 2)
        f.write(bytes([b[0] ^ 0xFF]))


class EngineSnapshotManager(CheckpointManager):
    """Engine-snapshot discipline on the :class:`CheckpointManager`
    chassis: step-numbered snapshot dirs under one root, keep-last-N
    rotation (older snapshots deleted only after the new one is durable),
    and the inherited :meth:`find_latest_complete` that skips torn
    snapshots — recording each rejection through any attached telemetry
    object's ``torn_snapshot(path, error)`` hook — so restore always lands
    on the newest INTACT engine state.

    The payload is a :meth:`ServingEngine.snapshot` state dict instead of
    train state; use :meth:`save_engine` / :meth:`restore_engine` (the
    inherited train-shaped ``save``/``restore`` are not used here)."""

    def __init__(self, root, keep_last: int | None = 2, telemetry=None):
        super().__init__(root, keep_last=keep_last, telemetry=telemetry)

    def save_engine(self, engine, step: int | None = None,
                    mode: str = "full_kv") -> str:
        """Write one crash-consistent engine snapshot and rotate.  ``step``
        defaults to one past the newest existing snapshot (a private
        monotonic sequence — engine snapshots are ordered by recency, not
        by train step)."""
        if step is None:
            dirs = self._step_dirs()
            step = dirs[-1][0] + 1 if dirs else 0
        # serve.snapshot: "raise" dies HERE (nothing staged, previous
        # snapshot stays latest); a "trigger" spec tears the committed
        # snapshot below, after the writer swears it is durable.  The
        # engine name rides the ctx so a fleet drill targets one replica
        # (match={"engine": "r0"}).
        spec = fault_point("serve.snapshot", step=int(step), mode=mode,
                           engine=getattr(engine, "name", "engine"))
        state = engine.snapshot(mode=mode)
        path = os.path.join(self.root, f"step_{int(step):08d}")
        save_state_dict(state, path)
        self._rotate()
        if spec is not None:
            _tear(path)
        return path

    def restore_engine(self, engine, path=None):
        """Restore ``path`` (default: newest intact snapshot) into a
        freshly constructed engine.  Returns ``(path, applied_mode)``
        where ``applied_mode`` is ``"full_kv"`` (KV pages scattered back,
        decode continues) or ``"reprefill"`` (compact snapshot or
        geometry mismatch — requests requeued for re-prefill), or ``None``
        when no intact snapshot exists."""
        if path is None:
            path = self.find_latest_complete()  # already fully verified
            if path is None:
                return None
        else:
            verify_checkpoint(path)
        applied = engine.restore(load_engine_snapshot(path))
        return path, applied
