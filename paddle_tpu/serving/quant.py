"""Quantized serving plane (ROADMAP item 2, single-chip half): int8/fp8 KV
pages, quantized serving weights, and the bit-drift parity harness.

PagePool capacity is the admission bottleneck of the whole serving stack —
the entire degradation ladder (admit -> queue -> reject -> evict-cache ->
preempt) exists because pages run out, so halving page bytes is a direct
~2x on concurrent users per chip.  This module is the numeric core the
quantized page store shares across every layer that touches it:

  * :func:`quantize_kv` / :func:`dequantize_kv` — the ONE symmetric-absmax
    KV codec (int8 grid, or fp8 e4m3 storage where the jax build has the
    dtype).  Scales are per (page, kv head, token slot): one f32 absmax
    per head per token row of a page.  That granularity is deliberate —
    it makes quantization WRITE-ORDER INDEPENDENT (a token row quantizes
    the same whether it arrived via dense prefill, a chunk, a decode step,
    a speculative verify scatter, or a preemption re-prefill), which is
    what lets the quantized engine keep every bit-exactness invariant the
    f32 engine holds against ITSELF: cache on/off, chunked prefill,
    preemption + re-prefill, COW, snapshot/restore, overlap, and the
    whole fleet failover matrix.  A coarser per-page scalar would need
    requantization as the running absmax grows, and requantization error
    depends on write order — every one of those invariants would die.
  * :func:`kv_spec` — kv_dtype name -> (storage dtype, qmax); the
    per-dtype registry `models/llama.build_llama_paged_decode` and the
    Pallas kernel agree on.
  * :func:`page_bytes` — bytes per KV page (both K and V, all layers,
    scales included) for a geometry/dtype: the telemetry
    `mem.pool_*_bytes` gauges and the fixed-pool-bytes capacity bench
    both size pools through this one function.
  * :func:`quantize_params` — per-channel int8 weight quantization for
    serving params (through `quantization.quantize_weight(axis=...)`):
    matmul weights snap to the int8 grid per output channel and are
    stored DEQUANTIZED in the compute dtype (this backend has no native
    int8 matmul — the grid snap is the accuracy-honest part; native int8
    GEMM is the TPU follow-up).  Norm weights stay f32 (standard
    practice: they are tiny and scale-sensitive).
  * :func:`parity_report` — the subsystem's CONTRACT: greedy exact-match
    rate and max teacher-forced logit drift of a quantized engine vs the
    f32 engine on the standard parity scenarios.  Exact match (not
    bit-exactness) is the quantized gate by design: quantization is a
    lossy code, so the question is whether greedy DECISIONS survive it
    (PERF.md §22 has the methodology).

EQuARX-style quantized AllReduce (arxiv 2506.17615) reuses exactly this
per-page scale machinery once TP decode (ROADMAP item 1) lands.
"""
from __future__ import annotations

import math

import numpy as np

__all__ = ["KV_DTYPES", "kv_spec", "quantize_kv", "dequantize_kv",
           "page_bytes", "quantize_params", "parity_scenarios",
           "parity_report", "logit_drift"]


def _jnp():
    import jax.numpy as jnp
    return jnp


# kv_dtype name -> (storage dtype name, qmax).  qmax is the grid half-range
# the absmax maps onto: 127 for the symmetric int8 grid (the -128 code is
# never emitted, keeping the code symmetric), 448 = the e4m3 max finite —
# scaling absmax onto it uses the whole fp8 dynamic range without ever
# rounding into inf/nan.
KV_DTYPES = {"int8": ("int8", 127.0), "fp8": ("float8_e4m3fn", 448.0)}


def kv_spec(kv_dtype):
    """``kv_dtype`` name -> (storage jnp dtype, qmax).  Raises a clear
    ValueError for unknown names and for ``fp8`` on a jax build without
    the ``float8_e4m3fn`` storage dtype (gate, don't crash mid-trace)."""
    if kv_dtype not in KV_DTYPES:
        raise ValueError(
            f"unknown kv_dtype {kv_dtype!r} (expected one of "
            f"{sorted(KV_DTYPES)}, or None for the f32/bf16 page store)")
    jnp = _jnp()
    name, qmax = KV_DTYPES[kv_dtype]
    dt = getattr(jnp, name, None)
    if dt is None:
        raise ValueError(
            f"kv_dtype {kv_dtype!r} needs the jnp.{name} storage dtype, "
            f"which this jax build lacks — use kv_dtype='int8'")
    return jnp.dtype(dt), qmax


def quantize_kv(x, *, qmax, dtype):
    """Symmetric absmax quantization of K/V rows: ``x [..., D]`` (any float
    dtype) -> ``(q [..., D] storage-dtype, scale [...] f32)`` with one
    scale per leading-index row (per token, per head).  ``qmax``/``dtype``
    are keyword-only STATICS (from :func:`kv_spec`) so the branch below is
    never traced.  Zero rows round-trip to exact zeros."""
    jnp = _jnp()
    xf = x.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(absmax, 1e-8) / qmax
    y = xf / scale[..., None]
    if jnp.issubdtype(dtype, jnp.integer):
        q = jnp.clip(jnp.round(y), -qmax, qmax).astype(dtype)
    else:
        # fp8 storage: the cast IS the rounding (|y| <= qmax = the e4m3
        # max finite by construction, so the cast never overflows)
        q = y.astype(dtype)
    return q, scale


def dequantize_kv(q, scale):
    """``(q [..., D], scale [...])`` -> f32 values.  The ONE dequant
    expression — every consumer (the Pallas kernel, its jnp ref, the
    chunk/verify gathers, the dense-prefill local fake-quant) routes
    through the same two ops, so identical stored rows dequantize to
    identical f32 values on every attention path."""
    jnp = _jnp()
    return q.astype(jnp.float32) * scale[..., None].astype(jnp.float32)


def page_bytes(config, page_size: int, kv_dtype=None, dtype=None) -> int:
    """Bytes ONE page of KV cache costs (K + V across all layers, per-page
    scales included for quantized dtypes).  This is the unit the telemetry
    memory observatory reports pool occupancy in and the unit the
    fixed-pool-bytes capacity bench holds constant across arms."""
    jnp = _jnp()
    L = config.num_hidden_layers
    hkv = config.num_key_value_heads
    d = config.hidden_size // config.num_attention_heads
    if kv_dtype is None:
        item = jnp.dtype(dtype if dtype is not None else jnp.float32).itemsize
        return 2 * L * hkv * page_size * d * item
    storage, _ = kv_spec(kv_dtype)
    data = 2 * L * hkv * page_size * d * storage.itemsize
    scales = 2 * L * hkv * page_size * 4          # one f32 per head per row
    return data + scales


# ---------------------------------------------------------------------------
# Serving weight quantization (per-channel, through quantization/)
# ---------------------------------------------------------------------------
def _quant_leaf(w, bits, reduce_axis):
    from ..quantization import dequantize_weight, quantize_weight
    q, scale = quantize_weight(w, bits=bits, axis=reduce_axis)
    return dequantize_weight(q, scale, dtype=w.dtype)


def quantize_params(params, bits: int = 8):
    """Snap the (embed, block, head) serving pytrees onto the per-channel
    int grid: matmul weights quantize with one absmax scale per OUTPUT
    channel (reduction over the contraction axis — the granularity the
    attention projections need; a per-tensor scale lets one hot channel
    flatten every other head's resolution), embeddings per ROW.  1-D norm
    gains (`ln1`/`ln2`/`ln_f`) pass through untouched.  Values come back
    DEQUANTIZED in the input dtype: this backend's matmul consumes
    f32/bf16, so the grid snap is what changes numerics — exactly what
    the parity harness must see."""
    ep, bp, hp = params
    ep = dict(ep, tok=_quant_leaf(ep["tok"], bits, -1))
    bp = {k: (v if k.startswith("ln") else _quant_leaf(v, bits, -2))
          for k, v in bp.items()}
    hp = dict(hp, lm=_quant_leaf(hp["lm"], bits, -2))
    return ep, bp, hp


# ---------------------------------------------------------------------------
# Parity harness — the subsystem's contract
# ---------------------------------------------------------------------------
def parity_scenarios(vocab: int, seed: int = 0, page_size: int = 8):
    """The standard parity scenario set: seeded prompts covering the same
    shapes every serving exactness suite sweeps — short, page-boundary
    (len % page_size == 0 and == page_size - 1), long/multi-page, and a
    shared-prefix pair (the prefix-cache hit path).  Returns a list of
    ``(prompt ndarray, max_new_tokens)``."""
    rng = np.random.default_rng(seed)
    lens = [3, page_size, page_size - 1, 2 * page_size,
            3 * page_size + 2, 2 * page_size + 1]
    out = []
    for t in lens:
        out.append((rng.integers(1, vocab, (int(t),)).astype(np.int32), 16))
    shared = rng.integers(1, vocab, (2 * page_size,)).astype(np.int32)
    for t in (3, page_size - 2):
        tail = rng.integers(1, vocab, (int(t),)).astype(np.int32)
        out.append((np.concatenate([shared, tail]), 16))
    return out


def _run_engine(factory, scenarios):
    outs = []
    eng = factory()
    rids = [eng.submit(p, max_new_tokens=m) for p, m in scenarios]
    done = eng.run()
    for r in rids:
        outs.append([int(t) for t in done[r].generated])
    return outs, eng


def logit_drift(params_ref, params_q, config, prompts, *, kv_dtype,
                page_size: int = 8, steps: int = 8, dtype=None,
                ref_build_kw=None, q_build_kw=None):
    """Max |logits_q - logits_ref| over a TEACHER-FORCED greedy decode:
    both page stores replay the REFERENCE engine's token trajectory, so
    the drift number measures the quantization error of each step's
    logits in isolation (a free-running comparison would conflate one
    early argmax flip with everything after it).  Returns (max_drift,
    per-step max drifts).

    ``ref_build_kw`` / ``q_build_kw``: extra build_llama_paged_decode
    kwargs per arm — how the TP serving bench drifts the quantized
    AllReduce against the f32-collective build (both arms
    ``mesh=<mesh>``, the q arm additionally ``quantized_allreduce=True``,
    with ``kv_dtype=None`` so page quantization stays out of the
    measurement)."""
    import jax.numpy as jnp
    from ..models.llama import build_llama_paged_decode

    per = max(math.ceil((len(p) + steps) / page_size) for p in prompts)
    n_pages = per + 1
    drifts = []
    builds = {}
    for tag, prm, kvd, bkw in (("ref", params_ref, None, ref_build_kw),
                               ("q", params_q, kv_dtype, q_build_kw)):
        builds[tag] = build_llama_paged_decode(
            config, page_size=page_size, num_pages=n_pages, dtype=dtype,
            attention_impl="ref", kv_dtype=kvd, **(bkw or {}))
    for prompt in prompts:
        T = len(prompt)
        ids = jnp.asarray(np.asarray(prompt, np.int32)[None])
        row = np.arange(per, dtype=np.int32)
        state = {}
        for tag in ("ref", "q"):
            init_pages, prefill, _c, decode_step, _v = builds[tag]
            pages = init_pages()
            prm = params_ref if tag == "ref" else params_q
            logits, pk, pv = prefill(prm, ids, jnp.asarray(T, jnp.int32),
                                     jnp.asarray(row), pages["k"],
                                     pages["v"])
            state[tag] = [logits, pk, pv]
        step_drift = [float(jnp.max(jnp.abs(state["q"][0]
                                            - state["ref"][0])))]
        # teacher forcing: the reference argmax feeds BOTH stores
        tok = int(np.asarray(jnp.argmax(state["ref"][0])))
        for i in range(steps - 1):
            pos = T + i
            for tag in ("ref", "q"):
                decode_step = builds[tag][3]
                prm = params_ref if tag == "ref" else params_q
                logits, pk, pv = decode_step(
                    prm, jnp.asarray([tok], jnp.int32),
                    jnp.asarray([pos], jnp.int32),
                    jnp.asarray(row[None]), state[tag][1], state[tag][2],
                    jnp.asarray([True]))
                state[tag] = [logits, pk, pv]
            step_drift.append(float(jnp.max(jnp.abs(
                state["q"][0] - state["ref"][0]))))
            tok = int(np.asarray(jnp.argmax(state["ref"][0][0])))
        drifts.append(step_drift)
    flat = [d for row_ in drifts for d in row_]
    return max(flat), drifts


def parity_report(params, config, *, kv_dtype="int8", quantize=8,
                  scenarios=None, engine_kw=None, drift_steps=8,
                  drift_prompts=2, ref_engine_kw=None, q_engine_kw=None,
                  ref_build_kw=None, q_build_kw=None):
    """Greedy exact-match rate + max logit drift of the quantized serving
    plane vs the f32 engine on the standard parity scenarios.

    Builds two engines from the SAME params/config — the f32 reference
    and one with ``kv_dtype`` pages (+ per-channel ``quantize``-bit
    weights when ``quantize`` is set) — runs every scenario greedily on
    both, and reports:

      * ``exact_match`` — fraction of requests whose FULL greedy output
        matches the f32 engine token-for-token (the gated number);
      * ``token_match`` — mean matched-prefix fraction over tokens (the
        diagnostic: how deep into a sequence the first divergence sits);
      * ``max_logit_drift`` — max |Δlogits| over a teacher-forced decode
        of the first ``drift_prompts`` scenarios (the raw numeric error
        the argmax survived).

    ``ref_engine_kw`` / ``q_engine_kw`` merge per-arm ON TOP of
    ``engine_kw`` — this is how the TP serving bench reuses the harness
    for quantized-vs-f32 COLLECTIVES instead of quantized-vs-f32 pages:
    both arms ``mesh=<mesh>``, the q arm ``quantized_allreduce=True``,
    with ``kv_dtype=None, quantize=None`` so the only difference under
    measurement is the per-layer AllReduce grid.  ``ref_build_kw`` /
    ``q_build_kw`` forward to :func:`logit_drift` the same way.

    Deterministic for a given params/config/scenario seed."""
    from ..inference.paged import ServingEngine

    kw = dict(num_slots=4, page_size=8, attention_impl="ref",
              prompt_bucket=8, decode_horizon=4)
    kw.update(engine_kw or {})
    if scenarios is None:
        # scenario lengths are built AROUND the engine's page size (the
        # page-boundary cases are the point of the set)
        scenarios = parity_scenarios(config.vocab_size,
                                     page_size=kw["page_size"])
    need = max(math.ceil((len(p) + m) / kw["page_size"]) + 1
               for p, m in scenarios)
    kw.setdefault("max_pages_per_seq", need)
    kw.setdefault("num_pages", need * (len(scenarios) + kw["num_slots"]))

    params_q = quantize_params(params, bits=int(quantize)) if quantize \
        else params

    ref_kw = dict(kw, **(ref_engine_kw or {}))
    q_kw = dict(kw, **(q_engine_kw or {}))
    ref_outs, ref_eng = _run_engine(
        lambda: ServingEngine(params, config, **ref_kw), scenarios)
    q_outs, q_eng = _run_engine(
        lambda: ServingEngine(params_q, config, kv_dtype=kv_dtype, **q_kw),
        scenarios)

    matches = [a == b for a, b in zip(ref_outs, q_outs)]
    tok_fracs = []
    for a, b in zip(ref_outs, q_outs):
        n = max(len(a), 1)
        m = 0
        while m < min(len(a), len(b)) and a[m] == b[m]:
            m += 1
        tok_fracs.append(m / n)
    if drift_prompts > 0:
        max_drift, _ = logit_drift(
            params, params_q, config,
            [p for p, _m in scenarios[:drift_prompts]], kv_dtype=kv_dtype,
            page_size=kw["page_size"], steps=drift_steps,
            ref_build_kw=ref_build_kw, q_build_kw=q_build_kw)
    else:
        max_drift = 0.0        # drift pass skipped (cheap smoke mode)
    ref_eng.check_invariants()
    q_eng.check_invariants()
    return {
        "kv_dtype": kv_dtype,
        "weight_bits": int(quantize) if quantize else None,
        "scenarios": len(scenarios),
        "exact_match": round(sum(matches) / len(matches), 4),
        "token_match": round(float(np.mean(tok_fracs)), 4),
        "max_logit_drift": round(max_drift, 6),
        "mismatched": [i for i, ok in enumerate(matches) if not ok],
    }
