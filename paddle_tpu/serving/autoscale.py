"""Sentinel-driven elastic autoscaling over the replica fleet.

ROADMAP item 5's scaling half.  :class:`~.fleet.ReplicaFleet` serves a
FIXED N; under a diurnal load curve that is wrong twice a day — peak
traffic queues behind too few replicas (TTFT SLO burns), trough traffic
pays for idle ones (goodput-per-replica-hour collapses).  This module
closes the loop:

  * :class:`AutoscalePolicy` — the decision layer, deliberately shaped
    like the training side's ``ElasticManager`` change/exit protocol
    (``distributed/fleet/elastic``): each evaluation returns
    :class:`AutoscaleDecision` ``HOLD`` / ``GROW`` / ``SHRINK``, derived
    from which sentinel alerts are active.  GROW fires on the sustained
    ``queue_growth`` signal (the PR 13 documented autoscaler trigger —
    the same :class:`~paddle_tpu.observability.health.TrendRule` shape,
    evaluated here over fleet-wide queue pressure) or on a TTFT SLO-burn
    signal (``slo_ttft_s=``); SHRINK fires on ``fleet_idle`` (windowed
    per-replica load below the idle floor).  ``scale_cooldown_s``
    separates actions so one incident scales one step at a time.
  * :class:`ElasticFleet` — a :class:`~.fleet.ReplicaFleet` whose
    ``step()`` additionally evaluates the policy's
    :class:`~paddle_tpu.observability.health.HealthSentinel` and acts:
    GROW -> :meth:`~.fleet.ReplicaFleet.add_replica` (up to
    ``max_replicas``); SHRINK -> :meth:`~.fleet.ReplicaFleet.
    retire_replica` on the idlest replica — the ZERO-LOSS drain:
    mark-unroutable -> live-migrate every in-flight request through the
    streamed-token re-prefill path (``cancel`` + ``adopt``; greedy
    outputs stay bit-exact by the PR 9 guarantee) -> destroy the empty
    engine (its tracer/telemetry/hit counters outlive it).

The sentinel runs under an INJECTABLE clock, and by default that clock
is *round time* (``fleet round * dt_per_round``): scaling decisions then
depend only on the work content of the trace, not on machine speed — a
seeded diurnal scenario produces the identical scale-event timeline on a
laptop and a TPU host (``tests/test_autoscale.py`` pins this), while
wall-clock metrics (TTFT, goodput) keep their own domain.
"""
from __future__ import annotations

import enum
from dataclasses import dataclass

from ..observability.health import HealthSentinel, AlertRule, autoscale_rules
from ..observability.slo import burn_rate, on_time
from .fleet import ReplicaFleet

__all__ = ["AutoscaleDecision", "AutoscalePolicy", "ElasticFleet"]


class AutoscaleDecision(enum.Enum):
    """The change/exit-protocol analog for serving capacity (the training
    side's ``ElasticStatus`` HOLD/CHANGE/EXIT, reshaped as a direction)."""
    HOLD = "hold"
    GROW = "grow"
    SHRINK = "shrink"


class _RecentBurnRule(AlertRule):
    """TTFT SLO burn over the most recent fleet request summaries —
    count-windowed rather than time-windowed so it shares whatever clock
    the sentinel runs on (round time by default).  Reads the shared
    :func:`~paddle_tpu.observability.slo.on_time` predicate and
    :func:`~paddle_tpu.observability.slo.burn_rate` math; fires when the
    recent-bad-fraction burns faster than ``threshold``."""

    def __init__(self, name: str, *, summaries_fn, slo_ttft_s: float,
                 slo_target: float = 0.95, recent: int = 8, **kw):
        kw.setdefault("threshold", 1.0)
        super().__init__(name, **kw)
        self.summaries_fn = summaries_fn
        self.slo_ttft_s = float(slo_ttft_s)
        self.slo_target = float(slo_target)
        self.recent = int(recent)
        self._seen = 0

    def reset(self):
        self._seen = 0

    def sample(self, ctx) -> float | None:
        rows = self.summaries_fn(ctx)
        if len(rows) < self.recent:
            return None
        if len(rows) == self._seen:
            # nothing NEW resolved since the last evaluation: idle
            # traffic is not an SLO emergency, and re-reporting the same
            # stale tail would pin the alert active forever — blocking
            # scale-down exactly when the fleet is most over-provisioned
            return 0.0
        self._seen = len(rows)
        tail = rows[-self.recent:]
        bad = sum(1 for s in tail if not on_time(s, self.slo_ttft_s))
        return burn_rate(bad / len(tail), self.slo_target)

    def describe(self) -> dict:
        d = super().describe()
        d.update(slo_ttft_s=self.slo_ttft_s, slo_target=self.slo_target,
                 recent=self.recent)
        return d


@dataclass
class AutoscalePolicy:
    """Every knob of the elastic loop.  Windows/cooldowns are in the
    SENTINEL's clock domain — round-virtual seconds by default (one fleet
    heartbeat == ``dt_per_round``), wall seconds if an explicit wall
    clock is injected into :class:`ElasticFleet`."""
    min_replicas: int = 1
    max_replicas: int = 4
    # scale-up: sustained fleet-queue growth (the PR 13 trigger)
    queue_growth: float = 4.0
    queue_min_depth: float = 3.0
    growth_window_s: float = 6.0
    growth_fire_frac: float = 0.5
    # scale-up (optional): TTFT SLO burn over recent resolutions
    slo_ttft_s: float | None = None
    slo_target: float = 0.95
    burn_threshold: float = 1.0
    burn_recent: int = 8
    # scale-down: sustained per-routable-replica load below the floor
    idle_per_replica: float = 0.5
    idle_window_s: float = 10.0
    # pacing
    min_samples: int = 3
    scale_cooldown_s: float = 6.0
    dt_per_round: float = 1.0

    def __post_init__(self):
        if self.min_replicas < 1:
            raise ValueError("min_replicas must be >= 1")
        if self.max_replicas < self.min_replicas:
            raise ValueError("max_replicas must be >= min_replicas")

    def build_rules(self, fleet: "ElasticFleet", role=None) -> list:
        rules = autoscale_rules(
            depth_fn=lambda ctx: fleet.queue_pressure(role),
            load_fn=lambda ctx: fleet.load_per_replica(role),
            queue_growth=self.queue_growth,
            queue_min_depth=self.queue_min_depth,
            growth_window_s=self.growth_window_s,
            growth_fire_frac=self.growth_fire_frac,
            idle_per_replica=self.idle_per_replica,
            idle_window_s=self.idle_window_s,
            min_samples=self.min_samples)
        if self.slo_ttft_s is not None:
            rules.append(_RecentBurnRule(
                "ttft_slo_burn",
                summaries_fn=lambda ctx: fleet._summaries,
                slo_ttft_s=self.slo_ttft_s, slo_target=self.slo_target,
                threshold=self.burn_threshold, recent=self.burn_recent,
                window_s=self.growth_window_s,
                min_samples=self.min_samples, fire_frac=0.6,
                # pacing lives in the POLICY's scale_cooldown_s, exactly
                # like the two autoscale_rules companions — the rule's
                # own 30 s default would deafen the trigger between
                # incidents
                cooldown_s=0.0,
                severity="page",
                description="recent resolutions burning the TTFT error "
                            "budget faster than allotted — elastic "
                            "scale-up trigger"))
        return rules

    def decide(self, sentinel: HealthSentinel, fleet: "ElasticFleet",
               now: float, last_action_t: float,
               role=None) -> AutoscaleDecision:
        """Map active alerts to a capacity direction.  GROW wins over
        SHRINK (pressure evidence beats idleness evidence — both can be
        momentarily active around a load edge), and every action honors
        the shared cooldown.  With ``role``, every reading is scoped to
        that role's slice of the fleet (a disaggregated fleet scales
        prefill and decode capacity independently)."""
        if now < last_action_t + self.scale_cooldown_s:
            return AutoscaleDecision.HOLD
        active = {a.rule for a in sentinel.active()}
        routable = fleet.routable_replicas(role)
        if "queue_growth" in active or "ttft_slo_burn" in active:
            # a live pressure signal NEVER shrinks — even at max
            # capacity (where growing is impossible) an also-active idle
            # alert must not drain a replica the queue is about to need;
            # an at-max oscillator (grow impossible -> shrink -> grow)
            # would otherwise thrash a replica per cooldown
            return AutoscaleDecision.GROW \
                if routable < self.max_replicas else AutoscaleDecision.HOLD
        if "fleet_idle" in active and routable > self.min_replicas:
            return AutoscaleDecision.SHRINK
        return AutoscaleDecision.HOLD


class ElasticFleet(ReplicaFleet):
    """A :class:`~.fleet.ReplicaFleet` that scales itself.  Starts at
    ``policy.min_replicas`` (``num_replicas`` may not be passed — the
    policy owns N), evaluates the sentinel at every fleet heartbeat, and
    grows/drains one replica per decision.  Everything else — routing
    (pass ``router=PrefixAffinityRouter()`` for cache-affine placement),
    failover, snapshots, streaming — is inherited unchanged, and the
    zero-loss/bit-exactness guarantees hold across every scale event
    (the drain path IS the PR 9 migration path)."""

    def __init__(self, engine_factory, *, policy: AutoscalePolicy | None = None,
                 role_policies: dict | None = None,
                 sentinel_clock=None, **kw):
        if "num_replicas" in kw:
            raise TypeError("ElasticFleet sizes itself — set "
                            "policy.min_replicas/max_replicas instead of "
                            "num_replicas")
        if role_policies:
            # disaggregated elastic (ISSUE 19): one AutoscalePolicy PER
            # ROLE, each with its own sentinel, readings, and cooldown —
            # a prefill burst grows prefill capacity without touching
            # the decode pool, and vice versa
            if policy is not None:
                raise TypeError("pass either policy= (role-less) or "
                                "role_policies= (disaggregated), not both")
            if "roles" in kw:
                raise TypeError("role_policies owns the role layout — "
                                "do not also pass roles=")
            bad = sorted(set(map(str, role_policies))
                         - {"any", "prefill", "decode"})
            if bad:
                raise ValueError(f"unknown roles in role_policies: {bad}")
            self.policy = None
            self.role_policies = {str(r): p
                                  for r, p in role_policies.items()}
            roles = [r for r in sorted(self.role_policies)
                     for _ in range(self.role_policies[r].min_replicas)]
            super().__init__(engine_factory, num_replicas=len(roles),
                             roles=roles, **kw)
        else:
            self.policy = policy if policy is not None else AutoscalePolicy()
            self.role_policies = None
            super().__init__(engine_factory,
                             num_replicas=self.policy.min_replicas, **kw)
        self._vclock = 0.0
        self._sentinel_clock = sentinel_clock
        clock = (sentinel_clock if sentinel_clock is not None
                 else (lambda: self._vclock))
        if self.role_policies is not None:
            self.sentinel = None
            self.sentinels = {
                role: HealthSentinel(rules=pol.build_rules(self, role=role),
                                     clock=clock)
                for role, pol in sorted(self.role_policies.items())}
            self._last_scale_by_role = {r: float("-inf")
                                        for r in self.role_policies}
        else:
            self.sentinel = HealthSentinel(
                rules=self.policy.build_rules(self), clock=clock)
            self.sentinels = {None: self.sentinel}
        self._last_scale_t = float("-inf")
        self.scale_events: list[dict] = []

    # -- the policy's fleet readings ---------------------------------------
    def _role_replicas(self, role=None):
        return [rep for rep in self._alive()
                if rep.routable and (role is None or rep.role == role)]

    def routable_replicas(self, role=None) -> int:
        return len(self._role_replicas(role))

    def queue_pressure(self, role=None) -> int:
        """Fleet-wide queued work: the fleet queue plus every routable
        replica's engine-side admission queue (work that has a home but
        no slot yet).  Role-scoped readings split it by who would absorb
        the work: fresh admissions always prefill, so the fleet queue is
        PREFILL pressure; exported-but-unplaced KV packets are DECODE
        pressure."""
        n = 0
        if role is None or role in ("prefill", "any"):
            n += len(self._waiting)
        if role is None or role in ("decode", "any"):
            n += len(self._pending_handoffs)
        for rep in self._role_replicas(role):
            n += len(rep.engine._queue)
        return n

    def load_per_replica(self, role=None) -> float | None:
        """Mean (active + queued) requests per routable replica — the
        idle detector's reading."""
        routable = self._role_replicas(role)
        if not routable:
            return None
        load = sum(rep.load() for rep in routable)
        if role is None or role in ("prefill", "any"):
            load += len(self._waiting)
        if role is None or role in ("decode", "any"):
            load += len(self._pending_handoffs)
        return load / len(routable)

    # -- the loop ----------------------------------------------------------
    def _dt_per_round(self) -> float:
        if self.role_policies is not None:
            return next(iter(self.role_policies.values())).dt_per_round
        return self.policy.dt_per_round

    def step(self) -> bool:
        progressed = super().step()
        self._vclock = self._round * self._dt_per_round()
        self._autoscale()
        return progressed

    def _sentinel_now(self) -> float:
        return float(self._sentinel_clock()
                     if self._sentinel_clock is not None else self._vclock)

    def _autoscale(self):
        now = self._sentinel_now()
        if self.role_policies is None:
            self.sentinel.evaluate(telemetry=None, now=now)
            decision = self.policy.decide(self.sentinel, self, now,
                                          self._last_scale_t)
            self._act(decision, now, role=None, policy=self.policy,
                      sentinel=self.sentinel)
            return
        # disaggregated: each role runs its own sentinel + cooldown —
        # deterministic role order so a seeded trace replays identically
        for role in sorted(self.role_policies):
            pol = self.role_policies[role]
            sen = self.sentinels[role]
            sen.evaluate(telemetry=None, now=now)
            decision = pol.decide(sen, self, now,
                                  self._last_scale_by_role[role],
                                  role=role)
            self._act(decision, now, role=role, policy=pol, sentinel=sen)

    def _act(self, decision: AutoscaleDecision, now: float, *, role,
             policy: AutoscalePolicy, sentinel: HealthSentinel):
        if decision is AutoscaleDecision.GROW:
            name = self.add_replica(role if role is not None else "any")
            self._record_scale("scale_up", name, now, role=role,
                               sentinel=sentinel)
        elif decision is AutoscaleDecision.SHRINK:
            # drain the idlest routable replica OF THIS ROLE (fewest
            # active+queued; deterministic name tie-break) — never below
            # the role policy's min_replicas, and retire_replica itself
            # refuses the last live one
            routable = self._role_replicas(role)
            if not routable:
                return
            victim = min(routable,
                         key=lambda rep: (rep.load(), rep.name))
            if self.retire_replica(victim.name):
                self._record_scale("scale_down", victim.name, now,
                                   role=role, sentinel=sentinel)

    def _record_scale(self, action: str, replica: str, now: float, *,
                      role=None, sentinel: HealthSentinel):
        self._last_scale_t = now
        if role is not None:
            # keyed by role (prefill/decode): bounded
            # graftlint: disable=LEAK001
            self._last_scale_by_role[role] = now
        ev = {
            "action": action, "replica": replica, "round": self._round,
            "t": round(now, 4),
            "replicas_alive": len(self._alive()),
            "active_alerts": sorted(a.rule for a in sentinel.active()),
        }
        if role is not None:
            ev["role"] = role
        # the drill's scale-event audit log: one entry per scale
        # decision, read whole by bench/check_obs
        self.scale_events.append(ev)  # graftlint: disable=LEAK001

    # -- readouts ----------------------------------------------------------
    def stats(self) -> dict:
        out = super().stats()
        if self.role_policies is not None:
            out["autoscale"] = {
                "scale_events": len(self.scale_events),
                "peak_replicas": max(
                    [e["replicas_alive"] for e in self.scale_events],
                    default=len(self._alive())),
                "per_role": {
                    role: {
                        "min_replicas": pol.min_replicas,
                        "max_replicas": pol.max_replicas,
                        "routable": self.routable_replicas(role),
                        "scale_events": sum(
                            1 for e in self.scale_events
                            if e.get("role") == role),
                        "rule_fires": {
                            rule.name:
                                self.sentinels[role]._states[rule.name].fires
                            for rule in self.sentinels[role].rules},
                    } for role, pol in sorted(self.role_policies.items())},
            }
            return out
        out["autoscale"] = {
            "min_replicas": self.policy.min_replicas,
            "max_replicas": self.policy.max_replicas,
            "scale_events": len(self.scale_events),
            "peak_replicas": max(
                [e["replicas_alive"] for e in self.scale_events],
                default=len(self._alive())),
            "rule_fires": {rule.name: self.sentinel._states[rule.name].fires
                           for rule in self.sentinel.rules},
        }
        return out
