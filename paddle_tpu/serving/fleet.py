"""Replica-fleet router: N ServingEngine replicas behind one ``submit()``.

One engine is one process is one failure domain — ROADMAP item 4's
"millions of users" needs a front end where a replica can die and its
in-flight requests MIGRATE instead of dying with it.  The fleet keeps the
authoritative request log (prompt + sampling params + every token STREAMED
out of the engines so far), drives its replicas step by step, and
self-heals:

  * **routing** — each submit lands on the least-loaded live replica
    (deterministic tie-break), falling through the fleet-wide degradation
    ladder *route -> queue -> reject*: replicas full -> the bounded fleet
    queue (placement retried with exponential backoff), fleet queue
    full -> typed ``AdmissionRejected`` backpressure;
  * **health watchdog** — a replica whose ``step()`` raises is CRASHED; a
    replica that keeps reporting no progress while holding work is WEDGED
    (``EngineStalledError`` after ``stall_threshold`` heartbeats).  Both
    are drilled deterministically via the seeded ``serve.crash`` /
    ``serve.wedge`` fault points (resilience/faults.py);
  * **failover** — a failed replica is revived from its newest INTACT
    engine snapshot (``EngineSnapshotManager``; torn snapshots are
    rejected via manifest and flight-recorded), and every outstanding
    request the snapshot does not cover migrates to a surviving replica by
    re-prefill of prompt + streamed tokens (``ServingEngine.adopt``).
    Greedy outputs stay bit-exact either way: a full-KV restore resumes
    the identical computation, and a re-prefill resume regenerates the
    identical greedy continuation (the PR 2/3 preemption guarantee) — any
    tokens re-decoded past an old snapshot are bit-identical to the ones
    already streamed, so nothing is lost and nothing diverges.

Failovers, migrations, and torn-snapshot rejections land in the fleet's
flight recorder stamped with the active fault-plan context
(``observability.fault_context``); ``fleet.migrations`` /
``fleet.failovers`` counters and the ``fleet.recovery_s`` histogram feed
the failover bench trace (``bench.py --trace failover``).
"""
from __future__ import annotations

import os
import time
from dataclasses import dataclass, field

import numpy as np

from ..inference.paged import (AdmissionRejected, EngineStalledError,
                               KVHandoffError, Request, ServingEngine)
from ..observability.distributed import (FleetTelemetry, TraceStitcher,
                                         new_trace_id)
from ..observability.flight import FlightRecorder
from ..observability.metrics import MetricsRegistry
from ..observability.slo import slo_report
from ..observability.tracing import Tracer
from ..observability.train import fault_context
from .routing import LeastLoadedRouter, Router
from .snapshot import EngineSnapshotManager

__all__ = ["ReplicaFleet", "FleetFailedError"]


class FleetFailedError(RuntimeError):
    """No replica could be kept alive (engine factory kept failing or the
    per-replica failover budget is exhausted) while requests were still
    outstanding — the fleet cannot make progress."""


@dataclass
class _FleetRequest:
    """The router's authoritative record of one request: enough to place
    it, re-place it after a crash (prompt + streamed tokens), and report
    it (fleet-level latency timestamps)."""
    frid: int
    prompt: np.ndarray
    kw: dict                       # max_new_tokens/temperature/top_p/eos
    deadline: float | None
    submit_t: float
    replica: str | None = None
    handle: Request | None = None  # live engine-side Request object
    streamed: list = field(default_factory=list)
    on_token: object | None = None  # router-fired streaming hook: called
                                    #   once per token as the ROUTER log
                                    #   extends, so a failover re-decode
                                    #   never double-emits
    result: Request | None = None
    # None until the first token streams: a 0.0 sentinel would collide
    # with a VIRTUAL clock legitimately reading t=0.0 in the first round
    first_token_t: float | None = None
    finish_t: float = 0.0
    retries: int = 0
    next_try_round: int = 0
    migrations: int = 0
    no_handoff: bool = False       # set after a handoff fallback so the
                                   #   request finishes wherever it lands
                                   #   instead of ping-ponging export /
                                   #   re-prefill forever
    trace_id: int | None = None    # fleet-wide stitching id; threaded into
                                   #   every engine-side adopt() so one
                                   #   Perfetto view binds the request's
                                   #   spans across replicas + failovers
    route_memo: dict = field(default_factory=dict)
                                   # per-placement-state routing scratch:
                                   #   the concatenated token stream and
                                   #   the router's chain digests, keyed
                                   #   by streamed length — a backoff
                                   #   retry must not re-hash an
                                   #   unchanged prompt every round


class _Replica:
    __slots__ = ("name", "engine", "alive", "routable", "stall", "failures",
                 "snapshots", "role")

    def __init__(self, name, engine, snapshots, role="any"):
        self.name = name
        self.engine = engine
        self.alive = True
        self.routable = True      # False while drain-retiring (scale-down)
        self.stall = 0            # consecutive no-progress steps w/ work
        self.failures = 0         # failovers consumed
        self.snapshots = snapshots
        self.role = role          # "any" | "prefill" | "decode" — sticky
                                  #   across failover revival (the replica
                                  #   is the same submesh either way)

    def load(self) -> int:
        """Active + queued requests — THE per-replica load notion,
        shared by router placement, the autoscaler's idle detector, and
        drain-victim selection (one definition, three consumers)."""
        return self.engine.num_active + len(self.engine._queue)


class _SnapTel:
    """CheckpointManager-telemetry duck for the snapshot managers: torn-
    snapshot rejections land in the FLEET flight record (with fault-plan
    context) and the fleet.torn_snapshots counter."""

    def __init__(self, fleet: "ReplicaFleet", name: str):
        self._fleet = fleet
        self._name = name

    def torn_snapshot(self, path, error):
        self._fleet._c_torn.inc()
        self._fleet.flight.record(
            "torn_snapshot", replica=self._name,
            path=os.path.basename(str(path)), error=str(error)[:200],
            fault_plan=fault_context())


class ReplicaFleet:
    """``engine_factory`` builds one fresh :class:`ServingEngine` per call
    (same params/config each time — replicas are interchangeable);
    the fleet names them ``r0..rN-1`` (the ``serve.crash`` /
    ``serve.wedge`` fault-point ``engine=`` ctx, so drills target one
    replica via ``match={"engine": "r0"}``).

    ``snapshot_root`` + ``snapshot_every`` turn on periodic engine
    snapshots (one ``EngineSnapshotManager`` per replica under
    ``snapshot_root/<name>``, mode ``snapshot_mode``); without them
    failover falls back to pure re-prefill migration — still zero-loss and
    greedy-bit-exact, just a cold KV start for the migrated requests."""

    def __init__(self, engine_factory, num_replicas: int = 2, *,
                 roles=None,
                 handoff_retry_rounds: int = 8,
                 router: Router | None = None,
                 snapshot_root: str | None = None,
                 snapshot_every: int | None = None,
                 snapshot_mode: str = "full_kv",
                 snapshot_keep_last: int = 2,
                 max_queue: int | None = None,
                 stall_threshold: int = 8,
                 retry_backoff_rounds: int = 1,
                 max_backoff_rounds: int = 32,
                 max_failovers_per_replica: int = 4,
                 clock=time.perf_counter,
                 flight_capacity: int = 256,
                 route_dump_last: int = 16):
        if num_replicas < 1:
            raise ValueError("num_replicas must be >= 1")
        # disaggregated prefill/decode (ISSUE 19): one role per replica.
        # "prefill" replicas run prefill + the first token, then hand
        # their head-sharded KV pages to a "decode"/"any" replica;
        # "any" replicas (the default) behave exactly like the colocated
        # fleets of PR 9-18 — no roles, no handoffs, no new behavior.
        if roles is None:
            roles = ["any"] * int(num_replicas)
        else:
            roles = [str(r) for r in roles]
            if len(roles) != int(num_replicas):
                raise ValueError(
                    f"roles needs one entry per replica: got {len(roles)} "
                    f"for num_replicas={num_replicas}")
            bad = sorted(set(roles) - {"any", "prefill", "decode"})
            if bad:
                raise ValueError(f"unknown replica roles {bad} "
                                 f"(valid: any/prefill/decode)")
            if "prefill" in roles \
                    and not any(r in ("decode", "any") for r in roles):
                raise ValueError(
                    "a disaggregated fleet needs at least one decode-"
                    "capable replica ('decode' or 'any') to receive "
                    "prefill handoffs")
        self._factory = engine_factory
        # factories that accept a role= keyword get told which submesh
        # they are building for (prefill and decode engines may want
        # different chunking / horizons); legacy factories are called
        # bare.  Detected ONCE here — a TypeError raised inside the
        # factory at spawn time must not be mistaken for "takes no role"
        try:
            import inspect
            params = inspect.signature(engine_factory).parameters.values()
            self._factory_takes_role = any(
                p.kind is inspect.Parameter.VAR_KEYWORD or p.name == "role"
                for p in params)
        except (TypeError, ValueError):
            self._factory_takes_role = False
        self.handoff_retry_rounds = int(handoff_retry_rounds)
        self._clock = clock
        self.router = router if router is not None else LeastLoadedRouter()
        self.snapshot_root = snapshot_root
        self.snapshot_every = snapshot_every
        self.snapshot_mode = snapshot_mode
        self.snapshot_keep_last = int(snapshot_keep_last)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.stall_threshold = int(stall_threshold)
        self.retry_backoff_rounds = int(retry_backoff_rounds)
        self.max_backoff_rounds = int(max_backoff_rounds)
        self.max_failovers_per_replica = int(max_failovers_per_replica)
        self.metrics = MetricsRegistry(clock=clock)
        self._c_failovers = self.metrics.counter("fleet.failovers")
        self._c_migrations = self.metrics.counter("fleet.migrations")
        self._c_rejections = self.metrics.counter("fleet.rejections")
        self._c_submitted = self.metrics.counter("fleet.requests_submitted")
        self._c_resolved = self.metrics.counter("fleet.requests_resolved")
        self._c_torn = self.metrics.counter("fleet.torn_snapshots")
        # elastic control plane (ROADMAP item 5): replica add/remove and
        # drain-migration accounting — fixed fleets report honest zeros
        self._c_scale_up = self.metrics.counter("fleet.scale_up")
        self._c_scale_down = self.metrics.counter("fleet.scale_down")
        self._c_drain_migr = self.metrics.counter("fleet.drain_migrations")
        self._h_recovery = self.metrics.histogram("fleet.recovery_s")
        # disaggregated KV handoff accounting (ISSUE 19): role-less
        # fleets report honest zeros, same contract as the elastic block
        self._c_handoffs = self.metrics.counter("fleet.kv_handoffs")
        self._c_handoff_fallbacks = self.metrics.counter(
            "fleet.kv_handoff_fallbacks")
        self._c_kv_pages = self.metrics.counter(
            "fleet.kv_pages_transferred")
        self._c_kv_bytes = self.metrics.counter(
            "fleet.kv_bytes_transferred")
        self._c_kv_rank_local = self.metrics.counter(
            "fleet.kv_rank_local_handoffs")
        self._h_kv_transfer = self.metrics.histogram("fleet.kv_transfer_s")
        # exported-but-not-yet-imported packets: export happens at the
        # END of a round (phase B, after streams), import at the START of
        # the next (phase A) — the one-round gap between the source and
        # destination residencies is what attribution classifies as the
        # kv_transfer segment
        self._pending_handoffs: list[dict] = []
        self.flight = FlightRecorder(capacity=flight_capacity, clock=clock)
        # the ROUTER track of the stitched fleet trace: one request record
        # per frid (submitted -> admitted(replica) -> first_token ->
        # retired, with migrations re-opening the queued phase), sharing
        # the fleet clock with every replica tracer
        self.tracer = Tracer(clock=clock)
        self.route_dump_last = int(route_dump_last)
        # tracers of crashed replica generations, kept so the stitched
        # trace still shows the spans a request ran on a now-dead engine
        self._dead_tracers: list[tuple[str, Tracer]] = []
        self._requests: dict[int, _FleetRequest] = {}
        self._assigned: dict[str, set[int]] = {}
        self._waiting: list[_FleetRequest] = []
        self._summaries: list[dict] = []
        self._next_frid = 0
        self._round = 0
        # router-observed token counter (one inc per streamed token — a
        # migrated engine's re-decode of already-streamed tokens does NOT
        # advance it) and replica-time accounting (integral of live
        # replica count over fleet heartbeats: the goodput-per-replica-
        # hour denominator)
        self.tokens_streamed = 0
        self.replica_seconds = 0.0
        self._last_tick: float | None = None
        # retired (drained) replicas: tracer rides _dead_tracers for the
        # stitched view; telemetry + final counters stay readable so the
        # fleet-wide hit-rate accounting covers their whole service life
        self._retired_telemetry: list[tuple[str, object]] = []
        self._retired_stats: list[tuple[str, dict]] = []
        self._replicas: list[_Replica] = []
        self._next_replica_idx = 0
        for role in roles:
            self._spawn_replica(role=role)

    # -- construction helpers ----------------------------------------------
    def _new_engine(self, name: str, role: str = "any") -> ServingEngine:
        eng = self._factory(role=role) if self._factory_takes_role \
            else self._factory()
        if not isinstance(eng, ServingEngine):
            raise TypeError("engine_factory must return a ServingEngine")
        eng.name = name
        return eng

    def _snapshot_manager(self, name: str):
        if self.snapshot_root is None:
            return None
        return EngineSnapshotManager(
            os.path.join(self.snapshot_root, name),
            keep_last=self.snapshot_keep_last,
            telemetry=_SnapTel(self, name))

    def _spawn_replica(self, role: str = "any") -> _Replica:
        """Build + register one replica under the next monotonic name
        (names are never reused — a retired r1's tracer track and a later
        r3 can coexist in one stitched view)."""
        name = f"r{self._next_replica_idx}"
        self._next_replica_idx += 1
        rep = _Replica(name, self._new_engine(name, role),
                       self._snapshot_manager(name), role=role)
        self._replicas.append(rep)
        self._assigned[name] = set()
        self._wire_router(rep)
        return rep

    def _wire_router(self, rep: _Replica):
        """Register a (new or revived) replica with the routing strategy
        and keep its cached-chain summary current: seed from whatever the
        engine's prefix cache already indexes (a snapshot-restored engine
        arrives warm), then subscribe to insert/evict notifications."""
        eng = rep.engine
        self.router.configure(page_size=eng.page_size)
        self.router.on_replica_added(rep.name)
        if eng.cache is not None:
            name = rep.name

            def _notify(kind, digests, _name=name):
                if kind == "insert":
                    self.router.note_cached(_name, digests)
                else:
                    self.router.note_evicted(_name, digests)

            eng.cache.notify = _notify
            existing = list(eng.cache.chain_digests())
            if existing:
                self.router.note_cached(name, existing)

    # -- elastic control plane (ROADMAP item 5) ----------------------------
    def add_replica(self, role: str = "any") -> str:
        """Scale up: spawn one fresh replica at runtime (the autoscaler's
        grow action).  Returns the new replica's name; it is routable
        immediately.  ``role`` lets a role-aware autoscaler grow prefill
        and decode capacity independently."""
        if role not in ("any", "prefill", "decode"):
            raise ValueError(f"unknown replica role {role!r}")
        rep = self._spawn_replica(role=role)
        self._c_scale_up.inc()
        self.flight.record("scale_up", replica=rep.name, role=role,
                           replicas=len(self._alive()))
        self.tracer.engine_event("scale_up", replica=rep.name, role=role)
        return rep.name

    def retire_replica(self, name: str) -> bool:
        """Scale down with ZERO request loss: mark the replica
        unroutable, live-migrate every in-flight request it carries to a
        surviving replica (engine-side ``cancel`` parks the written KV
        and quiesces any in-flight dispatch at an exact host state, then
        the router's authoritative record re-places via ``adopt`` — the
        streamed-token re-prefill path, greedy-bit-exact by the PR 9
        guarantee), then destroy the empty engine.  Returns True only
        when the replica was ACTUALLY retired: False for unknown/dead
        replicas, when it would drain the last live one, and when the
        target CRASHES mid-drain — that case falls through to the
        normal failover path (the requests still migrate, still
        zero-loss, and the replica is revived instead of retired, so no
        scale-down happened)."""
        rep = next((r for r in self._replicas
                    if r.name == name and r.alive), None)
        if rep is None or len(self._alive()) <= 1:
            return False
        rep.routable = False
        outstanding = [self._requests[f]
                       for f in sorted(self._assigned[name])]
        self.flight.record("drain_begin", replica=name,
                           inflight=len(outstanding))
        self.tracer.engine_event("drain", replica=name,
                                 inflight=len(outstanding))
        for fr in outstanding:
            rid = fr.handle.rid if fr.handle is not None else None
            try:
                if rid is not None:
                    rep.engine.cancel(rid)
            except Exception as exc:  # noqa: BLE001 — the drain target
                # died mid-migration: the failover path WINS (it migrates
                # every outstanding request, this one included) and the
                # replica is revived instead of retired — still
                # zero-loss, but NOT a scale-down (the caller must not
                # record a phantom retirement)
                self._fail(rep, "crash", exc)
                return False
            self._assigned[name].discard(fr.frid)
            fr.replica = None
            fr.handle = None
            self._c_drain_migr.inc()
            self._migrate(fr)
        # anything else still on the engine is a zombie the router never
        # tracked (e.g. snapshot-restored requests resolved elsewhere) —
        # same crash guard as the migration loop: a death HERE must also
        # fall through to failover, not escape the serve loop
        try:
            for rid in [sl.req.rid for sl in rep.engine._slots
                        if sl is not None] \
                    + [r.rid for r in rep.engine._queue]:
                rep.engine.cancel(rid)
        except Exception as exc:  # noqa: BLE001 — died cancelling zombies
            self._fail(rep, "crash", exc)
            return False
        self._destroy_replica(rep)
        return True

    def _destroy_replica(self, rep: _Replica):
        """Tear down a drained (empty) replica: detach the cache feed,
        keep its tracer (stitched views) + telemetry + final counters
        (fleet-wide hit-rate accounting spans its whole service life),
        verify its page accounting one last time, and drop the engine."""
        eng = rep.engine
        if eng.cache is not None:
            eng.cache.notify = None
        eng.release_cache()
        eng.check_invariants()      # retired-then-destroyed leak guard
        if eng.telemetry is not None:
            self._dead_tracers.append(
                (f"{rep.name} (retired)", eng.telemetry.tracer))
            self._retired_telemetry.append(
                (rep.name, eng.telemetry.registry))
        self._retired_stats.append((rep.name, eng.stats()))
        self.router.on_replica_removed(rep.name)
        rep.alive = False
        rep.engine = None
        self._replicas.remove(rep)
        del self._assigned[rep.name]
        self._c_scale_down.inc()
        self.flight.record("scale_down", replica=rep.name,
                           replicas=len(self._alive()))
        self.tracer.engine_event("scale_down", replica=rep.name)

    # -- submission (fleet ladder: route -> queue -> reject) ---------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_p: float = 1.0,
               eos_token_id: int | None = None,
               timeout: float | None = None, on_token=None,
               trace_id: int | None = None) -> int:
        """Queue one request with the fleet; returns the fleet request id.
        Routing tries every live replica least-loaded-first; when all
        reject (their admission queues are full), the request waits in the
        bounded fleet queue; when THAT is full, typed
        ``AdmissionRejected`` backpressure.

        ``on_token`` is the fleet-level streaming hook: fired once per
        token as the ROUTER's authoritative log extends (at the fleet
        heartbeat that drained the token), in emission order.  It is
        deliberately NOT passed to the replica engines: after a failover
        a revived/migrated engine RE-decodes tokens the router already
        streamed (greedy-identical by the bit-exactness guarantee), and
        an engine-side hook would re-fire them — the router log only ever
        extends, so the fleet hook emits each position exactly once
        across any number of crashes and migrations.

        ``trace_id`` (optional) is the end-to-end stitching id from an
        upstream front end; the fleet mints one when none is supplied, so
        every request is stitchable."""
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        now = self._clock()
        fr = _FleetRequest(
            frid=self._next_frid, prompt=prompt,
            kw=dict(max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature), top_p=float(top_p),
                    eos_token_id=eos_token_id),
            deadline=None if timeout is None else now + float(timeout),
            submit_t=now, on_token=on_token,
            trace_id=new_trace_id() if trace_id is None else int(trace_id))
        self._next_frid += 1
        self.flight.record("submit", frid=fr.frid,
                           prompt_tokens=len(prompt), trace_id=fr.trace_id)
        self.tracer.request_event(fr.frid, "submitted", t=now,
                                  prompt_tokens=len(prompt),
                                  trace_id=fr.trace_id)
        self.tracer.request_event(fr.frid, "queued", t=now,
                                  depth=len(self._waiting))
        # place BEFORE registering: a placement-time PoolCapacityError /
        # ValueError (a request that can never fit) must propagate without
        # leaving an unresolvable ghost in self._requests (which would
        # wedge every later run()) — and without leaving a never-terminated
        # ghost in the router TRACER either (its live table is unbounded
        # and ghosts would pollute every stitched trace)
        try:
            placed = self._place(fr)
        except BaseException:
            self.tracer.request_event(fr.frid, "retired", rejected=True,
                                      error=True, tokens=0)
            raise
        if not placed:
            if self.max_queue is not None \
                    and len(self._waiting) >= self.max_queue:
                self._c_rejections.inc()
                self.flight.record("reject", frid=fr.frid,
                                   waiting=len(self._waiting))
                self.tracer.request_event(fr.frid, "retired",
                                          rejected=True, tokens=0)
                raise AdmissionRejected(
                    f"fleet queue full ({len(self._waiting)}/"
                    f"{self.max_queue} waiting) — backpressure, retry later")
            fr.next_try_round = self._round + 1
            self._waiting.append(fr)
            self.flight.record("queue", frid=fr.frid,
                               waiting=len(self._waiting))
        self._requests[fr.frid] = fr
        self._c_submitted.inc()
        return fr.frid

    def cancel(self, frid: int) -> bool:
        """Drop a fleet request wherever it lives (client disconnect from
        the async front end): cancel it on its replica engine (pages free
        mid-decode), remove it from the fleet queue and the router record.
        Returns True when the frid was known.  Already-resolved requests
        are forgotten (their result is discarded)."""
        fr = self._requests.pop(frid, None)
        if fr is None:
            return False
        self._waiting = [w for w in self._waiting if w.frid != frid]
        if fr.replica is not None:
            self._assigned.get(fr.replica, set()).discard(frid)
            for rep in self._replicas:
                if rep.name == fr.replica and rep.alive \
                        and fr.handle is not None:
                    rep.engine.cancel(fr.handle.rid)
                    break
        self.flight.record("cancel", frid=frid,
                           streamed=len(fr.streamed))
        self.tracer.request_event(frid, "retired", cancelled=True,
                                  tokens=len(fr.streamed))
        return True

    def _alive(self):
        return [rep for rep in self._replicas if rep.alive]

    @property
    def _has_roles(self) -> bool:
        """True when ANY replica carries a non-"any" role — checked per
        placement (not cached) so an elastic fleet that grows its first
        prefill replica at runtime becomes role-aware on the spot."""
        return any(rep.role != "any" for rep in self._replicas)

    def _backoff(self, fr: _FleetRequest):
        """One failed placement attempt: exponential backoff (capped) until
        the next retry round."""
        fr.retries += 1
        fr.next_try_round = self._round + min(
            self.max_backoff_rounds,
            self.retry_backoff_rounds * (2 ** min(fr.retries, 10)))

    def _place(self, fr: _FleetRequest) -> bool:
        """Route rung: ask the routing strategy for the candidate order
        (least-loaded by default; prefix-affine with
        :class:`~paddle_tpu.serving.routing.PrefixAffinityRouter`) and
        try each candidate in turn.  Placement always goes through
        ``adopt`` so the fleet-anchored absolute deadline is preserved
        and a migrated request resumes from its streamed tokens (empty
        stream == fresh submission).  Typed ``PoolCapacityError`` (can
        NEVER fit) propagates to the caller.

        Role-aware fleets filter the candidates to prefill-capable
        replicas first (adopt ALWAYS prefills — prompt, or prompt +
        streamed for a migration); when none survives, every routable
        replica is eligible again: role is a throughput preference and
        must never become a reason to drop or strand work."""
        cands = {rep.name: rep for rep in self._alive() if rep.routable}
        role = None
        if self._has_roles:
            role = "prefill"
            pref = {n: r for n, r in cands.items()
                    if r.role in ("prefill", "any")}
            if pref:
                cands = pref
        if not cands:
            return False
        # the token stream the placement would prefill: prompt for a
        # fresh submission, prompt + streamed[:-1] for a migration (the
        # last streamed token rides as the pending sample, never
        # written).  Memoized per placement state: a backoff retry of an
        # unchanged request reuses the concatenation AND the router's
        # chain digests instead of re-hashing the whole prompt per round
        memo = fr.route_memo
        if memo.get("n_streamed") != len(fr.streamed):
            memo.clear()
            memo["n_streamed"] = len(fr.streamed)
            memo["tokens"] = fr.prompt if not fr.streamed \
                else np.concatenate(
                    [fr.prompt, np.asarray(fr.streamed[:-1], np.int32)])
        decision = self.router.decide(
            memo["tokens"],
            [(name, rep.load()) for name, rep in cands.items()],
            memo=memo, role=role)
        for name in decision.order:
            rep = cands.get(name)
            if rep is None:
                continue
            try:
                rid = rep.engine.adopt(fr.prompt, fr.streamed,
                                       deadline=fr.deadline,
                                       trace_id=fr.trace_id, **fr.kw)
            except AdmissionRejected:
                continue
            fr.replica = rep.name
            fr.handle = rep.engine.lookup(rid)
            self._assigned[rep.name].add(fr.frid)
            self.flight.record("route", frid=fr.frid, replica=rep.name,
                               resumed_tokens=len(fr.streamed),
                               routing=decision.kind,
                               affinity_blocks=decision.matched_blocks,
                               trace_id=fr.trace_id)
            self.tracer.request_event(fr.frid, "admitted",
                                      replica=rep.name,
                                      routing=decision.kind,
                                      affinity_blocks=decision.matched_blocks,
                                      resumed_tokens=len(fr.streamed))
            return True
        return False

    # -- the fleet loop ----------------------------------------------------
    def step(self) -> bool:
        """One fleet round: retry queued placements whose backoff expired,
        heartbeat-step every live replica (catching crashes, counting
        wedge stalls), stream newly emitted tokens into the router record,
        fail over dead replicas, and take periodic snapshots.  Returns
        True when anything progressed."""
        self._round += 1
        # replica-time accounting: the integral of live-replica count
        # over fleet heartbeats (draining replicas still cost machine
        # time until destroyed) — goodput-per-replica-hour's denominator
        now = self._clock()
        if self._last_tick is not None:
            self.replica_seconds += len(self._alive()) \
                * max(0.0, now - self._last_tick)
        self._last_tick = now
        progressed = False
        # phase A of the KV handoff: packets exported at the END of the
        # previous round splice into a decode replica BEFORE any other
        # placement this round (the handed-off request must not lose its
        # slot to a fresh admission racing it out of the fleet queue)
        if self._import_pending_handoffs():
            progressed = True
        for fr in list(self._waiting):
            if fr.next_try_round > self._round:
                continue
            if self._place(fr):
                self._waiting.remove(fr)
                progressed = True
            else:
                self._backoff(fr)
        for rep in self._replicas:
            if not rep.alive:
                continue
            eng = rep.engine
            # a double-buffered engine with nothing queued may still hold
            # an in-flight dispatch whose tokens only land at the next
            # drain — keep heartbeating it (step() reports the in-flight
            # progress) instead of parking it un-drained
            if not (eng.num_active or eng._queue or eng.inflight_depth):
                rep.stall = 0
                continue
            try:
                ok = eng.step()
            except Exception as exc:  # noqa: BLE001 — ANY escaped exception
                # is a dead replica (the drills raise InjectedFault; a real
                # deployment segfaults); the corpse's host state is not
                # trusted — recovery uses snapshots + the router record
                self._fail(rep, "crash", exc)
                progressed = True
                continue
            self._stream(rep)
            if ok:
                rep.stall = 0
                progressed = True
            else:
                rep.stall += 1
                if rep.stall >= self.stall_threshold:
                    self._fail(rep, "wedge", EngineStalledError(
                        f"replica {rep.name}: no progress for {rep.stall} "
                        f"consecutive heartbeats with work pending"))
                    progressed = True
        # phase B: prefill-role replicas export finished prefills AFTER
        # their streams drained (the router log must already cover every
        # token the packet carries, so the decode replica's re-emission
        # only ever EXTENDS it)
        if self._begin_handoffs():
            progressed = True
        if self.snapshot_root is not None and self.snapshot_every \
                and self._round % self.snapshot_every == 0:
            for rep in self._replicas:
                if not rep.alive:
                    continue
                try:
                    path = rep.snapshots.save_engine(
                        rep.engine, mode=self.snapshot_mode)
                    self.flight.record("snapshot", replica=rep.name,
                                       path=os.path.basename(path))
                except Exception as exc:  # noqa: BLE001 — died mid-snapshot
                    self._fail(rep, "crash", exc)
                    progressed = True   # the failover IS progress (same as
                    # the heartbeat crash path — the stall watchdog must
                    # not starve on rounds that spent their time recovering)
        return progressed

    # -- disaggregated KV handoff (ISSUE 19) -------------------------------
    def _begin_handoffs(self) -> bool:
        """Phase B of the disaggregated handoff: every prefill-role
        replica exports each request whose prefill is DONE (first token
        decoded, no chunk in flight — ``ServingEngine.handoff_ready``)
        as a KV packet (head-sharded page planes + scale planes + exact
        request state), cancels it locally (the written KV parks in the
        prefix cache: an affinity bonus if the fallback path ever
        re-prefills here), and queues the packet for phase-A import next
        round.  Pure host work — no engine steps, no device transfers
        beyond the page gather itself."""
        if not self._has_roles:
            return False
        progressed = False
        for rep in self._replicas:
            if not rep.alive or rep.role != "prefill":
                continue
            for frid in sorted(self._assigned[rep.name]):
                if frid not in self._assigned[rep.name]:
                    continue       # resolved by a _stream below
                fr = self._requests.get(frid)
                if fr is None or fr.result is not None \
                        or fr.handle is None or fr.no_handoff:
                    continue
                eng = rep.engine
                if not eng.handoff_ready(fr.handle.rid):
                    continue
                t0 = self._clock()
                try:
                    packet = eng.export_kv([fr.handle.rid])
                except KeyError:
                    # retired during the export quiesce (deadline race) —
                    # the drain below observes the retirement; nothing to
                    # hand off
                    self._stream(rep)
                    continue
                # drain tokens decoded up to the quiesce point FIRST: the
                # router log must cover everything the packet carries
                self._stream(rep)
                if fr.result is not None:
                    continue       # finished at the quiesce edge
                eng.cancel(fr.handle.rid)
                self._assigned[rep.name].discard(frid)
                fr.replica = None
                fr.handle = None
                self._pending_handoffs.append({
                    "fr": fr, "packet": packet, "src": rep.name,
                    "src_tp": int(packet["tp"]), "t0": t0, "tries": 0})
                self.flight.record("handoff_export", frid=frid,
                                   src=rep.name,
                                   pages=len(packet["kv_pages"]),
                                   bytes=int(packet["bytes"]),
                                   trace_id=fr.trace_id)
                # "preempted" re-opens the queued phase on the router
                # track; the import closes it with routing="handoff"
                self.tracer.request_event(frid, "preempted",
                                          kind="handoff",
                                          tokens=len(fr.streamed))
                progressed = True
        return progressed

    def _import_pending_handoffs(self) -> bool:
        """Phase A: splice every pending KV packet into a decode-capable
        replica.  Admission pressure retries for ``handoff_retry_rounds``
        rounds, then falls back to re-prefill migration; a geometry/
        dtype/mp-degree mismatch (``KVHandoffError`` — the packet can
        NEVER splice there) falls back immediately.  Either fallback
        rides the normal degradation ladder (route -> queue -> reject
        exempt: migrations are never dropped)."""
        if not self._pending_handoffs:
            return False
        progressed = False
        still: list[dict] = []
        for h in self._pending_handoffs:
            fr = h["fr"]
            if fr.result is not None or fr.frid not in self._requests:
                continue           # resolved or client-cancelled in flight
            outcome = self._import_one(h)
            if outcome == "retry":
                h["tries"] += 1
                if h["tries"] >= self.handoff_retry_rounds:
                    fr.no_handoff = True
                    self._c_handoff_fallbacks.inc()
                    self.flight.record("handoff_fallback", frid=fr.frid,
                                       src=h["src"],
                                       reason="no_decode_capacity",
                                       tries=h["tries"])
                    self._migrate(fr)
                    progressed = True
                else:
                    still.append(h)
            else:
                progressed = True  # placed, or fallback-migrated inline
        self._pending_handoffs = still
        return progressed

    def _import_one(self, h: dict) -> str:
        """Try one packet: returns ``"placed"`` (spliced into a decode
        replica), ``"fallback"`` (mismatch — already re-prefill-migrated),
        or ``"retry"`` (admission pressure / no decode capacity now)."""
        fr = h["fr"]
        cands = {rep.name: rep for rep in self._alive()
                 if rep.routable and rep.role == "decode"}
        if not cands:
            # no decode replica alive (mid-failover): "any" replicas can
            # decode too — never strand the packet on role purity.
            # Never a PREFILL replica: importing there would undo the
            # disaggregation the export just paid for.
            cands = {rep.name: rep for rep in self._alive()
                     if rep.routable and rep.role == "any"}
        if not cands:
            return "retry"
        memo = fr.route_memo
        if memo.get("n_streamed") != len(fr.streamed):
            memo.clear()
            memo["n_streamed"] = len(fr.streamed)
            memo["tokens"] = fr.prompt if not fr.streamed \
                else np.concatenate(
                    [fr.prompt, np.asarray(fr.streamed[:-1], np.int32)])
        decision = self.router.decide(
            memo["tokens"],
            [(name, rep.load()) for name, rep in cands.items()],
            memo=memo, role="decode")
        for name in decision.order:
            rep = cands.get(name)
            if rep is None:
                continue
            try:
                mapping = rep.engine.import_kv(h["packet"])
            except AdmissionRejected:
                continue
            except KVHandoffError as exc:
                fr.no_handoff = True
                self._c_handoff_fallbacks.inc()
                self.flight.record("handoff_fallback", frid=fr.frid,
                                   src=h["src"], dst=name,
                                   reason=str(exc)[:160])
                self._migrate(fr)
                return "fallback"
            rid = next(iter(mapping.values()))
            fr.replica = rep.name
            fr.handle = rep.engine.lookup(rid)
            self._assigned[rep.name].add(fr.frid)
            dt = max(0.0, self._clock() - h["t0"])
            rank_local = int(rep.engine.tp) == h["src_tp"]
            self._c_handoffs.inc()
            self._c_kv_pages.inc(len(h["packet"]["kv_pages"]))
            self._c_kv_bytes.inc(int(h["packet"]["bytes"]))
            if rank_local:
                self._c_kv_rank_local.inc()
            self._h_kv_transfer.observe(dt)
            self.flight.record("handoff", frid=fr.frid, src=h["src"],
                               dst=rep.name,
                               pages=len(h["packet"]["kv_pages"]),
                               bytes=int(h["packet"]["bytes"]),
                               rank_local=rank_local,
                               transfer_s=round(dt, 6),
                               trace_id=fr.trace_id)
            self.tracer.request_event(fr.frid, "admitted",
                                      replica=rep.name,
                                      routing="handoff",
                                      rank_local=rank_local,
                                      resumed_tokens=len(fr.streamed))
            return "placed"
        return "retry"

    def _stream(self, rep: _Replica):
        """Drain newly emitted tokens from the replica into the router's
        per-request record (the token-streaming path), and capture results
        for retired requests.  After a migration or snapshot restore the
        engine may RE-emit tokens the router already streamed — greedy
        regeneration is bit-identical, so the record only ever extends."""
        now = self._clock()
        for frid in sorted(self._assigned[rep.name]):
            fr = self._requests[frid]
            req = fr.handle
            gen = req.generated
            if len(gen) > len(fr.streamed):
                if fr.first_token_t is None:
                    fr.first_token_t = now
                    self.tracer.request_event(fr.frid, "first_token",
                                              t=now, replica=rep.name)
                for t in gen[len(fr.streamed):]:
                    t = int(t)
                    fr.streamed.append(t)
                    self.tokens_streamed += 1
                    if fr.on_token is not None:
                        # router-authoritative emission: fires exactly once
                        # per position, even when a migrated engine
                        # re-decodes already-streamed tokens
                        fr.on_token(t)
            if req.finish_time:
                self._resolve(fr, req, now)

    def _resolve(self, fr: _FleetRequest, req: Request, now: float):
        fr.result = req
        fr.finish_t = now
        self._c_resolved.inc()
        if fr.replica is not None:
            self._assigned[fr.replica].discard(fr.frid)
        n = len(req.generated)
        ttft = fr.first_token_t - fr.submit_t \
            if fr.first_token_t is not None else None
        tpot = (fr.finish_t - fr.first_token_t) / (n - 1) \
            if n > 1 and fr.first_token_t is not None else None
        # per-request result store the drill harness reads whole;
        # fleet lifetime is one drill  # graftlint: disable=LEAK001
        self._summaries.append({
            "rid": fr.frid, "tokens": n, "ttft_s": ttft, "tpot_s": tpot,
            "e2e_s": now - fr.submit_t, "timed_out": req.timed_out,
            "migrations": fr.migrations, "at": now,
        })
        self.flight.record("resolve", frid=fr.frid, tokens=n,
                           timed_out=req.timed_out,
                           migrations=fr.migrations)
        self.tracer.request_event(fr.frid, "retired", t=now, tokens=n,
                                  timed_out=req.timed_out,
                                  migrations=fr.migrations)

    # -- failover ----------------------------------------------------------
    def _fail(self, rep: _Replica, kind: str, exc: BaseException):
        """Replica death: flight-record the failover (with any active
        fault-plan context), revive the replica — from its newest intact
        snapshot when one exists, blank otherwise — and migrate every
        outstanding request the revived engine does not already carry."""
        t0 = self._clock()
        self._c_failovers.inc()
        rep.failures += 1
        rep.alive = False
        # the unroutable mark happens-before EVERYTHING else in the
        # failover — placement candidates are filtered on it, so no
        # adopt can race a replica the supervisor already condemned
        rep.routable = False
        corpse = rep.engine
        rep.engine = None          # the corpse's state is not trusted
        rep.stall = 0
        # wedge-race quiesce (ISSUE 17 satellite): a wedged-but-ALIVE
        # engine can un-wedge after the failover decision — and anything
        # still holding a reference (an autoscaler sweep, a frontend
        # worker thread) could step it and keep decoding requests the
        # fleet is about to migrate: double emission through any
        # engine-level hook, pages pinned on the corpse.  Cancel the
        # outstanding requests ON THE CORPSE before any adopt happens,
        # so the quiesce happens-before the migration.  Crash corpses
        # are not trusted (possibly corrupt host state) — best-effort,
        # first failure aborts the sweep.
        if kind == "wedge" and corpse is not None:
            quiesced = 0
            for frid in sorted(self._assigned[rep.name]):
                fr = self._requests[frid]
                if fr.handle is None:
                    continue
                try:
                    corpse.cancel(fr.handle.rid)
                    quiesced += 1
                except BaseException:  # noqa: BLE001 — corpse may be wedged
                    break              # beyond cooperation; migration still
                                       # proceeds (router log is authoritative)
            self.flight.record("wedge_quiesce", replica=rep.name,
                               cancelled=quiesced)
        # the dead engine's cached chains died with it: the router must
        # not keep routing affinity traffic at a corpse (revival re-seeds
        # from whatever the restored snapshot actually carries)
        self.router.on_replica_removed(rep.name)
        # postmortem capture BEFORE the corpse is dropped: its flight ring
        # (what the replica was doing when it died) and its tracer (so the
        # stitched fleet trace keeps the spans this generation ran)
        corpse_ring = None
        if corpse is not None and corpse.telemetry is not None:
            corpse_ring = corpse.telemetry.flight.events()
            # one entry per replica death — failover forensics, read
            # whole by the stitched export  # graftlint: disable=LEAK001
            self._dead_tracers.append(
                (f"{rep.name} (crashed#{rep.failures})",
                 corpse.telemetry.tracer))
        self.flight.record("failover", replica=rep.name, kind=kind,
                           failures=rep.failures, error=str(exc)[:200],
                           fault_plan=fault_context())
        self.tracer.engine_event("failover", replica=rep.name, kind=kind)
        # ONE merged postmortem artifact: the dying replica's ring PLUS
        # the router's last-N routing decisions — a misroute (the request
        # was on the wrong replica when it died) is diagnosable from this
        # dump alone, without correlating two files
        routing = [e for e in self.flight.events()
                   if e["event"] in ("route", "migrate")]
        self.flight.dump(
            "failover", replica=rep.name, kind=kind,
            routing_decisions=routing[-self.route_dump_last:],
            replica_ring=corpse_ring)
        outstanding = [self._requests[f]
                       for f in sorted(self._assigned[rep.name])]
        self._assigned[rep.name] = set()
        restored_rids = None
        if rep.failures <= self.max_failovers_per_replica:
            restored_rids = self._revive(rep)
        still = outstanding
        if rep.alive and restored_rids is not None:
            still = []
            kept: set[int] = set()
            for fr in outstanding:
                rid = fr.handle.rid if fr.handle is not None else None
                if rid is not None and rid in restored_rids \
                        and fr.kw["temperature"] <= 0.0:
                    # the snapshot carries this GREEDY request — it
                    # continues on the revived replica from the snapshot
                    # state (any re-decoded tokens are greedy-identical to
                    # the ones already streamed).  Sampled requests must
                    # NOT resume from a stale snapshot: re-sampling past
                    # the snapshot point diverges from tokens the router
                    # already streamed — they migrate via adopt() below,
                    # which continues from the streamed tokens exactly
                    # (their snapshot copy is pruned as a zombie).
                    fr.handle = rep.engine.lookup(rid)
                    self._assigned[rep.name].add(fr.frid)
                    kept.add(rid)
                else:
                    still.append(fr)
            # prune ZOMBIES: snapshot-restored requests the router already
            # resolved before the crash would otherwise occupy slots/pages
            # on the revived replica and decode to completion unobserved
            for rid in sorted(restored_rids - kept):
                rep.engine.cancel(rid)
        for fr in still:
            fr.replica = None
            fr.handle = None
            self._migrate(fr)
        if not self._alive() and any(fr.result is None
                                     for fr in self._requests.values()):
            raise FleetFailedError(
                f"no live replicas left ({len(self._requests)} requests "
                f"tracked, failover budget "
                f"{self.max_failovers_per_replica}/replica exhausted)")
        self._h_recovery.observe(self._clock() - t0)

    def _revive(self, rep: _Replica):
        """Build a replacement engine for a dead replica; restore it from
        the newest intact snapshot when one exists.  Returns the set of
        engine-side rids the restored engine carries (empty for a blank
        replacement), or None when the replacement could not be built
        (the replica stays dead)."""
        try:
            eng = self._new_engine(rep.name, rep.role)
        except Exception as exc:  # noqa: BLE001 — factory failure
            self.flight.record("revive_failed", replica=rep.name,
                               error=str(exc)[:200])
            return None
        restored: set[int] = set()
        if rep.snapshots is not None:
            try:
                res = rep.snapshots.restore_engine(eng)
            except Exception as exc:  # noqa: BLE001 — unreadable snapshot
                self.flight.record("restore_failed", replica=rep.name,
                                   error=str(exc)[:200])
                res = None
            if res is not None:
                path, applied = res
                restored = set(eng._finished) \
                    | {sl.req.rid for sl in eng._slots if sl is not None} \
                    | {r.rid for r in eng._queue}
                self.flight.record("restore", replica=rep.name,
                                   path=os.path.basename(path),
                                   mode=applied, requests=len(restored))
        rep.engine = eng
        rep.alive = True
        rep.routable = True
        self._wire_router(rep)
        return restored

    def _migrate(self, fr: _FleetRequest):
        """Move one orphaned request to a live replica by re-prefill of
        prompt + streamed tokens; unplaceable requests wait in the fleet
        queue with backoff (migrated requests are never dropped — the
        reject rung applies to NEW submissions only)."""
        self._c_migrations.inc()
        fr.migrations += 1
        self.flight.record("migrate", frid=fr.frid,
                           tokens=len(fr.streamed),
                           trace_id=fr.trace_id,
                           fault_plan=fault_context())
        # "preempted" re-opens the queued phase on the router track — a
        # migration reads as: left its replica, waiting for placement
        self.tracer.request_event(fr.frid, "preempted", kind="migrate",
                                  tokens=len(fr.streamed))
        kw = fr.kw
        eos = kw["eos_token_id"]
        if fr.streamed and (len(fr.streamed) >= kw["max_new_tokens"]
                            or (eos is not None and eos in fr.streamed)):
            # completion edge: every token was streamed before the crash
            # but the retirement was never observed — nothing to continue,
            # synthesize the result from the router record
            req = Request(rid=-1, prompt=fr.prompt,
                          max_new_tokens=kw["max_new_tokens"],
                          temperature=kw["temperature"], top_p=kw["top_p"],
                          eos_token_id=eos, generated=list(fr.streamed),
                          submit_time=fr.submit_t)
            req.finish_time = self._clock()
            self._resolve(fr, req, req.finish_time)
            return
        if not self._place(fr):
            self._backoff(fr)
            self._waiting.append(fr)

    # -- driving -----------------------------------------------------------
    # the supervisor loop is single-threaded by design: all fleet state
    # (placement, retries, summaries) is owned by the driving thread —
    # owner=main turns any future thread reaching it into a lint error
    def run(self, max_rounds: int | None = None,  # graftlint: owner=main
            max_stall_rounds: int = 1000) -> dict:
        """Drive the fleet until every submitted request resolved; returns
        ``{frid: Request}``.  ``max_stall_rounds`` consecutive no-progress
        rounds raise :class:`EngineStalledError` (only reachable under a
        never-clearing injected fault window)."""
        stalled = 0
        rounds = 0
        while any(fr.result is None for fr in self._requests.values()):
            progressed = self.step()
            stalled = 0 if progressed else stalled + 1
            if stalled >= max_stall_rounds:
                raise EngineStalledError(
                    f"fleet made no progress for {stalled} consecutive "
                    f"rounds ({sum(fr.result is None for fr in self._requests.values())} "
                    f"unresolved, {len(self._waiting)} waiting)")
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        return self.results()

    def results(self) -> dict:
        return {frid: fr.result for frid, fr in self._requests.items()
                if fr.result is not None}

    # -- readouts ----------------------------------------------------------
    def stats(self) -> dict:
        q = self._h_recovery.percentiles()
        tq = self._h_kv_transfer.percentiles()
        handoffs = self._c_handoffs.value
        return {
            "replicas": len(self._replicas),
            "replicas_alive": len(self._alive()),
            "replicas_routable": sum(1 for rep in self._alive()
                                     if rep.routable),
            "replicas_retired": len(self._retired_stats),
            "failovers": self._c_failovers.value,
            "migrations": self._c_migrations.value,
            "rejections": self._c_rejections.value,
            "torn_snapshots": self._c_torn.value,
            "scale_ups": self._c_scale_up.value,
            "scale_downs": self._c_scale_down.value,
            "drain_migrations": self._c_drain_migr.value,
            "handoffs": handoffs,
            "handoff_fallbacks": self._c_handoff_fallbacks.value,
            "handoffs_pending": len(self._pending_handoffs),
            "kv_transfer": {
                "pages": self._c_kv_pages.value,
                "bytes": self._c_kv_bytes.value,
                "rank_local": self._c_kv_rank_local.value,
                "rank_local_hit_rate":
                    round(self._c_kv_rank_local.value / handoffs, 4)
                    if handoffs else None,
                "transfer_s": {
                    "count": self._h_kv_transfer.count,
                    "p50_ms": round(tq[50] * 1e3, 3),
                    "p95_ms": round(tq[95] * 1e3, 3),
                    "p99_ms": round(tq[99] * 1e3, 3),
                    "max_ms": round(self._h_kv_transfer.max * 1e3, 3)
                    if self._h_kv_transfer.count else 0.0},
            },
            "roles": {rep.name: rep.role for rep in self._replicas},
            "requests_submitted": self._c_submitted.value,
            "requests_resolved": self._c_resolved.value,
            "tokens_streamed": self.tokens_streamed,
            "replica_seconds": round(self.replica_seconds, 4),
            "waiting": len(self._waiting),
            "recovery": {"count": self._h_recovery.count,
                         "p50_ms": round(q[50] * 1e3, 3),
                         "p95_ms": round(q[95] * 1e3, 3),
                         "p99_ms": round(q[99] * 1e3, 3),
                         "max_ms": round(self._h_recovery.max * 1e3, 3)
                         if self._h_recovery.count else 0.0},
            "per_replica": {rep.name: (dict(rep.engine.stats(),
                                            routable=rep.routable,
                                            role=rep.role)
                                       if rep.alive else None)
                            for rep in self._replicas},
        }

    @staticmethod
    def _hit_rate(stats: dict) -> float | None:
        """One replica's lifetime prefix-cache hit rate: cached tokens
        over (cached + executed) prefill tokens; None before any
        prefill."""
        hit = stats.get("cached_prefix_tokens", 0)
        ex = stats.get("prefill_tokens_executed", 0)
        return round(hit / (hit + ex), 4) if hit + ex else None

    def fleet_hit_rate(self) -> dict:
        """Fleet-wide prefix-cache hit rate over the fleet's WHOLE
        service history — live replicas plus retired ones (a drained
        replica's hits must not vanish from the accounting the moment
        the autoscaler destroys it)."""
        hit = ex = 0
        per: dict[str, float | None] = {}
        for name, st in self._retired_stats:
            hit += st.get("cached_prefix_tokens", 0)
            ex += st.get("prefill_tokens_executed", 0)
            per[name] = self._hit_rate(st)
        for rep in self._alive():
            st = rep.engine.stats()
            hit += st.get("cached_prefix_tokens", 0)
            ex += st.get("prefill_tokens_executed", 0)
            per[rep.name] = self._hit_rate(st)
        return {
            "cached_prefix_tokens": hit,
            "prefill_tokens_executed": ex,
            "hit_rate": round(hit / (hit + ex), 4) if hit + ex else 0.0,
            "per_replica": per,
        }

    def stats_snapshot(self, ttft_deadline_s: float | None = None) -> dict:
        """The fleet-wide observability snapshot (ISSUE 12): the router
        :meth:`stats` plus the :class:`FleetTelemetry` aggregation over
        every live telemetry-bearing replica — replica histograms merged
        BUCKET-WISE into fleet quantiles (``merged``), gauges/series/
        counters side-by-side per replica (``per_replica_telemetry``).
        With ``ttft_deadline_s``, a fleet-wide SLO report read straight
        off the merged TTFT histogram rides along (``fleet_slo``).
        Since ISSUE 13 the snapshot also carries ``alerts`` — the
        aggregated health-sentinel view across replicas (empty components
        when no replica runs a sentinel)."""
        ft = FleetTelemetry.from_fleet(self)
        snap = ft.snapshot()
        out = dict(self.stats())
        out["replica_names"] = snap["replicas"]
        out["merged"] = snap["merged"]
        out["per_replica_telemetry"] = snap["per_replica"]
        out["alerts"] = self.alerts_report()
        # routing observability (ROADMAP item 5): per-replica hit rates +
        # the router's affinity-hit/fallback counters ride every snapshot
        out["cache"] = self.fleet_hit_rate()
        for rep in self._replicas:
            if rep.alive:
                pr = out["per_replica"].get(rep.name)
                if isinstance(pr, dict):
                    pr["cache_hit_rate"] = self._hit_rate(pr)
        out["router"] = self.router.stats()
        if ttft_deadline_s is not None:
            out["fleet_slo"] = ft.slo_report(ttft_deadline_s)
        return out

    # -- latency forensics + health sentinel (ISSUE 13) --------------------
    def _sentinels(self) -> dict:
        out: dict = {}
        for rep in self._replicas:
            if rep.alive and rep.engine is not None \
                    and rep.engine.telemetry is not None \
                    and rep.engine.telemetry.sentinel is not None:
                out[rep.name] = rep.engine.telemetry.sentinel
        return out

    def alerts_report(self) -> dict:
        """Aggregated health-sentinel view across live replicas (worst
        status wins, fire counts sum) — the failover artifact's
        ``alerts`` section and the frontend exporter's ``/alerts``
        source when the fleet is the backend."""
        from ..observability.health import aggregate_alerts
        return aggregate_alerts(self._sentinels())

    def slow_requests(self, k: int = 8) -> list:
        """Fleet-level tail forensics: the top-``k`` slowest captured
        requests across every live replica's TailRecorder, slowest
        first (flight-style outlier dumps with attribution + engine
        context)."""
        from ..observability.attribution import merge_tail_dumps
        tails = [(rep.name, rep.engine.telemetry.tail)
                 for rep in self._replicas
                 if rep.alive and rep.engine is not None
                 and rep.engine.telemetry is not None
                 and rep.engine.telemetry.tail is not None]
        return merge_tail_dumps(tails, k=k)

    def attribution_report(self, top_k: int = 5) -> dict:
        """Stitched critical-path attribution over every END-TO-END
        request the fleet resolved: each trace_id's residencies attribute
        on their replica's spans, inter-replica gaps classify as
        ``migration`` / ``snapshot_restore`` — crashed generations'
        tracers included, so a failover-migrated request still decomposes
        exactly (observability.attribution)."""
        from ..observability.attribution import stitched_attribution_report
        return stitched_attribution_report(self.trace_components(),
                                           top_k=top_k)

    def trace_components(self) -> list:
        """(name, Tracer) per stitched-trace component: the router track
        first, then crashed replica generations, then the live replicas
        (telemetry-bearing only — a tracer lives inside Telemetry)."""
        comps: list = [("router", self.tracer)]
        comps.extend(self._dead_tracers)
        for rep in self._replicas:
            if rep.alive and rep.engine is not None \
                    and rep.engine.telemetry is not None:
                comps.append((rep.name, rep.engine.telemetry.tracer))
        return comps

    def stitcher(self, frontend=None) -> TraceStitcher:
        """A :class:`TraceStitcher` over this fleet's components (plus an
        optional upstream front end's ``(name, tracer)`` first)."""
        st = TraceStitcher()
        if frontend is not None:
            st.add("frontend", frontend.tracer
                   if hasattr(frontend, "tracer") else frontend)
        for name, tracer in self.trace_components():
            st.add(name, tracer)
        return st

    def stitched_trace(self, frontend=None) -> dict:
        """ONE Perfetto view of every request across frontend/router/
        replica tracks, failovers included (crashed generations keep
        their own tracks; flow events follow each trace_id)."""
        return self.stitcher(frontend=frontend).to_chrome_trace()

    def slo_report(self, ttft_deadline_s: float,
                   window_s: float | None = None) -> dict:
        """Fleet-level SLO report (TTFT measured at the ROUTER — token
        observed leaving a replica — which is what a user would see)."""
        return slo_report(self._summaries, ttft_deadline_s,
                          window_s=window_s)

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()
