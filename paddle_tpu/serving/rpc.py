"""Length-prefixed loopback RPC for the cross-process fleet (ISSUE 17).

One frame = an 8-byte header (magic ``PWKR`` + big-endian body length)
followed by a UTF-8 JSON body.  Requests are ``{"m": method, "k": key,
"p": params}``; replies are ``{"ok": true, "r": result}`` or ``{"ok":
false, "etype": <exception class name>, "error": <message>}``.

The client owns the reliability story so a worker stays dumb:

* **deadline-per-call** — ``call()`` takes an absolute time budget; each
  attempt gets ``min(remaining, attempt_timeout)`` as its socket timeout
  and the loop raises :class:`RpcTimeout` when the budget is spent.
* **exponential backoff with jitter** — failed attempts sleep
  ``backoff_base * 2**attempt`` capped at ``backoff_cap``, scaled by a
  seeded jitter factor, so a wedged worker is not hammered in lockstep.
* **idempotent retry keys** — every logical call mints one key reused
  verbatim across retries; the server caches the reply per key (bounded
  LRU) and a duplicate key returns the cached reply *without re-invoking
  the handler*.  A lost response frame therefore never double-submits a
  request or double-streams a token.  A duplicate that races the original
  (still in flight) waits on a per-key event and receives the same reply.

Wire-level fault points (consulted client-side — fault plans are
in-process, and the supervisor is where chaos drills run; see the catalog
in :mod:`paddle_tpu.resilience.faults`):

* ``rpc.drop_frame``     (trigger) — the request frame never reaches the
  wire; the client still waits on the reply, burning the attempt timeout
  exactly like a frame lost by the kernel.
* ``rpc.delay_frame``    (trigger) — the frame is sent ``fault_delay_s``
  late (reordering / congestion).
* ``rpc.truncate_frame`` (trigger) — half the body is sent, then the
  connection dies; the server must drop the torn frame without invoking
  the handler.
* ``rpc.half_open``      (trigger) — the frame is fully sent but the
  client's side dies before the reply; the handler runs exactly once and
  the retry must be served from the idempotency cache.
"""
from __future__ import annotations

import itertools
import json
import os
import socket
import struct
import threading
import time
from collections import OrderedDict

import numpy as np

from ..resilience.faults import fault_point

__all__ = ["RpcError", "RpcTimeout", "RpcRemoteError", "RpcClient",
           "RpcServer"]

_MAGIC = 0x50574B52          # "PWKR"
_HEADER = struct.Struct(">II")
_MAX_FRAME = 64 * 1024 * 1024


class RpcError(RuntimeError):
    """Base class for RPC failures."""


class RpcTimeout(RpcError):
    """The per-call deadline elapsed (or retries exhausted) without a
    reply.  The call may or may not have executed on the server — callers
    that need certainty re-issue with the same semantics (submit/adopt are
    keyed, so a later health/poll reconciles)."""


class RpcRemoteError(RpcError):
    """The handler raised on the worker.  ``etype`` carries the remote
    exception class name so supervisors can map admission/capacity errors
    back onto their local types."""

    def __init__(self, etype: str, message: str):
        super().__init__(f"{etype}: {message}")
        self.etype = etype
        self.emsg = message


class _WireError(Exception):
    """Internal: a retryable transport-level failure."""


def _send_frame(sock: socket.socket, obj: dict) -> None:
    body = json.dumps(obj, separators=(",", ":")).encode("utf-8")
    sock.sendall(_HEADER.pack(_MAGIC, len(body)) + body)


def _recv_exact(sock: socket.socket, n: int) -> bytes:
    buf = bytearray()
    while len(buf) < n:
        chunk = sock.recv(n - len(buf))
        if not chunk:
            raise _WireError(f"connection closed after {len(buf)}/{n} bytes")
        buf += chunk
    return bytes(buf)


def _recv_frame(sock: socket.socket) -> dict:
    magic, length = _HEADER.unpack(_recv_exact(sock, _HEADER.size))
    if magic != _MAGIC:
        raise _WireError(f"bad frame magic 0x{magic:08x}")
    if length > _MAX_FRAME:
        raise _WireError(f"frame length {length} exceeds cap {_MAX_FRAME}")
    return json.loads(_recv_exact(sock, length).decode("utf-8"))


class RpcClient:
    """One logical connection to one worker, with deadlines, backoff,
    and idempotent retries.  Not thread-safe; the supervisor serialises
    calls per worker (one client per worker)."""

    def __init__(self, address, *, attempt_timeout: float = 2.0,
                 call_timeout: float = 10.0, connect_timeout: float = 1.0,
                 max_retries: int = 8, backoff_base: float = 0.02,
                 backoff_cap: float = 0.5, jitter: float = 0.5,
                 fault_delay_s: float = 0.05, seed: int = 0):
        self.address = tuple(address)
        self.attempt_timeout = float(attempt_timeout)
        self.call_timeout = float(call_timeout)
        self.connect_timeout = float(connect_timeout)
        self.max_retries = int(max_retries)
        self.backoff_base = float(backoff_base)
        self.backoff_cap = float(backoff_cap)
        self.jitter = float(jitter)
        self.fault_delay_s = float(fault_delay_s)
        self._rng = np.random.default_rng(seed)
        self._cid = f"{os.getpid():x}.{id(self) & 0xFFFFFF:x}"
        self._seq = itertools.count()
        self._sock: socket.socket | None = None
        self.stats = {"calls": 0, "retries": 0, "reconnects": 0,
                      "timeouts": 0, "backoff_s": 0.0}

    # -- transport ---------------------------------------------------------
    def _connect(self, timeout: float) -> socket.socket:
        if self._sock is None:
            s = socket.create_connection(self.address,
                                         timeout=max(timeout,
                                                     self.connect_timeout))
            s.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            self._sock = s
            self.stats["reconnects"] += 1
        self._sock.settimeout(timeout)
        return self._sock

    def close(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def _attempt(self, frame: dict, timeout: float, attempt: int) -> dict:
        method = frame["m"]
        sock = self._connect(timeout)
        dropped = fault_point("rpc.drop_frame",
                              method=method, attempt=attempt) is not None
        if fault_point("rpc.delay_frame",
                       method=method, attempt=attempt) is not None:
            time.sleep(self.fault_delay_s)
        if not dropped:
            if fault_point("rpc.truncate_frame",
                           method=method, attempt=attempt) is not None:
                body = json.dumps(frame).encode("utf-8")
                sock.sendall(_HEADER.pack(_MAGIC, len(body))
                             + body[:max(1, len(body) // 2)])
                self.close()
                raise _WireError("frame truncated by fault plan")
            _send_frame(sock, frame)
            if fault_point("rpc.half_open",
                           method=method, attempt=attempt) is not None:
                # Request fully delivered; our side dies before the reply.
                self.close()
                raise _WireError("half-open socket (fault plan)")
        # A dropped frame still burns the attempt timeout waiting for a
        # reply that can never come — the honest shape of packet loss.
        return _recv_frame(sock)

    # -- public API --------------------------------------------------------
    def call(self, method: str, *, deadline_s: float | None = None,
             **params):
        """Invoke ``method`` on the worker.  ``deadline_s`` is this call's
        total wall-clock budget (default ``call_timeout``)."""
        deadline = time.monotonic() + (self.call_timeout
                                       if deadline_s is None
                                       else float(deadline_s))
        frame = {"m": method, "k": f"{self._cid}:{next(self._seq)}",
                 "p": params}
        self.stats["calls"] += 1
        attempt = 0
        last_err: Exception | None = None
        while True:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or attempt > self.max_retries:
                self.stats["timeouts"] += 1
                raise RpcTimeout(
                    f"rpc {method!r} to {self.address} exceeded deadline "
                    f"after {attempt} attempt(s); last error: {last_err!r}")
            try:
                reply = self._attempt(
                    frame, min(remaining, self.attempt_timeout), attempt)
            except (_WireError, OSError) as e:   # socket.timeout is OSError
                self.close()
                last_err = e
                attempt += 1
                self.stats["retries"] += 1
                pause = min(self.backoff_cap,
                            self.backoff_base * (2.0 ** (attempt - 1)))
                pause *= 1.0 + self.jitter * (self._rng.random() - 0.5)
                pause = max(0.0, min(pause, deadline - time.monotonic()))
                self.stats["backoff_s"] += pause
                if pause:
                    time.sleep(pause)
                continue
            if reply.get("ok"):
                return reply.get("r")
            raise RpcRemoteError(reply.get("etype", "RuntimeError"),
                                 reply.get("error", "remote failure"))


class RpcServer:
    """Threaded accept loop with a bounded idempotency reply cache.

    ``handler(method, params) -> jsonable`` runs at most once per retry
    key; exceptions become error replies (cached too — a failed submit
    retried on the same key fails the same way, it does not re-run)."""

    IDEMPOTENCY_CACHE = 1024

    def __init__(self, handler, host: str = "127.0.0.1", port: int = 0):
        self._handler = handler
        self._listener = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._listener.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._listener.bind((host, port))
        self._listener.listen(16)
        self._listener.settimeout(0.2)
        self.address = self._listener.getsockname()
        self.port = self.address[1]
        self._stop = threading.Event()
        self._threads: list[threading.Thread] = []
        self._ilock = threading.Lock()
        self._done: OrderedDict[str, dict] = OrderedDict()
        self._inflight: dict[str, threading.Event] = {}
        # stats lock: every rpc-conn thread bumps these counters and the
        # supervisor reads them live — unlocked `+=` is a lost-update
        # race (THREAD001); `_ilock` is not reused so a slow idempotency
        # sweep never serializes the per-frame accounting
        self._slock = threading.Lock()
        self.stats = {"frames": 0, "handler_invocations": 0,
                      "dup_hits": 0, "errors": 0, "torn_frames": 0}

        self._accept_thread: threading.Thread | None = None

    def _bump(self, key: str, by: float = 1) -> None:
        with self._slock:
            self.stats[key] += by

    def start(self) -> "RpcServer":
        t = threading.Thread(target=self._accept_loop,
                             name="rpc-accept", daemon=True)
        t.start()
        self._accept_thread = t
        return self

    def stop(self) -> None:
        self._stop.set()
        try:
            self._listener.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=2.0)
        for t in self._threads:
            t.join(timeout=1.0)

    # -- internals ---------------------------------------------------------
    # the accept thread owns the conn-thread registry; stop() only
    # reads _threads after _stop is set and the accept thread joined
    def _accept_loop(self) -> None:  # graftlint: owner=worker
        while not self._stop.is_set():
            try:
                conn, _ = self._listener.accept()
            except socket.timeout:
                continue
            except OSError:
                break
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            t = threading.Thread(target=self._serve_conn, args=(conn,),
                                 name="rpc-conn", daemon=True)
            t.start()
            self._threads.append(t)
            self._threads = [th for th in self._threads if th.is_alive()]

    def _serve_conn(self, conn: socket.socket) -> None:
        with conn:
            conn.settimeout(0.5)
            while not self._stop.is_set():
                try:
                    frame = _recv_frame(conn)
                except socket.timeout:
                    continue
                except (_WireError, OSError, ValueError):
                    self._bump("torn_frames")
                    return
                self._bump("frames")
                reply = self._dispatch(frame)
                try:
                    _send_frame(conn, reply)
                except OSError:
                    # Half-open peer: the reply is lost but cached; the
                    # retry on the same key will pick it up.
                    return

    def _dispatch(self, frame: dict) -> dict:
        key = frame.get("k")
        waiter = None
        with self._ilock:
            if key in self._done:
                self._bump("dup_hits")
                return self._done[key]
            if key in self._inflight:
                waiter = self._inflight[key]
            else:
                self._inflight[key] = threading.Event()
        if waiter is not None:
            self._bump("dup_hits")
            waiter.wait(timeout=30.0)
            with self._ilock:
                reply = self._done.get(key)
            return reply if reply is not None else {
                "ok": False, "etype": "RpcTimeout",
                "error": "duplicate waited but original never finished"}
        try:
            self._bump("handler_invocations")
            reply = {"ok": True,
                     "r": self._handler(frame.get("m"), frame.get("p") or {})}
        except BaseException as e:  # noqa: BLE001 — wire boundary
            self._bump("errors")
            reply = {"ok": False, "etype": type(e).__name__, "error": str(e)}
        with self._ilock:
            self._done[key] = reply
            while len(self._done) > self.IDEMPOTENCY_CACHE:
                self._done.popitem(last=False)
            ev = self._inflight.pop(key, None)
        if ev is not None:
            ev.set()
        return reply
