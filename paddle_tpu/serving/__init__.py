"""Serving plane (ROADMAP item 4): durable engine snapshots, the
replica-fleet router, and the async front end + traffic harness.

* :class:`EngineSnapshotManager` — crash-consistent
  ``ServingEngine.snapshot()`` persistence through the checkpoint commit
  protocol (staged tmp + fsync + SHA-256 manifest + atomic rename), with
  keep-last-N rotation and torn-snapshot-skipping discovery.
* :class:`ReplicaFleet` — N engine replicas behind one ``submit()``:
  least-loaded routing, health watchdog (crash + wedge detection),
  snapshot-restore / re-prefill failover with zero request loss and
  greedy-bit-exact outputs, fleet-wide degradation ladder
  (route -> queue -> reject), router-authoritative token streaming
  (``submit(on_token=...)`` survives failover without double emission).
* :class:`AsyncFrontend` — the asyncio transport (ISSUE 11): ``await
  submit()`` returns a bounded async token stream with per-client
  backpressure; client disconnect cancels the request mid-decode; the
  engine steps on one worker thread.  :class:`AdmissionController` /
  :class:`TTFTPredictor` add SLO-aware admission — reject on PREDICTED
  TTFT (typed :class:`SLORejected`) instead of raw queue depth, with the
  prediction error itself tracked (``frontend.ttft_pred_err_s``).
* :mod:`.traffic` — seeded, replayable scenario generators (Poisson
  bursty + diurnal arrivals, shared-prefix user fleets, mixed
  greedy/sampled/long-context, streaming-abandon clients) plus engine,
  fleet, and virtual-clock replays reporting goodput-under-SLO.
* :mod:`.quant` — the quantized serving plane (ROADMAP item 2):
  the one int8/fp8 KV codec (per-page, per-head, per-token-row absmax
  scales — write-order independent, so the quantized engine keeps every
  self-exactness invariant), per-channel int8 serving weights, page-byte
  accounting for the memory observatory, and :func:`parity_report` —
  greedy exact-match + teacher-forced logit drift vs the f32 engine on
  the standard parity scenarios (`bench.py --trace quant` gates it).
* :mod:`.routing` + :mod:`.autoscale` — the elastic control plane
  (ROADMAP item 5): pluggable placement strategies
  (:class:`LeastLoadedRouter`, :class:`PrefixAffinityRouter` — route
  shared-prefix users to the replica already holding their KV via the
  cache's own chained block-hash, under a bounded-imbalance guard) and
  :class:`ElasticFleet` — sentinel-driven replica autoscaling
  (:class:`AutoscalePolicy` GROW on sustained queue growth / SLO burn,
  SHRINK on sustained idle) with zero-loss, greedy-bit-exact drain
  through the live-migration path.
* Disaggregated prefill/decode (ISSUE 19): ``ReplicaFleet(roles=
  ["prefill", "decode", ...])`` splits the fleet into prefill replicas
  (dense/chunked prefill + first token on their own TP submesh) and
  decode replicas that receive the head-sharded KV pages via
  ``ServingEngine.export_kv``/``import_kv`` — rank-local at equal ``mp``
  degree, scale planes included, with re-prefill fallback on any
  geometry mismatch (:class:`~paddle_tpu.inference.paged.KVHandoffError`)
  and the transfer itself visible as the ``kv_transfer`` attribution
  segment plus fleet counters/histograms.  ``ElasticFleet(role_policies=
  {"prefill": ..., "decode": ...})`` scales each role independently.
* :mod:`.rpc` + :mod:`.worker` + :mod:`.procfleet` — the cross-process
  fleet (ISSUE 17): replicas as real worker processes behind a
  length-prefixed loopback wire (deadline-per-call timeouts,
  exponential backoff with jitter, idempotent retry keys), with
  :class:`ProcessFleet` supervising spawn/reap/failover under real
  ``SIGKILL``/``SIGSTOP`` — same zero-loss, greedy-bit-exact recovery
  bar, now across an actual process boundary.
"""
from ..inference.paged import KVHandoffError
from .autoscale import AutoscaleDecision, AutoscalePolicy, ElasticFleet
from .quant import (dequantize_kv, kv_spec, page_bytes, parity_report,
                    parity_scenarios, quantize_kv, quantize_params)
from .fleet import FleetFailedError, ReplicaFleet
from .frontend import (AdmissionController, AdmissionView, AsyncFrontend,
                       AsyncStream, SLORejected, TTFTPredictor,
                       admission_view)
from .routing import (LeastLoadedRouter, PrefixAffinityRouter, Router,
                      RoutingDecision)
from .procfleet import ProcessFleet, WorkerDiedError
from .rpc import RpcClient, RpcError, RpcRemoteError, RpcServer, RpcTimeout
from .snapshot import EngineSnapshotManager, load_engine_snapshot
from .traffic import (ClientRequest, Scenario, VirtualClock,
                      goodput_report, make_scenario, replay_engine,
                      replay_fleet, replay_sim)

__all__ = ["ReplicaFleet", "FleetFailedError", "EngineSnapshotManager",
           "load_engine_snapshot", "AsyncFrontend", "AsyncStream",
           "SLORejected", "AdmissionController", "AdmissionView",
           "TTFTPredictor", "admission_view", "ClientRequest", "Scenario",
           "make_scenario", "replay_engine", "replay_fleet", "replay_sim",
           "goodput_report", "VirtualClock", "Router", "RoutingDecision",
           "LeastLoadedRouter", "PrefixAffinityRouter", "AutoscalePolicy",
           "AutoscaleDecision", "ElasticFleet", "quantize_kv",
           "dequantize_kv", "kv_spec", "page_bytes", "quantize_params",
           "parity_report", "parity_scenarios", "ProcessFleet",
           "WorkerDiedError", "RpcClient", "RpcServer", "RpcError",
           "RpcTimeout", "RpcRemoteError", "KVHandoffError"]
