"""Serving fleet layer (ROADMAP item 4): durable engine snapshots +
replica-fleet router with live request migration.

* :class:`EngineSnapshotManager` — crash-consistent
  ``ServingEngine.snapshot()`` persistence through the checkpoint commit
  protocol (staged tmp + fsync + SHA-256 manifest + atomic rename), with
  keep-last-N rotation and torn-snapshot-skipping discovery.
* :class:`ReplicaFleet` — N engine replicas behind one ``submit()``:
  least-loaded routing, health watchdog (crash + wedge detection),
  snapshot-restore / re-prefill failover with zero request loss and
  greedy-bit-exact outputs, fleet-wide degradation ladder
  (route -> queue -> reject).
"""
from .fleet import FleetFailedError, ReplicaFleet
from .snapshot import EngineSnapshotManager, load_engine_snapshot

__all__ = ["ReplicaFleet", "FleetFailedError", "EngineSnapshotManager",
           "load_engine_snapshot"]
