"""Serving plane (ROADMAP item 4): durable engine snapshots, the
replica-fleet router, and the async front end + traffic harness.

* :class:`EngineSnapshotManager` — crash-consistent
  ``ServingEngine.snapshot()`` persistence through the checkpoint commit
  protocol (staged tmp + fsync + SHA-256 manifest + atomic rename), with
  keep-last-N rotation and torn-snapshot-skipping discovery.
* :class:`ReplicaFleet` — N engine replicas behind one ``submit()``:
  least-loaded routing, health watchdog (crash + wedge detection),
  snapshot-restore / re-prefill failover with zero request loss and
  greedy-bit-exact outputs, fleet-wide degradation ladder
  (route -> queue -> reject), router-authoritative token streaming
  (``submit(on_token=...)`` survives failover without double emission).
* :class:`AsyncFrontend` — the asyncio transport (ISSUE 11): ``await
  submit()`` returns a bounded async token stream with per-client
  backpressure; client disconnect cancels the request mid-decode; the
  engine steps on one worker thread.  :class:`AdmissionController` /
  :class:`TTFTPredictor` add SLO-aware admission — reject on PREDICTED
  TTFT (typed :class:`SLORejected`) instead of raw queue depth, with the
  prediction error itself tracked (``frontend.ttft_pred_err_s``).
* :mod:`.traffic` — seeded, replayable scenario generators (Poisson
  bursty + diurnal arrivals, shared-prefix user fleets, mixed
  greedy/sampled/long-context, streaming-abandon clients) plus engine
  and virtual-clock replays reporting goodput-under-SLO.
"""
from .fleet import FleetFailedError, ReplicaFleet
from .frontend import (AdmissionController, AdmissionView, AsyncFrontend,
                       AsyncStream, SLORejected, TTFTPredictor,
                       admission_view)
from .snapshot import EngineSnapshotManager, load_engine_snapshot
from .traffic import (ClientRequest, Scenario, goodput_report,
                      make_scenario, replay_engine, replay_sim)

__all__ = ["ReplicaFleet", "FleetFailedError", "EngineSnapshotManager",
           "load_engine_snapshot", "AsyncFrontend", "AsyncStream",
           "SLORejected", "AdmissionController", "AdmissionView",
           "TTFTPredictor", "admission_view", "ClientRequest", "Scenario",
           "make_scenario", "replay_engine", "replay_sim",
           "goodput_report"]
