"""Async serving front end: asyncio transport + SLO-aware admission.

ROADMAP item 4's production transport (ISSUE 11 tentpole).  PR 10 seeded
streaming (``submit(on_token=...)`` / ``Request.stream()``) but the hooks
are synchronous callbacks inside the engine thread: no backpressure, no
cancel-on-disconnect, and admission was raw queue depth.  This module is
the missing layer between "an engine that can stream" and "thousands of
concurrent clients":

  * :class:`AsyncFrontend` — an asyncio transport wrapping a
    :class:`~paddle_tpu.inference.paged.ServingEngine` or a
    :class:`~paddle_tpu.serving.fleet.ReplicaFleet`.  The engine steps on
    ONE worker thread (engines are deliberately not thread-safe); every
    token crosses into the event loop via ``call_soon_threadsafe`` in
    emission order.  ``await submit()`` returns an :class:`AsyncStream` —
    an async token iterator backed by a BOUNDED per-request
    ``asyncio.Queue``.  A slow client fills its queue and stalls only its
    own drain fan-out task (the engine-side feed buffers host ints and
    never blocks): backpressure is per-client, the engine never waits on
    a consumer.  Client disconnect — task cancellation inside the
    iterator, ``async with`` exit, an explicit ``abandon()``, or the
    stream being garbage-collected — propagates to ``engine.cancel(rid)``
    on the worker thread, so a mid-decode disconnect frees its KV pages
    instead of decoding to an audience of zero.
  * :class:`AdmissionController` + :class:`TTFTPredictor` — SLO-aware
    admission.  The predictor turns the live PR 6/7 telemetry (decode
    phase histograms + prefill-token accounting) plus the engine's
    host-visible schedulable state (free slots, per-slot remaining
    budgets, queued prefill backlog — an :class:`AdmissionView`) into a
    PREDICTED TTFT via a tiny earliest-free-slot simulation; the
    controller rejects (typed :class:`SLORejected`, an
    ``AdmissionRejected`` subclass) when the prediction exceeds the
    request's deadline.  Prediction error is itself a tracked metric —
    ``frontend.ttft_pred_err_s`` — because an admission controller whose
    predictions silently rot is worse than a depth cap.  The depth-cap
    policy (``policy="depth"``) is kept as the A/B baseline
    ``bench.py --trace frontend`` gates against.

Everything here is pure host-side asyncio/numpy: no jitted code, no new
executables, zero effect on the engine's PERF.md §12 variant table.
"""
from __future__ import annotations

import asyncio
import heapq
import threading
import weakref
from collections import deque
from dataclasses import dataclass, field

import numpy as np

from ..inference.paged import AdmissionRejected, ServingEngine
from ..observability.distributed import new_trace_id
from ..observability.metrics import MetricsRegistry
from ..observability.tracing import Tracer

__all__ = ["AsyncFrontend", "AsyncStream", "SLORejected", "AdmissionView",
           "TTFTPredictor", "AdmissionController", "admission_view"]


class SLORejected(AdmissionRejected):
    """Admission rejected because the PREDICTED TTFT exceeds the request's
    deadline — the SLO-aware analog of the queue-full
    ``AdmissionRejected`` (and a subclass of it, so existing backpressure
    handling catches both)."""


# --------------------------------------------------------------------------
# Predicted-TTFT admission
# --------------------------------------------------------------------------
@dataclass
class AdmissionView:
    """A host-only snapshot of everything the TTFT predictor needs —
    built from a live engine (:func:`admission_view`), an aggregated
    fleet, or a simulator (:func:`~paddle_tpu.serving.traffic.replay_sim`).

    ``active`` rows are (prefill_tokens_left, decode_tokens_left) per
    busy slot; ``queued`` rows are (prefill_tokens, max_new_tokens) in
    queue order.  ``step_s`` is the measured wall cost of one decode
    dispatch (``decode_horizon`` tokens per live slot)."""
    free_slots: int
    active: list = field(default_factory=list)
    queued: list = field(default_factory=list)
    prefill_rate_tps: float = 2000.0
    step_s: float = 0.02
    decode_horizon: int = 8

    @property
    def queue_depth(self) -> int:
        return len(self.queued)


def _hist(registry, name: str):
    """A registry histogram ONLY if it already exists (reading rates must
    not register phantom metrics)."""
    if registry is not None and name in registry:
        return registry.histogram(name)
    return None


def admission_view(engine: ServingEngine, *,
                   default_prefill_rate_tps: float = 2000.0,
                   default_step_s: float = 0.02,
                   min_samples: int = 3) -> AdmissionView:
    """Build an :class:`AdmissionView` from a live engine.

    Rates come from the PR 6/7 telemetry when the engine carries one with
    enough samples — prefill tokens/s from the executed-prefill counter
    over the ``prefill_dense``/``prefill_chunk`` phase totals, decode
    step seconds from the ``engine.step_host_s`` histogram mean — and
    fall back to the supplied priors on a cold engine.  Prediction error
    against reality is tracked either way
    (``frontend.ttft_pred_err_s``)."""
    prefill_rate = default_prefill_rate_tps
    step_s = default_step_s
    tel = engine.telemetry
    if tel is not None:
        r = tel.registry
        pf_s = 0.0
        pf_n = 0
        for name in ("engine.phase.prefill_dense_s",
                     "engine.phase.prefill_chunk_s"):
            h = _hist(r, name)
            if h is not None:
                pf_s += h.total
                pf_n += h.count
        # windowed tokens over windowed seconds — both reset together by
        # Telemetry.reset_window(); the engine's lifetime prefill_tokens
        # counter over a freshly reset phase histogram would inflate the
        # rate unboundedly
        ht = _hist(r, "engine.prefill_tokens_per_dispatch")
        pf_tokens = ht.total if ht is not None else 0.0
        if pf_n >= min_samples and pf_s > 0.0 and pf_tokens > 0.0:
            prefill_rate = pf_tokens / pf_s
        hs = _hist(r, "engine.step_host_s")
        if hs is not None and hs.count >= min_samples:
            step_s = hs.mean
    active = []
    for s, slot in enumerate(engine._slots):
        if slot is None:
            continue
        if slot.prefill_pos is not None:
            pf_left = len(slot.ctx) - slot.prefill_pos
            dec_left = slot.req.max_new_tokens - len(slot.req.generated)
        else:
            pf_left = 0
            dec_left = max(1, slot.req.max_new_tokens
                           - len(slot.req.generated))
        active.append((int(pf_left), int(dec_left)))
    queued = [(len(r_.prompt) + max(0, len(r_.generated) - 1),
               max(1, r_.max_new_tokens - len(r_.generated)))
              for r_ in engine._queue]
    return AdmissionView(
        free_slots=engine.num_slots - len(active), active=active,
        queued=queued, prefill_rate_tps=float(prefill_rate),
        step_s=float(step_s), decode_horizon=engine.decode_horizon)


class TTFTPredictor:
    """Predict a new request's TTFT from an :class:`AdmissionView` with a
    tiny earliest-free-slot (FIFO, S-server) simulation:

      * each busy slot frees after its remaining prefill + decode work
        (decode at ``step_s / decode_horizon`` seconds per token — the
        whole batch shares one dispatch, so per-slot token cost is the
        step cost, not the step cost times the batch);
      * queued requests ahead are granted slots earliest-free-first and
        occupy them for their own prefill + full budget;
      * the new request's TTFT = the wait for the slot it would get,
        plus its own prefill (the fused prefill+sample emits the first
        token at prefill end).

    Deliberately ignores the prefix cache (a hit only makes TTFT better
    — predictions stay conservative) and chunked-prefill interleaving.
    The point is not a perfect model: the controller tracks
    ``frontend.ttft_pred_err_s`` precisely so the error is a measured,
    gateable quantity instead of a hidden assumption."""

    def predict(self, view: AdmissionView, prompt_tokens: int) -> float:
        tpt = view.step_s / max(1, view.decode_horizon)
        inv = 1.0 / max(view.prefill_rate_tps, 1e-9)
        free = [0.0] * max(0, view.free_slots)
        busy = [pf * inv + dec * tpt for pf, dec in view.active]
        heap = free + busy
        if not heap:
            heap = [0.0]
        heapq.heapify(heap)
        for pf, mn in view.queued:
            t = heapq.heappop(heap)
            heapq.heappush(heap, t + pf * inv + mn * tpt)
        t_admit = heap[0] if heap else 0.0
        return float(t_admit + prompt_tokens * inv)


class AdmissionController:
    """Admission policy front door: ``policy`` is

      * ``"predictive"`` — reject (:class:`SLORejected`) when the
        predicted TTFT exceeds the request's ``slo_ttft_s`` deadline
        times ``margin``; otherwise admit (counted ``admitted`` when a
        slot is free and nothing queues ahead, ``queued`` otherwise);
      * ``"depth"`` — the baseline: reject (``AdmissionRejected``) when
        the queue is ``max_queue_depth`` deep, regardless of any SLO;
      * ``"always"`` — admit everything (the bit-equality harness runs
        here: admission must not perturb outputs).

    Decisions, predictions, and prediction error land in an owned (or
    injected) :class:`~paddle_tpu.observability.metrics.MetricsRegistry`:
    counters ``frontend.offered`` / ``admitted`` / ``queued`` /
    ``rejected_slo`` / ``rejected_depth`` (admitted + queued + rejections
    == offered — the fraction-sum the obs gate checks), histograms
    ``frontend.ttft_pred_s`` and ``frontend.ttft_pred_err_s`` (|predicted
    - actual| at first token)."""

    POLICIES = ("predictive", "depth", "always")

    def __init__(self, policy: str = "predictive", *,
                 slo_ttft_s: float | None = None,
                 max_queue_depth: int | None = None,
                 margin: float = 1.0,
                 predictor: TTFTPredictor | None = None,
                 default_prefill_rate_tps: float = 2000.0,
                 default_step_s: float = 0.02,
                 metrics: MetricsRegistry | None = None):
        if policy not in self.POLICIES:
            raise ValueError(f"unknown admission policy {policy!r} "
                             f"(expected one of {self.POLICIES})")
        self.policy = policy
        self.slo_ttft_s = slo_ttft_s
        self.max_queue_depth = max_queue_depth
        self.margin = float(margin)
        self.predictor = predictor or TTFTPredictor()
        self.default_prefill_rate_tps = float(default_prefill_rate_tps)
        self.default_step_s = float(default_step_s)
        self.metrics = metrics or MetricsRegistry()
        r = self.metrics
        self._c_offered = r.counter("frontend.offered")
        self._c_admitted = r.counter("frontend.admitted")
        self._c_queued = r.counter("frontend.queued")
        self._c_rej_slo = r.counter("frontend.rejected_slo")
        self._c_rej_depth = r.counter("frontend.rejected_depth")
        self._h_pred = r.histogram("frontend.ttft_pred_s")
        self._h_err = r.histogram("frontend.ttft_pred_err_s")
        self._pending: dict[int, float] = {}      # rid -> predicted ttft

    # -- decision ----------------------------------------------------------
    def decide(self, view: AdmissionView, prompt_tokens: int,
               slo_ttft_s: float | None = None) -> float:
        """Count the offered request, predict its TTFT, and either return
        the prediction (admitted/queued) or raise the typed rejection."""
        self._c_offered.inc()
        pred = self.predictor.predict(view, prompt_tokens)
        self._h_pred.observe(pred)
        if self.policy == "depth":
            depth = self.max_queue_depth
            if depth is not None and view.queue_depth >= depth:
                self._c_rej_depth.inc()
                raise AdmissionRejected(
                    f"admission queue full ({view.queue_depth}/{depth} "
                    f"deep) — depth-based backpressure, retry later")
        elif self.policy == "predictive":
            slo = slo_ttft_s if slo_ttft_s is not None else self.slo_ttft_s
            if slo is not None and pred > slo * self.margin:
                self._c_rej_slo.inc()
                raise SLORejected(
                    f"predicted TTFT {pred * 1e3:.1f} ms exceeds the "
                    f"{slo * 1e3:.1f} ms deadline "
                    f"({view.queue_depth} queued, {view.free_slots} free "
                    f"slots) — SLO-aware rejection, retry later or relax "
                    f"the deadline")
        if view.free_slots > 0 and view.queue_depth == 0:
            self._c_admitted.inc()
        else:
            self._c_queued.inc()
        return pred

    def submit(self, engine, prompt, *, slo_ttft_s: float | None = None,
               **kw) -> int:
        """Decide + submit to a live engine (the synchronous replay entry;
        :class:`AsyncFrontend` routes through :meth:`decide` on its
        worker thread).  ``**kw`` passes through to ``engine.submit``."""
        view = admission_view(
            engine, default_prefill_rate_tps=self.default_prefill_rate_tps,
            default_step_s=self.default_step_s)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        pred = self.decide(view, len(prompt), slo_ttft_s=slo_ttft_s)
        rid = engine.submit(prompt, **kw)
        self._pending[rid] = pred
        return rid

    # -- outcome tracking --------------------------------------------------
    def track(self, rid: int, predicted_ttft_s: float):
        self._pending[rid] = float(predicted_ttft_s)

    def resolve(self, rid: int, req) -> None:
        """Fold a retired/abandoned request's actual TTFT into the
        prediction-error histogram (no-op for untracked rids or requests
        that never produced a first token)."""
        pred = self._pending.pop(rid, None)
        if pred is None or req is None:
            return
        ttft = getattr(req, "ttft", 0.0)
        if ttft:
            self._h_err.observe(abs(ttft - pred))

    def resolve_sim(self, predicted: float, actual: float) -> None:
        """Simulator-side outcome (no Request object exists there)."""
        self._h_err.observe(abs(actual - predicted))

    def report(self) -> dict:
        """Admission counters + fraction decomposition + prediction-error
        stats — the artifact section ``perf/check_obs.py`` schema-gates
        (admit/queue/reject fractions must sum to ~1 over offered)."""
        offered = self._c_offered.value
        parts = {
            "admitted": self._c_admitted.value,
            "queued": self._c_queued.value,
            "rejected_slo": self._c_rej_slo.value,
            "rejected_depth": self._c_rej_depth.value,
        }
        fr = {f"{k}_frac": round(v / offered, 4) if offered else 0.0
              for k, v in parts.items()}
        err = self._h_err
        q = err.percentiles()
        return {
            "policy": self.policy,
            "slo_ttft_s": self.slo_ttft_s,
            "max_queue_depth": self.max_queue_depth,
            "offered": offered,
            **parts,
            **fr,
            "fraction_sum": round(sum(fr.values()), 4),
            "ttft_pred_err_s": {
                "count": err.count,
                "mean_s": round(err.mean, 6),
                "p50_s": round(q[50], 6),
                "p95_s": round(q[95], 6),
                "max_s": round(err.max, 6) if err.count else 0.0,
            },
            "ttft_pred_s": {
                "count": self._h_pred.count,
                "mean_s": round(self._h_pred.mean, 6),
                "p95_s": round(self._h_pred.percentiles()[95], 6),
            },
        }


# --------------------------------------------------------------------------
# Transport adapters (one engine, one fleet — same worker-side surface)
# --------------------------------------------------------------------------
class _EngineAdapter:
    """Worker-side view of a single ServingEngine."""

    def __init__(self, engine: ServingEngine):
        self.engine = engine

    def has_work(self) -> bool:
        e = self.engine
        return bool(e.num_active or e._queue or e.inflight_depth)

    def step(self) -> bool:
        return self.engine.step()

    def view(self, controller: AdmissionController) -> AdmissionView:
        return admission_view(
            self.engine,
            default_prefill_rate_tps=controller.default_prefill_rate_tps,
            default_step_s=controller.default_step_s)

    def submit(self, prompt, **kw) -> int:
        return self.engine.submit(prompt, **kw)

    def cancel(self, rid: int) -> bool:
        return self.engine.cancel(rid)

    def result(self, rid: int):
        req = self.engine._finished.get(rid)
        return req if req is not None and req.finish_time else None


class _FleetAdapter:
    """Worker-side view of a ReplicaFleet: admission aggregates the live
    replicas (free slots summed, queues concatenated fleet-queue-last,
    rates from the first telemetry-bearing replica), tokens ride the
    router-authoritative ``on_token`` (satellite: a stream survives
    failover without double emission because the router log only ever
    extends)."""

    def __init__(self, fleet):
        self.fleet = fleet

    def has_work(self) -> bool:
        return any(fr.result is None
                   for fr in self.fleet._requests.values())

    def step(self) -> bool:
        return self.fleet.step()

    def view(self, controller: AdmissionController) -> AdmissionView:
        free = 0
        active: list = []
        queued: list = []
        rate = controller.default_prefill_rate_tps
        step_s = controller.default_step_s
        horizon = 8
        got_rates = False
        for rep in self.fleet._replicas:
            # a drain-retiring (unroutable) replica's slots must not be
            # promised to admission — new work can never be placed there
            if not rep.alive or not rep.routable:
                continue
            v = admission_view(
                rep.engine,
                default_prefill_rate_tps=controller.default_prefill_rate_tps,
                default_step_s=controller.default_step_s)
            free += v.free_slots
            active.extend(v.active)
            queued.extend(v.queued)
            horizon = v.decode_horizon
            if not got_rates and rep.engine.telemetry is not None:
                rate, step_s = v.prefill_rate_tps, v.step_s
                got_rates = True
        queued.extend((len(fr.prompt), fr.kw["max_new_tokens"])
                      for fr in self.fleet._waiting)
        return AdmissionView(free_slots=free, active=active, queued=queued,
                             prefill_rate_tps=rate, step_s=step_s,
                             decode_horizon=horizon)

    def submit(self, prompt, *, on_token=None, timeout=None, **kw) -> int:
        return self.fleet.submit(prompt, timeout=timeout,
                                 on_token=on_token, **kw)

    def cancel(self, frid: int) -> bool:
        return self.fleet.cancel(frid)

    def result(self, frid: int):
        fr = self.fleet._requests.get(frid)
        return fr.result if fr is not None else None


# --------------------------------------------------------------------------
# The async transport
# --------------------------------------------------------------------------
_END = object()


def _gc_abandon(fe_ref, rid_box, state):
    """weakref.finalize hook: an AsyncStream garbage-collected while its
    request is still live cancels the request (the async analog of the
    ``Request.stream()`` early-exit guarantee).  Must not capture the
    stream itself — and CAN fire, because every frontend-side reference
    to a stream (the engine's on_token closure, the tracking tables, the
    fan-out task) is deliberately weak."""
    if state.get("open"):
        fe = fe_ref()
        rid = rid_box.get("rid")
        if fe is not None and rid is not None:
            fe._request_cancel(rid, handle=None)


async def _drain_overflow(sref):
    """Per-request drain fan-out: move buffered tokens into the bounded
    client queue, awaiting queue space — THE backpressure stall point
    (per request; the engine thread never blocks here).  Holds the stream
    only through a weakref and re-checks liveness every quarter second,
    so a garbage-collected stream releases its fan-out instead of
    pinning it behind a queue nobody will ever drain."""
    while True:
        s = sref()
        if s is None or not s._overflow:
            return
        item = s._overflow[0]
        q = s._q
        s = None                       # drop the strong ref across waits
        while True:
            try:
                q.put_nowait(item)     # never double-delivers (a timed-out
                break                  # q.put() can race its own success)
            except asyncio.QueueFull:
                if sref() is None:     # client vanished mid-backpressure
                    return
                await asyncio.sleep(0.05)
        s = sref()
        if s is None:
            return
        s._overflow.popleft()


class AsyncStream:
    """One client's async token stream.

    ``async for tok in stream`` yields host-int tokens in emission order;
    the iterator ends when the request retires.  ``await stream.result()``
    returns the final :class:`~paddle_tpu.inference.paged.Request` record
    (``None`` when the request was cancelled).  Disconnect semantics —
    every path lands in ``engine.cancel(rid)`` on the worker thread:

      * the consuming task is CANCELLED while waiting on the iterator;
      * ``async with stream:`` exits before the stream finished;
      * explicit :meth:`abandon`;
      * the stream object is garbage-collected while the request lives.

    Backpressure: tokens land in a bounded ``asyncio.Queue``; when a slow
    client lets it fill, excess tokens buffer in an engine-side deque and
    a per-request fan-out task awaits queue space — the stall is entirely
    inside this request's fan-out, the engine thread never blocks."""

    def __init__(self, frontend: "AsyncFrontend", buffer: int):
        self._fe = frontend
        self.rid: int | None = None
        self.trace_id: int | None = None
        self.predicted_ttft_s: float | None = None
        self._q: asyncio.Queue = asyncio.Queue(maxsize=max(1, buffer))
        self._overflow: deque = deque()
        self._fanout: asyncio.Task | None = None
        self._result = None
        self._done = asyncio.Event()
        self._end_seen = False
        self._abandoned = False
        # GC-abandon guard: shared mutable boxes, not the stream itself
        self._rid_box: dict = {}
        self._state = {"open": True}
        self._finalizer = weakref.finalize(
            self, _gc_abandon, weakref.ref(frontend), self._rid_box,
            self._state)

    # -- loop-thread feeders (always via call_soon_threadsafe) -------------
    def _feed(self, item):
        if not self._overflow and (self._fanout is None
                                   or self._fanout.done()):
            try:
                self._q.put_nowait(item)
                return
            except asyncio.QueueFull:
                pass
        self._overflow.append(item)
        if self._fanout is None or self._fanout.done():
            # the fan-out task holds only a WEAK ref to the stream: a
            # pinned strong ref would keep an abandoned-by-GC stream
            # alive forever behind its own full queue
            self._fanout = self._fe._loop.create_task(
                _drain_overflow(weakref.ref(self)))

    def _finish(self, req):
        self._state["open"] = False
        self._result = req
        self._done.set()
        self._feed(_END)

    # -- client surface ----------------------------------------------------
    def __aiter__(self):
        return self

    async def __anext__(self):
        if self._end_seen:
            raise StopAsyncIteration
        try:
            item = await self._q.get()
        except asyncio.CancelledError:
            # client disconnect: the consuming task died mid-stream —
            # propagate to the engine so the pages free mid-decode
            self.abandon()
            raise
        if item is _END:
            self._end_seen = True
            raise StopAsyncIteration
        return item

    def abandon(self):
        """Disconnect: cancel the request on the worker thread (idempotent;
        a no-op once the request retired)."""
        if self._abandoned or self._done.is_set():
            return
        self._abandoned = True
        self._state["open"] = False
        if self.rid is not None:
            self._fe._request_cancel(self.rid, handle=self)

    async def result(self):
        """The final Request record (None when cancelled/abandoned)."""
        await self._done.wait()
        return self._result

    async def __aenter__(self):
        return self

    async def __aexit__(self, exc_type, exc, tb):
        if not self._done.is_set():
            self.abandon()
            await self._done.wait()
        return False


class AsyncFrontend:
    """The asyncio serving transport.  Construct over a live
    ``ServingEngine`` or ``ReplicaFleet``, enter it (``async with`` or
    ``await start()``), then ``await submit(...)`` from any number of
    client coroutines:

        async with AsyncFrontend(engine, slo_ttft_s=0.5) as fe:
            stream = await fe.submit(prompt, max_new_tokens=64)
            async for tok in stream:
                ...                       # tokens in emission order
            final = await stream.result() # the Request record

    The engine steps on one daemon worker thread; submissions, cancels,
    and admission decisions all execute THERE (engines are not
    thread-safe), bridged back via ``call_soon_threadsafe`` futures.
    ``admission`` picks the :class:`AdmissionController` policy (or pass
    a controller instance); ``submit`` raises :class:`SLORejected` /
    ``AdmissionRejected`` exactly like the engine's bounded queue.

    ``await drain()`` waits until every open stream finished (the clean
    shutdown point); ``aclose()`` stops the worker (the engine object —
    with whatever state it still holds — stays valid and inspectable)."""

    def __init__(self, engine, *, admission="always",
                 slo_ttft_s: float | None = None,
                 max_queue_depth: int | None = None,
                 stream_buffer: int = 64,
                 poll_interval_s: float = 0.002):
        from .fleet import ReplicaFleet
        if isinstance(engine, ServingEngine):
            self._adapter = _EngineAdapter(engine)
        elif isinstance(engine, ReplicaFleet):
            self._adapter = _FleetAdapter(engine)
        else:
            raise TypeError("AsyncFrontend wraps a ServingEngine or a "
                            f"ReplicaFleet, not {type(engine).__name__}")
        self.engine = engine
        if isinstance(admission, AdmissionController):
            self.controller = admission
        else:
            self.controller = AdmissionController(
                policy=admission, slo_ttft_s=slo_ttft_s,
                max_queue_depth=max_queue_depth)
        self.stream_buffer = int(stream_buffer)
        self._poll = float(poll_interval_s)
        # the FRONTEND track of the stitched trace: one span per request,
        # from the admission decision to retirement, stamped with the
        # trace_id that threads through router placement and replica
        # admission.  All writes happen on the worker thread.
        self.tracer = Tracer()
        self.exporter = None
        self._loop: asyncio.AbstractEventLoop | None = None
        self._thread: threading.Thread | None = None
        self._cv = threading.Condition()
        self._cmds: list = []
        self._stop = False
        # BOTH tables hold weak refs: a client that silently drops its
        # stream must be able to reach the GC-abandon finalizer (the
        # frontend must never be the thing keeping a dead client alive)
        self._tracked: dict[int, weakref.ref] = {}   # worker-owned
        self._streams: "weakref.WeakSet[AsyncStream]" = weakref.WeakSet()
        self._error: BaseException | None = None

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> "AsyncFrontend":
        if self._thread is not None:
            raise RuntimeError("AsyncFrontend already started")
        self._stop = False          # restartable after aclose()
        self._loop = asyncio.get_running_loop()
        self._thread = threading.Thread(target=self._worker,
                                        name="frontend-engine", daemon=True)
        self._thread.start()
        return self

    async def __aenter__(self):
        return await self.start()

    async def __aexit__(self, exc_type, exc, tb):
        await self.aclose()
        return False

    async def aclose(self):
        """Stop the worker thread (after it finishes the step in
        progress).  Outstanding streams are finished with ``None``."""
        if self.exporter is not None:
            self.exporter.stop()
            self.exporter = None
        if self._thread is None:
            return
        with self._cv:
            self._stop = True
            self._cv.notify_all()
        await asyncio.get_running_loop().run_in_executor(
            None, self._thread.join)
        self._thread = None

    async def drain(self):
        """Wait until every open stream has finished (retired, cancelled,
        or failed) — the graceful-shutdown barrier."""
        while self._streams:
            waiters = [s._done.wait() for s in list(self._streams)]
            await asyncio.gather(*waiters)

    # -- client surface ----------------------------------------------------
    async def submit(self, prompt, max_new_tokens: int = 32,
                     temperature: float = 0.0, top_p: float = 1.0,
                     eos_token_id: int | None = None,
                     timeout: float | None = None,
                     slo_ttft_s: float | None = None,
                     stream_buffer: int | None = None) -> AsyncStream:
        """Admission-checked async submit; returns the token stream.
        Raises :class:`SLORejected` when predictive admission says the
        deadline cannot be met, ``AdmissionRejected`` on depth/queue
        backpressure — both BEFORE the request touches the engine."""
        if self._thread is None:
            raise RuntimeError("AsyncFrontend not started — use "
                               "'async with AsyncFrontend(...)' or await "
                               "start()")
        if self._error is not None:
            raise RuntimeError("frontend worker died") from self._error
        loop = self._loop
        fut: asyncio.Future = loop.create_future()
        stream = AsyncStream(self, stream_buffer or self.stream_buffer)
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        sref = weakref.ref(stream)
        # the end-to-end stitching id: minted HERE (the outermost
        # component) and threaded through router placement, replica
        # admission, migration, and snapshot restore
        trace_id = new_trace_id()
        stream.trace_id = trace_id

        def on_token(tok, _sref=sref, _self=self):
            # worker thread -> event loop, in emission order.  Weak ref
            # only: the engine Request holds this closure until
            # retirement, and a strong ref here would keep an
            # abandoned-by-GC stream alive for the request's lifetime
            s = _sref()
            if s is not None:
                _self._post(s._feed, tok)

        def do_submit():
            # captures `sref`, never `stream`: a closure cell here would
            # outlive the call and keep a dropped stream from ever
            # reaching the GC-abandon finalizer.  The awaiting submit()
            # coroutine holds the stream strongly until this resolves.
            try:
                if self._error is not None:   # worker died before us
                    raise RuntimeError("frontend worker died") \
                        from self._error
                t_decide = self.tracer.clock()
                view = self._adapter.view(self.controller)
                pred = self.controller.decide(view, len(prompt),
                                              slo_ttft_s=slo_ttft_s)
                rid = self._adapter.submit(
                    prompt, max_new_tokens=max_new_tokens,
                    temperature=temperature, top_p=top_p,
                    eos_token_id=eos_token_id, timeout=timeout,
                    on_token=on_token, trace_id=trace_id)
                self.controller.track(rid, pred)
                self._tracked[rid] = sref
                # stamped at the admission DECISION time (before the
                # engine-side submit), so the frontend span is the
                # outermost touch in the stitched flow chain
                self.tracer.request_event(
                    rid, "submitted", t=t_decide, trace_id=trace_id,
                    prompt_tokens=len(prompt),
                    predicted_ttft_s=round(pred, 6))
            except BaseException as exc:  # noqa: BLE001 — delivered async
                self._post(self._reject_future, fut, exc)
                return
            s = sref()
            if s is not None:
                self._post(self._resolve_submit, fut, s, rid, pred)

        self._enqueue_cmd(do_submit)
        await fut
        return stream

    @staticmethod
    def _reject_future(fut: asyncio.Future, exc: BaseException):
        if not fut.done():
            fut.set_exception(exc)

    def _resolve_submit(self, fut: asyncio.Future, stream: AsyncStream,
                        rid: int, pred: float):
        stream.rid = rid
        stream._rid_box["rid"] = rid
        stream.predicted_ttft_s = pred
        self._streams.add(stream)
        if not fut.done():
            fut.set_result(rid)

    def stats(self) -> dict:
        """Admission report + open-stream count (host-only reads)."""
        rep = self.controller.report()
        rep["open_streams"] = len(self._streams)
        return rep

    # -- live exporter -----------------------------------------------------
    def _sentinels(self) -> dict:
        """{label: HealthSentinel} for every telemetry-bearing component
        behind this front end that carries one — recomputed per scrape so
        failover-revived replicas appear automatically, and every
        sentinel found gets the admission controller's registry attached
        (the prediction-error drift rule reads it; a revived replica's
        FRESH sentinel must be wired on discovery, not only at
        start_exporter time)."""
        out: dict = {}
        eng = self.engine
        if isinstance(eng, ServingEngine):
            tel = eng.telemetry
            if tel is not None and tel.sentinel is not None:
                out["engine"] = tel.sentinel
        else:                                     # ReplicaFleet
            out.update(eng._sentinels())
        for s in out.values():
            s.registries.setdefault("frontend", self.controller.metrics)
        return out

    def _slow_dumps(self) -> list:
        """The /slow body: tail-outlier dumps merged across components."""
        from ..observability.attribution import merge_tail_dumps
        eng = self.engine
        if isinstance(eng, ServingEngine):
            tel = eng.telemetry
            if tel is None or tel.tail is None:
                return []
            return merge_tail_dumps([("engine", tel.tail)])
        return eng.slow_requests()                # ReplicaFleet

    def _export_registries(self) -> dict:
        """{label: MetricsRegistry} for every component behind this front
        end — recomputed per scrape, so failover-revived replicas (fresh
        registries) appear automatically."""
        regs = {"frontend": self.controller.metrics}
        eng = self.engine
        if isinstance(eng, ServingEngine):
            if eng.telemetry is not None:
                regs["engine"] = eng.telemetry.registry
        else:                                     # ReplicaFleet
            regs["router"] = eng.metrics
            for rep in eng._replicas:
                if rep.alive and rep.engine is not None \
                        and rep.engine.telemetry is not None:
                    regs[rep.name] = rep.engine.telemetry.registry
        return regs

    # -- HTTP/SSE streaming endpoint (ROADMAP item 4's socket leftover) ----
    def _sse_generate(self, payload: dict):
        """``POST /generate`` body -> SSE-framed event strings.  Runs on
        the exporter's HTTP thread: the submit and every token pull hop
        onto the asyncio loop via ``run_coroutine_threadsafe``, so the
        transport semantics (admission, backpressure, cancel path) are
        EXACTLY :meth:`submit`'s.  A client disconnect closes this
        generator mid-iteration; the ``finally`` abandons the stream —
        the same ``engine.cancel()`` path as an async client vanishing,
        pages freed mid-decode."""
        import json as _json

        def _ev(event, obj):
            return f"event: {event}\ndata: {_json.dumps(obj)}\n\n"

        loop = self._loop
        if loop is None or self._thread is None:
            yield _ev("error", {"error": "frontend not started"})
            return
        try:
            prompt = np.asarray(payload["prompt"], np.int32).reshape(-1)
            kw = dict(
                max_new_tokens=int(payload.get("max_new_tokens", 32)),
                temperature=float(payload.get("temperature", 0.0)),
                top_p=float(payload.get("top_p", 1.0)),
                eos_token_id=payload.get("eos_token_id"),
                slo_ttft_s=payload.get("slo_ttft_s"))
        except (KeyError, TypeError, ValueError) as exc:
            yield _ev("error", {"error": f"bad request: {exc}"})
            return
        try:
            stream = asyncio.run_coroutine_threadsafe(
                self.submit(prompt, **kw), loop).result()
        except AdmissionRejected as exc:
            yield _ev("rejected", {"error": str(exc),
                                   "slo": isinstance(exc, SLORejected)})
            return
        except Exception as exc:  # noqa: BLE001 — surfaced to the client
            yield _ev("error", {"error": f"{type(exc).__name__}: {exc}"})
            return
        done = False
        err: Exception | None = None
        n = 0
        try:
            yield _ev("start", {"rid": stream.rid,
                                "trace_id": stream.trace_id,
                                "predicted_ttft_s": stream.predicted_ttft_s})
            while True:
                try:
                    tok = asyncio.run_coroutine_threadsafe(
                        stream.__anext__(), loop).result()
                except StopAsyncIteration:
                    break
                except Exception as exc:  # noqa: BLE001 — engine/worker
                    # died mid-stream: the contract is a TYPED error
                    # frame, not a silent truncation indistinguishable
                    # from a network drop (GeneratorExit — the client
                    # disconnect — is BaseException and still propagates)
                    err = exc
                    break
                n += 1
                yield f"data: {_json.dumps({'token': int(tok)})}\n\n"
            done = err is None
        finally:
            if not done:
                # generator closed mid-stream (disconnect) or the stream
                # errored: cancel any live request through the existing
                # abandon path
                loop.call_soon_threadsafe(stream.abandon)
        if err is not None:
            yield _ev("error", {"error": f"{type(err).__name__}: {err}",
                                "tokens": n})
        else:
            yield _ev("done", {"tokens": n})

    def start_exporter(self, host: str = "127.0.0.1", port: int = 0,
                       freeze: bool = True):
        """Attach the live pull endpoint: ``/metrics`` (Prometheus text,
        every component labeled), ``/metrics.json``, ``/healthz``
        (degraded-aware when a health sentinel rides the engine
        telemetry), ``/alerts`` (the aggregated sentinel report),
        ``/slow`` (top-K slowest requests with their critical-path
        attribution, merged across replicas), and ``/requests`` (recent
        request summaries) on a stdlib ``http.server`` daemon thread —
        plus the streaming ingress ``POST /generate``: a JSON body
        (``{"prompt": [...], "max_new_tokens": ...}``) answered with a
        Server-Sent-Events token stream (``event: start`` ->
        ``data: {"token": N}`` per token -> ``event: done``; admission
        rejections arrive as ``event: rejected``), and a mid-stream
        disconnect cancels the request and frees its pages exactly like
        an async client vanishing.  Off by default; ``port=0`` picks a
        free port (read ``.port`` back from the returned exporter).

        SECURITY: binds ``127.0.0.1`` by default — metrics and request
        summaries expose workload shape; put real auth in front before
        binding a routable interface.

        Rendering happens entirely on the HTTP thread from registry
        snapshots — the engine worker does zero exporter work.  With
        ``freeze`` (default), every component registry is frozen first
        (registry-freeze invariant): all hot-path metrics are
        pre-registered, so a scrape can never race a metric being
        created at first use from the worker thread."""
        from ..observability.export import MetricsExporter, export_snapshot
        from ..observability.health import aggregate_alerts
        if self.exporter is not None:
            raise RuntimeError("exporter already attached")
        if freeze:
            for reg in self._export_registries().values():
                reg.freeze()

        def snapshot_fn():
            return {lab: export_snapshot(reg)
                    for lab, reg in self._export_registries().items()}

        def requests_fn():
            eng = self.engine
            if isinstance(eng, ServingEngine):
                tel = eng.telemetry
                return list(tel.request_summaries)[-64:] \
                    if tel is not None else []
            return list(eng._summaries)[-64:]

        def health_fn():
            h = {"worker_alive": self._thread is not None
                 and self._thread.is_alive(),
                 "open_streams": len(self._streams),
                 "worker_error": None if self._error is None
                 else str(self._error)[:200]}
            sentinels = self._sentinels()
            if sentinels:
                # degraded-aware /healthz: worst component status wins,
                # active alerts counted fleet-wide (HTTP 200 either way)
                agg = aggregate_alerts(sentinels)
                h["status"] = agg["status"]
                h["active_alerts"] = agg["active_alerts"]
            return h

        def alerts_fn():
            return aggregate_alerts(self._sentinels())

        def slow_fn():
            return self._slow_dumps()

        self.exporter = MetricsExporter(
            snapshot_fn, requests_fn=requests_fn, health_fn=health_fn,
            alerts_fn=alerts_fn, slow_fn=slow_fn,
            generate_fn=self._sse_generate,
            host=host, port=port).start()
        return self.exporter

    # -- worker ------------------------------------------------------------
    def _post(self, fn, *args) -> bool:
        """call_soon_threadsafe that tolerates a closed/gone event loop
        (teardown race: the engine may still be mid-step when asyncio.run
        returns) — the engine must never die because a client's loop
        left first."""
        loop = self._loop
        if loop is None:
            return False
        try:
            loop.call_soon_threadsafe(fn, *args)
            return True
        except RuntimeError:
            return False

    def _enqueue_cmd(self, fn):
        with self._cv:
            self._cmds.append(fn)
            self._cv.notify_all()

    def _request_cancel(self, rid: int, handle: AsyncStream | None):
        """Schedule an engine-side cancel from the event loop (or a GC
        finalizer).  Safe to call multiple times."""
        def do_cancel():
            # the disconnect may race the retirement: if the request
            # already finished, deliver the real record instead of
            # cancelling a ghost (engine.cancel would discard it)
            req = self._adapter.result(rid)
            ref = self._tracked.pop(rid, None)
            h = ref() if ref is not None else handle
            if req is None:
                self._adapter.cancel(rid)
                self.controller._pending.pop(rid, None)
                self.tracer.request_event(rid, "retired", cancelled=True)
            else:
                self.controller.resolve(rid, req)
                self.tracer.request_event(rid, "retired",
                                          tokens=len(req.generated))
            if h is not None:
                self._post(self._finish_stream, h, req)
        self._enqueue_cmd(do_cancel)

    def _finish_stream(self, stream: AsyncStream, req):
        self._streams.discard(stream)
        if not stream._done.is_set():
            stream._finish(req)

    def _sweep_retired(self):
        """Worker-side: notify streams whose request retired (finish,
        deadline, fleet resolution)."""
        if not self._tracked:
            return
        for rid in list(self._tracked):
            req = self._adapter.result(rid)
            if req is None:
                continue
            stream = self._tracked.pop(rid)()
            self.controller.resolve(rid, req)
            self.tracer.request_event(rid, "retired",
                                      tokens=len(req.generated))
            if stream is not None:        # GC-abandoned: finalizer's
                self._post(self._finish_stream, stream, req)  # cancel
                                          # command races the retirement
                                          # and resolves as a no-op

    def _fail_all(self, exc: BaseException):
        self._error = exc
        for rid, ref in list(self._tracked.items()):
            stream = ref()
            if stream is not None:
                self._post(self._finish_stream, stream, None)
            self.tracer.request_event(rid, "retired", failed=True)
        self._tracked.clear()

    def _drain_cmds_on_exit(self):
        """Run (or fail) every still-queued command before the worker
        exits: a do_submit enqueued moments before a crash/stop would
        otherwise leave its client awaiting a future nobody resolves.
        Each command owns its error delivery (do_submit's except posts
        the rejection); anything it raises beyond that is swallowed —
        the worker is already on its way out."""
        with self._cv:
            cmds, self._cmds = self._cmds, []
        for fn in cmds:
            try:
                fn()
            except BaseException:  # noqa: BLE001 — exit path, best effort
                pass

    # the worker thread OWNS _tracked/_cmds-drain/_error: every other
    # thread reaches them through _enqueue_cmd (loop->worker) or _post
    # (worker->loop) — never directly (README §Async frontend)
    def _worker(self):  # graftlint: owner=worker
        adapter = self._adapter
        while True:
            with self._cv:
                if not self._cmds and not adapter.has_work() \
                        and not self._stop:
                    self._cv.wait(timeout=self._poll)
                cmds, self._cmds = self._cmds, []
                stop = self._stop
            for fn in cmds:
                fn()
            if adapter.has_work():
                try:
                    adapter.step()
                except BaseException as exc:  # noqa: BLE001 — a dead
                    # engine must not hang every client: fail the open
                    # streams, resolve any queued commands, and stop the
                    # worker (the engine object keeps its state for
                    # postmortem; new submits raise via self._error)
                    self._fail_all(exc)
                    self._drain_cmds_on_exit()
                    return
            self._sweep_retired()
            if stop:
                # finish whatever is still open with None (closed while
                # requests were live), resolve late-enqueued commands,
                # and exit
                self._drain_cmds_on_exit()
                self._sweep_retired()
                for rid, ref in list(self._tracked.items()):
                    stream = ref()
                    if stream is not None:
                        self._post(self._finish_stream, stream, None)
                self._tracked.clear()
                return
