"""Worker process entrypoint for the cross-process fleet (ISSUE 17).

``python -m paddle_tpu.serving.worker --name r0 --spec spec.json
--portfile /tmp/r0.port [--snapshot-root DIR --snapshot-every N]`` hosts
one full :class:`~paddle_tpu.inference.paged.ServingEngine` behind the
length-prefixed loopback RPC of :mod:`paddle_tpu.serving.rpc` and speaks
the fleet wire protocol:

=============  ============================================================
``hello``      identity + boot-restore report: pid, restored snapshot
               path/mode, the live rids the restore reinstated, and the
               post-restore ``check_invariants()`` verdict (the supervisor
               relays this into the conftest cross-process leak guard for
               workers that died mid-drill and can no longer answer)
``submit``     queue one request -> rid
``adopt``      queue with already-emitted tokens (migration re-prefill
               path; also the supervisor's unified placement primitive)
``poll``       incremental token stream: ``{rid: have_n}`` -> new tokens
               past ``have_n`` per rid + finished/timed-out flags — the
               supervisor's record only ever EXTENDS, so a retried poll
               (idempotency cache) can never double-stream a token
``cancel``     drop a request wherever it lives (KV parks in prefix cache)
``health``     heartbeat seq + step count + load + engine ``stats()`` +
               live invariants verdict (PagePool refcounts / page tables /
               cache accounting), every call — the leak guard's wire
``snapshot``   force one crash-consistent EngineSnapshotManager snapshot
``drain``      stop admitting, cancel all live work (zero-loss ladder:
               the supervisor has already adopted the streams elsewhere)
``trace``      the engine Tracer in wire form (stitched cross-process
               spans; worker telemetry runs on ``time.time`` so the
               supervisor's clock domain matches)
``stats``      engine ``stats()``
``stop``       final teardown report (release_cache + check_invariants),
               then process exit 0
=============  ============================================================

Determinism: the spec carries the model config + a PRNG key integer, and
the worker rebuilds params via ``build_functional_llama(cfg,
key=PRNGKey(k))`` — bit-identical to a supervisor-side reference build,
which is what makes the SIGKILL failover drill's bit-equality bar
meaningful.  A crash inside ``engine.step()`` exits the process non-zero:
the supervisor observes a real death, not an exception.
"""
from __future__ import annotations

import argparse
import json
import os
import signal
import sys
import threading
import time
import traceback

__all__ = ["build_from_spec", "main", "WORKER_CRASH_EXIT"]

WORKER_CRASH_EXIT = 13      # engine.step raised: distinguishable from OOM-kill


def build_from_spec(spec: dict):
    """(params, cfg, engine_kwargs) from a fleet worker spec — shared by
    worker processes and supervisor-side reference builds so both sides
    hold bit-identical weights."""
    import jax
    import jax.numpy as jnp

    import paddle_tpu as paddle
    from ..models.llama import LlamaConfig, build_functional_llama

    model = spec["model"]
    paddle.seed(int(spec.get("seed", 2024)))
    cfg = LlamaConfig(**model["config"])
    dtype = None if not model.get("dtype") else jnp.dtype(model["dtype"])
    ep, bp, hp, *_ = build_functional_llama(
        cfg, key=jax.random.PRNGKey(int(model.get("prng_key", 0))),
        dtype=dtype, n_micro=int(model.get("n_micro", 1)))
    return (ep, bp, hp), cfg, dict(spec.get("engine", {}))


class _WorkerHost:
    """The handler + serve loop around one engine."""

    def __init__(self, name: str, engine, snapshots=None,
                 snapshot_every: int = 0, snapshot_mode: str = "full_kv"):
        self.name = name
        self.engine = engine
        self.snapshots = snapshots
        self.snapshot_every = int(snapshot_every)
        self.snapshot_mode = snapshot_mode
        self.lock = threading.RLock()
        self.stop_event = threading.Event()
        self.draining = False
        self.hb = 0
        self.steps = 0
        self.restored = None          # (path, mode) | None
        self.restored_rids: list[int] = []
        self.restore_invariants_ok = True
        self.restore_error = ""
        self.final_report: dict | None = None

    # -- engine helpers ----------------------------------------------------
    def _live_rids(self) -> list[int]:
        eng = self.engine
        rids = [r.rid for r in eng._queue]
        rids += [sl.req.rid for sl in eng._slots if sl is not None]
        rids += list(eng._finished)
        return sorted(int(r) for r in set(rids))

    def _invariants(self) -> tuple[bool, str]:
        try:
            self.engine.check_invariants()
            return True, ""
        except AssertionError as e:
            return False, str(e)

    def boot_restore(self):
        if self.snapshots is None:
            return
        with self.lock:
            got = self.snapshots.restore_engine(self.engine)
            if got is not None:
                self.restored = (str(got[0]), got[1])
                self.restored_rids = self._live_rids()
            ok, err = self._invariants()
            self.restore_invariants_ok = ok
            self.restore_error = err

    def _maybe_snapshot(self):
        if self.snapshots is None or self.snapshot_every <= 0:
            return
        if self.steps % self.snapshot_every:
            return
        try:
            self.snapshots.save_engine(self.engine, mode=self.snapshot_mode)
        except Exception:
            # durability is best-effort from inside the worker; a failed
            # snapshot must not take down live decode
            traceback.print_exc()

    # the serve thread owns the engine and the hb counter behind self.lock;
    # the RPC handler threads only touch them under the same lock
    def serve_loop(self):  # graftlint: owner=worker
        eng = self.engine
        while not self.stop_event.is_set():
            did = False
            with self.lock:
                self.hb += 1
                if eng._queue or eng.num_active or eng._inflight is not None:
                    try:
                        eng.step()
                    except BaseException:
                        traceback.print_exc()
                        os._exit(WORKER_CRASH_EXIT)
                    self.steps += 1
                    did = True
                    self._maybe_snapshot()
            if not did:
                self.stop_event.wait(0.002)

    # -- RPC handler -------------------------------------------------------
    def handle(self, method: str, p: dict):
        import numpy as np
        eng = self.engine
        if method == "hello":
            return {"name": self.name, "pid": os.getpid(),
                    "restored": self.restored is not None,
                    "restored_path": None if self.restored is None
                    else self.restored[0],
                    "restored_mode": None if self.restored is None
                    else self.restored[1],
                    "restored_rids": self.restored_rids,
                    "restore_invariants_ok": self.restore_invariants_ok,
                    "restore_error": self.restore_error}
        if method == "submit":
            if self.draining:
                raise RuntimeError("worker draining: admission closed")
            with self.lock:
                return int(eng.submit(
                    np.asarray(p["prompt"], np.int32),
                    max_new_tokens=int(p.get("max_new_tokens", 32)),
                    temperature=float(p.get("temperature", 0.0)),
                    top_p=float(p.get("top_p", 1.0)),
                    eos_token_id=p.get("eos_token_id"),
                    timeout=p.get("timeout"),
                    trace_id=p.get("trace_id")))
        if method == "adopt":
            if self.draining:
                raise RuntimeError("worker draining: admission closed")
            with self.lock:
                return int(eng.adopt(
                    np.asarray(p["prompt"], np.int32),
                    generated=tuple(int(t) for t in p.get("generated", ())),
                    max_new_tokens=int(p.get("max_new_tokens", 32)),
                    temperature=float(p.get("temperature", 0.0)),
                    top_p=float(p.get("top_p", 1.0)),
                    eos_token_id=p.get("eos_token_id"),
                    deadline=p.get("deadline"),
                    trace_id=p.get("trace_id")))
        if method == "poll":
            out = {}
            with self.lock:
                for rid_s, have in (p.get("have") or {}).items():
                    r = eng.lookup(int(rid_s))
                    if r is None:
                        out[rid_s] = None
                        continue
                    gen = r.generated
                    out[rid_s] = {
                        "new": [int(t) for t in gen[int(have):]],
                        "done": r.finish_time > 0.0,
                        "timed_out": bool(r.timed_out),
                        "n": len(gen)}
                load = {"active": int(eng.num_active),
                        "queued": len(eng._queue)}
            return {"rids": out, "hb": self.hb, "load": load}
        if method == "cancel":
            with self.lock:
                return bool(eng.cancel(int(p["rid"])))
        if method == "health":
            with self.lock:
                ok, err = self._invariants()
                return {"hb": self.hb, "steps": self.steps,
                        "pid": os.getpid(),
                        "load": {"active": int(eng.num_active),
                                 "queued": len(eng._queue)},
                        "draining": self.draining,
                        "invariants_ok": ok, "invariants_error": err,
                        "stats": {k: (float(v) if isinstance(v, float)
                                      else int(v))
                                  for k, v in eng.stats().items()
                                  if isinstance(v, (int, float))}}
        if method == "snapshot":
            if self.snapshots is None:
                raise RuntimeError("worker has no snapshot root")
            with self.lock:
                path = self.snapshots.save_engine(
                    eng, mode=p.get("mode") or self.snapshot_mode)
            return {"path": str(path)}
        if method == "drain":
            with self.lock:
                self.draining = True
                live = [r for r in self._live_rids()
                        if r not in eng._finished]
                for rid in live:
                    eng.cancel(rid)
                ok, err = self._invariants()
            return {"cancelled": live, "invariants_ok": ok,
                    "invariants_error": err}
        if method == "trace":
            from ..observability.tracing import tracer_to_wire
            with self.lock:
                if eng.telemetry is None:
                    return {"requests": [], "engine": [], "counters": []}
                return tracer_to_wire(eng.telemetry.tracer)
        if method == "stats":
            with self.lock:
                return {k: (v if isinstance(v, (int, float, str, bool))
                            else str(v)) for k, v in eng.stats().items()}
        if method == "stop":
            with self.lock:
                self.draining = True
                try:
                    eng.release_cache()
                except Exception as e:   # release must not mask the report
                    return self._finalize(False, f"release_cache: {e}")
                ok, err = self._invariants()
            return self._finalize(ok, err)
        raise RuntimeError(f"unknown rpc method {method!r}")

    def _finalize(self, ok: bool, err: str) -> dict:
        self.final_report = {"invariants_ok": bool(ok),
                             "invariants_error": err, "name": self.name}
        self.stop_event.set()
        return self.final_report


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="paddle_tpu.serving.worker")
    ap.add_argument("--name", required=True)
    ap.add_argument("--spec", required=True,
                    help="JSON file: {model, engine, seed, snapshot}")
    ap.add_argument("--portfile", required=True,
                    help="written atomically with the bound port")
    ap.add_argument("--port", type=int, default=0,
                    help="bind this port (0 = ephemeral); the supervisor "
                         "pre-assigns via the elastic-launch _free_port")
    ap.add_argument("--snapshot-root", default=None)
    ap.add_argument("--snapshot-every", type=int, default=0)
    ap.add_argument("--snapshot-mode", default="full_kv")
    args = ap.parse_args(argv)

    with open(args.spec) as f:
        spec = json.load(f)

    # Heavy imports AFTER argparse so --help stays fast.
    import time as _time  # noqa: F401 — clock domain note below

    from ..inference.paged import ServingEngine
    from ..observability.telemetry import Telemetry
    from .rpc import RpcServer
    from .snapshot import EngineSnapshotManager

    params, cfg, engine_kw = build_from_spec(spec)
    # One clock domain fleet-wide: the supervisor stitches worker spans
    # with its own, so both must stamp wall-clock time.time.
    telemetry = Telemetry(clock=time.time)
    engine = ServingEngine(params, cfg, telemetry=telemetry, **engine_kw)

    snaps = None
    if args.snapshot_root:
        os.makedirs(args.snapshot_root, exist_ok=True)
        snaps = EngineSnapshotManager(
            args.snapshot_root,
            keep_last=int(spec.get("snapshot", {}).get("keep_last", 2)))
    host = _WorkerHost(args.name, engine, snapshots=snaps,
                       snapshot_every=args.snapshot_every,
                       snapshot_mode=args.snapshot_mode)
    host.boot_restore()

    server = RpcServer(host.handle, port=args.port).start()
    tmp = args.portfile + ".tmp"
    with open(tmp, "w") as f:
        f.write(f"{server.port}\n")
    os.replace(tmp, args.portfile)

    signal.signal(signal.SIGTERM, lambda *_: host.stop_event.set())

    loop = threading.Thread(target=host.serve_loop, name="serve-loop",
                            daemon=True)
    loop.start()
    host.stop_event.wait()
    # Grace so the in-flight `stop` reply flushes before the listener dies.
    time.sleep(0.2)
    server.stop()
    loop.join(timeout=2.0)
    return 0


if __name__ == "__main__":
    sys.exit(main())
