"""ProcessFleet: the replica boundary promoted from thread to OS process
(ISSUE 17 tentpole).

Each replica is a real ``python -m paddle_tpu.serving.worker`` process
hosting a full ServingEngine, spawned through the elastic-launch
machinery (``_free_port`` port assignment, ``_rank_env`` PADDLE_* env
contract, :class:`ElasticManager` membership accounting) and spoken to
over the :mod:`paddle_tpu.serving.rpc` loopback wire.  The supervisor
keeps the same authoritative per-request token log the thread-based
:class:`~paddle_tpu.serving.fleet.ReplicaFleet` keeps — the log only
ever EXTENDS, so `on_token` fires exactly once per position across any
number of process deaths — and recovers exactly the same way: newest
intact :class:`EngineSnapshotManager` snapshot first (greedy requests
reattach to the restored replacement), ``adopt`` re-prefill on surviving
workers otherwise, zombies pruned.  What changes is the failure model:

* **death detection** — SIGCHLD (when the supervisor owns the main
  thread) plus ``Popen.poll()`` reaping plus health-RPC heartbeat
  timeouts.  A worker that answers nothing for ``wedge_heartbeats``
  consecutive probes (a SIGSTOP'd process, a livelocked loop) is
  SIGKILLed and failed over — the thread fleet's stall watchdog, made
  honest against a process that cannot cooperate.
* **crash drills** — real ``SIGKILL`` mid-decode, not an injected
  exception: nothing in the worker runs after the kill, so recovery can
  only use what the durability story actually persisted.
* **drain** — SIGTERM (or :meth:`shutdown`) walks the PR 14 ladder per
  worker: mark unroutable, migrate/complete the live streams, then
  ``stop`` which makes the worker release its cache, re-check PagePool /
  page-table / prefix-cache invariants, and report the verdict as its
  final RPC reply — the cross-process end of the conftest leak guard.

Supervisor-side wall-clock recovery times land in the
``proc.recovery_s`` histogram (these are REAL seconds — process spawn +
jit warmup + snapshot restore — not virtual-clock ticks), and per-worker
restart counters ride :meth:`stats`.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import threading
import time
import weakref
from dataclasses import dataclass, field

import numpy as np

from ..distributed.fleet.elastic.manager import ElasticManager, MemoryStore
from ..distributed.launch.main import _free_port, _rank_env
from ..inference.paged import (AdmissionRejected, EngineStalledError,
                               PoolCapacityError, Request)
from ..observability.distributed import TraceStitcher, new_trace_id
from ..observability.flight import FlightRecorder
from ..observability.metrics import MetricsRegistry
from ..observability.tracing import Tracer, tracer_from_wire
from ..observability.train import fault_context
from .fleet import FleetFailedError
from .routing import LeastLoadedRouter
from .rpc import RpcClient, RpcError, RpcRemoteError, RpcTimeout

__all__ = ["ProcessFleet", "WorkerDiedError"]

# conftest's cross-process leak guard iterates this (weak — a collected
# fleet was either shut down or already failed its test)
_LIVE_FLEETS: "weakref.WeakSet[ProcessFleet]" = weakref.WeakSet()


class WorkerDiedError(RuntimeError):
    """A worker process died and could not be replaced."""


@dataclass
class _ProcRequest:
    frid: int
    prompt: np.ndarray
    kw: dict
    deadline: float | None
    submit_t: float
    on_token: object
    trace_id: int
    streamed: list = field(default_factory=list)
    worker: str | None = None
    rid: int | None = None          # worker-engine rid
    result: Request | None = None
    first_token_t: float | None = None
    retries: int = 0
    next_try_round: int = 0
    migrations: int = 0


@dataclass
class _Worker:
    name: str
    generation: int = 0
    proc: subprocess.Popen | None = None
    client: RpcClient | None = None
    port: int = 0
    pid: int = 0
    alive: bool = False
    routable: bool = False
    missed: int = 0                  # consecutive health-probe timeouts
    load: int = 0
    hb: int = 0
    log: object = None               # open log file handle
    trace_cache: dict | None = None  # last fetched wire-form tracer

    def key(self) -> str:
        return f"{self.name}#{self.generation}"


class ProcessFleet:
    """Spawn/reap/fail-over a fleet of worker processes; mirror the
    ReplicaFleet request surface (submit/cancel/step/run/results/stats
    plus stitched traces)."""

    def __init__(self, spec: dict, num_workers: int = 2, *,
                 workdir: str | None = None,
                 snapshot_every: int = 0,
                 snapshot_mode: str = "full_kv",
                 heartbeat_timeout: float = 2.0,
                 wedge_heartbeats: int = 3,
                 max_queue: int | None = None,
                 retry_backoff_rounds: int = 1,
                 max_backoff_rounds: int = 32,
                 max_restarts_per_worker: int = 4,
                 spawn_timeout: float = 180.0,
                 trace_every: int = 8,
                 router=None,
                 python: str | None = None,
                 install_sigchld: bool = True,
                 clock=time.time):
        if num_workers < 1:
            raise ValueError("num_workers must be >= 1")
        self.spec = dict(spec)
        self.clock = clock
        self.heartbeat_timeout = float(heartbeat_timeout)
        self.wedge_heartbeats = int(wedge_heartbeats)
        self.max_queue = None if max_queue is None else int(max_queue)
        self.retry_backoff_rounds = int(retry_backoff_rounds)
        self.max_backoff_rounds = int(max_backoff_rounds)
        self.max_restarts_per_worker = int(max_restarts_per_worker)
        self.spawn_timeout = float(spawn_timeout)
        self.snapshot_every = int(snapshot_every)
        self.snapshot_mode = snapshot_mode
        self.trace_every = int(trace_every)
        self.router = router if router is not None else LeastLoadedRouter()
        self.python = python or sys.executable
        self.workdir = workdir or tempfile.mkdtemp(prefix="procfleet-")
        os.makedirs(self.workdir, exist_ok=True)
        self._spec_path = os.path.join(self.workdir, "spec.json")
        with open(self._spec_path, "w") as f:
            json.dump(self.spec, f)

        self.metrics = MetricsRegistry(clock=clock)
        self._c_failovers = self.metrics.counter("proc.failovers")
        self._c_migrations = self.metrics.counter("proc.migrations")
        self._c_restarts = self.metrics.counter("proc.restarts")
        self._c_spawns = self.metrics.counter("proc.spawns")
        self._c_submitted = self.metrics.counter("proc.requests_submitted")
        self._c_resolved = self.metrics.counter("proc.requests_resolved")
        # WALL-CLOCK failover recovery: detect -> replacement serving
        self._h_recovery = self.metrics.histogram("proc.recovery_s")
        self.flight = FlightRecorder(capacity=256, clock=clock)
        self.tracer = Tracer(clock=clock)
        self._dead_tracers: list[tuple[str, Tracer]] = []

        # membership accounting through the existing elastic machinery:
        # registered on spawn, heartbeaten on every healthy probe,
        # deregistered on death/retire — `members()` is the fleet roster
        self.elastic = ElasticManager(
            MemoryStore(), np_min=1, np_max=max(num_workers * 4, 8),
            heartbeat_timeout=max(30.0, heartbeat_timeout * 10))

        self._requests: dict[int, _ProcRequest] = {}
        self._assigned: dict[str, set[int]] = {}
        self._waiting: list[_ProcRequest] = []
        self._next_frid = 0
        self._round = 0
        self.tokens_streamed = 0
        self.restarts: dict[str, int] = {}
        # "name#generation" -> final invariants report; every spawned
        # generation must end up here with invariants_ok True (killed
        # generations are vouched for by their replacement's post-restore
        # check) — asserted by the conftest cross-process leak guard
        self.final_reports: dict[str, dict] = {}
        self.closed = False
        self._in_shutdown = False
        self._terminate = False
        self._sigchld = False
        self._prev_sigchld = None
        self._prev_sigterm = None
        if install_sigchld:
            self._install_signals()

        self._workers: list[_Worker] = []
        for i in range(int(num_workers)):
            w = _Worker(name=f"w{i}")
            self._workers.append(w)
            self._assigned[w.name] = set()
            self.restarts[w.name] = 0
            self._spawn(w)
        _LIVE_FLEETS.add(self)

    # -- signals -----------------------------------------------------------
    def _install_signals(self):
        """SIGCHLD -> reap flag; SIGTERM -> drain-shutdown flag.  Only the
        main thread may own handlers; elsewhere the poll()-based reaper
        alone carries death detection."""
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            self._prev_sigchld = signal.signal(
                signal.SIGCHLD, lambda *_: setattr(self, "_sigchld", True))
            self._prev_sigterm = signal.signal(
                signal.SIGTERM, lambda *_: setattr(self, "_terminate", True))
        except ValueError:
            self._prev_sigchld = self._prev_sigterm = None

    def _restore_signals(self):
        if threading.current_thread() is not threading.main_thread():
            return
        try:
            if self._prev_sigchld is not None:
                signal.signal(signal.SIGCHLD, self._prev_sigchld)
            if self._prev_sigterm is not None:
                signal.signal(signal.SIGTERM, self._prev_sigterm)
        except (ValueError, TypeError):
            pass
        self._prev_sigchld = self._prev_sigterm = None

    # -- spawning ----------------------------------------------------------
    def _spawn(self, w: _Worker):
        """Launch one worker generation and block until its hello."""
        w.generation += 0 if w.proc is None else 1
        gen = w.generation
        port = _free_port()
        portfile = os.path.join(self.workdir, f"{w.name}.g{gen}.port")
        snapdir = os.path.join(self.workdir, "snapshots", w.name)
        os.makedirs(snapdir, exist_ok=True)
        logpath = os.path.join(self.workdir, f"{w.name}.g{gen}.log")
        log = open(logpath, "ab")
        idx = self._workers.index(w) if w in self._workers \
            else len(self._workers)
        names = [wk.name for wk in self._workers] or [w.name]
        endpoints = ",".join(f"127.0.0.1:{port}" for _ in names)
        env = _rank_env(os.environ, rank=idx, local_rank=idx,
                        world=len(names), master=f"127.0.0.1:{port}",
                        endpoints=endpoints, nnodes=1, node_rank=0)
        env.setdefault("JAX_PLATFORMS", "cpu")
        # the worker must import the same paddle_tpu tree regardless of
        # the supervisor's cwd
        pkg_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        cmd = [self.python, "-m", "paddle_tpu.serving.worker",
               "--name", w.name, "--spec", self._spec_path,
               "--portfile", portfile, "--port", str(port),
               "--snapshot-root", snapdir,
               "--snapshot-every", str(self.snapshot_every),
               "--snapshot-mode", self.snapshot_mode]
        w.proc = subprocess.Popen(cmd, stdout=log, stderr=log, env=env)
        w.log = log
        w.pid = w.proc.pid
        w.port = port
        w.missed = 0
        w.trace_cache = None
        self._c_spawns.inc()
        self.flight.record("spawn", worker=w.name, generation=gen,
                           pid=w.pid, port=port)
        deadline = time.monotonic() + self.spawn_timeout
        while not os.path.exists(portfile):
            if w.proc.poll() is not None:
                raise WorkerDiedError(
                    f"worker {w.name} gen {gen} exited rc={w.proc.returncode}"
                    f" before binding (log: {logpath})")
            if time.monotonic() > deadline:
                w.proc.kill()
                raise WorkerDiedError(
                    f"worker {w.name} gen {gen} never bound within "
                    f"{self.spawn_timeout}s (log: {logpath})")
            time.sleep(0.02)
        w.client = RpcClient(("127.0.0.1", port),
                             attempt_timeout=max(1.0, self.heartbeat_timeout),
                             call_timeout=self.spawn_timeout)
        hello = w.client.call(
            "hello", deadline_s=max(5.0, deadline - time.monotonic()))
        w.alive = True
        w.routable = True
        self.elastic.register(w.key())
        self.tracer.engine_event("spawn", worker=w.name, generation=gen,
                                 pid=w.pid)
        return hello

    # -- request surface ---------------------------------------------------
    def submit(self, prompt, max_new_tokens: int = 32,
               temperature: float = 0.0, top_p: float = 1.0,
               eos_token_id: int | None = None,
               timeout: float | None = None, on_token=None,
               trace_id: int | None = None) -> int:
        """Queue one request with the fleet; same contract as
        :meth:`ReplicaFleet.submit` (router-authoritative streaming,
        least-loaded placement, bounded waiting queue backpressure)."""
        if self.closed:
            raise RuntimeError("ProcessFleet is shut down")
        prompt = np.asarray(prompt, np.int32).reshape(-1)
        now = self.clock()
        fr = _ProcRequest(
            frid=self._next_frid, prompt=prompt,
            kw=dict(max_new_tokens=int(max_new_tokens),
                    temperature=float(temperature), top_p=float(top_p),
                    eos_token_id=eos_token_id),
            deadline=None if timeout is None else now + float(timeout),
            submit_t=now, on_token=on_token,
            trace_id=new_trace_id() if trace_id is None else int(trace_id))
        self._next_frid += 1
        self.flight.record("submit", frid=fr.frid,
                           prompt_tokens=len(prompt), trace_id=fr.trace_id)
        self.tracer.request_event(fr.frid, "submitted", t=now,
                                  prompt_tokens=len(prompt),
                                  trace_id=fr.trace_id)
        self.tracer.request_event(fr.frid, "queued", t=now,
                                  depth=len(self._waiting))
        try:
            placed = self._place(fr)
        except BaseException:
            self.tracer.request_event(fr.frid, "retired", rejected=True,
                                      error=True, tokens=0)
            raise
        if not placed:
            if self.max_queue is not None \
                    and len(self._waiting) >= self.max_queue:
                self.tracer.request_event(fr.frid, "retired",
                                          rejected=True, tokens=0)
                raise AdmissionRejected(
                    f"fleet queue full ({len(self._waiting)}/"
                    f"{self.max_queue} waiting)")
            fr.next_try_round = self._round + 1
            self._waiting.append(fr)
        self._requests[fr.frid] = fr
        self._c_submitted.inc()
        return fr.frid

    def cancel(self, frid: int) -> bool:
        """Client disconnect: drop the request everywhere — fleet queue,
        router record, and (best-effort RPC) the worker engine, whose KV
        parks in its prefix cache."""
        fr = self._requests.pop(frid, None)
        if fr is None:
            return False
        self._waiting = [x for x in self._waiting if x.frid != frid]
        if fr.worker is not None:
            self._assigned.get(fr.worker, set()).discard(frid)
            w = self._by_name(fr.worker)
            if w is not None and w.alive and fr.rid is not None:
                try:
                    w.client.call("cancel", rid=int(fr.rid), deadline_s=5.0)
                except RpcError:
                    pass     # a dead/wedged worker's failover sweeps it
        self.flight.record("cancel", frid=frid, streamed=len(fr.streamed))
        self.tracer.request_event(frid, "retired", cancelled=True,
                                  tokens=len(fr.streamed))
        return True

    # -- placement ---------------------------------------------------------
    def _by_name(self, name: str) -> _Worker | None:
        for w in self._workers:
            if w.name == name:
                return w
        return None

    def _routable(self) -> list[_Worker]:
        return [w for w in self._workers if w.alive and w.routable]

    def _backoff(self, fr: _ProcRequest):
        fr.retries += 1
        fr.next_try_round = self._round + min(
            self.max_backoff_rounds,
            self.retry_backoff_rounds * (2 ** min(fr.retries, 10)))

    def _place(self, fr: _ProcRequest) -> bool:
        cands = {w.name: w for w in self._routable()}
        if not cands:
            return False
        loads = [(n, w.load + len(self._assigned.get(n, ())))
                 for n, w in cands.items()]
        tokens = fr.prompt if not fr.streamed else np.concatenate(
            [fr.prompt, np.asarray(fr.streamed[:-1], np.int32)])
        decision = self.router.decide(tokens, loads, memo={})
        for name in decision.order:
            w = cands.get(name)
            if w is None:
                continue
            try:
                rid = w.client.call(
                    "adopt", prompt=[int(t) for t in fr.prompt],
                    generated=[int(t) for t in fr.streamed],
                    deadline=fr.deadline, trace_id=fr.trace_id,
                    deadline_s=10.0, **fr.kw)
            except RpcRemoteError as e:
                if e.etype == "AdmissionRejected":
                    continue
                if e.etype == "PoolCapacityError":
                    raise PoolCapacityError(e.emsg) from e
                raise
            except RpcError:
                # unreachable worker: not a placement verdict — the
                # health loop owns its fate; try the next candidate
                continue
            fr.worker = w.name
            fr.rid = int(rid)
            self._assigned[w.name].add(fr.frid)
            self.flight.record("route", frid=fr.frid, worker=w.name,
                               resumed_tokens=len(fr.streamed),
                               routing=decision.kind,
                               trace_id=fr.trace_id)
            self.tracer.request_event(fr.frid, "admitted", replica=w.name,
                                      routing=decision.kind,
                                      resumed_tokens=len(fr.streamed))
            return True
        return False

    # -- the supervisor loop ----------------------------------------------
    def step(self) -> bool:
        """One supervisor round: reap dead processes (SIGCHLD flag or
        poll()), health-probe every live worker (heartbeat timeouts count
        toward the wedge verdict; SIGKILL past the budget), drain new
        tokens into the authoritative log, retry queued placements."""
        self._round += 1
        progressed = False
        # 1. reap real deaths
        if self._sigchld or True:    # poll() is the portable reap; the
            self._sigchld = False    # SIGCHLD flag just makes it prompt
            for w in list(self._workers):
                if w.alive and w.proc is not None \
                        and w.proc.poll() is not None:
                    self._fail(w, "crash",
                               WorkerDiedError(
                                   f"{w.name} rc={w.proc.returncode}"))
                    progressed = True
        # 2. placements whose backoff expired
        for fr in list(self._waiting):
            if fr.next_try_round > self._round:
                continue
            if self._place(fr):
                self._waiting.remove(fr)
                progressed = True
            else:
                self._backoff(fr)
        # 3. health + token drain
        for w in list(self._workers):
            if not w.alive:
                continue
            try:
                h = w.client.call("health",
                                  deadline_s=self.heartbeat_timeout)
            except RpcError as e:
                if w.proc.poll() is not None:
                    self._fail(w, "crash", e)
                    progressed = True
                    continue
                w.missed += 1
                self.flight.record("missed_heartbeat", worker=w.name,
                                   missed=w.missed)
                if w.missed >= self.wedge_heartbeats:
                    # an unresponsive-but-running process (SIGSTOP, a
                    # livelock): kill it for real, then fail over
                    try:
                        os.kill(w.pid, signal.SIGKILL)
                    except OSError:
                        pass
                    w.proc.wait(timeout=10)
                    self._fail(w, "wedge", EngineStalledError(
                        f"{w.name}: {w.missed} consecutive heartbeat "
                        f"timeouts with work pending"))
                    progressed = True
                continue
            w.missed = 0
            w.hb = h.get("hb", 0)
            w.load = int(h["load"]["active"]) + int(h["load"]["queued"])
            self.elastic.heartbeat(w.key())
            if not h.get("invariants_ok", True):
                self.flight.record("invariants_violated", worker=w.name,
                                   error=h.get("invariants_error", ""))
            if self.trace_every and self._round % self.trace_every == 0:
                self._fetch_trace(w)
            if self._assigned.get(w.name):
                progressed |= self._drain(w)
        return progressed

    def _fetch_trace(self, w: _Worker):
        try:
            w.trace_cache = w.client.call(
                "trace", deadline_s=self.heartbeat_timeout)
        except RpcError:
            pass

    def _drain(self, w: _Worker) -> bool:
        have = {}
        frid_by_rid: dict[str, _ProcRequest] = {}
        for frid in sorted(self._assigned[w.name]):
            fr = self._requests[frid]
            rid_s = str(fr.rid)
            have[rid_s] = len(fr.streamed)
            frid_by_rid[rid_s] = fr
        try:
            rep = w.client.call("poll", have=have,
                                deadline_s=self.heartbeat_timeout)
        except RpcError:
            return False             # health loop owns the verdict
        now = self.clock()
        progressed = False
        for rid_s, st in rep.get("rids", {}).items():
            fr = frid_by_rid.get(rid_s)
            if fr is None or st is None:
                continue
            new = st.get("new", ())
            # `new` answers the have-count we sent THIS call; an
            # idempotency-cache replay can therefore never double-extend
            if new:
                if fr.first_token_t is None:
                    fr.first_token_t = now
                    self.tracer.request_event(fr.frid, "first_token",
                                              t=now, replica=w.name)
                for t in new:
                    fr.streamed.append(int(t))
                    self.tokens_streamed += 1
                    if fr.on_token is not None:
                        fr.on_token(int(t))
                progressed = True
            if st.get("done"):
                self._resolve(fr, now, timed_out=bool(st.get("timed_out")))
                progressed = True
        return progressed

    def _resolve(self, fr: _ProcRequest, now: float,
                 timed_out: bool = False):
        kw = fr.kw
        req = Request(rid=fr.frid, prompt=fr.prompt,
                      max_new_tokens=kw["max_new_tokens"],
                      temperature=kw["temperature"], top_p=kw["top_p"],
                      eos_token_id=kw["eos_token_id"],
                      generated=list(fr.streamed),
                      submit_time=fr.submit_t)
        req.finish_time = now
        req.timed_out = timed_out
        fr.result = req
        if fr.worker is not None:
            self._assigned.get(fr.worker, set()).discard(fr.frid)
        self._c_resolved.inc()
        self.flight.record("resolve", frid=fr.frid,
                           tokens=len(fr.streamed), timed_out=timed_out,
                           migrations=fr.migrations)
        self.tracer.request_event(fr.frid, "retired", t=now,
                                  tokens=len(fr.streamed),
                                  timed_out=timed_out,
                                  migrations=fr.migrations)

    # -- failover ----------------------------------------------------------
    def _fail(self, w: _Worker, kind: str, exc: BaseException):
        """A worker process died (or was just SIGKILLed for wedging).
        Unroutable mark happens FIRST — nothing can be placed on (or
        polled from) this generation once the failover decision is made —
        then spawn a replacement on the same snapshot directory, reattach
        what the snapshot carries, migrate the rest."""
        t0 = self.clock()
        w.routable = False
        w.alive = False
        w.missed = 0
        self._c_failovers.inc()
        self.elastic.deregister(w.key())
        dead_key = w.key()
        if w.trace_cache is not None:
            # one entry per worker death — failover forensics, read
            # whole by the stitched export  # graftlint: disable=LEAK001
            self._dead_tracers.append(
                (f"{w.name} (crashed#{self.restarts[w.name] + 1})",
                 tracer_from_wire(w.trace_cache, clock=self.clock)))
        try:
            w.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            w.proc.kill()
        if w.log is not None:
            w.log.close()
            w.log = None
        if w.client is not None:
            w.client.close()
        self.flight.record("failover", worker=w.name, kind=kind,
                           rc=w.proc.returncode, error=str(exc)[:200],
                           fault_plan=fault_context())
        self.tracer.engine_event("failover", worker=w.name, kind=kind)
        routing = [e for e in self.flight.events()
                   if e["event"] in ("route", "migrate")]
        self.flight.dump("proc_failover", worker=w.name, kind=kind,
                         routing_decisions=routing[-16:])
        outstanding = [self._requests[f]
                       for f in sorted(self._assigned[w.name])]
        # keyed by worker name: bounded by fleet size
        # graftlint: disable=LEAK001
        self._assigned[w.name] = set()

        restored_rids: set[int] = set()
        replaced = False
        if self.restarts[w.name] < self.max_restarts_per_worker:
            self.restarts[w.name] += 1
            self._c_restarts.inc()
            try:
                hello = self._spawn(w)
                replaced = True
            except WorkerDiedError as e:
                self.flight.record("respawn_failed", worker=w.name,
                                   error=str(e)[:200])
            else:
                restored_rids = {int(r) for r in hello["restored_rids"]}
                # the dead generation's final invariants verdict, vouched
                # by its replacement's post-restore check over the state
                # the generation actually persisted
                # keyed per spawned generation — every generation must
                # file a report (ISSUE 17 gate)
                # graftlint: disable=LEAK001
                self.final_reports[dead_key] = {
                    "invariants_ok": bool(hello["restore_invariants_ok"]),
                    "invariants_error": hello.get("restore_error", ""),
                    "kind": f"killed:{kind}", "via": "replacement_restore"}
                self.flight.record(
                    "restore", worker=w.name,
                    mode=hello.get("restored_mode"),
                    requests=len(restored_rids))
        if not replaced:
            self.final_reports.setdefault(dead_key, {
                "invariants_ok": None, "kind": f"killed:{kind}",
                "via": "unverified (restart budget exhausted)"})

        still: list[_ProcRequest] = []
        kept: set[int] = set()
        for fr in outstanding:
            if replaced and fr.rid is not None and fr.rid in restored_rids \
                    and fr.kw["temperature"] <= 0.0:
                # the snapshot carries this GREEDY request — it continues
                # on the replacement; re-decoded tokens are bit-identical
                # to ones already streamed so the log only extends.
                # Sampled requests must NOT resume from a stale snapshot
                # (re-sampling diverges from streamed tokens) — migrated.
                fr.worker = w.name
                self._assigned[w.name].add(fr.frid)
                kept.add(fr.rid)
            else:
                still.append(fr)
        if replaced:
            for rid in sorted(restored_rids - kept):
                try:
                    w.client.call("cancel", rid=rid, deadline_s=10.0)
                except RpcError:
                    pass
        for fr in still:
            fr.worker = None
            fr.rid = None
            self._migrate(fr)
        if not self._routable() and any(fr.result is None
                                        for fr in self._requests.values()):
            raise FleetFailedError(
                f"no live workers left ({len(self._requests)} requests "
                f"tracked, restart budget "
                f"{self.max_restarts_per_worker}/worker exhausted)")
        self._h_recovery.observe(self.clock() - t0)

    def _migrate(self, fr: _ProcRequest):
        self._c_migrations.inc()
        fr.migrations += 1
        self.flight.record("migrate", frid=fr.frid,
                           tokens=len(fr.streamed), trace_id=fr.trace_id,
                           fault_plan=fault_context())
        self.tracer.request_event(fr.frid, "preempted", kind="migrate",
                                  tokens=len(fr.streamed))
        kw = fr.kw
        eos = kw["eos_token_id"]
        if fr.streamed and (len(fr.streamed) >= kw["max_new_tokens"]
                            or (eos is not None and eos in fr.streamed)):
            # completion edge: everything streamed before the death;
            # synthesize the result from the authoritative log
            self._resolve(fr, self.clock())
            return
        if not self._place(fr):
            self._backoff(fr)
            self._waiting.append(fr)

    # -- drain ladder (PR 14, across the wire) -----------------------------
    def retire_worker(self, name: str):
        """Zero-loss scale-down of one worker: mark unroutable (nothing
        new lands), live-migrate its streams to surviving workers, then
        ``drain`` + ``stop`` — the worker's final reply is its teardown
        invariants report — and reap the process."""
        w = self._by_name(name)
        if w is None or not w.alive:
            raise ValueError(f"no live worker {name!r}")
        if len(self._routable()) <= 1 and self._assigned.get(name):
            raise RuntimeError("cannot retire the last routable worker "
                               "with live requests")
        w.routable = False
        self.flight.record("retire", worker=name)
        self._fetch_trace(w)
        for frid in sorted(self._assigned[name]):
            fr = self._requests[frid]
            try:
                w.client.call("cancel", rid=int(fr.rid), deadline_s=10.0)
            except RpcError:
                pass
            fr.worker = None
            fr.rid = None
            self._migrate(fr)
        self._assigned[name] = set()
        self._stop_worker(w, kind="retired")

    def _stop_worker(self, w: _Worker, kind: str):
        try:
            report = w.client.call("stop", deadline_s=30.0)
        except RpcError as e:
            report = {"invariants_ok": None,
                      "invariants_error": f"stop rpc failed: {e}"}
        self.final_reports[w.key()] = dict(report, kind=kind)
        self.elastic.deregister(w.key())
        if w.trace_cache is not None:
            self._dead_tracers.append(
                (f"{w.name} ({kind})",
                 tracer_from_wire(w.trace_cache, clock=self.clock)))
        try:
            w.proc.wait(timeout=15)
        except subprocess.TimeoutExpired:
            w.proc.kill()
            w.proc.wait(timeout=5)
        w.alive = False
        w.routable = False
        if w.log is not None:
            w.log.close()
            w.log = None
        if w.client is not None:
            w.client.close()
        self.tracer.engine_event("scale_down", worker=w.name)

    # -- driving -----------------------------------------------------------
    # ProcessFleet supervision is deliberately single-threaded (workers are
    # PROCESSES; the supervisor polls their clients in one loop): owner=main
    # makes handing this state to a thread a THREAD001 violation
    def run(self, max_rounds: int | None = None,  # graftlint: owner=main
            max_stall_rounds: int = 2000) -> dict:
        """Drive until every request resolved (or SIGTERM: drain + stop).
        Returns ``{frid: Request}``."""
        stalled = 0
        rounds = 0
        while any(fr.result is None for fr in self._requests.values()):
            if self._terminate and not self._in_shutdown:
                self.shutdown(drain=True)
                break
            progressed = self.step()
            if progressed:
                stalled = 0
            else:
                stalled += 1
                time.sleep(0.005)
            if stalled >= max_stall_rounds:
                raise EngineStalledError(
                    f"process fleet made no progress for {stalled} rounds "
                    f"({sum(fr.result is None for fr in self._requests.values())}"
                    f" unresolved, {len(self._waiting)} waiting)")
            rounds += 1
            if max_rounds is not None and rounds >= max_rounds:
                break
        if self._terminate and not self._in_shutdown and not self.closed:
            # SIGTERM observed with nothing left to drain: finish the
            # ladder (per-worker stop + final invariants reports)
            self.shutdown(drain=True)
        return self.results()

    def results(self) -> dict:
        return {frid: fr.result for frid, fr in self._requests.items()
                if fr.result is not None}

    def shutdown(self, drain: bool = True, force: bool = False):
        """Stop the fleet.  ``drain=True`` finishes the live streams
        first (zero-loss); every surviving worker then tears down through
        ``stop`` and files its final invariants report.  ``force=True``
        SIGKILLs everything (leak-guard salvage path only)."""
        if self.closed:
            return
        self._in_shutdown = True
        if force:
            for w in self._workers:
                if w.proc is not None and w.proc.poll() is None:
                    w.proc.kill()
                    w.proc.wait(timeout=5)
                w.alive = False
                w.routable = False
                if w.log is not None:
                    w.log.close()
                    w.log = None
                self.final_reports.setdefault(w.key(), {
                    "invariants_ok": None, "kind": "force_killed"})
            self.closed = True
            self._restore_signals()
            return
        if drain and any(fr.result is None
                         for fr in self._requests.values()):
            self.run(max_stall_rounds=2000)
        for w in list(self._workers):
            if w.alive:
                self._fetch_trace(w)
                self._stop_worker(w, kind="shutdown")
        self.closed = True
        self._restore_signals()

    # -- leak guard --------------------------------------------------------
    def assert_worker_invariants(self):
        """Every spawned worker generation must have filed a final
        invariants report that holds — directly (stop/retire/shutdown) or
        through its replacement's post-restore check (killed mid-drill).
        The conftest cross-process leak guard calls this after every
        test that built a ProcessFleet."""
        assert self.closed, "ProcessFleet was never shut down"
        missing = []
        for w in self._workers:
            for gen in range(w.generation + 1):
                key = f"{w.name}#{gen}"
                rep = self.final_reports.get(key)
                if rep is None:
                    missing.append(f"{key}: no final report")
                elif rep.get("invariants_ok") is not True:
                    missing.append(
                        f"{key}: invariants_ok={rep.get('invariants_ok')} "
                        f"({rep.get('invariants_error', '')[:160]} "
                        f"via {rep.get('via', rep.get('kind', '?'))})")
        assert not missing, \
            "cross-process leak guard: " + "; ".join(missing)

    # -- readouts ----------------------------------------------------------
    def stats(self) -> dict:
        q = self._h_recovery.percentiles()
        rpc = {"calls": 0, "retries": 0, "timeouts": 0, "reconnects": 0}
        for w in self._workers:
            if w.client is not None:
                for k in rpc:
                    rpc[k] += w.client.stats[k]
        return {
            "workers": len(self._workers),
            "workers_alive": sum(1 for w in self._workers if w.alive),
            "workers_routable": len(self._routable()),
            "members": self.elastic.members(),
            "failovers": self._c_failovers.value,
            "migrations": self._c_migrations.value,
            "spawns": self._c_spawns.value,
            "restarts": self._c_restarts.value,
            "worker_restarts": dict(self.restarts),
            "requests_submitted": self._c_submitted.value,
            "requests_resolved": self._c_resolved.value,
            "tokens_streamed": self.tokens_streamed,
            "waiting": len(self._waiting),
            "rpc": rpc,
            "recovery": {"count": self._h_recovery.count,
                         "p50_ms": round(q[50] * 1e3, 3),
                         "p95_ms": round(q[95] * 1e3, 3),
                         "p99_ms": round(q[99] * 1e3, 3),
                         "max_ms": round(self._h_recovery.max * 1e3, 3)
                         if self._h_recovery.count else 0.0},
            "per_worker": {w.name: {"pid": w.pid, "generation": w.generation,
                                    "alive": w.alive,
                                    "routable": w.routable,
                                    "load": w.load, "hb": w.hb,
                                    "restarts": self.restarts[w.name]}
                           for w in self._workers},
        }

    def trace_components(self) -> list:
        """(name, Tracer) components for the stitched cross-process
        trace: the supervisor track, dead/retired generations, then a
        fresh fetch from every live worker."""
        comps: list = [("supervisor", self.tracer)]
        comps.extend(self._dead_tracers)
        for w in self._workers:
            if w.alive:
                self._fetch_trace(w)
            if w.trace_cache is not None and w.alive:
                comps.append((w.name,
                              tracer_from_wire(w.trace_cache,
                                               clock=self.clock)))
        return comps

    def stitcher(self) -> TraceStitcher:
        st = TraceStitcher()
        for name, tracer in self.trace_components():
            st.add(name, tracer)
        return st

    def stitched_trace(self) -> dict:
        """ONE Perfetto view of every request across the supervisor track
        and every worker PROCESS track, failovers included."""
        return self.stitcher().to_chrome_trace()

    def metrics_snapshot(self) -> dict:
        return self.metrics.snapshot()
