"""Traffic harness: seeded, replayable serving scenarios at 10k+ scale.

ROADMAP item 4's scenario-diversity prerequisite: every multi-chip /
quantized / elastic-fleet PR needs a *gate* shaped like "goodput-under-SLO
on realistic traffic", and that needs traffic that is (a) realistic —
bursty and diurnal arrival processes, shared-prefix user fleets, mixed
greedy/sampled/long-context requests, streaming clients that abandon
mid-decode — and (b) REPLAYABLE: one integer seed pins the entire
scenario (arrival schedule, prompts, sampling params, abandon points)
with zero wall-clock leakage, so two policies, two engines, or two PRs
can be compared on the identical offered load.

Three layers:

  * :func:`make_scenario` — pure generation: a :class:`Scenario` is a
    list of :class:`ClientRequest` rows derived from ONE
    ``np.random.default_rng(seed)`` stream.  ``Scenario.signature()``
    SHA-256-fingerprints every replay-relevant byte (the determinism
    tests pin ``make_scenario(seed) == make_scenario(seed)`` through it).
  * :func:`replay_engine` — drive a real :class:`ServingEngine` through a
    scenario.  Arrivals are paced in TOKEN TIME (request i is submitted
    once the engine has generated ``arrival_s * load_tps`` tokens —
    machine-independent offered load, the same trick bench.py's serving
    trace uses), admission goes through an
    :class:`~paddle_tpu.serving.frontend.AdmissionController`, and
    abandon clients cancel their request mid-decode through the engine's
    ``cancel()`` (deferred to the step boundary: ``on_token`` fires
    inside the drain and must never re-enter the engine).
  * :func:`replay_sim` — the same scenario against an analytic
    S-slot server model on a VIRTUAL clock: no jax, no wall time,
    deterministic to the last float.  It exercises the real
    :class:`~paddle_tpu.serving.frontend.AdmissionController` /
    :class:`~paddle_tpu.serving.frontend.TTFTPredictor` code path at
    10k+ requests in well under a second — the scale the tier-1 lane
    cannot afford to push through a real engine (that replay is
    slow-marked).
"""
from __future__ import annotations

import hashlib
import heapq
import math
from dataclasses import dataclass, field

import numpy as np

__all__ = ["ClientRequest", "Scenario", "VirtualClock", "make_scenario",
           "replay_engine", "replay_fleet", "replay_sim", "goodput_report"]


class VirtualClock:
    """Round-driven virtual time for fleet replays.

    On a shared host every replica time-slices one CPU, so wall-clock
    fleet economics are a lie: an N-replica fleet's heartbeat costs ~N×
    the wall time of a 1-replica fleet's, which would bill the elastic
    arm for parallelism the simulation cannot express.  The virtual
    clock models the real deployment instead — each replica is its own
    machine, all stepping CONCURRENTLY — by advancing a fixed ``dt``
    per fleet round regardless of replica count.  Inject it as the
    fleet's ``clock=`` (request timestamps, TTFT, ``replica_seconds``
    all move to the virtual domain) and hand it to
    :func:`replay_fleet` (arrival pacing + idle jumps); every metric the
    elastic A/B gates on then becomes DETERMINISTIC: same seed, same
    scale-event timeline, same goodput-per-replica-hour, on any host."""

    def __init__(self, dt: float = 1.0):
        self.t = 0.0
        self.dt = float(dt)

    def __call__(self) -> float:
        return self.t

    def tick(self):
        self.t += self.dt

    def advance_to(self, t: float):
        self.t = max(self.t, float(t))


@dataclass
class ClientRequest:
    """One scenario row: everything a replay needs to submit (and maybe
    abandon) the request.  ``arrival_s`` is on the SCENARIO clock —
    replays map it to token time (engine) or a virtual clock (sim)."""
    idx: int
    arrival_s: float
    prompt: np.ndarray                 # int32 [T]
    max_new_tokens: int
    temperature: float = 0.0
    top_p: float = 1.0
    slo_ttft_s: float | None = None    # per-request TTFT deadline override
    abandon_after: int | None = None   # client disconnects after streaming
                                       #   this many tokens (None: stays)
    user: int | None = None            # shared-prefix fleet user id
    kind: str = "short"                # short | long | sampled


@dataclass
class Scenario:
    """A named, seeded batch of :class:`ClientRequest` rows (arrival-time
    ordered) plus the generation knobs that produced them."""
    name: str
    seed: int
    requests: list[ClientRequest] = field(default_factory=list)
    meta: dict = field(default_factory=dict)

    def __len__(self) -> int:
        return len(self.requests)

    @property
    def duration_s(self) -> float:
        return self.requests[-1].arrival_s if self.requests else 0.0

    def offered_tokens(self) -> int:
        return sum(r.max_new_tokens for r in self.requests)

    def signature(self) -> str:
        """SHA-256 over every replay-relevant field of every request —
        identical seeds MUST yield identical signatures (the determinism
        contract; no wall clock, host, or dict-order leakage)."""
        h = hashlib.sha256()
        h.update(self.name.encode())
        h.update(str(self.seed).encode())
        for r in self.requests:
            h.update(np.float64(r.arrival_s).tobytes())
            h.update(np.ascontiguousarray(r.prompt, np.int32).tobytes())
            h.update(np.int64(r.max_new_tokens).tobytes())
            h.update(np.float64(r.temperature).tobytes())
            h.update(np.float64(r.top_p).tobytes())
            h.update(np.float64(-1.0 if r.slo_ttft_s is None
                                else r.slo_ttft_s).tobytes())
            h.update(np.int64(-1 if r.abandon_after is None
                              else r.abandon_after).tobytes())
            h.update(np.int64(-1 if r.user is None else r.user).tobytes())
            h.update(r.kind.encode())
        return h.hexdigest()


def _arrivals(rng, n: int, arrival: str, mean_interarrival_s: float,
              burst_every_s: float, burst_size: int, burst_spread_s: float,
              diurnal_period_s: float, diurnal_amplitude: float):
    """Arrival offsets (seconds, sorted, starting at 0) for the three
    supported processes.

      * ``poisson`` — homogeneous: exp(mean) inter-arrivals.
      * ``bursty``  — the poisson base plus a burst of ``burst_size``
        arrivals every ``burst_every_s``, packed into ``burst_spread_s``
        (flash-crowd traffic; the burst members come out of the SAME
        request budget ``n``, so offered totals stay comparable across
        processes).
      * ``diurnal`` — non-homogeneous poisson with rate(t) = base *
        (1 + amplitude * sin(2*pi*t / period)), via per-step thinning of
        the instantaneous rate (peak/trough traffic over one or more
        simulated days, squeezed to ``period``).
    """
    if n <= 0:
        return np.zeros((0,), np.float64)
    if arrival == "poisson":
        gaps = rng.exponential(mean_interarrival_s, n)
        gaps[0] = 0.0
        return np.cumsum(gaps)
    if arrival == "bursty":
        n_bursts = max(1, int(n // max(1, 4 * burst_size)))
        n_burst_reqs = min(n - 1, n_bursts * burst_size)
        n_base = n - n_burst_reqs
        gaps = rng.exponential(mean_interarrival_s, n_base)
        gaps[0] = 0.0
        base = np.cumsum(gaps)
        ts = [base]
        for b in range(n_bursts):
            t0 = (b + 1) * burst_every_s
            k = min(burst_size, n_burst_reqs - b * burst_size)
            if k <= 0:
                break
            ts.append(t0 + np.sort(rng.uniform(0.0, burst_spread_s, k)))
        return np.sort(np.concatenate(ts))[:n]
    if arrival == "diurnal":
        base_rate = 1.0 / mean_interarrival_s
        out = np.empty((n,), np.float64)
        t = 0.0
        # thinning: draw from the PEAK rate, accept with rate(t)/peak
        peak = base_rate * (1.0 + diurnal_amplitude)
        i = 0
        out[0] = 0.0
        i = 1
        while i < n:
            t += rng.exponential(1.0 / peak)
            rate = base_rate * (1.0 + diurnal_amplitude
                                * math.sin(2.0 * math.pi * t
                                           / diurnal_period_s))
            if rng.uniform() * peak <= max(rate, 1e-9):
                out[i] = t
                i += 1
        return out
    raise ValueError(f"unknown arrival process {arrival!r} "
                     f"(expected poisson | bursty | diurnal)")


def make_scenario(name: str, *, seed: int, n_requests: int, vocab: int,
                  arrival: str = "poisson",
                  mean_interarrival_s: float = 0.5,
                  burst_every_s: float = 10.0, burst_size: int = 8,
                  burst_spread_s: float = 0.25,
                  diurnal_period_s: float = 60.0,
                  diurnal_amplitude: float = 0.9,
                  prompt_len: tuple[int, int] = (8, 48),
                  max_new: tuple[int, int] = (8, 24),
                  long_context_frac: float = 0.0,
                  long_prompt_len: tuple[int, int] = (96, 160),
                  sampled_frac: float = 0.0,
                  shared_prefix_users: int = 0,
                  system_prompt_len: int = 32,
                  abandon_frac: float = 0.0,
                  abandon_range: tuple[int, int] = (2, 8),
                  slo_ttft_s: float | None = None) -> Scenario:
    """Generate one seeded scenario.  EVERY random draw comes from the one
    ``np.random.default_rng(seed)`` stream in a fixed order, and nothing
    reads a clock — ``make_scenario(seed=s, ...)`` is a pure function of
    its arguments (see :meth:`Scenario.signature`).

    ``shared_prefix_users=U`` gives the scenario a U-user fleet sharing
    one system prompt: each request's prompt is ``system + user-history +
    fresh turn``, and a user's history grows with every request they send
    (the multi-turn shape the prefix cache exists for).  ``sampled_frac``
    marks that fraction temperature>0 (they ride the same engine but are
    excluded from greedy bit-equality checks); ``long_context_frac``
    draws that fraction's prompt from ``long_prompt_len``.
    ``abandon_frac`` marks streaming clients that disconnect after
    ``abandon_range`` tokens — a replay must turn each into an
    ``engine.cancel()`` mid-decode."""
    rng = np.random.default_rng(seed)
    at = _arrivals(rng, n_requests, arrival, mean_interarrival_s,
                   burst_every_s, burst_size, burst_spread_s,
                   diurnal_period_s, diurnal_amplitude)
    system = rng.integers(1, vocab, (system_prompt_len,)).astype(np.int32) \
        if shared_prefix_users > 0 else None
    histories = [[] for _ in range(max(0, shared_prefix_users))]
    reqs: list[ClientRequest] = []
    for i in range(n_requests):
        is_long = rng.uniform() < long_context_frac
        lo, hi = long_prompt_len if is_long else prompt_len
        t_len = int(rng.integers(lo, hi))
        user = None
        if shared_prefix_users > 0 and not is_long:
            user = int(rng.integers(0, shared_prefix_users))
            turn = rng.integers(1, vocab, (t_len,)).astype(np.int32)
            prompt = np.concatenate(
                [system, np.asarray(histories[user], np.int32), turn])
            histories[user].extend(int(t) for t in turn)
        else:
            prompt = rng.integers(1, vocab, (t_len,)).astype(np.int32)
        mn = int(rng.integers(max_new[0], max_new[1]))
        sampled = rng.uniform() < sampled_frac
        abandon = None
        if rng.uniform() < abandon_frac:
            # clamp BOTH bounds into [1, mn]: a short request must not
            # crash generation when abandon_range sits above its budget
            a_lo = max(1, min(abandon_range[0], mn))
            a_hi = max(a_lo, min(abandon_range[1], mn))
            abandon = int(rng.integers(a_lo, a_hi + 1))
        reqs.append(ClientRequest(
            idx=i, arrival_s=float(at[i]), prompt=prompt,
            max_new_tokens=mn,
            temperature=0.7 if sampled else 0.0,
            top_p=0.9 if sampled else 1.0,
            slo_ttft_s=slo_ttft_s, abandon_after=abandon, user=user,
            kind="sampled" if sampled else ("long" if is_long else "short")))
    return Scenario(name=name, seed=int(seed), requests=reqs, meta=dict(
        arrival=arrival, n_requests=n_requests, vocab=vocab,
        mean_interarrival_s=mean_interarrival_s,
        shared_prefix_users=shared_prefix_users,
        sampled_frac=sampled_frac, long_context_frac=long_context_frac,
        abandon_frac=abandon_frac, slo_ttft_s=slo_ttft_s))


def goodput_report(records: list[dict], slo_ttft_s: float,
                   window_s: float | None = None) -> dict:
    """Goodput-under-SLO over OFFERED requests: a request is good iff it
    was admitted and its first token arrived within ``slo_ttft_s`` of
    submission.  Rejected requests count in the denominator (an admission
    policy cannot improve its goodput by rejecting everything), abandoned
    clients count like any other (their first token either met the SLO or
    did not).  Delegates the quantile shape to the shared
    :func:`~paddle_tpu.observability.slo.slo_report` so artifacts stay
    schema-compatible with every other serving trace."""
    from ..observability.slo import slo_report
    summaries = []
    for r in records:
        summaries.append({
            "rid": r.get("idx"),
            "tokens": int(r.get("tokens", 0)),
            "ttft_s": r.get("ttft_s"),
            "tpot_s": r.get("tpot_s"),
            "e2e_s": r.get("e2e_s"),
            "timed_out": bool(r.get("timed_out")),
        })
    rep = slo_report(summaries, slo_ttft_s, window_s=window_s)
    n = len(records)
    rejected = sum(1 for r in records if r.get("rejected"))
    abandoned = sum(1 for r in records if r.get("abandoned"))
    rep["offered_requests"] = n
    rep["rejected_requests"] = rejected
    rep["abandoned_requests"] = abandoned
    rep["goodput_under_slo"] = round(rep["on_time_requests"] / n, 4) \
        if n else 0.0
    return rep


def replay_engine(engine, scenario: Scenario, controller=None, *,
                  load_tps: float, slo_ttft_s: float,
                  collect_tokens: bool = False,
                  max_stall_steps: int = 2000) -> dict:
    """Drive a real ServingEngine through ``scenario``.

    Arrivals are paced in token time: request i is submitted once the
    engine has generated ``arrival_s * load_tps`` tokens since the replay
    began (``load_tps`` converts the scenario clock into offered load
    relative to THIS machine's measured capacity — the same offered load
    reaches a fast TPU and a slow CI host).  Admission goes through
    ``controller`` (an
    :class:`~paddle_tpu.serving.frontend.AdmissionController`; None =
    admit-always).  Abandon clients stream through ``on_token`` and
    cancel at their scenario-pinned token count — the cancel itself runs
    at the step boundary (``on_token`` must never re-enter the engine).

    Returns ``{"records": [...], "window_s": ..., "report":
    goodput_report(...), "admission": controller report}``; with
    ``collect_tokens`` each record carries the streamed token list (the
    bit-equality surface)."""
    import time as _time

    from .frontend import AdmissionController, SLORejected
    from ..inference.paged import AdmissionRejected

    if controller is None:
        controller = AdmissionController(policy="always")
    n = len(scenario.requests)
    records: list[dict] = [
        {"idx": r.idx, "rejected": False, "abandoned": False, "tokens": 0,
         "ttft_s": None, "tpot_s": None, "e2e_s": None, "timed_out": False,
         "kind": r.kind}
        for r in scenario.requests]
    streams: dict[int, list] = {}
    to_cancel: list[int] = []
    rid_to_idx: dict[int, int] = {}
    idx_to_rid: dict[int, int] = {}

    def _mk_cb(idx: int, abandon_after):
        toks: list = []
        streams[idx] = toks

        def cb(tok, _toks=toks, _aa=abandon_after, _idx=idx):
            _toks.append(tok)
            if _aa is not None and len(_toks) == _aa:
                # disconnect mid-decode: defer the cancel to the step
                # boundary (we are inside the engine's drain right now)
                to_cancel.append(_idx)
        return cb

    base_tok = engine.tokens_generated
    i = 0
    stalled = 0

    def _submit_next():
        """Submit scenario request i through the controller (recording a
        rejection instead of raising) and advance i."""
        nonlocal i
        sr = scenario.requests[i]
        try:
            rid = controller.submit(
                engine, sr.prompt, max_new_tokens=sr.max_new_tokens,
                temperature=sr.temperature, top_p=sr.top_p,
                slo_ttft_s=sr.slo_ttft_s
                if sr.slo_ttft_s is not None else slo_ttft_s,
                on_token=_mk_cb(sr.idx, sr.abandon_after))
            rid_to_idx[rid] = sr.idx
            idx_to_rid[sr.idx] = rid
        except (SLORejected, AdmissionRejected):
            records[sr.idx]["rejected"] = True
        i += 1

    t0 = _time.perf_counter()
    while True:
        while i < n and scenario.requests[i].arrival_s * load_tps \
                <= engine.tokens_generated - base_tok:
            _submit_next()
        if i < n and engine.num_active == 0 and not engine._queue \
                and not engine.inflight_depth:
            # idle jump: nothing is running, so token time cannot advance
            # to the next arrival on its own — submit it now (the analog
            # of a wall clock rolling forward through an idle valley)
            _submit_next()
            continue
        if i >= n and not engine.num_active and not engine._queue \
                and not engine.inflight_depth:
            break
        progressed = engine.step()
        stalled = 0 if progressed else stalled + 1
        if stalled >= max_stall_steps:
            raise RuntimeError(
                f"replay_engine: no progress for {stalled} steps "
                f"({engine.num_active} active, {len(engine._queue)} queued)")
        if to_cancel:
            for idx in to_cancel:
                rec = records[idx]
                if not rec["abandoned"]:
                    rec["abandoned"] = True
                    rid = idx_to_rid[idx]
                    req = engine.lookup(rid)
                    if req is not None and req.first_token_time:
                        rec["ttft_s"] = req.ttft
                    controller.resolve(rid, req)
                    engine.cancel(rid)
                    rec["tokens"] = len(streams[idx])
            to_cancel.clear()
    engine.quiesce()
    window_s = _time.perf_counter() - t0
    for rid, idx in rid_to_idx.items():
        rec = records[idx]
        if rec["abandoned"]:
            continue
        req = engine._finished.get(rid)
        if req is None:
            continue
        rec["tokens"] = len(req.generated)
        rec["ttft_s"] = req.ttft or None
        rec["tpot_s"] = req.tpot or None
        rec["e2e_s"] = req.finish_time - req.submit_time
        rec["timed_out"] = req.timed_out
        controller.resolve(rid, req)
    if collect_tokens:
        for idx, toks in streams.items():
            records[idx]["stream"] = list(toks)
    return {
        "records": records,
        "window_s": window_s,
        "report": goodput_report(records, slo_ttft_s, window_s=window_s),
        "admission": controller.report(),
    }


def replay_fleet(fleet, scenario: Scenario, *, slo_ttft_s: float,
                 load_tps: float | None = None,
                 virtual_clock: VirtualClock | None = None,
                 collect_tokens: bool = False,
                 max_stall_rounds: int = 4000) -> dict:
    """Drive a :class:`~paddle_tpu.serving.fleet.ReplicaFleet` (fixed-N
    or :class:`~paddle_tpu.serving.autoscale.ElasticFleet`) through
    ``scenario`` — the fleet-shaped twin of :func:`replay_engine`.
    Exactly one pacing mode:

      * ``load_tps`` — ROUTER token time: request i is submitted once
        the fleet has streamed ``arrival_s * load_tps`` tokens since the
        replay began (the router's ``tokens_streamed`` counter advances
        once per authoritative emission, so a failover/migration
        re-decode never inflates the clock).  Machine-independent
        offered load, but fleet-SIZE-normalizing: aggregate generation
        IS the clock, so capacity differences between fleets cancel out
        of the queue dynamics — use it for exactness/chaos drills, not
        capacity A/Bs.
      * ``virtual_clock`` — ROUND time (:class:`VirtualClock`): each
        fleet heartbeat advances ``dt`` virtual seconds as if every
        replica were its own concurrently-stepping host, and idle
        valleys jump the clock to the next arrival (idle replicas still
        accrue ``replica_seconds`` across the jump — exactly the cost
        scale-down exists to shed).  An N-replica fleet then clears an
        arrival backlog N× faster in virtual time, so capacity and
        elasticity are measurable — and every reported number is
        DETERMINISTIC for a given seed.  The fleet must have been built
        with ``clock=virtual_clock`` (one clock domain for request
        stamps, replica-time, and pacing); ``slo_ttft_s`` is then in
        virtual seconds.

    Abandon clients cancel through ``fleet.cancel`` at the round
    boundary.  Returns the :func:`replay_engine` report shape plus
    ``replica_seconds`` — the integral of live-replica count over the
    replay (the goodput-per-replica-hour denominator bench.py's elastic
    trace A/Bs on)."""
    import time as _time

    from ..inference.paged import AdmissionRejected

    if (load_tps is None) == (virtual_clock is None):
        raise ValueError("pass exactly one of load_tps / virtual_clock")
    if virtual_clock is not None and fleet._clock is not virtual_clock:
        raise ValueError("virtual-clock replay requires the fleet to run "
                         "on the SAME clock: ReplicaFleet(clock=vc)")
    n = len(scenario.requests)
    records: list[dict] = [
        {"idx": r.idx, "rejected": False, "abandoned": False, "tokens": 0,
         "ttft_s": None, "tpot_s": None, "e2e_s": None, "timed_out": False,
         "migrations": 0, "kind": r.kind}
        for r in scenario.requests]
    streams: dict[int, list] = {}
    to_cancel: list[int] = []
    frid_of: dict[int, int] = {}

    def _mk_cb(idx: int, abandon_after):
        toks: list = []
        streams[idx] = toks

        def cb(tok, _toks=toks, _aa=abandon_after, _idx=idx):
            _toks.append(tok)
            if _aa is not None and len(_toks) == _aa:
                # disconnect mid-decode: the fleet hook fires inside the
                # router's stream drain — defer to the round boundary
                to_cancel.append(_idx)
        return cb

    base_tok = fleet.tokens_streamed
    rs0 = fleet.replica_seconds
    i = 0
    stalled = 0

    def _submit_next():
        nonlocal i
        sr = scenario.requests[i]
        try:
            frid = fleet.submit(
                sr.prompt, max_new_tokens=sr.max_new_tokens,
                temperature=sr.temperature, top_p=sr.top_p,
                on_token=_mk_cb(sr.idx, sr.abandon_after))
            frid_of[sr.idx] = frid
        except AdmissionRejected:
            records[sr.idx]["rejected"] = True
        i += 1

    def _busy():
        return any(fr.result is None for fr in fleet._requests.values())

    def _due() -> bool:
        if i >= n:
            return False
        at = scenario.requests[i].arrival_s
        if virtual_clock is not None:
            return at <= virtual_clock()
        return at * load_tps <= fleet.tokens_streamed - base_tok

    t0 = _time.perf_counter()
    v0 = virtual_clock() if virtual_clock is not None else 0.0
    while True:
        while _due():
            _submit_next()
        if i < n and not _busy():
            # idle jump: the clock cannot advance to the next arrival on
            # its own — roll forward through the empty valley (virtual
            # mode jumps the shared clock, so idle replicas keep
            # accruing replica_seconds across the gap)
            if virtual_clock is not None:
                virtual_clock.advance_to(scenario.requests[i].arrival_s)
            _submit_next()
            continue
        if i >= n and not _busy():
            break
        progressed = fleet.step()
        if virtual_clock is not None:
            virtual_clock.tick()
        stalled = 0 if progressed else stalled + 1
        if stalled >= max_stall_rounds:
            raise RuntimeError(
                f"replay_fleet: no progress for {stalled} rounds "
                f"({sum(fr.result is None for fr in fleet._requests.values())}"
                f" unresolved, {len(fleet._waiting)} waiting)")
        if to_cancel:
            for idx in to_cancel:
                rec = records[idx]
                if not rec["abandoned"]:
                    rec["abandoned"] = True
                    frid = frid_of[idx]
                    fr = fleet._requests.get(frid)
                    if fr is not None and fr.first_token_t is not None:
                        rec["ttft_s"] = fr.first_token_t - fr.submit_t
                    rec["tokens"] = len(streams[idx])
                    fleet.cancel(frid)
            to_cancel.clear()
    window_s = (virtual_clock() - v0) if virtual_clock is not None \
        else _time.perf_counter() - t0
    for idx, frid in frid_of.items():
        rec = records[idx]
        if rec["abandoned"]:
            continue
        fr = fleet._requests.get(frid)
        if fr is None or fr.result is None:
            continue
        ngen = len(fr.result.generated)
        rec["tokens"] = ngen
        rec["ttft_s"] = (fr.first_token_t - fr.submit_t
                         if fr.first_token_t is not None else None)
        rec["tpot_s"] = ((fr.finish_t - fr.first_token_t) / (ngen - 1)
                         if ngen > 1 and fr.first_token_t is not None
                         else None)
        rec["e2e_s"] = fr.finish_t - fr.submit_t
        rec["timed_out"] = fr.result.timed_out
        rec["migrations"] = fr.migrations
    if collect_tokens:
        for idx, toks in streams.items():
            records[idx]["stream"] = list(toks)
    return {
        "records": records,
        "window_s": window_s,
        "replica_seconds": fleet.replica_seconds - rs0,
        "report": goodput_report(records, slo_ttft_s, window_s=window_s),
    }


def replay_sim(scenario: Scenario, *, num_slots: int,
               prefill_rate_tps: float, step_s: float, decode_horizon: int,
               policy: str = "predictive", slo_ttft_s: float = 1.0,
               max_queue_depth: int | None = None,
               controller=None) -> dict:
    """Replay ``scenario`` against an analytic S-slot server on a virtual
    clock — deterministic, jax-free, fast at 10k+ requests.

    The server model matches the
    :class:`~paddle_tpu.serving.frontend.TTFTPredictor`'s: a request
    occupies one slot for ``prefill/rate + decode * step_s/horizon``
    seconds, slots are granted FIFO (earliest-free first).  Admission
    runs through the REAL :class:`AdmissionController` — each arrival
    gets an :class:`AdmissionView` built from the sim state, so the
    controller/predictor logic is exercised at a scale the engine replay
    cannot afford (the tier-1 10k determinism + A/B tests run here).

    Returns the same report shape as :func:`replay_engine`."""
    from .frontend import (AdmissionController, AdmissionView, SLORejected)
    from ..inference.paged import AdmissionRejected

    if controller is None:
        controller = AdmissionController(
            policy=policy, slo_ttft_s=slo_ttft_s,
            max_queue_depth=max_queue_depth)
    tpt = step_s / max(1, decode_horizon)
    inv_rate = 1.0 / max(prefill_rate_tps, 1e-9)
    slot_free = [0.0] * num_slots           # heap of slot free times
    heapq.heapify(slot_free)
    # (start_time, prefill_tokens, decode_tokens) of admitted-not-started
    waiting: list[tuple[float, int, int]] = []
    records: list[dict] = []
    for sr in scenario.requests:
        t = sr.arrival_s
        waiting = [w for w in waiting if w[0] > t]
        busy = [ft for ft in slot_free if ft > t]
        view = AdmissionView(
            free_slots=num_slots - len(busy),
            active=[(0, max(1, int(math.ceil((ft - t) / tpt))))
                    for ft in busy],
            queued=[(pf, mn) for (_st, pf, mn) in waiting],
            prefill_rate_tps=prefill_rate_tps, step_s=step_s,
            decode_horizon=decode_horizon)
        dec = min(sr.max_new_tokens, sr.abandon_after
                  or sr.max_new_tokens)
        rec = {"idx": sr.idx, "rejected": False,
               "abandoned": sr.abandon_after is not None,
               "tokens": dec, "ttft_s": None, "tpot_s": None,
               "e2e_s": None, "timed_out": False, "kind": sr.kind}
        try:
            pred = controller.decide(
                view, len(sr.prompt),
                slo_ttft_s=sr.slo_ttft_s
                if sr.slo_ttft_s is not None else slo_ttft_s)
        except (SLORejected, AdmissionRejected):
            rec["rejected"] = True
            rec["tokens"] = 0
            records.append(rec)
            continue
        free_at = heapq.heappop(slot_free)
        start = max(t, free_at)
        pf_s = len(sr.prompt) * inv_rate
        finish = start + pf_s + dec * tpt
        heapq.heappush(slot_free, finish)
        if start > t:
            waiting.append((start, len(sr.prompt), dec))
        ttft = start - t + pf_s
        rec["ttft_s"] = ttft
        rec["tpot_s"] = tpt
        rec["e2e_s"] = finish - t
        records.append(rec)
        controller.resolve_sim(pred, ttft)
    window = max((r["e2e_s"] + scenario.requests[r["idx"]].arrival_s)
                 for r in records if r["e2e_s"] is not None) \
        if any(r["e2e_s"] is not None for r in records) else 0.0
    return {
        "records": records,
        "window_s": window,
        "report": goodput_report(records, slo_ttft_s, window_s=window
                                 if window > 0 else None),
        "admission": controller.report(),
    }
