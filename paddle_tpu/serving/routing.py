"""Fleet routing strategies: least-loaded and prefix-affinity placement.

ROADMAP item 5's routing half.  :class:`~paddle_tpu.serving.fleet.
ReplicaFleet` (PR 9) placed every request least-loaded-first with the
policy inlined in ``_place`` — correct for interchangeable replicas, and
provably wrong at fleet scale with per-replica prefix caches: two turns
of the same conversation land on different replicas, each re-prefills the
shared history, and the fleet-wide cache hit rate collapses to a fraction
of what a single engine gets on the identical traffic (``bench.py
--trace elastic`` measures exactly this split).

This module turns placement into a strategy seam:

  * :class:`Router` — the interface: ``decide(tokens, candidates)``
    returns a :class:`RoutingDecision` (candidate try-order + why).  The
    fleet walks the order and admits on the first replica that accepts;
    routers also receive replica lifecycle (``on_replica_added`` /
    ``on_replica_removed``) and cached-chain feed
    (``note_cached`` / ``note_evicted``) notifications.
  * :class:`LeastLoadedRouter` — the PR 9 policy, extracted verbatim:
    ascending (load, name).
  * :class:`PrefixAffinityRouter` — computes the prompt's page-aligned
    chained block-hash with the SAME implementation the engine-side
    :class:`~paddle_tpu.inference.paged.PrefixCache` indexes
    (:func:`~paddle_tpu.inference.paged.prefix_chain_hashes` — one
    function, two callers, bit-identical chains), consults a compact
    per-replica summary of cached chain digests kept current from the
    cache's insert/evict notifications, and routes to the replica holding
    the LONGEST cached chain — subject to a bounded-imbalance guard
    (``max_imbalance``): when the affinity target already carries that
    many more requests than the least-loaded replica, the router falls
    back to least-loaded so affinity can never starve load balance.

The summary stores ``digest_bytes``-truncated digests (8 bytes default):
a few MB would cover millions of cached blocks, and a truncation
collision merely makes one routing HINT wrong — correctness is untouched
(the engine's own full-digest cache decides what actually attaches).
"""
from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ..inference.paged import prefix_chain_hashes

__all__ = ["Router", "RoutingDecision", "LeastLoadedRouter",
           "PrefixAffinityRouter"]


@dataclass
class RoutingDecision:
    """One placement decision: the candidate try-order plus the routing
    reason (the fleet flight-records it; ``kind`` is one of
    ``least_loaded`` — no affinity information used, ``affinity`` — the
    longest-chain replica leads the order, ``affinity_fallback`` — a
    chain existed but the imbalance guard overrode it)."""
    order: list[str]
    kind: str = "least_loaded"
    target: str | None = None
    matched_blocks: int = 0


class Router:
    """Placement-strategy interface.  ``candidates`` is a list of
    ``(name, load)`` pairs for every live, routable replica (load = the
    replica's active + queued request count — the PR 9 least-loaded
    metric); routers never see engine internals.  ``tokens`` is the token
    stream the placement would prefill (prompt, or prompt + streamed
    tokens for a migration) — affinity-aware routers hash it, others
    ignore it."""

    name = "base"

    def configure(self, *, page_size: int | None = None):
        """Fleet wiring hook: called once with the engine geometry before
        the first placement (routers that hash pages need ``page_size``;
        others ignore it)."""

    # -- placement ---------------------------------------------------------
    def decide(self, tokens, candidates, memo=None,
               role=None) -> RoutingDecision:
        """``memo`` (optional dict) is per-request scratch the FLEET
        clears whenever the request's token stream changes — routers may
        park derived state there (the affinity chain digests) so a
        backoff retry of an unchanged request costs no re-hashing.

        ``role`` (disaggregated fleets) names the replica role this
        placement targets — ``"prefill"`` for fresh admissions,
        ``"decode"`` for KV handoffs, None for a role-less fleet.  The
        FLEET pre-filters ``candidates`` to that role; routers order what
        they are given and record the role for ``stats()``."""
        raise NotImplementedError

    def _note_role(self, role):
        """Per-role placement accounting (lazy: a role-less fleet never
        allocates the dict)."""
        if role is None:
            return
        counts = getattr(self, "_role_counts", None)
        if counts is None:
            counts = self._role_counts = {}
        counts[role] = counts.get(role, 0) + 1

    def _role_stats(self) -> dict:
        counts = getattr(self, "_role_counts", None)
        return {} if not counts else {"routed_by_role": dict(counts)}

    # -- replica lifecycle -------------------------------------------------
    def on_replica_added(self, name: str):
        """A replica joined (initial build, scale-up, or failover
        revival) — routers reset any per-replica state they keep."""

    def on_replica_removed(self, name: str):
        """A replica left (crash or drain-retirement) — its cached state
        is gone with it."""

    # -- cached-chain feed -------------------------------------------------
    def note_cached(self, name: str, digests):
        """``digests`` full-block chain digests were inserted into
        ``name``'s prefix cache."""

    def note_evicted(self, name: str, digests):
        """``digests`` were evicted from ``name``'s prefix cache."""

    def stats(self) -> dict:
        return {"router": self.name, **self._role_stats()}


class LeastLoadedRouter(Router):
    """The PR 9 inline policy as a strategy: every live replica in
    ascending (load, name) order — deterministic tie-break, no state."""

    name = "least_loaded"

    def decide(self, tokens, candidates, memo=None,
               role=None) -> RoutingDecision:
        self._note_role(role)
        order = [n for n, _load in sorted(candidates,
                                          key=lambda c: (c[1], c[0]))]
        return RoutingDecision(order=order, kind="least_loaded",
                               target=order[0] if order else None)


class PrefixAffinityRouter(Router):
    """Route shared-prefix traffic to the replica already holding its KV.

    For each placement: compute the chained block-hash of the tokens to
    prefill (capped at ``len - 1``, mirroring ``PrefixCache.lookup``'s
    attach cap), count how many leading blocks each candidate's summary
    holds, and lead the try-order with the longest-chain replica —
    unless that replica's load exceeds the least-loaded candidate's by
    more than ``max_imbalance`` requests (the bounded-imbalance guard:
    affinity is a throughput hint, never a reason to queue behind a hot
    replica while others idle).  Ties break toward lower load, then
    name.  The rest of the order is least-loaded, so a full affinity
    target degrades to exactly the PR 9 behavior.

    Counters (also surfaced via ``ReplicaFleet.stats_snapshot``):
    ``affinity_hits`` placements led by a cached chain,
    ``affinity_fallbacks`` guard overrides, ``affinity_misses``
    placements where no candidate held any block."""

    name = "prefix_affinity"

    def __init__(self, *, page_size: int | None = None,
                 max_imbalance: int = 4, digest_bytes: int = 8):
        self.page_size = None if page_size is None else int(page_size)
        self.max_imbalance = int(max_imbalance)
        self.digest_bytes = int(digest_bytes)
        self._summary: dict[str, set[bytes]] = {}
        self.affinity_hits = 0
        self.affinity_fallbacks = 0
        self.affinity_misses = 0
        self.matched_blocks_total = 0

    def configure(self, *, page_size: int | None = None):
        if page_size is not None and self.page_size is None:
            self.page_size = int(page_size)

    def _trunc(self, d: bytes) -> bytes:
        return d[:self.digest_bytes]

    # -- lifecycle + feed --------------------------------------------------
    def on_replica_added(self, name: str):
        self._summary[name] = set()

    def on_replica_removed(self, name: str):
        self._summary.pop(name, None)

    def note_cached(self, name: str, digests):
        s = self._summary.setdefault(name, set())
        for d in digests:
            s.add(self._trunc(d))

    def note_evicted(self, name: str, digests):
        s = self._summary.get(name)
        if s is not None:
            for d in digests:
                s.discard(self._trunc(d))

    def summary_blocks(self, name: str) -> int:
        return len(self._summary.get(name, ()))

    # -- placement ---------------------------------------------------------
    def _matched(self, chain: list[bytes], name: str) -> int:
        s = self._summary.get(name)
        if not s:
            return 0
        n = 0
        for d in chain:
            if self._trunc(d) not in s:
                break
            n += 1
        return n

    def decide(self, tokens, candidates, memo=None,
               role=None) -> RoutingDecision:
        self._note_role(role)
        by_load = sorted(candidates, key=lambda c: (c[1], c[0]))
        order = [n for n, _load in by_load]
        if not order or self.page_size is None:
            return RoutingDecision(order=order, kind="least_loaded",
                                   target=order[0] if order else None)
        chain = memo.get("chain") if memo is not None else None
        if chain is None:
            tokens = np.asarray(tokens, np.int32).reshape(-1)
            # mirror PrefixCache.lookup's cap: at least one suffix token
            # must remain to prefill, so the final boundary block never
            # attaches
            chain = prefix_chain_hashes(tokens[:-1], self.page_size)
            if memo is not None:
                memo["chain"] = chain
        best_name, best_load, best_m = None, 0, 0
        if chain:
            for name, load in by_load:
                m = self._matched(chain, name)
                # strictly-greater: ties stay with the lower-load
                # candidate (by_load order)
                if m > best_m:
                    best_name, best_load, best_m = name, load, m
        if best_m == 0:
            self.affinity_misses += 1
            return RoutingDecision(order=order, kind="least_loaded",
                                   target=order[0] if order else None)
        min_load = by_load[0][1]
        if best_load - min_load > self.max_imbalance:
            self.affinity_fallbacks += 1
            return RoutingDecision(order=order, kind="affinity_fallback",
                                   target=order[0] if order else None,
                                   matched_blocks=best_m)
        self.affinity_hits += 1
        self.matched_blocks_total += best_m
        order = [best_name] + [n for n in order if n != best_name]
        return RoutingDecision(order=order, kind="affinity",
                               target=best_name, matched_blocks=best_m)

    def stats(self) -> dict:
        routed = self.affinity_hits + self.affinity_fallbacks \
            + self.affinity_misses
        return {
            "router": self.name,
            "max_imbalance": self.max_imbalance,
            "routed": routed,
            "affinity_hits": self.affinity_hits,
            "affinity_fallbacks": self.affinity_fallbacks,
            "affinity_misses": self.affinity_misses,
            "matched_blocks_total": self.matched_blocks_total,
            "summary_blocks": {n: len(s)
                               for n, s in sorted(self._summary.items())},
            **self._role_stats(),
        }
