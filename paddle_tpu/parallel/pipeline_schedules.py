"""1F1B and interleaved (VPP) pipeline schedules, compiled SPMD.

Reference semantics: fleet/meta_parallel/pipeline_parallel.py:242
(`PipelineParallel` 1F1B), :1308 (interleaved VPP),
passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62.

Unlike the GPipe scan in pipeline.py (jax.grad through the whole schedule —
every tick's activations stay live until the backward scan), these schedules
do the backward *inside* the tick loop with an explicit jax.vjp:

* each rank keeps a ring buffer of only the **stage inputs** for in-flight
  microbatches — depth min(n_micro, 2*(n_virtual_stages-1)+1), independent of
  n_micro in the long-batch regime (the 1F1B memory bound; remat-inside-stage
  because vjp recomputes the stage forward at backward time);
* forward of microbatch f runs on virtual stage s at tick f + s; backward of
  microbatch b runs at tick 2*(S-1) - s + b (S = total virtual stages) — the
  synchronous 1F1B order: the last stage's backward of mb 0 starts the tick
  of its forward, n_micro-independent activation footprint;
* activations hop stage->stage+1 with `lax.ppermute` (ICI neighbor), grad
  cotangents hop the reverse ring; with v>1 chunks per rank (VPP) the ring
  carries a [v, ...] stack and rank 0 / rank n-1 rotate the chunk axis on
  wrap, exactly the interleaved virtual-stage order.

All of it sits inside one shard_map/jit: XLA overlaps the ppermutes with the
stage compute.
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor
from .pipeline import _flatten, _unflatten, _opt_specs, _axes_in_scope

__all__ = ["spmd_pipeline_1f1b", "Pipeline1F1BTrainStep",
           "GenericPipeline1F1BTrainStep"]


def _vary(x, axes):
    """Cast x to be manual-varying over every axis in `axes` it isn't yet
    (aligns lax.cond branch output types under shard_map's vma typing)."""
    have = getattr(getattr(x, "aval", None), "vma", frozenset())
    missing = tuple(a for a in axes if a not in have)
    return jax.lax.pcast(x, missing, to="varying") if missing else x


def spmd_pipeline_1f1b(fwd_mb: Callable, params, n_micro: int,
                       act_sd, axis: str = "pp", n_chunks: int = 1,
                       varying_axes=("dp", "pp", "mp", "ep")):
    """Run the 1F1B (v=1) / interleaved (v>1) schedule inside shard_map.

    fwd_mb(params, chunk_idx, act_in, mb_idx) -> (act_out, loss_mb)
        chunk_idx: which of this rank's v parameter chunks to apply;
        the caller gates embed (global stage 0) / head-loss (global last
        stage) inside fwd_mb with lax.cond on (rank, chunk).
    params: this rank's full parameter pytree (stage chunks + embed + head).
    act_sd: jax.ShapeDtypeStruct of one microbatch activation.
    Returns (loss_sum_on_last_stage, grads_like_params).
    """
    n = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    v = n_chunks
    S = v * n                                   # virtual stages
    total = n_micro + 2 * (S - 1)
    depth = int(min(n_micro, 2 * (S - 1) + 1))
    depth = max(depth, 1)
    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]

    mb_shape, mb_dtype = act_sd.shape, act_sd.dtype
    va = _axes_in_scope(varying_axes)
    # Cast params to axis-varying BEFORE the per-tick vjp: jax.vjp inserts an
    # implicit psum over the mesh axes for cotangents of invariant inputs
    # used in varying computation, which would (a) pre-sum embed/head grads
    # across ranks per tick, corrupting the masked accumulation, and (b) put
    # collectives inside masked code paths.  With varying params the vjp is
    # purely rank-local; the caller combines grads explicitly afterwards.
    params = jax.tree_util.tree_map(lambda p: _vary(p, va), params)

    def tick(carry, t):
        fwd_in, bwd_in, buf, gacc, loss_acc = carry
        fwd_out = jnp.zeros_like(fwd_in)
        bwd_out = jnp.zeros_like(bwd_in)
        for c in range(v):
            s = c * n + r                        # this chunk's virtual stage
            # ---- forward slot: microbatch f = t - s -----------------------
            f = t - s
            do_f = (f >= 0) & (f < n_micro)
            fc = jnp.clip(f, 0, n_micro - 1)
            a_in = fwd_in[c]
            a_out, l_mb = fwd_mb(params, c, a_in, fc)
            buf = jnp.where(do_f, buf.at[c, jnp.mod(fc, depth)].set(a_in), buf)
            loss_acc = loss_acc + jnp.where(
                do_f, l_mb.astype(jnp.float32), 0.0)
            fwd_out = fwd_out.at[c].set(a_out)
            # ---- backward slot: microbatch b ------------------------------
            b = t - (2 * (S - 1) - s)
            do_b = (b >= 0) & (b < n_micro)
            bc = jnp.clip(b, 0, n_micro - 1)
            a_saved = buf[c, jnp.mod(bc, depth)]
            _, vjp_fn = jax.vjp(
                lambda p, a: fwd_mb(p, c, a, bc), params, a_saved)
            is_last = s == S - 1
            g_act = jnp.where(is_last, jnp.zeros_like(bwd_in[c]), bwd_in[c])
            gp, ga = vjp_fn((g_act, _vary(jnp.ones((), jnp.float32), va)))
            gacc = jax.tree_util.tree_map(
                lambda acc, g: acc + jnp.where(do_b, g, 0).astype(acc.dtype),
                gacc, gp)
            bwd_out = bwd_out.at[c].set(jnp.where(do_b, ga, 0))
        # ---- communicate ----------------------------------------------
        recv_f = jax.lax.ppermute(fwd_out, axis, perm_f)
        # chunk rotation on the wrap rank: rank n-1's chunk c output feeds
        # rank 0's chunk c+1 (interleaved virtual-stage order)
        fwd_in = jnp.where(r == 0, jnp.roll(recv_f, 1, axis=0), recv_f)
        recv_b = jax.lax.ppermute(bwd_out, axis, perm_b)
        bwd_in = jnp.where(r == n - 1, jnp.roll(recv_b, -1, axis=0), recv_b)
        return (fwd_in, bwd_in, buf, gacc, loss_acc), None

    carry = (jnp.zeros((v,) + mb_shape, mb_dtype),          # fwd ring
             jnp.zeros((v,) + mb_shape, mb_dtype),          # bwd ring
             jnp.zeros((v, depth) + mb_shape, mb_dtype),    # saved inputs
             jax.tree_util.tree_map(
                 lambda p: jnp.zeros(p.shape, p.dtype), params),  # grad acc
             jnp.zeros((), jnp.float32))                    # loss acc
    if va:
        carry = jax.tree_util.tree_map(lambda x: _vary(x, va), carry)
    (fwd_in, bwd_in, buf, gacc, loss_acc), _ = jax.lax.scan(
        tick, carry, jnp.arange(total))
    return loss_acc, gacc


class Pipeline1F1BTrainStep:
    """Hybrid dp×pp(×mp) compiled train step on the 1F1B / interleaved
    schedule for LM-shaped models (embed / L stacked blocks / head).

    Same model contract as PipelineTrainStep, but:
      * per-microbatch embed + head run inside the pipelined tick (memory
        does not scale with n_micro);
      * schedule="1f1b" (default) or n_chunks>1 for interleaved VPP.

    block_params leaves: leading dim L = n_pp * n_chunks * layers_per_chunk.
    """

    def __init__(self, mesh: Mesh, embed_apply_mb, block_apply, head_loss_mb,
                 embed_params, block_params, head_params, optimizer,
                 n_micro: int, n_chunks: int = 1, batch_spec=None,
                 donate=True, remat_stage: bool = False, block_specs=None,
                 schedule: str = "1f1b"):
        """block_specs: optional {leaf_name: partition-suffix tuple} for the
        block params (excluding the leading stacked-layer dim), e.g.
        llama_block_specs("mp") — wires real tensor parallelism: those leaves
        are placed P("pp", *suffix) and their grads are NOT averaged over the
        axes the suffix names (each rank owns a distinct shard)."""
        if batch_spec is None:
            data_axes = tuple(a for a in ("dp", "ep") if a in mesh.axis_names)
            batch_spec = P(data_axes) if data_axes else P()
        if schedule not in ("1f1b", "zero_bubble"):
            raise ValueError(f"unknown schedule {schedule!r}")
        if schedule == "zero_bubble" and n_chunks != 1:
            raise ValueError("zero_bubble schedule has no VPP chunks")
        self.schedule = schedule
        self.mesh = mesh
        self.n_micro = n_micro
        self.n_chunks = n_chunks
        self.opt = optimizer
        n_pp = mesh.shape.get("pp", 1)
        self.n_pp = n_pp
        if block_specs is not None and not isinstance(block_params, dict):
            raise ValueError("block_specs requires dict block_params")
        self._block_specs = block_specs or {}
        # the grad-combine below (and spmd_pipeline_1f1b's varying_axes)
        # assumes the tensor-parallel axis is literally named "mp" and the
        # expert-parallel axis "ep".
        # 'pp' is NOT allowed in suffixes: the leading stacked-layer dim is
        # already placed on 'pp', a suffix repeat would be a duplicate axis
        bad = {a for sfx in self._block_specs.values()
               for a in sfx if a not in (None, "mp", "ep")}
        if bad:
            raise ValueError(
                f"block_specs may only shard over 'mp'/'ep', got {bad}")

        L = jax.tree_util.tree_leaves(block_params)[0].shape[0]
        if L % (n_pp * n_chunks) != 0:
            raise ValueError(
                f"layers {L} not divisible by n_pp*n_chunks = "
                f"{n_pp}*{n_chunks}")

        def place(tree, spec_fn):
            return jax.tree_util.tree_map(
                lambda va: jax.device_put(
                    va, NamedSharding(mesh, spec_fn(va))), tree)

        rep = lambda va: P(*([None] * va.ndim))
        # reorder layers so that chunk c of rank r holds virtual stage c*n+r:
        # layer order along dim0 becomes [chunk0: ranks 0..n-1][chunk1: ...]
        stacked = lambda va: P(*(["pp"] + [None] * (va.ndim - 1)))
        lpc = L // (n_pp * n_chunks)            # layers per chunk

        def blk_leaf_spec(name, va):
            suffix = self._block_specs.get(name)
            if suffix is not None:
                return P("pp", *suffix)
            return P(*(["pp"] + [None] * (va.ndim - 1)))

        def vpp_order(x):
            # [L, ...] -> [n_chunks*n_pp, lpc, ...] grouped so that
            # shard_map's pp split gives rank r chunks [c, lpc, ...]
            xs = x.reshape((n_chunks, n_pp, lpc) + x.shape[1:])
            xs = jnp.swapaxes(xs, 0, 1)          # [n_pp, n_chunks, lpc, ...]
            return xs.reshape((n_pp, n_chunks * lpc) + x.shape[1:]) \
                     .reshape((n_pp * n_chunks * lpc,) + x.shape[1:])

        self._vpp = n_chunks > 1
        bp = jax.tree_util.tree_map(vpp_order, block_params) if self._vpp \
            else block_params
        self.embed_params = place(embed_params, rep)
        if isinstance(bp, dict):
            self.block_params = {
                name: jax.device_put(
                    v, NamedSharding(mesh, blk_leaf_spec(name, v)))
                for name, v in bp.items()}
        else:
            self.block_params = place(bp, stacked)
        self.head_params = place(head_params, rep)
        self.opt_state = {
            "embed": self.opt.init_opt_state(_flatten(self.embed_params)),
            "block": self.opt.init_opt_state(_flatten(self.block_params)),
            "head": self.opt.init_opt_state(_flatten(self.head_params)),
        }

        from jax import shard_map

        if isinstance(self.block_params, dict):
            blk_spec = {name: blk_leaf_spec(name, va)
                        for name, va in self.block_params.items()}
        else:
            blk_spec = jax.tree_util.tree_map(
                lambda va: P(*(["pp"] + [None] * (va.ndim - 1))),
                self.block_params)
        rep_spec_e = jax.tree_util.tree_map(
            lambda va: P(*([None] * va.ndim)), self.embed_params)
        rep_spec_h = jax.tree_util.tree_map(
            lambda va: P(*([None] * va.ndim)), self.head_params)

        n_ck = n_chunks
        self._embed_apply_mb = embed_apply_mb
        self._block_apply = jax.checkpoint(block_apply) if remat_stage \
            else block_apply
        self._head_loss_mb = head_loss_mb

        def grad_step(embed_p, block_p, head_p, eo, bo, ho, lr, batch):
            # inside shard_map: block_p leading dim = n_chunks * lpc
            n = jax.lax.psum(1, "pp")
            r = jax.lax.axis_index("pp")
            S = n_ck * n
            ids = batch[0]
            B = ids.shape[0]
            mbs = B // self.n_micro
            va = _axes_in_scope(mesh.axis_names)
            # pre-vary the batch over every mesh axis: ints carry no grads,
            # so the pcast transpose (a psum) is harmless — and everything
            # computed from it is then fully varying, keeping implicit
            # collectives out of the masked embed/head paths
            mb_batch = jax.tree_util.tree_map(
                lambda x: _vary(
                    x.reshape((self.n_micro, mbs) + x.shape[1:]), va), batch)

            params = {"embed": embed_p, "block": block_p, "head": head_p}
            # activation ShapeDtypeStruct: embed of one microbatch
            act_sd = jax.eval_shape(
                lambda p, mb: self._embed_apply_mb(p, mb), embed_p,
                jax.tree_util.tree_map(lambda x: x[0], mb_batch))

            def fwd_mb(ps, c, a_in, f):
                mb = jax.tree_util.tree_map(lambda x: x[f], mb_batch)
                s = c * n + r
                # embed/head run (masked) on every rank: where-select keeps
                # collectives out of conditionals, and grads route only to
                # the owning stage through the select
                emb = self._embed_apply_mb(ps["embed"], mb).astype(a_in.dtype)
                a0 = jnp.where(s == 0, emb, a_in)
                lpc = jax.tree_util.tree_leaves(
                    ps["block"])[0].shape[0] // n_ck
                chunk = jax.tree_util.tree_map(
                    lambda x: x[c * lpc:(c + 1) * lpc], ps["block"])

                def one(a, lp):
                    return self._block_apply(lp, a), None
                out, _ = jax.lax.scan(one, a0, chunk)
                l_mb = self._head_loss_mb(ps["head"], out, mb).astype(
                    jnp.float32)
                loss = l_mb * jnp.where(s == S - 1, 1.0, 0.0)
                return out, loss

            if self.schedule == "zero_bubble":
                from .zero_bubble import spmd_pipeline_zero_bubble
                loss_sum, g = spmd_pipeline_zero_bubble(
                    fwd_mb, params, self.n_micro, act_sd, axis="pp")
            else:
                loss_sum, g = spmd_pipeline_1f1b(
                    fwd_mb, params, self.n_micro, act_sd, axis="pp",
                    n_chunks=n_ck)
            # per-mb head losses were means; global loss = mean over mbs
            loss = loss_sum / self.n_micro
            loss = jax.lax.psum(loss, "pp")      # nonzero on last stage only
            for axn in mesh.axis_names:
                if axn != "pp":
                    loss = jax.lax.pmean(loss, axn)

            ge, gb, gh = g["embed"], g["block"], g["head"]
            scale = 1.0 / self.n_micro
            ge, gb, gh = jax.tree_util.tree_map(
                lambda x: x * scale, (ge, gb, gh))
            # embed/head grads live on their owning stage only -> share
            ge, gh = jax.tree_util.tree_map(
                lambda va: jax.lax.psum(va, "pp"), (ge, gh))
            if "dp" in mesh.axis_names:
                ge, gb, gh = jax.tree_util.tree_map(
                    lambda va: jax.lax.pmean(va, "dp"), (ge, gb, gh))
            for ax in ("mp", "ep"):
                if ax not in mesh.axis_names:
                    continue
                ge, gh = jax.tree_util.tree_map(
                    lambda va: jax.lax.pmean(va, ax), (ge, gh))
                # replicated block leaves: copies hold rank-partial grads
                # (TP psum transpose / EP batch split) — pmean is right for
                # both: per-tick vjp seeds the loss on every rank of the
                # axis, so partial sums arrive psum'd * n_ax.
                # axis-sharded leaves: each rank owns a distinct shard whose
                # accumulated grad is n_ax x the true shard grad (TP: the
                # row-parallel psum/pvary transpose broadcasts the summed
                # cotangent; EP: every rank's 1/T_local loss normalisation
                # over-counts by the axis size vs the global mean) -> scale
                # by 1/n_ax, no collective.
                inv_ax = 1.0 / mesh.shape[ax]

                def _combine(name, g, ax=ax, inv_ax=inv_ax):
                    if ax in self._block_specs.get(name, ()):
                        return g * inv_ax
                    return jax.lax.pmean(g, ax)
                if isinstance(gb, dict) and self._block_specs:
                    gb = {name: _combine(name, g)
                          for name, g in gb.items()}
                else:
                    gb = jax.tree_util.tree_map(
                        lambda va, ax=ax: jax.lax.pmean(va, ax), gb)
            ne, neo = self.opt.apply_gradients_functional(
                _flatten(embed_p), _flatten(ge), eo, lr=lr)
            nb, nbo = self.opt.apply_gradients_functional(
                _flatten(block_p), _flatten(gb), bo, lr=lr)
            nh, nho = self.opt.apply_gradients_functional(
                _flatten(head_p), _flatten(gh), ho, lr=lr)
            return (_unflatten(ne, embed_p), _unflatten(nb, block_p),
                    _unflatten(nh, head_p), neo, nbo, nho, loss)

        from .pipeline import _opt_specs_named
        blk_opt_spec = (_opt_specs_named(self.opt_state["block"],
                                         self._block_specs, "pp")
                        if self._block_specs
                        else _opt_specs(self.opt_state["block"], "pp"))
        sm = shard_map(
            grad_step, mesh=mesh,
            in_specs=(rep_spec_e, blk_spec, rep_spec_h,
                      _opt_specs(self.opt_state["embed"], None),
                      blk_opt_spec,
                      _opt_specs(self.opt_state["head"], None),
                      P(), batch_spec),
            out_specs=(rep_spec_e, blk_spec, rep_spec_h,
                       _opt_specs(self.opt_state["embed"], None),
                       blk_opt_spec,
                       _opt_specs(self.opt_state["head"], None),
                       P()))
        donate_args = tuple(range(6)) if donate else ()
        self._step = jax.jit(sm, donate_argnums=donate_args)

    def __call__(self, batch):
        val = jax.tree_util.tree_map(
            lambda b: b._value if isinstance(b, Tensor) else jnp.asarray(b),
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        (self.embed_params, self.block_params, self.head_params,
         self.opt_state["embed"], self.opt_state["block"],
         self.opt_state["head"], loss) = self._step(
            self.embed_params, self.block_params, self.head_params,
            self.opt_state["embed"], self.opt_state["block"],
            self.opt_state["head"], lr, val)
        self.opt.finish_step()
        return Tensor(loss)


class GenericPipeline1F1BTrainStep:
    """Compiled 1F1B schedule for an arbitrary PipelineLayer (the LayerDesc /
    SegmentLayers segmentation wired into the compiled path — reference
    pp_layers.py:258 + pipeline_parallel.py:242).

    Stages come from pipeline_layer.segment_parts; heterogeneous stages are
    dispatched with lax.switch on the rank index (parameters replicated over
    'pp' — simple and correct; the homogeneous-block Pipeline1F1BTrainStep is
    the scalable path for big LMs).  Requires: every stage boundary carries
    one activation array of the same shape/dtype, and pipeline_layer.loss_fn
    is set.
    """

    def __init__(self, mesh: Mesh, pipeline_layer, optimizer, n_micro: int,
                 example_input, batch_spec=None, donate=True):
        from ..nn.layer import functional_state
        if batch_spec is None:
            batch_spec = P("dp") if "dp" in mesh.axis_names else P()
        self.mesh = mesh
        self.pl = pipeline_layer
        self.opt = optimizer
        self.n_micro = n_micro
        n_pp = mesh.shape.get("pp", 1)
        self.n_pp = n_pp
        if pipeline_layer.loss_fn is None:
            raise ValueError("PipelineLayer needs loss_fn for training")
        segs = pipeline_layer.segment_parts
        if len(segs) - 1 != n_pp:
            raise ValueError(
                f"PipelineLayer has {len(segs) - 1} stages, mesh pp={n_pp}")

        self.params = {name: p._value
                       for name, p in pipeline_layer.named_parameters()}
        self.opt_state = self.opt.init_opt_state(self.params)

        # stage apply functions over the substituted functional state
        def make_stage(si):
            lo, hi = segs[si], segs[si + 1]

            def apply(ps, x):
                with functional_state(pipeline_layer, ps):
                    t = Tensor(x)
                    for i in range(lo, hi):
                        layer = pipeline_layer.run_function[i]
                        t = layer(*t) if isinstance(t, tuple) else layer(t)
                return t._value if isinstance(t, Tensor) else t
            return apply

        self._stage_fns = [make_stage(s) for s in range(n_pp)]

        # activation contract: every stage boundary same shape/dtype
        mb_in = jax.tree_util.tree_map(
            lambda x: jax.eval_shape(lambda v: v[:max(1, x.shape[0] // n_micro)],
                                     x), example_input)
        act_sd = jax.eval_shape(self._stage_fns[0], self.params,
                                mb_in if not isinstance(mb_in, (tuple, list))
                                else mb_in[0])
        for s in range(1, n_pp):
            nxt = jax.eval_shape(self._stage_fns[s], self.params, act_sd)
            if s < n_pp - 1 and (nxt.shape != act_sd.shape
                                 or nxt.dtype != act_sd.dtype):
                raise ValueError(
                    f"stage {s} output {nxt.shape}/{nxt.dtype} != activation "
                    f"contract {act_sd.shape}/{act_sd.dtype}")
        self._act_sd = act_sd

        from jax import shard_map
        rep_spec = jax.tree_util.tree_map(
            lambda v: P(*([None] * v.ndim)), self.params)
        loss_fn = pipeline_layer.loss_fn

        def grad_step(params, opt_state, lr, batch):
            n = jax.lax.psum(1, "pp")
            r = jax.lax.axis_index("pp")
            va = _axes_in_scope(mesh.axis_names)
            x_in, y_in = batch
            B = x_in.shape[0]
            mbs = B // self.n_micro
            mb_batch = jax.tree_util.tree_map(
                lambda x: _vary(
                    x.reshape((self.n_micro, mbs) + x.shape[1:]), va), batch)

            def fwd_mb(ps, c, a_in, f):
                mb_x, mb_y = jax.tree_util.tree_map(
                    lambda x: x[f], mb_batch)
                s = r
                # index-aware branches: stage 0 eats the microbatch
                def branch(si):
                    fn = self._stage_fns[si]
                    if si == 0:
                        return lambda ops, ox, oa: fn(ops, ox)
                    return lambda ops, ox, oa: fn(ops, oa)
                out = jax.lax.switch(s, [branch(si) for si in range(n_pp)],
                                     ps, mb_x, a_in)
                lt = loss_fn(Tensor(out), Tensor(mb_y))
                lv = (lt._value if isinstance(lt, Tensor) else lt).astype(
                    jnp.float32)
                return out, lv * jnp.where(s == n - 1, 1.0, 0.0)

            loss_sum, g = spmd_pipeline_1f1b(
                fwd_mb, params, self.n_micro, self._act_sd, axis="pp",
                n_chunks=1)
            loss = jax.lax.psum(loss_sum / self.n_micro, "pp")
            g = jax.tree_util.tree_map(
                lambda v: jax.lax.psum(v / self.n_micro, "pp"), g)
            for axn in mesh.axis_names:
                if axn != "pp" and mesh.shape[axn] > 1:
                    loss = jax.lax.pmean(loss, axn)
                    g = jax.tree_util.tree_map(
                        lambda v: jax.lax.pmean(v, axn), g)
            new_p, new_o = self.opt.apply_gradients_functional(
                params, g, opt_state, lr=lr)
            return new_p, new_o, loss

        opt_spec = jax.tree_util.tree_map(
            lambda v: P(*([None] * getattr(v, "ndim", 0))), self.opt_state)
        sm = shard_map(grad_step, mesh=mesh,
                       in_specs=(rep_spec, opt_spec, P(), batch_spec),
                       out_specs=(rep_spec, opt_spec, P()))
        self._step = jax.jit(sm, donate_argnums=(0, 1) if donate else ())

    def __call__(self, batch):
        val = jax.tree_util.tree_map(
            lambda b: b._value if isinstance(b, Tensor) else jnp.asarray(b),
            batch, is_leaf=lambda x: isinstance(x, Tensor))
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        self.params, self.opt_state, loss = self._step(
            self.params, self.opt_state, lr, val)
        self.opt.finish_step()
        return Tensor(loss)

    def sync_to_model(self):
        targets = dict(self.pl.named_parameters())
        for nme, v in self.params.items():
            if nme in targets:
                targets[nme]._set_value(v)
