"""Ring attention + Ulysses attention over a mesh axis.

SURVEY.md §5: the reference's `sep` segment parallelism has NO ring/blockwise
attention — it re-gathers the full sequence for attention. These two
implementations are the upgrade the TPU build ships:

* **ring_attention** — blockwise attention with online softmax; KV shards
  rotate around the ICI ring via `lax.ppermute`, one hop per step, overlapping
  the next hop's transfer with the current block's compute (XLA schedules the
  ppermute DMA async). Memory per chip: O(S_local²) scores, O(S/N) KV.
* **ulysses_attention** — all-to-all head↔sequence swap: each chip trades its
  sequence shard of all heads for all sequence of its head shard, runs dense
  (flash) attention locally, and swaps back. Two all-to-alls instead of N-1
  ring hops; best when heads ≥ axis size.

Both are called inside shard_map with the sequence axis sharded over `axis`.
"""
from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp

__all__ = ["ring_attention", "ulysses_attention", "ring_flash_attention"]


def _block_attn_lse(q, k, v, scale, mask=None):
    """Dense block attention returning (out_unnorm [B,Sq,H,D], m [B,H,Sq,1],
    l [B,H,Sq,1]) for online combination."""
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask, s, -1e30)
    m = jnp.max(s, axis=-1, keepdims=True)                    # [B,H,Sq,1]
    m = jnp.maximum(m, -1e30)
    p = jnp.exp(s - m)
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1, keepdims=True)
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)
    return o.astype(jnp.float32), m, l


def ring_attention(q, k, v, axis: str = "sep", causal: bool = False):
    """q,k,v: local shards [B, S_local, H, D], sequence sharded over `axis`.
    Returns local output shard [B, S_local, H, D]."""
    n = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    b, s_local, h, d = q.shape
    scale = 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def step(carry, t):
        k_cur, v_cur, o_acc, m_acc, l_acc = carry
        src = (r - t) % n  # whose KV shard we hold this step
        if causal:
            # global positions: q rows r*s_local + i, kv cols src*s_local + j
            qi = r * s_local + jax.lax.broadcasted_iota(
                jnp.int32, (s_local, s_local), 0)
            kj = src * s_local + jax.lax.broadcasted_iota(
                jnp.int32, (s_local, s_local), 1)
            mask = (qi >= kj)[None, None]
        else:
            mask = None
        o_t, m_t, l_t = _block_attn_lse(q, k_cur, v_cur, scale, mask)
        m_new = jnp.maximum(m_acc, m_t)
        a_old = jnp.exp(m_acc - m_new)
        a_new = jnp.exp(m_t - m_new)
        # o_acc layout [B,Sq,H,D]; alphas are [B,H,Sq,1] -> move to [B,Sq,H,1]
        a_old_o = jnp.transpose(a_old, (0, 2, 1, 3))
        a_new_o = jnp.transpose(a_new, (0, 2, 1, 3))
        o_new = o_acc * a_old_o + o_t * a_new_o
        l_new = l_acc * a_old + l_t * a_new
        # rotate KV to the next neighbor (overlaps with next step's compute)
        k_nxt = jax.lax.ppermute(k_cur, axis, perm)
        v_nxt = jax.lax.ppermute(v_cur, axis, perm)
        return (k_nxt, v_nxt, o_new, m_new, l_new), None

    o0 = jnp.zeros((b, s_local, h, d), jnp.float32)
    m0 = jnp.full((b, h, s_local, 1), -1e30, jnp.float32)
    l0 = jnp.zeros((b, h, s_local, 1), jnp.float32)
    # scan carries must be typed axis-varying like the per-shard k/v
    o0 = jax.lax.pcast(o0, (axis,), to="varying")
    m0 = jax.lax.pcast(m0, (axis,), to="varying")
    l0 = jax.lax.pcast(l0, (axis,), to="varying")
    (k_f, v_f, o, m, l), _ = jax.lax.scan(
        step, (k, v, o0, m0, l0), jnp.arange(n))
    l_o = jnp.transpose(l, (0, 2, 1, 3))              # [B,Sq,H,1]
    out = o / jnp.maximum(l_o, 1e-30)
    return out.astype(q.dtype)


def ulysses_attention(q, k, v, axis: str = "sep", causal: bool = False):
    """All-to-all attention (DeepSpeed-Ulysses style): swap seq-sharding for
    head-sharding, attend over the full sequence locally, swap back.
    Requires num_heads % axis_size == 0."""
    n = jax.lax.psum(1, axis)
    b, s_local, h, d = q.shape

    def seq2head(x):
        # [B, S/n, H, D] -> [B, S, H/n, D]
        xs = x.reshape(b, s_local, n, h // n, d)
        y = jax.lax.all_to_all(xs, axis, split_axis=2, concat_axis=1, tiled=False)
        # all_to_all over axis 2 (size n): gather seq, scatter heads
        return y.reshape(b, s_local * n, h // n, d)

    def head2seq(x):
        xs = x.reshape(b, n, s_local, h // n, d)
        y = jax.lax.all_to_all(xs, axis, split_axis=1, concat_axis=2, tiled=False)
        return y.reshape(b, s_local, h, d)

    qh = seq2head(q)
    kh = seq2head(k)
    vh = seq2head(v)
    scale = 1.0 / math.sqrt(d)
    s = jnp.einsum("bqhd,bkhd->bhqk", qh, kh).astype(jnp.float32) * scale
    if causal:
        S = s.shape[-1]
        mask = jnp.tril(jnp.ones((S, S), bool))
        s = jnp.where(mask, s, -1e30)
    p = jax.nn.softmax(s, axis=-1).astype(q.dtype)
    oh = jnp.einsum("bhqk,bkhd->bqhd", p, vh)
    return head2seq(oh)


# ---------------------------------------------------------------------------
# Pallas-grade ring flash attention (VERDICT r4 item #6 / SURVEY §5's
# "ring/blockwise attention as a Pallas kernel over the ICI ring")
# ---------------------------------------------------------------------------
def _vary_axis(x, axis):
    from .pipeline_schedules import _vary
    return _vary(x, (axis,))


def _to_kernel_layout(x):
    # [B, S, H, D] -> [B*H, S, D]
    b, s, h, d = x.shape
    return jnp.transpose(x, (0, 2, 1, 3)).reshape(b * h, s, d)


def _from_kernel_layout(x, b, h):
    bh, s, d = x.shape
    return jnp.transpose(x.reshape(b, h, s, d), (0, 2, 1, 3))


def ring_flash_attention(q, k, v, axis: str = "sep", causal: bool = False,
                         interpret: bool = False):
    """Ring attention where every hop runs the Pallas FA kernel and the
    per-hop (out, lse) pairs merge by online-softmax rescaling; GQA rides
    the kernel's native KV-head index maps.

    Backward is a custom_vjp that RE-ROTATES the saved local KV shard
    around the ring (residuals are only the local q/k/v/out/lse — O(S/N)
    per chip, asserted in tests) and runs the FA backward kernels per hop
    with the GLOBAL lse/delta, which makes the flash decomposition exact
    per KV block; dk/dv accumulators rotate along with the KV so each
    shard's gradient arrives home after the full cycle.

    Causal hop-skipping: with block-aligned shards, hops holding a strictly
    future shard (src > r) are skipped via lax.switch — ~half the FLOPs at
    scale, the blockwise-causal schedule the jnp fallback can't exploit.
    """
    from ..ops.pallas.flash_attention import (
        flash_attention_fwd_kernel_call, _bwd_call)

    n = jax.lax.psum(1, axis)          # static: axis size
    b, s_local, hq, d = q.shape
    hkv = k.shape[2]
    scale = 1.0 / math.sqrt(d)
    perm = [(i, (i + 1) % n) for i in range(n)]

    def fwd_hop(qk, k_cur, v_cur, hop_kind):
        """hop_kind: 0 skip, 1 diagonal (causal), 2 full."""
        kk = _to_kernel_layout(k_cur)
        vk = _to_kernel_layout(v_cur)

        def run(flag_causal):
            def f(_):
                o, lse = flash_attention_fwd_kernel_call(
                    qk, kk, vk, flag_causal, scale, interpret=interpret,
                    n_q_heads=hq, n_kv_heads=hkv)
                return (_vary_axis(o.astype(jnp.float32), axis),
                        _vary_axis(lse, axis))
            return f

        def skip(_):
            return (_vary_axis(jnp.zeros((b * hq, s_local, d), jnp.float32),
                               axis),
                    _vary_axis(jnp.full((b * hq, s_local), -jnp.inf,
                                        jnp.float32), axis))

        return jax.lax.switch(hop_kind, [skip, run(True), run(False)], 0)

    def hop_kind_of(t, r):
        src = jnp.mod(r - t, n)
        if not causal:
            return jnp.int32(2)
        return jnp.where(src > r, 0, jnp.where(src == r, 1, 2)).astype(
            jnp.int32)

    @jax.custom_vjp
    def _ring(q, k, v):
        out, _lse = _ring_fwd(q, k, v)[0]
        return out

    def _ring_fwd(q, k, v):
        r = jax.lax.axis_index(axis)
        qk = _to_kernel_layout(q)
        o_acc = _vary_axis(jnp.zeros((b * hq, s_local, d), jnp.float32), axis)
        lse_acc = _vary_axis(
            jnp.full((b * hq, s_local), -jnp.inf, jnp.float32), axis)
        k_cur, v_cur = k, v
        for t in range(n):
            o_t, lse_t = fwd_hop(qk, k_cur, v_cur, hop_kind_of(t, r))
            lse_new = jnp.logaddexp(lse_acc, lse_t)
            a_old = jnp.exp(lse_acc - lse_new)[..., None]
            a_new = jnp.exp(lse_t - lse_new)[..., None]
            o_acc = o_acc * a_old + o_t * a_new
            lse_acc = lse_new
            if t != n - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
        out = _from_kernel_layout(o_acc.astype(q.dtype), b, hq)
        return (out, lse_acc), (q, k, v, out, lse_acc)

    def _ring_bwd(res, g):
        q, k, v, out, lse = res
        r = jax.lax.axis_index(axis)
        qk = _to_kernel_layout(q)
        ok = _to_kernel_layout(out)
        gk = _to_kernel_layout(g.astype(out.dtype))
        # delta = rowsum(do*o) is hop-invariant: compute once, not per hop
        delta = jnp.sum(gk.astype(jnp.float32) * ok.astype(jnp.float32),
                        axis=-1)          # [bh, s] (2-D: lse layout contract)
        dq_acc = _vary_axis(jnp.zeros_like(qk, jnp.float32), axis)
        k_cur, v_cur = k, v
        dk_acc = _vary_axis(jnp.zeros(k.shape, jnp.float32), axis)
        dv_acc = _vary_axis(jnp.zeros(v.shape, jnp.float32), axis)

        def bwd_hop(k_cur, v_cur, hop_kind):
            kk = _to_kernel_layout(k_cur)
            vk = _to_kernel_layout(v_cur)

            def run(flag_causal):
                def f(_):
                    dq, dk, dv = _bwd_call(
                        (qk, kk, vk, ok, lse), gk, flag_causal, scale,
                        interpret, n_q_heads=hq, n_kv_heads=hkv,
                        delta=delta)
                    return (_vary_axis(dq.astype(jnp.float32), axis),
                            _vary_axis(dk.astype(jnp.float32), axis),
                            _vary_axis(dv.astype(jnp.float32), axis))
                return f

            def skip(_):
                z = lambda s: _vary_axis(jnp.zeros(s, jnp.float32), axis)
                return (z((b * hq, s_local, d)),
                        z((b * hkv, s_local, d)),
                        z((b * hkv, s_local, d)))

            dq, dk, dv = jax.lax.switch(
                hop_kind, [skip, run(True), run(False)], 0)
            return (dq, _from_kernel_layout(dk, b, hkv),
                    _from_kernel_layout(dv, b, hkv))

        for t in range(n):
            dq_t, dk_t, dv_t = bwd_hop(k_cur, v_cur, hop_kind_of(t, r))
            dq_acc = dq_acc + dq_t
            dk_acc = dk_acc + dk_t
            dv_acc = dv_acc + dv_t
            # grad accumulators rotate the FULL cycle (n hops) so each
            # shard's sum lands back at its owner; KV itself only needs the
            # first n-1 rotations
            if t != n - 1:
                k_cur = jax.lax.ppermute(k_cur, axis, perm)
                v_cur = jax.lax.ppermute(v_cur, axis, perm)
            dk_acc = jax.lax.ppermute(dk_acc, axis, perm)
            dv_acc = jax.lax.ppermute(dv_acc, axis, perm)
        dq = _from_kernel_layout(dq_acc, b, hq).astype(q.dtype)
        return dq, dk_acc.astype(k.dtype), dv_acc.astype(v.dtype)

    def _fwd_rule(q, k, v):
        (out, _lse), res = _ring_fwd(q, k, v)
        return out, res

    _ring.defvjp(_fwd_rule, _ring_bwd)
    return _ring(q, k, v)
