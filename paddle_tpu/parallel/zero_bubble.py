"""Zero-bubble pipeline schedule (B/W-split backward), compiled SPMD.

Reference: passes/pipeline_scheduler_pass/pipeline_zero_bubble.py:62 — the
ZB-H1 family splits each microbatch's backward into

    B: input-grad only   (dL/dx — the inter-stage critical path)
    W: weight-grad only  (dL/dW — no successor, schedulable into bubbles)

TPU-native design: unlike the fused-tick 1F1B engine
(pipeline_schedules.spmd_pipeline_1f1b), this engine is *slot-granular*.  A
schedule TABLE (built in Python by a greedy list scheduler, one row per pp
rank, one column per tick) assigns each rank one slot per tick:
IDLE / F(mb) / B(mb) / W(mb).  Inside shard_map every tick executes
`lax.switch` on this rank's table entry — real per-device control flow, so a
tick costs one slot's work — then ppermutes the fwd/bwd rings.

Backward-splitting without a recompute tax (round 5; the round-4 engine
re-ran the stage forward inside BOTH the B and the W vjp, which is why it
lost to 1F1B at large n_micro — PERF.md r4 §6):

* the F slot runs the stage forward through `jax.vjp` and saves the
  **residuals** (the AD tape: every intermediate the backward needs) into a
  ring buffer, exactly like ZB-H1's activation store — this is the real
  ZB memory model, the H1 in-flight cap bounds it to ~n_stages microbatches;
* residual leaves that are literally the parameter arrays or the stage
  input are deduped out of the buffer by tracer identity (the weights are
  already resident; the stage input is already in the activation ring) —
  only true intermediates are stored;
* the B slot rebuilds the saved vjp and takes ONLY the input-cotangent —
  XLA's dead-code elimination prunes the dW contractions, so B costs just
  the dx matmul chain, no forward recompute;
* the W slot rebuilds the same vjp and takes the weight-cotangent (the dx
  chain inside the stage is re-derived from residuals — pure matmuls, no
  forward — plus the dW contractions).

Per microbatch this totals ≈ fwd + dx + (dx + dW): the same FLOPs as the
fused-1F1B backward-with-recompute, with the critical-path B slot ~3×
cheaper — so the table's bubble win is no longer paid back as recompute.
"""
from __future__ import annotations

from typing import Callable, List, Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .pipeline import _flatten, _unflatten, _opt_specs, _axes_in_scope
from .pipeline_schedules import _vary

__all__ = ["build_schedule", "schedule_stats", "spmd_pipeline_zero_bubble",
           "PipelineZeroBubbleTrainStep", "IDLE", "F", "B", "W"]

IDLE, F, B, W = 0, 1, 2, 3


def build_schedule(n_stages: int, n_micro: int, policy: str = "zb1"
                   ) -> List[List[Tuple[int, int]]]:
    """Greedy list scheduler. Returns per-rank slot lists [(kind, mb), ...]
    (all rows same length = makespan).

    policy "1f1b": W is chained right after its B (the classic fused
    backward, split into two unit slots — the fair fine-grained baseline).
    policy "zb1": W defers; B and F take priority, W fills bubbles (ZB-H1).
    In-flight activations per rank are capped at n_stages (H1's memory
    bound ~ 1F1B's).
    """
    S, M = n_stages, n_micro
    f_done = [[-1] * M for _ in range(S)]   # tick F(s,m) executed
    b_done = [[-1] * M for _ in range(S)]
    w_done = [[-1] * M for _ in range(S)]
    rows: List[List[Tuple[int, int]]] = [[] for _ in range(S)]
    forced: List[Tuple[int, int]] = [None] * S  # 1f1b: W forced next tick
    t = 0
    limit = 4 * (M + 2 * S) + 8
    while (any(w_done[s][m] < 0 for s in range(S) for m in range(M))
           and t < limit):
        for s in range(S):
            rows[s].append((IDLE, 0))

        def ready_F(s, m):
            if f_done[s][m] >= 0:
                return False
            if s > 0 and not (0 <= f_done[s - 1][m] < t):
                return False
            # memory cap: in-flight (F done or now, W not done) < S + 1
            inflight = sum(1 for mm in range(M)
                           if f_done[s][mm] >= 0 and w_done[s][mm] < 0)
            return inflight <= S

        def ready_B(s, m):
            if b_done[s][m] >= 0 or f_done[s][m] < 0:
                return False
            if s == S - 1:
                return f_done[s][m] < t
            return 0 <= b_done[s + 1][m] < t

        def ready_W(s, m):
            return w_done[s][m] < 0 <= b_done[s][m] and b_done[s][m] < t

        for s in range(S):
            if forced[s] is not None:
                m = forced[s][1]
                rows[s][t] = (W, m)
                w_done[s][m] = t
                forced[s] = None
                continue
            slot = None
            # priority: B first (critical path), then F, then W
            for m in range(M):
                if ready_B(s, m):
                    slot = (B, m)
                    break
            if slot is None:
                for m in range(M):
                    if ready_F(s, m):
                        slot = (F, m)
                        break
            if slot is None and policy == "zb1":
                for m in range(M):
                    if ready_W(s, m):
                        slot = (W, m)
                        break
            if slot is None:
                continue
            kind, m = slot
            rows[s][t] = slot
            if kind == F:
                f_done[s][m] = t
            elif kind == B:
                b_done[s][m] = t
                if policy == "1f1b":
                    forced[s] = (W, m)
            elif kind == W:
                w_done[s][m] = t
        t += 1
    if t >= limit:
        raise RuntimeError("schedule did not converge")
    return rows


def schedule_stats(rows):
    """(makespan, idle_slots, bubble_fraction)."""
    T = len(rows[0])
    idle = sum(1 for r in rows for k, _ in r if k == IDLE)
    return T, idle, idle / (T * len(rows))


def _slot_ticks(rows):
    S = len(rows)
    f_t = [{} for _ in range(S)]
    b_t = [{} for _ in range(S)]
    w_t = [{} for _ in range(S)]
    for s in range(S):
        for t, (k, m) in enumerate(rows[s]):
            if k == F:
                f_t[s][m] = t
            elif k == B:
                b_t[s][m] = t
            elif k == W:
                w_t[s][m] = t
    return f_t, b_t, w_t


def _depths(rows, n_micro):
    """Ring-buffer depths (act, cot, res): max lifetime span (in distinct
    mbs) of saved activations, cotangents and vjp residuals.

    Lifetimes MUST start at the *arrival* tick, not this stage's own
    execution tick: stage s ingests mb m's activation at f_done[s-1][m]+1
    (cotangent at b_done[s+1][m]+1), and the scan's ingest phase runs
    *before* the slot executes — so an arrival at tick t conflicts with a
    same-tick W reading another mb in the same slot.  Lifetimes end at this
    stage's W tick inclusive (W re-reads the activation, the cotangent and
    the residuals).  Residuals are written at this stage's own F tick and
    read at B and W.  Deriving the window from local F/B ticks (the
    pre-round-4 bug) silently corrupted last-stage weight grads whenever
    n_micro > n_stages."""
    S = len(rows)
    T = len(rows[0])
    f_t, b_t, w_t = _slot_ticks(rows)
    act_d, cot_d, res_d = 1, 1, 1
    for s in range(S):
        for t in range(T):
            # activation arrival: upstream F + 1.  Slot conflicts only come
            # from ingest writes, so stage 0 (which never ingests — take_f
            # requires r > 0; its act_buf stays the zeros it was initialised
            # to) and the last stage's cotangents (take_b requires r < n-1;
            # g_in is masked by is_last) don't constrain the buffers.
            if s > 0:
                live_a = [m for m in range(n_micro)
                          if f_t[s - 1].get(m, 10**9) + 1 <= t
                          and w_t[s].get(m, -1) >= t]
                if live_a:
                    act_d = max(act_d, max(live_a) - min(live_a) + 1)
            if s < S - 1:
                live_c = [m for m in range(n_micro)
                          if b_t[s + 1].get(m, 10**9) + 1 <= t
                          and w_t[s].get(m, -1) >= t]
                if live_c:
                    cot_d = max(cot_d, max(live_c) - min(live_c) + 1)
            # residuals: written at OWN F tick (execution phase, after
            # ingest), read through the W tick inclusive
            live_r = [m for m in range(n_micro)
                      if f_t[s].get(m, 10**9) <= t
                      and w_t[s].get(m, -1) >= t]
            if live_r:
                res_d = max(res_d, max(live_r) - min(live_r) + 1)
    return (min(act_d, n_micro), min(cot_d, n_micro), min(res_d, n_micro))


def spmd_pipeline_zero_bubble(fwd_mb: Callable, params, n_micro: int,
                              act_sd, axis: str = "pp", policy: str = "zb1",
                              varying_axes=("dp", "pp", "mp", "ep")):
    """Run the slot-table schedule inside shard_map over `axis`.

    fwd_mb(params, c, act_in, mb_idx) -> (act_out, loss_mb) — same contract
    as spmd_pipeline_1f1b (c is always 0; no VPP chunks here).
    Returns (loss_sum_on_last_stage, grads_like_params).
    """
    n = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    S = n
    rows = build_schedule(S, n_micro, policy)
    T = len(rows[0])
    act_depth, cot_depth, res_depth = _depths(rows, n_micro)
    kind_arr = jnp.asarray([[k for k, _ in row] for row in rows], jnp.int32)
    mb_arr = jnp.asarray([[m for _, m in row] for row in rows], jnp.int32)
    perm_f = [(i, (i + 1) % n) for i in range(n)]
    perm_b = [(i, (i - 1) % n) for i in range(n)]

    va = _axes_in_scope(varying_axes)
    params = jax.tree_util.tree_map(lambda p: _vary(p, va), params)
    mb_shape, mb_dtype = act_sd.shape, act_sd.dtype

    # ---- residual structure probe -----------------------------------------
    # Trace the stage vjp once (outputs unused -> the probe's compute is
    # DCE'd) to learn the residual pytree: which leaves are true
    # intermediates (buffered) vs. the parameter arrays / the stage input
    # (deduped — substituted back at B/W time).  The per-tick do_F trace of
    # the same function at the same shapes is deterministic, so leaf order
    # matches.
    param_leaves = jax.tree_util.tree_leaves(params)
    param_ids = {id(l): i for i, l in enumerate(param_leaves)}
    probe_a = _vary(jnp.zeros(mb_shape, mb_dtype), va)
    probe_m = jnp.zeros((), jnp.int32)
    _, probe_vjp = jax.vjp(
        lambda a, p: fwd_mb(p, 0, a, probe_m), probe_a, params)
    probe_leaves, vjp_treedef = jax.tree_util.tree_flatten(probe_vjp)
    # leaf classification: ("param", idx) | ("act",) | ("buf", buf_slot)
    leaf_kind = []
    buf_shapes = []
    for leaf in probe_leaves:
        if id(leaf) in param_ids:
            leaf_kind.append(("param", param_ids[id(leaf)]))
        elif leaf is probe_a:
            leaf_kind.append(("act",))
        else:
            leaf_kind.append(("buf", len(buf_shapes)))
            buf_shapes.append((leaf.shape, leaf.dtype))

    def _rebuild_vjp(buf_leaves, a_in):
        leaves = []
        for kind in leaf_kind:
            if kind[0] == "param":
                leaves.append(param_leaves[kind[1]])
            elif kind[0] == "act":
                leaves.append(a_in)
            else:
                leaves.append(buf_leaves[kind[1]])
        return jax.tree_util.tree_unflatten(vjp_treedef, leaves)

    def tick(carry, t):
        act_buf, cot_buf, res_buf, gacc, loss_acc, send_f, send_b = carry
        # ---- ingest last tick's arrivals (table-addressed) ---------------
        prev_r = jnp.mod(r - 1, n)
        next_r = jnp.mod(r + 1, n)
        pk = kind_arr[prev_r, jnp.maximum(t - 1, 0)]
        pm = mb_arr[prev_r, jnp.maximum(t - 1, 0)]
        recv_f = jax.lax.ppermute(send_f, axis, perm_f)
        recv_b = jax.lax.ppermute(send_b, axis, perm_b)
        take_f = (t > 0) & (pk == F) & (r > 0)
        act_buf = jnp.where(take_f,
                            act_buf.at[jnp.mod(pm, act_depth)].set(recv_f),
                            act_buf)
        nk = kind_arr[next_r, jnp.maximum(t - 1, 0)]
        nm = mb_arr[next_r, jnp.maximum(t - 1, 0)]
        take_b = (t > 0) & (nk == B) & (r < n - 1)
        cot_buf = jnp.where(take_b,
                            cot_buf.at[jnp.mod(nm, cot_depth)].set(recv_b),
                            cot_buf)

        my_k = kind_arr[r, t]
        my_m = mb_arr[r, t]
        a_in = act_buf[jnp.mod(my_m, act_depth)]
        res_slot = jnp.mod(my_m, res_depth)

        def load_res():
            # called INSIDE do_B/do_W only: lax.switch operands are strict,
            # so slicing before the switch would read every residual buffer
            # on every tick (F/idle included) — the largest arrays in the
            # carry
            return tuple(buf[res_slot] for buf in res_buf)

        def zeros_res():
            return tuple(jnp.zeros(shp, dt) for shp, dt in buf_shapes)

        def norm_out(a, g, gp, l, res):
            # align vma types across lax.switch branches
            return (_vary(a, va), _vary(g, va),
                    jax.tree_util.tree_map(lambda x: _vary(x, va), gp),
                    _vary(l, va),
                    tuple(_vary(x, va) for x in res))

        def do_idle(a_in, g_in):
            return norm_out(jnp.zeros(mb_shape, mb_dtype),
                            jnp.zeros(mb_shape, mb_dtype),
                            jax.tree_util.tree_map(jnp.zeros_like, params),
                            jnp.zeros((), jnp.float32), zeros_res())

        def do_F(a_in, g_in):
            # forward + residual capture (the AD tape for this mb's B and W)
            (a_out, l_mb), vjp_fn = jax.vjp(
                lambda a, p: fwd_mb(p, 0, a, my_m), a_in, params)
            leaves = jax.tree_util.tree_leaves(vjp_fn)
            res = tuple(leaves[i] for i, kind in enumerate(leaf_kind)
                        if kind[0] == "buf")
            return norm_out(a_out, jnp.zeros(mb_shape, mb_dtype),
                            jax.tree_util.tree_map(jnp.zeros_like, params),
                            l_mb.astype(jnp.float32), res)

        def do_B(a_in, g_in):
            # input-grad only from saved residuals: the dW contractions are
            # dead code here (gp discarded) and get pruned by XLA — no
            # forward recompute, just the dx chain
            vjp_fn = _rebuild_vjp(load_res(), a_in)
            is_last = r == n - 1
            g_act = jnp.where(is_last, jnp.zeros(mb_shape, mb_dtype), g_in)
            ga, _ = vjp_fn((g_act, _vary(jnp.ones((), jnp.float32), va)))
            return norm_out(jnp.zeros(mb_shape, mb_dtype), ga,
                            jax.tree_util.tree_map(jnp.zeros_like, params),
                            jnp.zeros((), jnp.float32), zeros_res())

        def do_W(a_in, g_in):
            # weight-grad from the SAME saved residuals (ga discarded)
            vjp_fn = _rebuild_vjp(load_res(), a_in)
            is_last = r == n - 1
            g_act = jnp.where(is_last, jnp.zeros(mb_shape, mb_dtype), g_in)
            _, gp = vjp_fn((g_act, _vary(jnp.ones((), jnp.float32), va)))
            return norm_out(jnp.zeros(mb_shape, mb_dtype),
                            jnp.zeros(mb_shape, mb_dtype), gp,
                            jnp.zeros((), jnp.float32), zeros_res())

        g_in = cot_buf[jnp.mod(my_m, cot_depth)]
        branches = [do_idle, do_F, do_B, do_W]
        a_out, g_out, gp, l_mb, res_out = jax.lax.switch(
            my_k, branches, a_in, g_in)
        # write residuals on F slots only; lax.cond (not jnp.where) so the
        # non-F path is a true no-op instead of a full-buffer select
        res_buf = jax.lax.cond(
            my_k == F,
            lambda bufs: tuple(buf.at[res_slot].set(new)
                               for buf, new in zip(bufs, res_out)),
            lambda bufs: bufs, res_buf)
        # last stage's loss counts only on F slots (head runs there)
        loss_acc = loss_acc + jnp.where(my_k == F, l_mb, 0.0)
        gacc = jax.tree_util.tree_map(lambda acc, g: acc + g.astype(acc.dtype),
                                      gacc, gp)
        return (act_buf, cot_buf, res_buf, gacc, loss_acc, a_out, g_out), None

    carry = (jnp.zeros((act_depth,) + mb_shape, mb_dtype),
             jnp.zeros((cot_depth,) + mb_shape, mb_dtype),
             tuple(jnp.zeros((res_depth,) + shp, dt)
                   for shp, dt in buf_shapes),
             jax.tree_util.tree_map(
                 lambda p: jnp.zeros(p.shape, p.dtype), params),
             jnp.zeros((), jnp.float32),
             jnp.zeros(mb_shape, mb_dtype),
             jnp.zeros(mb_shape, mb_dtype))
    if va:
        carry = jax.tree_util.tree_map(lambda x: _vary(x, va), carry)
    (_, _, _, gacc, loss_acc, _, _), _ = jax.lax.scan(
        tick, carry, jnp.arange(T))
    return loss_acc, gacc
