"""Compiled SPMD pipeline parallelism over the 'pp' mesh axis.

Reference: fleet/meta_parallel/pipeline_parallel.py (1F1B :242) + P2P helper
(p2p_communication.py:651) + zero-bubble schedule pass
(pipeline_zero_bubble.py:62).

TPU-native design: XLA is a static-graph world, so the schedule is a
differentiable program — a `lax.scan` over ticks where every stage computes
its microbatch and hands activations to the next stage with `lax.ppermute`
(ICI neighbor hop). `jax.grad` through the scan yields the reverse schedule
automatically (backward ppermutes run opposite the ring), which XLA overlaps
with compute. This is the GPipe/1F1B-equivalent steady-state with the same
bubble fraction (n_stages-1)/(n_micro+n_stages-1).

The model is expressed in three functional pieces (the LayerDesc segmentation
analog for the common LM case):
  embed_apply(embed_params, batch)        -> activations  (runs on stage 0)
  block_apply(one_block_params, act)      -> activations  (layers_per_stage per stage)
  head_loss_apply(head_params, act, batch)-> scalar loss  (runs on last stage)
"""
from __future__ import annotations

import functools
from typing import Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.tensor import Tensor

__all__ = ["spmd_pipeline", "PipelineTrainStep"]


def spmd_pipeline(block_fn, stage_params, x, n_micro: int, axis: str = "pp",
                  varying_axes=("dp", "pp", "mp")):
    """Run x ([n_micro, mbs, ...]) through n_stages stages connected in a ring.

    Must be called inside shard_map with `axis` in scope; `stage_params` are
    this stage's parameters. block_fn(stage_params, act) -> act.
    Returns [n_micro, mbs, ...] outputs (valid on the LAST stage).
    """
    n = jax.lax.psum(1, axis)
    r = jax.lax.axis_index(axis)
    mb_shape = x.shape[1:]
    total = n_micro + n - 1
    perm = [(i, (i + 1) % n) for i in range(n)]

    state = jnp.zeros(mb_shape, x.dtype)      # incoming activation
    outputs = jnp.zeros((n_micro,) + mb_shape, x.dtype)
    # mark carries as axis-varying so scan carry typing matches per-shard values
    va = _axes_in_scope(varying_axes)
    if va:
        state = jax.lax.pcast(state, va, to="varying")
        outputs = jax.lax.pcast(outputs, va, to="varying")

    def tick(carry, t):
        state, outputs = carry
        # stage 0 ingests microbatch t (clamped); later stages consume `state`
        inp = jnp.where(r == 0, x[jnp.minimum(t, n_micro - 1)], state)
        out = block_fn(stage_params, inp)
        # last stage records its result for microbatch (t - (n-1))
        idx = t - (n - 1)
        write = (r == n - 1) & (idx >= 0)
        updated = outputs.at[jnp.clip(idx, 0, n_micro - 1)].set(out)
        outputs = jnp.where(write, updated, outputs)
        # rotate activations around the ring
        state = jax.lax.ppermute(out, axis, perm)
        return (state, outputs), None

    (state, outputs), _ = jax.lax.scan(tick, (state, outputs), jnp.arange(total))
    return outputs


def _axes_in_scope(names):
    out = []
    for n in names:
        try:
            jax.lax.axis_index(n)
            out.append(n)
        except Exception:
            pass
    return tuple(out)


class PipelineTrainStep:
    """Hybrid dp×pp(×mp via constraints) compiled train step for LM-shaped
    models. Parameters:

      embed_params: pytree (replicated over pp; used on stage 0)
      block_params: pytree with leading dim L = n_pp * layers_per_stage,
                    sharded over 'pp' on that dim
      head_params:  pytree (used on last stage)

    The step scans layers_per_stage blocks inside each pipeline stage.
    """

    def __init__(self, mesh: Mesh, embed_apply, block_apply, head_loss_apply,
                 embed_params, block_params, head_params, optimizer,
                 n_micro: int, batch_spec=P("dp"), donate=True):
        self.mesh = mesh
        self.n_micro = n_micro
        self.embed_apply = embed_apply
        self.block_apply = block_apply
        self.head_loss_apply = head_loss_apply
        self.opt = optimizer

        n_pp = mesh.shape.get("pp", 1)
        self.n_pp = n_pp

        def place(tree, spec_fn):
            return jax.tree_util.tree_map(
                lambda v: jax.device_put(v, NamedSharding(mesh, spec_fn(v))), tree)

        rep = lambda v: P(*([None] * v.ndim))
        stacked = lambda v: P(*(["pp"] + [None] * (v.ndim - 1)))
        self.embed_params = place(embed_params, rep)
        self.block_params = place(block_params, stacked)
        self.head_params = place(head_params, rep)
        self.opt_state = {
            "embed": self.opt.init_opt_state(_flatten(self.embed_params)),
            "block": self.opt.init_opt_state(_flatten(self.block_params)),
            "head": self.opt.init_opt_state(_flatten(self.head_params)),
        }
        # keep opt state co-sharded with params
        self.opt_state = jax.tree_util.tree_map(lambda v: v, self.opt_state)

        from jax import shard_map

        blk_spec = jax.tree_util.tree_map(lambda v: P(*(["pp"] + [None] * (v.ndim - 1))),
                                          self.block_params)
        rep_spec_e = jax.tree_util.tree_map(lambda v: P(*([None] * v.ndim)),
                                            self.embed_params)
        rep_spec_h = jax.tree_util.tree_map(lambda v: P(*([None] * v.ndim)),
                                            self.head_params)

        def loss_fn(embed_p, block_p, head_p, batch):
            # inside shard_map: block_p leading dim = layers_per_stage
            x = self.embed_apply(embed_p, batch)           # [n_micro, mbs, ...]
            def stage(bp, act):
                def one(act, layer_p):
                    return self.block_apply(layer_p, act), None
                out, _ = jax.lax.scan(lambda a, p: one(a, p), act, bp)
                return out
            y = spmd_pipeline(stage, block_p, x, self.n_micro)
            loss = self.head_loss_apply(head_p, y, batch)  # valid on last stage
            n = jax.lax.psum(1, "pp")
            r = jax.lax.axis_index("pp")
            loss = jnp.where(r == n - 1, loss, 0.0)
            loss = jax.lax.psum(loss, "pp")                # broadcast last-stage loss
            for ax in mesh.axis_names:
                if ax != "pp":
                    loss = jax.lax.pmean(loss, ax)
            return loss

        def grad_step(embed_p, block_p, head_p, eo, bo, ho, lr, batch):
            loss, g = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
                embed_p, block_p, head_p, batch)
            ge, gb, gh = g
            # embed/head grads live on their owning stage only → share over pp
            # (the broadcast_*_parameters analog, done on grads)
            ge, gh = jax.tree_util.tree_map(
                lambda v: jax.lax.psum(v, "pp"), (ge, gh))
            # dp gradient sync (XLA fuses/overlaps with backward)
            if "dp" in mesh.axis_names and mesh.shape["dp"] > 1:
                ge, gb, gh = jax.tree_util.tree_map(
                    lambda v: jax.lax.pmean(v, "dp"), (ge, gb, gh))
            # mp axis unused by the scalar program here: grads already equal;
            # pmean makes replication explicit for the partitioner
            if "mp" in mesh.axis_names and mesh.shape["mp"] > 1:
                ge, gb, gh = jax.tree_util.tree_map(
                    lambda v: jax.lax.pmean(v, "mp"), (ge, gb, gh))
            ne, neo = self.opt.apply_gradients_functional(
                _flatten(embed_p), _flatten(ge), eo, lr=lr)
            nb, nbo = self.opt.apply_gradients_functional(
                _flatten(block_p), _flatten(gb), bo, lr=lr)
            nh, nho = self.opt.apply_gradients_functional(
                _flatten(head_p), _flatten(gh), ho, lr=lr)
            return (_unflatten(ne, embed_p), _unflatten(nb, block_p),
                    _unflatten(nh, head_p), neo, nbo, nho, loss)

        batch_in_spec = batch_spec
        state_spec_e = rep_spec_e
        opt_spec = lambda ps: jax.tree_util.tree_map(lambda v: P(*([None] * v.ndim)), ps)

        sm = shard_map(
            grad_step, mesh=mesh,
            in_specs=(rep_spec_e, blk_spec, rep_spec_h,
                      _opt_specs(self.opt_state["embed"], None),
                      _opt_specs(self.opt_state["block"], "pp"),
                      _opt_specs(self.opt_state["head"], None),
                      P(), batch_in_spec),
            out_specs=(rep_spec_e, blk_spec, rep_spec_h,
                       _opt_specs(self.opt_state["embed"], None),
                       _opt_specs(self.opt_state["block"], "pp"),
                       _opt_specs(self.opt_state["head"], None),
                       P()))
        donate_args = tuple(range(6)) if donate else ()
        self._step = jax.jit(sm, donate_argnums=donate_args)

    def __call__(self, batch):
        v = jax.tree_util.tree_map(
            lambda b: b._value if isinstance(b, Tensor) else jnp.asarray(b), batch,
            is_leaf=lambda x: isinstance(x, Tensor))
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        (self.embed_params, self.block_params, self.head_params,
         self.opt_state["embed"], self.opt_state["block"], self.opt_state["head"],
         loss) = self._step(self.embed_params, self.block_params, self.head_params,
                            self.opt_state["embed"], self.opt_state["block"],
                            self.opt_state["head"], lr, v)
        self.opt.finish_step()
        return Tensor(loss)


def _flatten(tree, prefix=""):
    out = {}
    if isinstance(tree, dict):
        for k, v in tree.items():
            key = f"{prefix}.{k}" if prefix else str(k)
            if isinstance(v, dict):
                out.update(_flatten(v, key))
            else:
                out[key] = v
        return out
    leaves, _ = jax.tree_util.tree_flatten(tree)
    return {f"{prefix}.{i}" if prefix else str(i): l for i, l in enumerate(leaves)}


def _unflatten(flat, like):
    if isinstance(like, dict):
        out = {}
        for k, v in like.items():
            if isinstance(v, dict):
                sub = {kk[len(str(k)) + 1:]: vv for kk, vv in flat.items()
                       if kk.startswith(f"{k}.")}
                out[k] = _unflatten(sub, v)
            else:
                out[k] = flat[str(k)]
        return out
    leaves, treedef = jax.tree_util.tree_flatten(like)
    return jax.tree_util.tree_unflatten(
        treedef, [flat[str(i)] for i in range(len(leaves))])


def _opt_specs(opt_state, stack_axis):
    def spec(v):
        nd = getattr(v, "ndim", 0)
        if stack_axis and nd >= 1:
            return P(*([stack_axis] + [None] * (nd - 1)))
        return P(*([None] * nd))
    return jax.tree_util.tree_map(spec, opt_state)


def _opt_specs_named(opt_state, param_suffixes, stack_axis):
    """Opt-state specs that co-shard moment buffers with tensor-parallel
    params: opt_state is {pname: {state_key: leaf}}; param_suffixes maps
    pname -> partition suffix (excluding the stacked-layer dim).  Moment
    leaves (same ndim as the param) inherit the param's spec; scalars and
    everything else fall back to stack-dim-only / replicated."""
    def spec_for(pname, v):
        nd = getattr(v, "ndim", 0)
        suffix = param_suffixes.get(pname)
        if suffix is not None and nd == len(suffix) + 1:
            return P(stack_axis, *suffix)
        if stack_axis and nd >= 1:
            return P(*([stack_axis] + [None] * (nd - 1)))
        return P(*([None] * nd))
    return {pname: jax.tree_util.tree_map(lambda v: spec_for(pname, v), st)
            for pname, st in opt_state.items()}
