"""Compiled training machinery — the performance path.

This is the TPU-native replacement for the reference's executor stack
(SURVEY.md §3.3): instead of an instruction interpreter, the WHOLE train step
(forward + backward + optimizer + collectives) is one jitted, buffer-donated
XLA program. Parallelism is expressed as shardings on the step's inputs:

* dp     — batch sharded over 'dp' (grad all-reduce emitted by XLA)
* ZeRO   — optimizer state / grads / params sharded over 'sharding'
* tp/sp  — layer-level sharding constraints (fleet.meta_parallel.mp_layers)
* pp     — stage-stacked params + ppermute microbatch schedule (pipeline.py)
"""
from __future__ import annotations

from .train_step import TrainStep, compile_train_step
from .pipeline import PipelineTrainStep
from .pipeline_schedules import (Pipeline1F1BTrainStep,
                                 GenericPipeline1F1BTrainStep)
from .sharded import ShardedTrainStep

__all__ = ["TrainStep", "compile_train_step", "PipelineTrainStep",
           "Pipeline1F1BTrainStep", "GenericPipeline1F1BTrainStep",
           "ShardedTrainStep"]
