"""Compiled ZeRO (group-sharded) train step — stages 1/2/3.

The TPU-native equivalent of the reference's group-sharded machinery
(python/paddle/distributed/fleet/meta_parallel/sharding/
group_sharded_stage2.py, group_sharded_stage3.py:174 `_param2buffer`,
:335 `_update_params_slice`, :560 forward gather/release hooks):

* every parameter is flattened and zero-padded to ``N * chunk`` so each of
  the N ranks on the ZeRO axis owns one contiguous ``chunk`` slice — the
  per-rank slice buffer analog of ``_param2buffer``;
* **stage 1** (os):    grads all-reduced (psum), each rank updates only its
  slice with its shard of the optimizer state, updated params all-gathered;
* **stage 2** (os_g):  grads reduce-scattered (``lax.psum_scatter`` — the
  collective the stage2 grad hooks issue), then as stage 1;
* **stage 3** (p_g_os): parameters live sharded between steps; the step
  all-gathers them just-in-time for the forward (the forward-prehook gather
  analog), re-gathers under remat for backward, reduce-scatters grads and
  writes back only the local slice (the posthook release analog is XLA
  buffer donation — the gathered full copy is transient).

Everything runs inside one ``shard_map`` + ``jax.jit`` so XLA schedules the
collectives (reduce-scatter/all-gather ride ICI) and fuses the optimizer
update over the slice.
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
from jax import shard_map

from ..core.tensor import Tensor

__all__ = ["ShardedTrainStep", "zero_stage_name"]


def zero_stage_name(stage) -> int:
    """Normalize Paddle level strings ('os', 'os_g', 'p_g_os') to 0/1/2/3
    (0 = plain data parallel, nothing sharded)."""
    if stage in (0, 1, 2, 3):
        return int(stage)
    table = {"os": 1, "os_g": 2, "p_g_os": 3,
             "stage1": 1, "stage2": 2, "stage3": 3, "none": 0,
             "0": 0, "1": 1, "2": 2, "3": 3}
    key = str(stage)
    if key not in table:
        raise ValueError(
            f"unknown ZeRO stage {stage!r}; expected one of 0/1/2/3 or "
            f"{sorted(table)}")
    return table[key]


class ShardedTrainStep:
    """One-jit ZeRO train step over an arbitrary params pytree.

    loss_fn(params_pytree, batch) -> scalar loss.  The batch's leading dim is
    split across the ZeRO axis (data parallel); loss is the global mean.
    """

    def __init__(self, mesh: Mesh, loss_fn: Callable, params: Any, opt,
                 stage=2, axis: str = "dp", remat: bool = False,
                 clip_norm: Optional[float] = None, donate: bool = True,
                 bucket: bool = False):
        """bucket=True fuses all same-dtype leaves into ONE contiguous flat
        buffer (the group_sharded_storage.py fused-storage analog): the
        grad reduce-scatter and param all-gather become one collective per
        dtype group instead of one per leaf — the collective-launch-overhead
        fix for models with hundreds of leaves."""
        self.mesh = mesh
        self.axis = axis
        self.stage = zero_stage_name(stage)
        self.opt = opt
        self.remat = remat
        self.clip_norm = clip_norm
        self.bucket = bucket
        n = mesh.shape[axis]
        self.n_shards = n

        leaves, self.treedef = jax.tree_util.tree_flatten(params)
        self.shapes = [l.shape for l in leaves]
        self.dtypes = [l.dtype for l in leaves]
        self.sizes = [int(np.prod(s)) if s else 1 for s in self.shapes]
        self.padded = [((sz + n - 1) // n) * n for sz in self.sizes]

        self._loss_fn = loss_fn

        # flattened padded global arrays, sharded over the ZeRO axis
        flat_sh = NamedSharding(mesh, P(axis))
        repl_sh = NamedSharding(mesh, P())

        def to_flat(leaf, pad):
            f = jnp.ravel(leaf)
            if pad != f.size:
                f = jnp.concatenate([f, jnp.zeros(pad - f.size, f.dtype)])
            return f

        if bucket:
            # fused layout: one buffer per dtype group; per-leaf (name, offset)
            groups = {}
            self._layout = []
            for i, l in enumerate(leaves):
                key = f"b_{jnp.dtype(self.dtypes[i]).name}"
                off = groups.setdefault(key, [0, []])
                self._layout.append((key, off[0]))
                off[0] += self.sizes[i]
                off[1].append(jnp.ravel(l))
            names, flats = [], []
            for key, (total, parts) in groups.items():
                pad = ((total + n - 1) // n) * n
                buf = jnp.concatenate(parts)
                if pad != buf.size:
                    buf = jnp.concatenate(
                        [buf, jnp.zeros(pad - buf.size, buf.dtype)])
                names.append(key)
                flats.append(buf)
        else:
            flats = [to_flat(l, p) for l, p in zip(leaves, self.padded)]
            names = [f"p{i}" for i in range(len(flats))]
            self._layout = [(nm, 0) for nm in names]
        self._names = names

        if self.stage >= 3:
            self.flat_params = {k: jax.device_put(v, flat_sh)
                                for k, v in zip(names, flats)}
        else:
            self.flat_params = {k: jax.device_put(v, repl_sh)
                                for k, v in zip(names, flats)}
        # optimizer state lives sharded from stage 1 up (stage 1's whole
        # point); scalars (beta pow counters) and stage 0 stay replicated
        def place_state(v):
            sh = flat_sh if (self.stage >= 1 and self._shardable(v)) \
                else repl_sh
            return jax.device_put(v, sh)
        self.opt_state = jax.tree_util.tree_map(
            place_state, opt.init_opt_state(self.flat_params))

        self._step = self._build(donate)

    def _shardable(self, v):
        return (hasattr(v, "ndim") and v.ndim >= 1 and v.shape[0] > 0
                and v.shape[0] % self.n_shards == 0)

    # -- pytree <-> flat slice plumbing ------------------------------------
    def _assemble(self, full_flats):
        """[padded] flat arrays -> original params pytree (local, in-step)."""
        leaves = []
        for (k, off), shape, size, dtype in zip(self._layout, self.shapes,
                                                self.sizes, self.dtypes):
            f = full_flats[k]
            leaves.append(f[off:off + size].reshape(shape).astype(dtype))
        return jax.tree_util.tree_unflatten(self.treedef, leaves)

    @staticmethod
    def _coerce_batch(batch):
        return tuple(b._value if isinstance(b, Tensor) else jnp.asarray(b)
                     for b in (batch if isinstance(batch, (tuple, list))
                               else (batch,)))

    # -- the compiled step --------------------------------------------------
    def _build(self, donate):
        ax, n, stage = self.axis, self.n_shards, self.stage
        mesh = self.mesh
        opt = self.opt

        remat = self.remat

        def local_step(flat_params, opt_state, lr, *batch):
            # flat_params local views: [padded/n] (stage 3) or [padded] (1/2)
            if stage >= 3:
                # Differentiate w.r.t. the LOCAL slices with the all_gather
                # INSIDE the (optionally rematted) loss: autodiff transposes
                # all_gather into psum_scatter, so grads arrive already
                # reduce-scattered, and under remat the backward re-gathers
                # params per use instead of keeping the full copy live —
                # the real ZeRO-3 memory behavior (stage3 gather/release
                # hooks, group_sharded_stage3.py:560).
                def loss_of(slices):
                    full = {k: jax.lax.all_gather(v, ax, tiled=True)
                            for k, v in slices.items()}
                    return self._loss_fn(self._assemble(full), batch)

                fn = jax.checkpoint(loss_of) if remat else loss_of
                loss, graw = jax.value_and_grad(fn)(flat_params)
                # psum_scatter summed over ranks -> mean
                gslice = {k: g.astype(jnp.float32) / n
                          for k, g in graw.items()}
                pslice = flat_params
            else:
                def loss_of(full_flats):
                    return self._loss_fn(self._assemble(full_flats), batch)

                fn = jax.checkpoint(loss_of) if remat else loss_of
                loss, gfull = jax.value_and_grad(fn)(flat_params)
                gflat = {k: jnp.ravel(g).astype(jnp.float32)
                         for k, g in gfull.items()}
                r = jax.lax.axis_index(ax)
                if stage == 0:
                    # plain DP: all-reduce grads, update replicated params
                    gslice = {k: jax.lax.pmean(g, ax) for k, g in gflat.items()}
                    pslice = flat_params
                elif stage == 1:
                    # all-reduce full grads, every rank slices its own chunk
                    gslice = {}
                    for k, g in gflat.items():
                        g = jax.lax.pmean(g, ax)
                        chunk = g.shape[0] // n
                        gslice[k] = jax.lax.dynamic_slice_in_dim(
                            g, r * chunk, chunk)
                else:
                    # reduce-scatter: each rank receives the mean of its slice
                    gslice = {k: jax.lax.psum_scatter(
                        g, ax, scatter_dimension=0, tiled=True) / n
                        for k, g in gflat.items()}
                if stage >= 1:
                    pslice = {}
                    for k, v in flat_params.items():
                        chunk = v.shape[0] // n
                        pslice[k] = jax.lax.dynamic_slice_in_dim(
                            v, r * chunk, chunk)

            loss = jax.lax.pmean(loss, ax)

            if self.clip_norm is not None:
                # global grad-norm over ALL shards (ClipGradByGlobalNorm
                # across the sharding group, hybrid_parallel_optimizer
                # analog); slices are disjoint chunks of the full grad
                sq = sum(jnp.sum(jnp.square(g)) for g in gslice.values())
                gnorm = jnp.sqrt(jax.lax.psum(sq, ax))
                scale = jnp.minimum(1.0, self.clip_norm / (gnorm + 1e-6))
                gslice = {k: g * scale for k, g in gslice.items()}

            # update only the local slice with the local optimizer shard
            new_slice, new_opt = opt.apply_gradients_functional(
                pslice, gslice, opt_state, lr=lr)

            if stage >= 3 or stage == 0:
                new_params = new_slice        # sharded (3) / replicated (0)
            else:
                new_params = {k: jax.lax.all_gather(v, ax, tiled=True)
                              for k, v in new_slice.items()}
            return new_params, new_opt, loss

        flat_spec = {k: P(ax) for k in self._names}
        repl_spec = {k: P() for k in self._names}
        param_spec = flat_spec if stage >= 3 else repl_spec
        opt_spec = jax.tree_util.tree_map(
            lambda v: P(ax) if (stage >= 1 and self._shardable(v)) else P(),
            self.opt_state)
        batch_spec = P(ax)

        def stepper(flat_params, opt_state, lr, batch):
            sm = shard_map(
                local_step, mesh=mesh,
                in_specs=(param_spec, opt_spec, P(),
                          *([batch_spec] * len(batch))),
                out_specs=(param_spec, opt_spec, P()),
                check_vma=False)
            return sm(flat_params, opt_state, lr, *batch)

        return jax.jit(stepper, donate_argnums=(0, 1) if donate else ())

    def __call__(self, batch):
        batch = self._coerce_batch(batch)
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        self.flat_params, self.opt_state, loss = self._step(
            self.flat_params, self.opt_state, lr, batch)
        self.opt.finish_step()
        return loss

    # -- introspection ------------------------------------------------------
    def materialized_params(self):
        """Gather the full (unsharded) params pytree — checkpoints, eval.
        Multi-host safe: reshards to replicated first (device_get on an array
        sharded across non-addressable devices would fail), then assembles on
        host with numpy — no round-trip back through the device."""
        out_leaves = []
        repl = NamedSharding(self.mesh, P())
        full = {}
        for k in self._names:
            v = jax.device_put(self.flat_params[k], repl)
            full[k] = np.asarray(jax.device_get(v))
        for (k, off), shape, size, dtype in zip(self._layout, self.shapes,
                                                self.sizes, self.dtypes):
            out_leaves.append(
                full[k][off:off + size].reshape(shape).astype(dtype))
        return jax.tree_util.tree_unflatten(self.treedef, out_leaves)

    def lowered_hlo(self, batch) -> str:
        """Compiler IR of the step (tests assert collective choice here)."""
        batch = self._coerce_batch(batch)
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        return self._step.lower(
            self.flat_params, self.opt_state, lr, batch).as_text()

    def bytes_per_device(self):
        """(param_bytes, opt_bytes) actually resident per device."""
        def local_bytes(tree):
            total = 0
            for v in jax.tree_util.tree_leaves(tree):
                if hasattr(v, "addressable_shards"):
                    shard = v.addressable_shards[0]
                    total += int(np.prod(shard.data.shape)) * v.dtype.itemsize
                else:
                    total += v.size * v.dtype.itemsize
            return total
        return local_bytes(self.flat_params), local_bytes(self.opt_state)
