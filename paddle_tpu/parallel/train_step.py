"""Fused train step: one jit = fwd + bwd + optimizer update.

The analog of the reference's fused-kernel + interpreter hot loop
(SURVEY.md §3.1-3.2): Paddle pays per-op dispatch in C++; here the per-op
Python dispatch happens once at trace time and the steady-state loop is a
single XLA executable with donated buffers (params/opt-state update in place
in HBM).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as random_mod
from ..nn.layer import Layer, functional_state
from ..optimizer.optimizer import Optimizer
from ..optimizer.lr import LRScheduler

__all__ = ["TrainStep", "compile_train_step"]


class TrainStep:
    """Holds functional state (params, buffers, opt state) and a compiled
    step(batch) -> loss. Mutates the Layer's tensors only on `sync_to_model`.
    """

    def __init__(self, model: Layer, opt: Optimizer, loss_fn: Callable,
                 donate: bool = True, in_shardings=None, with_amp=False,
                 amp_dtype="bfloat16", grad_accum: int = 1):
        self.model = model
        self.opt = opt
        self.loss_fn = loss_fn
        self.with_amp = with_amp
        self.amp_dtype = amp_dtype
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        self.params = {n: p._value for n, p in model.named_parameters()
                       if not p.stop_gradient}
        self._lr_scales = {
            n: float(p.optimize_attr.get("learning_rate", 1.0))
            for n, p in model.named_parameters()
            if hasattr(p, "optimize_attr")
            and p.optimize_attr.get("learning_rate", 1.0) != 1.0}
        self.frozen = {n: p._value for n, p in model.named_parameters()
                       if p.stop_gradient}
        self.buffers = {n: b._value for n, b in model.named_buffers()}
        self.opt_state = opt.init_opt_state(self.params)
        self._rng = random_mod.split_key()

        donate_args = (0, 1, 2) if donate else ()
        self._step = jax.jit(self._step_impl, donate_argnums=donate_args)

    # pure: (params, opt_state, buffers, rng, lr, *batch) -> (loss, ...)
    def _step_impl(self, params, opt_state, buffers, rng, lr, *batch):
        if self.grad_accum == 1:
            (loss_v, new_buffers), grads = jax.value_and_grad(
                lambda p: self._loss_with(p, buffers, rng, batch),
                has_aux=True)(params)
        else:
            # gradient merge (reference gradient_merge pass analog): split the
            # global batch into grad_accum microbatches on the leading axis and
            # lax.scan the fwd+bwd, averaging loss and grads; one optimizer
            # update per call.
            a = self.grad_accum
            micro = []
            for b in batch:
                if b.shape[0] % a != 0:
                    raise ValueError(
                        f"batch dim {b.shape[0]} not divisible by "
                        f"grad_accum={a}")
                micro.append(b.reshape((a, b.shape[0] // a) + b.shape[1:]))
            rngs = jax.random.split(rng, a)

            def one(carry, xs):
                mb_rng, mb = xs[0], xs[1:]
                acc_loss, acc_grads, bufs = carry
                # buffers (e.g. BatchNorm running stats) chain microbatch to
                # microbatch, exactly as grad_accum sequential steps would
                (lv, new_bufs), g = jax.value_and_grad(
                    lambda p: self._loss_with(p, bufs, mb_rng, mb),
                    has_aux=True)(params)
                acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, g)
                return (acc_loss + lv, acc_grads, new_bufs), None

            zero_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_sum, gsum, new_buffers), _ = jax.lax.scan(
                one, (jnp.zeros((), jnp.float32), zero_g, buffers),
                (rngs, *micro))
            loss_v = loss_sum / a
            grads = jax.tree_util.tree_map(lambda g: g / a, gsum)
        new_params, new_opt = self.opt.apply_gradients_functional(
            params, grads, opt_state, lr=lr, lr_scales=self._lr_scales or None)
        return new_params, new_opt, new_buffers, loss_v

    def _loss_with(self, params, buffers, rng, batch):
        """Single-microbatch loss; shared by the plain and grad-accum paths."""
        state = {}
        state.update(params)
        state.update(self.frozen)
        state.update(buffers)
        with random_mod.trace_rng(rng):
            if self.with_amp:
                from ..amp import auto_cast
                ctx = auto_cast(dtype=self.amp_dtype)
            else:
                import contextlib
                ctx = contextlib.nullcontext()
            with ctx, functional_state(self.model, state) as fs:
                batch_t = [Tensor(b) for b in batch]
                loss = self.loss_fn(self.model, *batch_t)
                new_state = fs.collect()
        new_buffers = {k: new_state[k] for k in buffers}
        lv = loss._value if isinstance(loss, Tensor) else loss
        return lv, new_buffers

    def __call__(self, *batch):
        vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        self._rng, sub = jax.random.split(self._rng)
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        self.params, self.opt_state, self.buffers, loss = self._step(
            self.params, self.opt_state, self.buffers, sub, lr, *vals)
        self.opt.finish_step()
        return Tensor(loss)

    def sync_to_model(self):
        """Write the functional state back into the Layer/Optimizer objects
        (checkpointing, eval interop)."""
        targets = dict(self.model.named_parameters())
        for n, v in self.params.items():
            if n in targets:
                targets[n]._set_value(v)
        btargets = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in btargets:
                btargets[n]._set_value(v)
        names = {n: p for n, p in self.model.named_parameters()}
        for n, st in self.opt_state.items():
            p = names.get(n)
            if p is not None:
                self.opt._accumulators[id(p)] = dict(st)


def compile_train_step(model, opt, loss_fn, **kw) -> TrainStep:
    """loss_fn(model, *batch_tensors) -> scalar Tensor."""
    return TrainStep(model, opt, loss_fn, **kw)
