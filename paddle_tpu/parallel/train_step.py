"""Fused train step: one jit = fwd + bwd + optimizer update.

The analog of the reference's fused-kernel + interpreter hot loop
(SURVEY.md §3.1-3.2): Paddle pays per-op dispatch in C++; here the per-op
Python dispatch happens once at trace time and the steady-state loop is a
single XLA executable with donated buffers (params/opt-state update in place
in HBM).
"""
from __future__ import annotations

from typing import Callable, Dict, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as random_mod
from ..nn.layer import Layer, functional_state
from ..observability.train import batch_samples
from ..optimizer.optimizer import Optimizer
from ..optimizer.lr import LRScheduler

__all__ = ["TrainStep", "compile_train_step"]


class TrainStep:
    """Holds functional state (params, buffers, opt state) and a compiled
    step(batch) -> loss. Mutates the Layer's tensors only on `sync_to_model`.

    Non-finite sentinel (`nonfinite_guard=M`): every step checks loss AND
    every gradient for NaN/Inf inside the jitted step; a bad step is
    skipped — params/opt-state/buffers keep their previous values — and
    counted, raising FloatingPointError only after M CONSECUTIVE bad steps
    (one preempted reduction or a loss-scale spike must not kill a
    multi-day job; a persistently diverged one must).  An attached
    GradScaler gets its dynamic-loss-scale backoff driven on every skipped
    step.  The guard reads the good/bad flag to the host each step, so it
    costs one device sync — leave it off (None) for pure-throughput loops.
    The `train.nonfinite` fault point (resilience/faults.py) poisons a
    step's loss+grads with NaN on demand, so the skip path is testable.
    """

    def __init__(self, model: Layer, opt: Optimizer, loss_fn: Callable,
                 donate: bool = True, in_shardings=None, with_amp=False,
                 amp_dtype="bfloat16", grad_accum: int = 1,
                 nonfinite_guard: Optional[int] = None, scaler=None,
                 telemetry=None):
        self.model = model
        self.opt = opt
        self.loss_fn = loss_fn
        # observability.TrainTelemetry (or None = off): host-side step
        # timing + nonfinite/backoff counters + flight events.  Hooks fire
        # only at points the loop already stands on the host (after the
        # guard's flag fetch); without the guard the recorded step time is
        # dispatch wall time (the call is async).  Numerics are untouched
        # either way.
        self.telemetry = telemetry
        self.with_amp = with_amp
        self.amp_dtype = amp_dtype
        if grad_accum < 1:
            raise ValueError(f"grad_accum must be >= 1, got {grad_accum}")
        self.grad_accum = int(grad_accum)
        if nonfinite_guard is not None and nonfinite_guard < 1:
            raise ValueError(
                f"nonfinite_guard must be >= 1, got {nonfinite_guard}")
        self.nonfinite_guard = nonfinite_guard
        self.scaler = scaler
        self.step_count = 0
        self.skipped_steps = 0
        self.consecutive_bad = 0
        self.last_step_good = True
        self.params = {n: p._value for n, p in model.named_parameters()
                       if not p.stop_gradient}
        self._lr_scales = {
            n: float(p.optimize_attr.get("learning_rate", 1.0))
            for n, p in model.named_parameters()
            if hasattr(p, "optimize_attr")
            and p.optimize_attr.get("learning_rate", 1.0) != 1.0}
        self.frozen = {n: p._value for n, p in model.named_parameters()
                       if p.stop_gradient}
        self.buffers = {n: b._value for n, b in model.named_buffers()}
        self.opt_state = opt.init_opt_state(self.params)
        self._rng = random_mod.split_key()

        donate_args = (0, 1, 2) if donate else ()
        self._step = jax.jit(self._step_impl, donate_argnums=donate_args)

    # pure: (params, opt_state, buffers, rng, lr, poison, *batch) ->
    # (params', opt', buffers', loss, good).  `poison` is 0.0 normally and
    # NaN when the train.nonfinite fault point fires — adding it to loss and
    # grads is the identity for 0.0 and a full poisoning for NaN, keeping
    # the executable identical either way.
    def _step_impl(self, params, opt_state, buffers, rng, lr, poison, *batch):
        if self.grad_accum == 1:
            (loss_v, new_buffers), grads = jax.value_and_grad(
                lambda p: self._loss_with(p, buffers, rng, batch),
                has_aux=True)(params)
        else:
            # gradient merge (reference gradient_merge pass analog): split the
            # global batch into grad_accum microbatches on the leading axis and
            # lax.scan the fwd+bwd, averaging loss and grads; one optimizer
            # update per call.
            a = self.grad_accum
            micro = []
            for b in batch:
                if b.shape[0] % a != 0:
                    raise ValueError(
                        f"batch dim {b.shape[0]} not divisible by "
                        f"grad_accum={a}")
                micro.append(b.reshape((a, b.shape[0] // a) + b.shape[1:]))
            rngs = jax.random.split(rng, a)

            def one(carry, xs):
                mb_rng, mb = xs[0], xs[1:]
                acc_loss, acc_grads, bufs = carry
                # buffers (e.g. BatchNorm running stats) chain microbatch to
                # microbatch, exactly as grad_accum sequential steps would
                (lv, new_bufs), g = jax.value_and_grad(
                    lambda p: self._loss_with(p, bufs, mb_rng, mb),
                    has_aux=True)(params)
                acc_grads = jax.tree_util.tree_map(jnp.add, acc_grads, g)
                return (acc_loss + lv, acc_grads, new_bufs), None

            zero_g = jax.tree_util.tree_map(
                lambda p: jnp.zeros(p.shape, jnp.float32), params)
            (loss_sum, gsum, new_buffers), _ = jax.lax.scan(
                one, (jnp.zeros((), jnp.float32), zero_g, buffers),
                (rngs, *micro))
            loss_v = loss_sum / a
            grads = jax.tree_util.tree_map(lambda g: g / a, gsum)
        loss_v = loss_v + poison
        grads = jax.tree_util.tree_map(lambda g: g + poison, grads)
        new_params, new_opt = self.opt.apply_gradients_functional(
            params, grads, opt_state, lr=lr, lr_scales=self._lr_scales or None)
        if self.nonfinite_guard is not None:
            # the full-gradient finiteness reduction and the skip selects
            # exist ONLY under the guard — the pure-throughput default pays
            # nothing.  Skip-and-count: a bad step must leave params /
            # opt-state / buffers untouched (NaN moments would otherwise
            # poison every later step).
            good = jnp.isfinite(loss_v)
            for g in jax.tree_util.tree_leaves(grads):
                good = good & jnp.all(jnp.isfinite(g))
            keep = lambda new, old: jax.tree_util.tree_map(  # noqa: E731
                lambda a_, b_: jnp.where(good, a_, b_), new, old)
            new_params = keep(new_params, params)
            new_opt = keep(new_opt, opt_state)
            new_buffers = keep(new_buffers, buffers)
        else:
            good = jnp.bool_(True)     # constant: free for XLA
        return new_params, new_opt, new_buffers, loss_v, good

    def _loss_with(self, params, buffers, rng, batch):
        """Single-microbatch loss; shared by the plain and grad-accum paths."""
        state = {}
        state.update(params)
        state.update(self.frozen)
        state.update(buffers)
        with random_mod.trace_rng(rng):
            if self.with_amp:
                from ..amp import auto_cast
                ctx = auto_cast(dtype=self.amp_dtype)
            else:
                import contextlib
                ctx = contextlib.nullcontext()
            with ctx, functional_state(self.model, state) as fs:
                batch_t = [Tensor(b) for b in batch]
                loss = self.loss_fn(self.model, *batch_t)
                new_state = fs.collect()
        new_buffers = {k: new_state[k] for k in buffers}
        lv = loss._value if isinstance(loss, Tensor) else loss
        return lv, new_buffers

    def __call__(self, *batch):
        from ..resilience.faults import fault_point
        tel = self.telemetry
        t0 = tel.clock() if tel is not None else 0.0
        vals = [b._value if isinstance(b, Tensor) else jnp.asarray(b) for b in batch]
        self._rng, sub = jax.random.split(self._rng)
        lr = jnp.asarray(self.opt.get_lr(), jnp.float32)
        poison = 0.0
        if fault_point("train.nonfinite", step=self.step_count) is not None:
            poison = float("nan")
        self.params, self.opt_state, self.buffers, loss, good = self._step(
            self.params, self.opt_state, self.buffers, sub, lr,
            jnp.asarray(poison, jnp.float32), *vals)
        self.step_count += 1
        if self.nonfinite_guard is None:
            self.opt.finish_step()
            if tel is not None:
                tel.step(tel.clock() - t0, samples=batch_samples(vals))
        else:
            self.last_step_good = bool(good)
            if tel is not None:
                # the guard's flag fetch above IS a device sync, so this
                # step time is real device latency, not dispatch time
                tel.step(tel.clock() - t0, samples=batch_samples(vals),
                         good=self.last_step_good)
            if self.last_step_good:
                # finish_step (LR-schedule tick / global step) only on REAL
                # progress — a skipped step must leave schedule state
                # untouched too, or warmup/decay drifts ahead of the params
                self.opt.finish_step()
                self.consecutive_bad = 0
                if self.scaler is not None:
                    self.scaler.update()   # good step: drive scale regrowth
            else:
                self.skipped_steps += 1
                self.consecutive_bad += 1
                if tel is not None:
                    # resilience on the record: the skip + the fault plan
                    # that (possibly) injected it, for chaos postmortems
                    tel.nonfinite_skip(self.step_count - 1,
                                       self.consecutive_bad)
                if self.scaler is not None:
                    # count only ACTUAL backoffs: notify_nonfinite tallies
                    # the bad step but only decays the scale every
                    # decr_every_n_nan_or_inf-th one (_scale is a host
                    # float — the compare costs nothing)
                    scale_before = self.scaler._scale
                    self.scaler.notify_nonfinite()
                    if tel is not None \
                            and self.scaler._scale != scale_before:
                        tel.scaler_backoff(self.step_count - 1)
                if self.consecutive_bad >= self.nonfinite_guard:
                    if tel is not None:
                        # auto-dump the flight ring BEFORE the raise — the
                        # diverged-run postmortem artifact
                        tel.nonfinite_raise(self.step_count - 1,
                                            self.consecutive_bad,
                                            self.skipped_steps)
                    raise FloatingPointError(
                        f"non-finite loss/gradients for "
                        f"{self.consecutive_bad} consecutive steps (step "
                        f"{self.step_count - 1}, {self.skipped_steps} skipped "
                        f"total) — the run has diverged; restore a "
                        f"checkpoint or lower the learning rate")
        return Tensor(loss)

    def sync_to_model(self):
        """Write the functional state back into the Layer/Optimizer objects
        (checkpointing, eval interop)."""
        targets = dict(self.model.named_parameters())
        for n, v in self.params.items():
            if n in targets:
                targets[n]._set_value(v)
        btargets = dict(self.model.named_buffers())
        for n, v in self.buffers.items():
            if n in btargets:
                btargets[n]._set_value(v)
        names = {n: p for n, p in self.model.named_parameters()}
        for n, st in self.opt_state.items():
            p = names.get(n)
            if p is not None:
                self.opt._accumulators[id(p)] = dict(st)


def compile_train_step(model, opt, loss_fn, **kw) -> TrainStep:
    """loss_fn(model, *batch_tensors) -> scalar Tensor."""
    return TrainStep(model, opt, loss_fn, **kw)
