"""Vision ops (reference: python/paddle/vision/ops.py) — detection helpers."""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = ["nms", "box_coder", "roi_align", "deform_conv2d"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (dynamic output size — eager only, like the reference's
    dygraph-only detection ops)."""
    b = np.asarray(boxes._value)
    s = np.asarray(scores._value) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        a1 = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        a2 = (b[order[1:], 2] - b[order[1:], 0]) * (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (a1 + a2 - inter + 1e-10)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep[:top_k] if top_k else keep, dtype=np.int64)
    return Tensor(jnp.asarray(keep))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0):
    raise NotImplementedError("box_coder lands with the detection model family")


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True):
    raise NotImplementedError("roi_align lands with the detection model family")


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None):
    raise NotImplementedError("deform_conv2d lands with the detection model family")
