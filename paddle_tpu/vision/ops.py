"""Vision ops (reference: python/paddle/vision/ops.py) — detection helpers."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = ["nms", "box_coder", "roi_align", "deform_conv2d"]


def nms(boxes, iou_threshold=0.3, scores=None, category_idxs=None,
        categories=None, top_k=None):
    """Host-side NMS (dynamic output size — eager only, like the reference's
    dygraph-only detection ops)."""
    b = np.asarray(boxes._value)
    s = np.asarray(scores._value) if scores is not None else np.arange(len(b))[::-1]
    order = np.argsort(-s)
    keep = []
    while order.size > 0:
        i = order[0]
        keep.append(i)
        if order.size == 1:
            break
        xx1 = np.maximum(b[i, 0], b[order[1:], 0])
        yy1 = np.maximum(b[i, 1], b[order[1:], 1])
        xx2 = np.minimum(b[i, 2], b[order[1:], 2])
        yy2 = np.minimum(b[i, 3], b[order[1:], 3])
        inter = np.clip(xx2 - xx1, 0, None) * np.clip(yy2 - yy1, 0, None)
        a1 = (b[i, 2] - b[i, 0]) * (b[i, 3] - b[i, 1])
        a2 = (b[order[1:], 2] - b[order[1:], 0]) * (b[order[1:], 3] - b[order[1:], 1])
        iou = inter / (a1 + a2 - inter + 1e-10)
        order = order[1:][iou <= iou_threshold]
    keep = np.asarray(keep[:top_k] if top_k else keep, dtype=np.int64)
    return Tensor(jnp.asarray(keep))


def box_coder(prior_box, prior_box_var, target_box, code_type="encode_center_size",
              box_normalized=True, axis=0, name=None):
    """Encode/decode boxes against priors (reference phi box_coder kernel).
    Boxes are [x1, y1, x2, y2]."""
    def impl(prior, target, *var):
        pv = var[0] if var else None
        norm = 0.0 if box_normalized else 1.0
        pw = prior[:, 2] - prior[:, 0] + norm
        ph = prior[:, 3] - prior[:, 1] + norm
        pcx = prior[:, 0] + pw * 0.5
        pcy = prior[:, 1] + ph * 0.5
        if code_type in ("encode_center_size", "EncodeCenterSize"):
            tw = target[:, 2] - target[:, 0] + norm
            th = target[:, 3] - target[:, 1] + norm
            tcx = target[:, 0] + tw * 0.5
            tcy = target[:, 1] + th * 0.5
            out = jnp.stack([(tcx[:, None] - pcx[None, :]) / pw[None, :],
                             (tcy[:, None] - pcy[None, :]) / ph[None, :],
                             jnp.log(tw[:, None] / pw[None, :]),
                             jnp.log(th[:, None] / ph[None, :])], axis=-1)
            if pv is not None:
                out = out / pv[None, :, :]
            return out
        # decode_center_size: target [N, M, 4] deltas against M priors
        t = target
        if pv is not None:
            if pv.ndim == 2:
                # broadcast the per-prior variance along the SAME axis the
                # prior geometry uses (axis = which target dim indexes priors)
                pv = pv[None, :, :] if axis == 0 else pv[:, None, :]
            t = t * pv
        if axis == 0:
            pw_, ph_, pcx_, pcy_ = (a[None, :] for a in (pw, ph, pcx, pcy))
        else:
            pw_, ph_, pcx_, pcy_ = (a[:, None] for a in (pw, ph, pcx, pcy))
        cx = t[..., 0] * pw_ + pcx_
        cy = t[..., 1] * ph_ + pcy_
        w = jnp.exp(t[..., 2]) * pw_
        h = jnp.exp(t[..., 3]) * ph_
        return jnp.stack([cx - w * 0.5, cy - h * 0.5,
                          cx + w * 0.5 - norm, cy + h * 0.5 - norm], axis=-1)

    if isinstance(prior_box_var, (list, tuple)):
        # paddle accepts a 4-float list: broadcast to every prior
        n_priors = prior_box.shape[0]
        prior_box_var = jnp.broadcast_to(
            jnp.asarray(prior_box_var, jnp.float32), (n_priors, 4))
    args = [prior_box, target_box]
    if prior_box_var is not None:
        args.append(prior_box_var)
    return op_call("box_coder", impl, *args)


def roi_align(x, boxes, boxes_num, output_size, spatial_scale=1.0,
              sampling_ratio=-1, aligned=True, name=None):
    """RoIAlign with bilinear sampling (reference phi roi_align kernel).
    x: [B, C, H, W]; boxes: [R, 4] (x1,y1,x2,y2); boxes_num: [B] rois per
    image. Static shapes: R and output_size fixed."""
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    ph, pw = output_size

    # adaptive sampling (reference: ceil(roi_size / pooled_size) PER ROI)
    # needs concrete boxes — under jit (traced boxes) shapes must be static,
    # so the fallback samples a fixed 2 points per bin axis
    ns_static = sampling_ratio if sampling_ratio > 0 else 2
    ns_per_roi = None
    if sampling_ratio <= 0:
        try:
            bnp = np.asarray(boxes._value if hasattr(boxes, "_value") else boxes)
            rh = (bnp[:, 3] - bnp[:, 1]) * spatial_scale
            rw = (bnp[:, 2] - bnp[:, 0]) * spatial_scale
            ns_per_roi = [max(1, int(max(math.ceil(float(rh[r]) / ph),
                                         math.ceil(float(rw[r]) / pw))))
                          for r in range(len(bnp))]
        except Exception:
            pass  # tracer: keep the fixed fallback

    def impl(xv, bv, bn):
        B, C, H, W = xv.shape
        R = bv.shape[0]
        # map each roi to its image index from boxes_num
        cum = jnp.cumsum(bn)
        img_idx = jnp.sum(jnp.arange(R)[:, None] >= cum[None, :], axis=1)

        off = 0.5 if aligned else 0.0
        x1 = bv[:, 0] * spatial_scale - off
        y1 = bv[:, 1] * spatial_scale - off
        x2 = bv[:, 2] * spatial_scale - off
        y2 = bv[:, 3] * spatial_scale - off
        rw = x2 - x1
        rh = y2 - y1
        if not aligned:
            rw = jnp.maximum(rw, 1.0)
            rh = jnp.maximum(rh, 1.0)
        bin_h = rh / ph
        bin_w = rw / pw

        def bilinear(img, yy, xx):
            # img [C, H, W]; yy [ph*ns], xx [pw*ns] -> [C, ph*ns, pw*ns]
            y0 = jnp.clip(jnp.floor(yy), 0, H - 1)
            x0 = jnp.clip(jnp.floor(xx), 0, W - 1)
            y1i = jnp.clip(y0 + 1, 0, H - 1).astype(jnp.int32)
            x1i = jnp.clip(x0 + 1, 0, W - 1).astype(jnp.int32)
            y0i = y0.astype(jnp.int32)
            x0i = x0.astype(jnp.int32)
            wy1 = jnp.clip(yy - y0, 0.0, 1.0)
            wx1 = jnp.clip(xx - x0, 0.0, 1.0)
            wy0 = 1.0 - wy1
            wx0 = 1.0 - wx1
            v00 = img[:, y0i][:, :, x0i]
            v01 = img[:, y0i][:, :, x1i]
            v10 = img[:, y1i][:, :, x0i]
            v11 = img[:, y1i][:, :, x1i]
            return (v00 * (wy0[:, None] * wx0[None, :])[None]
                    + v01 * (wy0[:, None] * wx1[None, :])[None]
                    + v10 * (wy1[:, None] * wx0[None, :])[None]
                    + v11 * (wy1[:, None] * wx1[None, :])[None])

        def one_roi(r, ns):
            img = xv[img_idx[r]]
            iy = (jnp.arange(ph)[:, None]
                  + (jnp.arange(ns)[None, :] + 0.5) / ns)       # [ph, ns]
            yy = (y1[r] + iy * bin_h[r]).reshape(ph * ns)
            ix = (jnp.arange(pw)[:, None]
                  + (jnp.arange(ns)[None, :] + 0.5) / ns)
            xx = (x1[r] + ix * bin_w[r]).reshape(pw * ns)
            sampled = bilinear(img, yy, xx)           # [C, ph*ns, pw*ns]
            sampled = sampled.reshape(C, ph, ns, pw, ns)
            return jnp.mean(sampled, axis=(2, 4))     # [C, ph, pw]

        if ns_per_roi is not None:
            # eager adaptive path: per-roi sample counts (reference parity)
            return jnp.stack([one_roi(r, ns_per_roi[r]) for r in range(R)])
        return jax.vmap(lambda r: one_roi(r, ns_static))(jnp.arange(R))

    return op_call("roi_align", impl, x, boxes, boxes_num)


def deform_conv2d(x, offset, weight, bias=None, stride=1, padding=0, dilation=1,
                  deformable_groups=1, groups=1, mask=None, name=None):
    """Deformable conv v1/v2 (reference phi deformable_conv kernel): bilinear
    sampling at offset positions + dense matmul. x [B,C,H,W]; offset
    [B, 2*dg*kh*kw, Ho, Wo]; weight [Co, C/groups, kh, kw]; mask (v2)
    [B, dg*kh*kw, Ho, Wo]."""
    s = (stride, stride) if isinstance(stride, int) else tuple(stride)
    p = (padding, padding) if isinstance(padding, int) else tuple(padding)
    d = (dilation, dilation) if isinstance(dilation, int) else tuple(dilation)

    def impl(xv, ov, wv, *rest):
        mv = bv = None
        rest = list(rest)
        if mask is not None:
            mv = rest.pop(0)
        if bias is not None:
            bv = rest.pop(0)
        B, C, H, W = xv.shape
        Co, Cg, kh, kw = wv.shape
        Ho = (H + 2 * p[0] - d[0] * (kh - 1) - 1) // s[0] + 1
        Wo = (W + 2 * p[1] - d[1] * (kw - 1) - 1) // s[1] + 1
        dg = deformable_groups
        cpg = C // dg                               # channels per deform group

        # base sampling positions per output pixel per kernel tap
        oy = jnp.arange(Ho) * s[0] - p[0]
        ox = jnp.arange(Wo) * s[1] - p[1]
        ky = jnp.arange(kh) * d[0]
        kx = jnp.arange(kw) * d[1]
        base_y = oy[:, None, None, None] + ky[None, None, :, None]  # [Ho,1,kh,1]
        base_x = ox[None, :, None, None] + kx[None, None, None, :]  # [1,Wo,1,kw]
        base_y = jnp.broadcast_to(base_y, (Ho, Wo, kh, kw)).astype(jnp.float32)
        base_x = jnp.broadcast_to(base_x, (Ho, Wo, kh, kw)).astype(jnp.float32)

        ov = ov.reshape(B, dg, kh * kw, 2, Ho, Wo)   # dy at [...,0], dx at 1
        mvr = (mv.reshape(B, dg, kh * kw, Ho, Wo) if mv is not None else None)

        def sample_img(img, yy, xx):
            # img [cpg, H, W]; yy/xx [Ho, Wo, kh, kw]
            y0 = jnp.floor(yy)
            x0 = jnp.floor(xx)
            wy1 = yy - y0
            wx1 = xx - x0
            out = 0.0
            for (yi, wy) in ((y0, 1.0 - wy1), (y0 + 1, wy1)):
                for (xi, wx) in ((x0, 1.0 - wx1), (x0 + 1, wx1)):
                    valid = ((yi >= 0) & (yi < H) & (xi >= 0) & (xi < W))
                    yc = jnp.clip(yi, 0, H - 1).astype(jnp.int32)
                    xc = jnp.clip(xi, 0, W - 1).astype(jnp.int32)
                    v = img[:, yc, xc]               # [cpg, Ho, Wo, kh, kw]
                    out = out + v * (wy * wx * valid)[None]
            return out

        def one_image(xi, oi, mi):
            cols = []
            for g in range(dg):
                yy = base_y + oi[g, :, 0].reshape(kh, kw, Ho, Wo) \
                    .transpose(2, 3, 0, 1)
                xx = base_x + oi[g, :, 1].reshape(kh, kw, Ho, Wo) \
                    .transpose(2, 3, 0, 1)
                sm = sample_img(xi[g * cpg:(g + 1) * cpg], yy, xx)
                if mi is not None:
                    sm = sm * mi[g].reshape(kh, kw, Ho, Wo) \
                        .transpose(2, 3, 0, 1)[None]
                cols.append(sm)
            col = jnp.concatenate(cols, axis=0)       # [C, Ho, Wo, kh, kw]
            col = col.transpose(1, 2, 0, 3, 4).reshape(Ho * Wo, C * kh * kw)
            wmat = wv.reshape(Co, Cg * kh * kw)
            if groups == 1:
                out = col @ wmat.T                    # [Ho*Wo, Co]
            else:
                cols_g = col.reshape(Ho * Wo, groups, Cg * kh * kw)
                w_g = wmat.reshape(groups, Co // groups, Cg * kh * kw)
                out = jnp.einsum("ngk,gok->ngo", cols_g, w_g) \
                    .reshape(Ho * Wo, Co)
            return out.T.reshape(Co, Ho, Wo)

        if mvr is not None:
            out = jax.vmap(one_image)(xv, ov, mvr)
        else:
            out = jax.vmap(lambda xi, oi: one_image(xi, oi, None))(xv, ov)
        if bv is not None:
            out = out + bv[None, :, None, None]
        return out

    args = [x, offset, weight]
    if mask is not None:
        args.append(mask)
    if bias is not None:
        args.append(bias)
    return op_call("deform_conv2d", impl, *args)
