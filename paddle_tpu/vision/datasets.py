"""Vision datasets (reference: python/paddle/vision/datasets/) — synthetic
fallbacks (zero egress: no downloads); ImageFolder/DatasetFolder read disk.
"""
from __future__ import annotations

import os

import numpy as np

from ..io import Dataset

__all__ = ["FakeImageNet", "DatasetFolder", "ImageFolder", "MNIST", "Cifar10"]


class FakeImageNet(Dataset):
    """Deterministic synthetic ImageNet-shaped data for benchmarks/tests."""

    def __init__(self, n=1280, image_size=224, num_classes=1000, transform=None,
                 channels=3, seed=0):
        self.n = n
        self.shape = (channels, image_size, image_size)
        self.num_classes = num_classes
        self.transform = transform
        self.seed = seed

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(self.seed + idx)
        img = rng.standard_normal(self.shape, dtype=np.float32)
        label = np.int64(rng.integers(0, self.num_classes))
        if self.transform:
            img = self.transform(img)
        return img, label


class DatasetFolder(Dataset):
    def __init__(self, root, loader=None, extensions=(".npy",), transform=None):
        self.samples = []
        classes = sorted(d for d in os.listdir(root)
                         if os.path.isdir(os.path.join(root, d)))
        self.class_to_idx = {c: i for i, c in enumerate(classes)}
        for c in classes:
            for fn in sorted(os.listdir(os.path.join(root, c))):
                if fn.endswith(extensions):
                    self.samples.append((os.path.join(root, c, fn),
                                         self.class_to_idx[c]))
        self.transform = transform
        self.loader = loader or (lambda p: np.load(p))

    def __len__(self):
        return len(self.samples)

    def __getitem__(self, idx):
        path, label = self.samples[idx]
        img = self.loader(path)
        if self.transform:
            img = self.transform(img)
        return img, label


ImageFolder = DatasetFolder


class MNIST(Dataset):
    """Synthetic MNIST-shaped data (no egress)."""

    def __init__(self, image_path=None, label_path=None, mode="train",
                 transform=None, download=False, backend=None):
        self.n = 60000 if mode == "train" else 10000
        self.transform = transform

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(idx)
        img = rng.standard_normal((1, 28, 28), dtype=np.float32)
        if self.transform:
            img = self.transform(img)
        return img, np.int64(idx % 10)


class Cifar10(Dataset):
    def __init__(self, data_file=None, mode="train", transform=None,
                 download=False, backend=None):
        self.n = 50000 if mode == "train" else 10000
        self.transform = transform

    def __len__(self):
        return self.n

    def __getitem__(self, idx):
        rng = np.random.default_rng(idx)
        img = rng.standard_normal((3, 32, 32), dtype=np.float32)
        if self.transform:
            img = self.transform(img)
        return img, np.int64(idx % 10)
