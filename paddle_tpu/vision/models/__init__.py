from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34,  # noqa: F401
                     resnet50, resnet101, resnet152, wide_resnet50_2,
                     wide_resnet101_2, resnext50_32x4d, resnext101_32x4d)
from .vit import VisionTransformer, vit_b_16, vit_l_16  # noqa: F401
