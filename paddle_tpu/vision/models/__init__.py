from .resnet import (ResNet, BasicBlock, BottleneckBlock, resnet18, resnet34,  # noqa: F401
                     resnet50, resnet101, resnet152, wide_resnet50_2,
                     wide_resnet101_2, resnext50_32x4d, resnext101_32x4d)
from .vit import VisionTransformer, vit_b_16, vit_l_16  # noqa: F401
from .mobilenet import (MobileNetV1, MobileNetV2, MobileNetV3Small,  # noqa: F401
                        MobileNetV3Large, mobilenet_v1, mobilenet_v2,
                        mobilenet_v3_small, mobilenet_v3_large)
from .densenet import (DenseNet, densenet121, densenet161, densenet169,  # noqa: F401
                       densenet201, densenet264, SqueezeNet, squeezenet1_0,
                       squeezenet1_1, ShuffleNetV2, shufflenet_v2_x1_0,
                       AlexNet, alexnet, VGG, vgg11, vgg13, vgg16, vgg19)
from .inception import (GoogLeNet, googlenet, InceptionV3,  # noqa: F401
                        inception_v3, LeNet)
