"""Vision Transformer (BASELINE.json config #2: ViT-L/16 data-parallel).

Reference ViT implementations live in PaddleClas; paddle.vision itself ships
the backbone zoo — we provide ViT here since it's a benchmark config.
"""
from __future__ import annotations

import jax.numpy as jnp

from ...core.tensor import Parameter, Tensor
from ...nn.layer import Layer
from ...nn import (Linear, LayerNorm, Dropout, Conv2D, Sequential, GELU,
                   LayerList)
from ...nn import functional as F
from ...nn.initializer import TruncatedNormal, Constant
from ...tensor import manipulation as manip

__all__ = ["VisionTransformer", "vit_b_16", "vit_l_16"]


class PatchEmbed(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, embed_dim=768):
        super().__init__()
        self.num_patches = (img_size // patch_size) ** 2
        self.proj = Conv2D(in_chans, embed_dim, patch_size, stride=patch_size)

    def forward(self, x):
        x = self.proj(x)  # [B, C, H/p, W/p]
        x = manip.flatten(x, 2)  # [B, C, N]
        return manip.transpose(x, [0, 2, 1])  # [B, N, C]


class MLP(Layer):
    def __init__(self, dim, hidden, drop=0.0):
        super().__init__()
        self.fc1 = Linear(dim, hidden)
        self.act = GELU()
        self.fc2 = Linear(hidden, dim)
        self.drop = Dropout(drop)

    def forward(self, x):
        return self.drop(self.fc2(self.drop(self.act(self.fc1(x)))))


class Attention(Layer):
    def __init__(self, dim, num_heads, attn_drop=0.0, proj_drop=0.0, qkv_bias=True):
        super().__init__()
        self.num_heads = num_heads
        self.head_dim = dim // num_heads
        self.qkv = Linear(dim, dim * 3, bias_attr=None if qkv_bias else False)
        self.proj = Linear(dim, dim)
        self.attn_drop = attn_drop
        self.proj_drop = Dropout(proj_drop)

    def forward(self, x):
        b, n, c = x.shape
        qkv = self.qkv(x)
        qkv = manip.reshape(qkv, [b, n, 3, self.num_heads, self.head_dim])
        q = qkv[:, :, 0]
        k = qkv[:, :, 1]
        v = qkv[:, :, 2]
        out = F.scaled_dot_product_attention(q, k, v, dropout_p=self.attn_drop,
                                             training=self.training)
        out = manip.reshape(out, [b, n, c])
        return self.proj_drop(self.proj(out))


class Block(Layer):
    def __init__(self, dim, num_heads, mlp_ratio=4.0, drop=0.0, attn_drop=0.0,
                 qkv_bias=True, epsilon=1e-6):
        super().__init__()
        self.norm1 = LayerNorm(dim, epsilon=epsilon)
        self.attn = Attention(dim, num_heads, attn_drop, drop, qkv_bias)
        self.norm2 = LayerNorm(dim, epsilon=epsilon)
        self.mlp = MLP(dim, int(dim * mlp_ratio), drop)

    def forward(self, x):
        x = x + self.attn(self.norm1(x))
        x = x + self.mlp(self.norm2(x))
        return x


class VisionTransformer(Layer):
    def __init__(self, img_size=224, patch_size=16, in_chans=3, num_classes=1000,
                 embed_dim=768, depth=12, num_heads=12, mlp_ratio=4.0,
                 qkv_bias=True, drop_rate=0.0, attn_drop_rate=0.0, epsilon=1e-6):
        super().__init__()
        self.patch_embed = PatchEmbed(img_size, patch_size, in_chans, embed_dim)
        n = self.patch_embed.num_patches
        self.cls_token = Parameter(jnp.zeros((1, 1, embed_dim), jnp.float32))
        self.pos_embed = Parameter(jnp.zeros((1, n + 1, embed_dim), jnp.float32))
        TruncatedNormal(std=0.02)(self.pos_embed)
        TruncatedNormal(std=0.02)(self.cls_token)
        self.pos_drop = Dropout(drop_rate)
        self.blocks = LayerList([
            Block(embed_dim, num_heads, mlp_ratio, drop_rate, attn_drop_rate,
                  qkv_bias, epsilon) for _ in range(depth)])
        self.norm = LayerNorm(embed_dim, epsilon=epsilon)
        self.head = Linear(embed_dim, num_classes) if num_classes > 0 else None

    def forward(self, x):
        x = self.patch_embed(x)
        b = x.shape[0]
        cls = manip.expand(self.cls_token, [b, 1, x.shape[2]])
        x = manip.concat([cls, x], axis=1)
        x = self.pos_drop(x + self.pos_embed)
        for blk in self.blocks:
            x = blk(x)
        x = self.norm(x)
        cls_out = x[:, 0]
        return self.head(cls_out) if self.head is not None else cls_out


def vit_b_16(**kwargs):
    return VisionTransformer(embed_dim=768, depth=12, num_heads=12, **kwargs)


def vit_l_16(**kwargs):
    return VisionTransformer(embed_dim=1024, depth=24, num_heads=16, **kwargs)
