"""DenseNet + SqueezeNet + ShuffleNetV2 + AlexNet + VGG (reference:
python/paddle/vision/models/{densenet,squeezenet,shufflenetv2,alexnet,
vgg}.py — standard architectures, original jax-backed Layer bodies)."""
from __future__ import annotations

from ...nn.layer import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, MaxPool2D, AvgPool2D,
                   AdaptiveAvgPool2D, Linear, Sequential, Dropout)
from ...tensor import manipulation as manip

__all__ = ["DenseNet", "densenet121", "densenet161", "densenet169",
           "densenet201", "densenet264", "SqueezeNet", "squeezenet1_0",
           "squeezenet1_1", "ShuffleNetV2", "shufflenet_v2_x1_0",
           "AlexNet", "alexnet", "VGG", "vgg11", "vgg13", "vgg16", "vgg19"]


# ---------------------------------------------------------------------------
# DenseNet (reference densenet.py)
# ---------------------------------------------------------------------------
class _DenseLayer(Layer):
    def __init__(self, cin, growth_rate, bn_size, dropout):
        super().__init__()
        self.bn1 = BatchNorm2D(cin)
        self.relu = ReLU()
        self.conv1 = Conv2D(cin, bn_size * growth_rate, 1, bias_attr=False)
        self.bn2 = BatchNorm2D(bn_size * growth_rate)
        self.conv2 = Conv2D(bn_size * growth_rate, growth_rate, 3, padding=1,
                            bias_attr=False)
        self.dropout = Dropout(dropout) if dropout else None

    def forward(self, x):
        out = self.conv1(self.relu(self.bn1(x)))
        out = self.conv2(self.relu(self.bn2(out)))
        if self.dropout is not None:
            out = self.dropout(out)
        return manip.concat([x, out], axis=1)


class _Transition(Layer):
    def __init__(self, cin, cout):
        super().__init__()
        self.bn = BatchNorm2D(cin)
        self.relu = ReLU()
        self.conv = Conv2D(cin, cout, 1, bias_attr=False)
        self.pool = AvgPool2D(2, stride=2)

    def forward(self, x):
        return self.pool(self.conv(self.relu(self.bn(x))))


_DENSE_CFG = {121: (6, 12, 24, 16), 161: (6, 12, 36, 24),
              169: (6, 12, 32, 32), 201: (6, 12, 48, 32),
              264: (6, 12, 64, 48)}


class DenseNet(Layer):
    """reference densenet.py:208 DenseNet."""

    def __init__(self, layers=121, bn_size=4, dropout=0.0, num_classes=1000,
                 with_pool=True, growth_rate=None):
        super().__init__()
        if layers not in _DENSE_CFG:
            raise ValueError(f"supported layers: {sorted(_DENSE_CFG)}")
        block_cfg = _DENSE_CFG[layers]
        growth = growth_rate or (48 if layers == 161 else 32)
        init_ch = 2 * growth
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            Conv2D(3, init_ch, 7, stride=2, padding=3, bias_attr=False),
            BatchNorm2D(init_ch), ReLU(), MaxPool2D(3, stride=2, padding=1))
        blocks = []
        ch = init_ch
        for bi, n in enumerate(block_cfg):
            for _ in range(n):
                blocks.append(_DenseLayer(ch, growth, bn_size, dropout))
                ch += growth
            if bi != len(block_cfg) - 1:
                blocks.append(_Transition(ch, ch // 2))
                ch //= 2
        self.features = Sequential(*blocks)
        self.bn_final = BatchNorm2D(ch)
        self.relu = ReLU()
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(ch, num_classes)

    def forward(self, x):
        x = self.relu(self.bn_final(self.features(self.stem(x))))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = manip.reshape(x, [x.shape[0], -1])
            x = self.fc(x)
        return x


def _dn(layers):
    def fn(pretrained=False, **kwargs):
        if pretrained:
            raise NotImplementedError("pretrained weights are not bundled")
        return DenseNet(layers=layers, **kwargs)
    fn.__name__ = f"densenet{layers}"
    return fn


densenet121 = _dn(121)
densenet161 = _dn(161)
densenet169 = _dn(169)
densenet201 = _dn(201)
densenet264 = _dn(264)


# ---------------------------------------------------------------------------
# SqueezeNet (reference squeezenet.py)
# ---------------------------------------------------------------------------
class _Fire(Layer):
    def __init__(self, cin, squeeze, e1, e3):
        super().__init__()
        self.squeeze = Conv2D(cin, squeeze, 1)
        self.relu = ReLU()
        self.e1 = Conv2D(squeeze, e1, 1)
        self.e3 = Conv2D(squeeze, e3, 3, padding=1)

    def forward(self, x):
        s = self.relu(self.squeeze(x))
        return manip.concat([self.relu(self.e1(s)), self.relu(self.e3(s))],
                            axis=1)


class SqueezeNet(Layer):
    """reference squeezenet.py:91."""

    def __init__(self, version="1.0", num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        if version == "1.0":
            feats = [Conv2D(3, 96, 7, stride=2), ReLU(),
                     MaxPool2D(3, stride=2),
                     _Fire(96, 16, 64, 64), _Fire(128, 16, 64, 64),
                     _Fire(128, 32, 128, 128), MaxPool2D(3, stride=2),
                     _Fire(256, 32, 128, 128), _Fire(256, 48, 192, 192),
                     _Fire(384, 48, 192, 192), _Fire(384, 64, 256, 256),
                     MaxPool2D(3, stride=2), _Fire(512, 64, 256, 256)]
        elif version == "1.1":
            feats = [Conv2D(3, 64, 3, stride=2), ReLU(),
                     MaxPool2D(3, stride=2),
                     _Fire(64, 16, 64, 64), _Fire(128, 16, 64, 64),
                     MaxPool2D(3, stride=2),
                     _Fire(128, 32, 128, 128), _Fire(256, 32, 128, 128),
                     MaxPool2D(3, stride=2),
                     _Fire(256, 48, 192, 192), _Fire(384, 48, 192, 192),
                     _Fire(384, 64, 256, 256), _Fire(512, 64, 256, 256)]
        else:
            raise ValueError("version must be '1.0' or '1.1'")
        self.features = Sequential(*feats)
        self.classifier = Sequential(Dropout(0.5),
                                     Conv2D(512, num_classes, 1), ReLU(),
                                     AdaptiveAvgPool2D(1))

    def forward(self, x):
        x = self.classifier(self.features(x))
        return manip.reshape(x, [x.shape[0], -1])


def squeezenet1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return SqueezeNet("1.0", **kwargs)


def squeezenet1_1(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return SqueezeNet("1.1", **kwargs)


# ---------------------------------------------------------------------------
# ShuffleNetV2 (reference shufflenetv2.py)
# ---------------------------------------------------------------------------
def _channel_shuffle(x, groups):
    n, c, h, w = x.shape
    x = manip.reshape(x, [n, groups, c // groups, h, w])
    x = manip.transpose(x, [0, 2, 1, 3, 4])
    return manip.reshape(x, [n, c, h, w])


class _ShuffleUnit(Layer):
    def __init__(self, cin, cout, stride):
        super().__init__()
        self.stride = stride
        branch = cout // 2
        if stride == 1:
            self.branch2 = Sequential(
                Conv2D(cin // 2, branch, 1, bias_attr=False),
                BatchNorm2D(branch), ReLU(),
                Conv2D(branch, branch, 3, stride=1, padding=1, groups=branch,
                       bias_attr=False), BatchNorm2D(branch),
                Conv2D(branch, branch, 1, bias_attr=False),
                BatchNorm2D(branch), ReLU())
            self.branch1 = None
        else:
            self.branch1 = Sequential(
                Conv2D(cin, cin, 3, stride=stride, padding=1, groups=cin,
                       bias_attr=False), BatchNorm2D(cin),
                Conv2D(cin, branch, 1, bias_attr=False), BatchNorm2D(branch),
                ReLU())
            self.branch2 = Sequential(
                Conv2D(cin, branch, 1, bias_attr=False), BatchNorm2D(branch),
                ReLU(),
                Conv2D(branch, branch, 3, stride=stride, padding=1,
                       groups=branch, bias_attr=False), BatchNorm2D(branch),
                Conv2D(branch, branch, 1, bias_attr=False),
                BatchNorm2D(branch), ReLU())

    def forward(self, x):
        if self.stride == 1:
            c = x.shape[1] // 2
            x1 = x[:, :c]
            x2 = x[:, c:]
            out = manip.concat([x1, self.branch2(x2)], axis=1)
        else:
            out = manip.concat([self.branch1(x), self.branch2(x)], axis=1)
        return _channel_shuffle(out, 2)


class ShuffleNetV2(Layer):
    """reference shufflenetv2.py:31."""

    _CFG = {0.5: (48, 96, 192, 1024), 1.0: (116, 232, 464, 1024),
            1.5: (176, 352, 704, 1024), 2.0: (244, 488, 976, 2048)}

    def __init__(self, scale=1.0, act="relu", num_classes=1000,
                 with_pool=True):
        super().__init__()
        c1, c2, c3, cout = self._CFG[scale]
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(Conv2D(3, 24, 3, stride=2, padding=1,
                                      bias_attr=False),
                               BatchNorm2D(24), ReLU(),
                               MaxPool2D(3, stride=2, padding=1))
        stages = []
        cin = 24
        for cmid, reps in ((c1, 4), (c2, 8), (c3, 4)):
            stages.append(_ShuffleUnit(cin, cmid, 2))
            for _ in range(reps - 1):
                stages.append(_ShuffleUnit(cmid, cmid, 1))
            cin = cmid
        self.stages = Sequential(*stages)
        self.conv_last = Sequential(Conv2D(cin, cout, 1, bias_attr=False),
                                    BatchNorm2D(cout), ReLU())
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(cout, num_classes)

    def forward(self, x):
        x = self.conv_last(self.stages(self.stem(x)))
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = manip.reshape(x, [x.shape[0], -1])
            x = self.fc(x)
        return x


def shufflenet_v2_x1_0(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return ShuffleNetV2(scale=1.0, **kwargs)


# ---------------------------------------------------------------------------
# AlexNet + VGG (reference alexnet.py, vgg.py)
# ---------------------------------------------------------------------------
class AlexNet(Layer):
    """reference alexnet.py:46."""

    def __init__(self, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(3, 64, 11, stride=4, padding=2), ReLU(),
            MaxPool2D(3, stride=2),
            Conv2D(64, 192, 5, padding=2), ReLU(), MaxPool2D(3, stride=2),
            Conv2D(192, 384, 3, padding=1), ReLU(),
            Conv2D(384, 256, 3, padding=1), ReLU(),
            Conv2D(256, 256, 3, padding=1), ReLU(), MaxPool2D(3, stride=2))
        self.pool = AdaptiveAvgPool2D(6)
        self.classifier = Sequential(
            Dropout(0.5), Linear(256 * 36, 4096), ReLU(),
            Dropout(0.5), Linear(4096, 4096), ReLU(),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        x = manip.reshape(x, [x.shape[0], -1])
        return self.classifier(x)


def alexnet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return AlexNet(**kwargs)


_VGG_CFG = {
    11: (64, "M", 128, "M", 256, 256, "M", 512, 512, "M", 512, 512, "M"),
    13: (64, 64, "M", 128, 128, "M", 256, 256, "M", 512, 512, "M",
         512, 512, "M"),
    16: (64, 64, "M", 128, 128, "M", 256, 256, 256, "M", 512, 512, 512, "M",
         512, 512, 512, "M"),
    19: (64, 64, "M", 128, 128, "M", 256, 256, 256, 256, "M",
         512, 512, 512, 512, "M", 512, 512, 512, 512, "M"),
}


class VGG(Layer):
    """reference vgg.py:36."""

    def __init__(self, layers=16, batch_norm=False, num_classes=1000):
        super().__init__()
        self.num_classes = num_classes
        feats = []
        cin = 3
        for v in _VGG_CFG[layers]:
            if v == "M":
                feats.append(MaxPool2D(2, stride=2))
            else:
                feats.append(Conv2D(cin, v, 3, padding=1))
                if batch_norm:
                    feats.append(BatchNorm2D(v))
                feats.append(ReLU())
                cin = v
        self.features = Sequential(*feats)
        self.pool = AdaptiveAvgPool2D(7)
        self.classifier = Sequential(
            Linear(512 * 49, 4096), ReLU(), Dropout(0.5),
            Linear(4096, 4096), ReLU(), Dropout(0.5),
            Linear(4096, num_classes))

    def forward(self, x):
        x = self.pool(self.features(x))
        x = manip.reshape(x, [x.shape[0], -1])
        return self.classifier(x)


def _vgg(layers):
    def fn(pretrained=False, batch_norm=False, **kwargs):
        if pretrained:
            raise NotImplementedError("pretrained weights are not bundled")
        return VGG(layers=layers, batch_norm=batch_norm, **kwargs)
    fn.__name__ = f"vgg{layers}"
    return fn


vgg11 = _vgg(11)
vgg13 = _vgg(13)
vgg16 = _vgg(16)
vgg19 = _vgg(19)
