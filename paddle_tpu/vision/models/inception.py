"""GoogLeNet (Inception v1), Inception v3 and LeNet (reference:
python/paddle/vision/models/{googlenet.py:130, inceptionv3.py:509,
lenet.py:30} — standard architectures, original jax-backed Layer bodies).

GoogLeNet keeps the reference's three-head return (main + two aux
classifiers); Inception v3 keeps its channel schedule
(A:192/256/288, B:288, C:768×4 with 128/160/160/192 7×7 widths, D:768,
E:1280/2048).
"""
from __future__ import annotations

from ...nn.layer import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, MaxPool2D, AvgPool2D,
                   AdaptiveAvgPool2D, Linear, Sequential, Dropout, LayerList)
from ...nn import functional as F
from ...tensor import manipulation as manip

__all__ = ["GoogLeNet", "googlenet", "InceptionV3", "inception_v3",
           "LeNet"]


# ---------------------------------------------------------------------------
# LeNet (reference lenet.py:30)
# ---------------------------------------------------------------------------
class LeNet(Layer):
    def __init__(self, num_classes=10):
        super().__init__()
        self.num_classes = num_classes
        self.features = Sequential(
            Conv2D(1, 6, 3, stride=1, padding=1), ReLU(), MaxPool2D(2, 2),
            Conv2D(6, 16, 5, stride=1, padding=0), ReLU(), MaxPool2D(2, 2))
        if num_classes > 0:
            self.fc = Sequential(Linear(400, 120), Linear(120, 84),
                                 Linear(84, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.num_classes > 0:
            x = self.fc(manip.flatten(x, 1))
        return x


# ---------------------------------------------------------------------------
# GoogLeNet / Inception v1 (reference googlenet.py:130)
# ---------------------------------------------------------------------------
class _Conv(Layer):
    """plain conv (no BN — v1 predates it), 'same'-style padding."""

    def __init__(self, cin, cout, k, stride=1):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride,
                           padding=(k - 1) // 2, bias_attr=False)

    def forward(self, x):
        return self.conv(x)


class _InceptionV1Block(Layer):
    """Four parallel branches concatenated on channels, then one ReLU
    (the reference applies relu to the concat, not per branch)."""

    def __init__(self, cin, f1, f3r, f3, f5r, f5, proj):
        super().__init__()
        self.b1 = _Conv(cin, f1, 1)
        self.b3r = _Conv(cin, f3r, 1)
        self.b3 = _Conv(f3r, f3, 3)
        self.b5r = _Conv(cin, f5r, 1)
        self.b5 = _Conv(f5r, f5, 5)
        self.pool = MaxPool2D(3, stride=1, padding=1)
        self.proj = _Conv(cin, proj, 1)

    def forward(self, x):
        cat = manip.concat(
            [self.b1(x), self.b3(self.b3r(x)), self.b5(self.b5r(x)),
             self.proj(self.pool(x))], axis=1)
        return F.relu(cat)


class GoogLeNet(Layer):
    """Returns (out, aux1, aux2) like the reference."""

    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.conv1 = _Conv(3, 64, 7, 2)
        self.pool = MaxPool2D(3, stride=2)
        self.conv2 = _Conv(64, 64, 1)
        self.conv3 = _Conv(64, 192, 3)
        B = _InceptionV1Block
        self.i3a = B(192, 64, 96, 128, 16, 32, 32)
        self.i3b = B(256, 128, 128, 192, 32, 96, 64)
        self.i4a = B(480, 192, 96, 208, 16, 48, 64)
        self.i4b = B(512, 160, 112, 224, 24, 64, 64)
        self.i4c = B(512, 128, 128, 256, 24, 64, 64)
        self.i4d = B(512, 112, 144, 288, 32, 64, 64)
        self.i4e = B(528, 256, 160, 320, 32, 128, 128)
        self.i5a = B(832, 256, 160, 320, 32, 128, 128)
        self.i5b = B(832, 384, 192, 384, 48, 128, 128)
        if with_pool:
            self.gap = AdaptiveAvgPool2D(1)
            self.aux_pool = AvgPool2D(5, stride=3)
        if num_classes > 0:
            self.drop = Dropout(0.4)
            self.fc = Linear(1024, num_classes)
            self.aux1_conv = _Conv(512, 128, 1)
            self.aux1_fc = Linear(1152, 1024)
            self.aux1_drop = Dropout(0.7)
            self.aux1_out = Linear(1024, num_classes)
            self.aux2_conv = _Conv(528, 128, 1)
            self.aux2_fc = Linear(1152, 1024)
            self.aux2_drop = Dropout(0.7)
            self.aux2_out = Linear(1024, num_classes)

    def forward(self, x):
        x = self.pool(self.conv1(x))
        x = self.pool(self.conv3(self.conv2(x)))
        x = self.pool(self.i3b(self.i3a(x)))
        a4a = self.i4a(x)
        x = self.i4c(self.i4b(a4a))
        a4d = self.i4d(x)
        x = self.pool(self.i4e(a4d))
        out = self.i5b(self.i5a(x))
        out1, out2 = a4a, a4d
        if self.with_pool:
            out = self.gap(out)
            out1 = self.aux_pool(out1)
            out2 = self.aux_pool(out2)
        if self.num_classes > 0:
            out = self.fc(self.drop(manip.squeeze(out, axis=[2, 3])))
            out1 = self.aux1_fc(manip.flatten(self.aux1_conv(out1), 1))
            out1 = self.aux1_out(self.aux1_drop(F.relu(out1)))
            out2 = self.aux2_fc(manip.flatten(self.aux2_conv(out2), 1))
            out2 = self.aux2_out(self.aux2_drop(out2))
        return out, out1, out2


def googlenet(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need network download (zero-egress build)")
    return GoogLeNet(**kwargs)


# ---------------------------------------------------------------------------
# Inception v3 (reference inceptionv3.py:509)
# ---------------------------------------------------------------------------
class _ConvBN(Layer):
    """conv + BN + ReLU with (possibly rectangular) kernel/padding."""

    def __init__(self, cin, cout, k, stride=1, padding=0):
        super().__init__()
        self.conv = Conv2D(cin, cout, k, stride=stride, padding=padding,
                           bias_attr=False)
        self.bn = BatchNorm2D(cout)

    def forward(self, x):
        return F.relu(self.bn(self.conv(x)))


class _IncA(Layer):
    def __init__(self, cin, pool_features):
        super().__init__()
        self.b1 = _ConvBN(cin, 64, 1)
        self.b5 = Sequential(_ConvBN(cin, 48, 1), _ConvBN(48, 64, 5, padding=2))
        self.b3d = Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, padding=1),
                              _ConvBN(96, 96, 3, padding=1))
        self.pool = AvgPool2D(3, stride=1, padding=1, exclusive=False)
        self.bp = _ConvBN(cin, pool_features, 1)

    def forward(self, x):
        return manip.concat([self.b1(x), self.b5(x), self.b3d(x),
                             self.bp(self.pool(x))], axis=1)


class _IncB(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = _ConvBN(cin, 384, 3, stride=2)
        self.b3d = Sequential(_ConvBN(cin, 64, 1), _ConvBN(64, 96, 3, padding=1),
                              _ConvBN(96, 96, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return manip.concat([self.b3(x), self.b3d(x), self.pool(x)], axis=1)


class _IncC(Layer):
    def __init__(self, cin, c7):
        super().__init__()
        self.b1 = _ConvBN(cin, 192, 1)
        self.b7 = Sequential(
            _ConvBN(cin, c7, 1),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, 192, (7, 1), padding=(3, 0)))
        self.b7d = Sequential(
            _ConvBN(cin, c7, 1),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, c7, (1, 7), padding=(0, 3)),
            _ConvBN(c7, c7, (7, 1), padding=(3, 0)),
            _ConvBN(c7, 192, (1, 7), padding=(0, 3)))
        self.pool = AvgPool2D(3, stride=1, padding=1, exclusive=False)
        self.bp = _ConvBN(cin, 192, 1)

    def forward(self, x):
        return manip.concat([self.b1(x), self.b7(x), self.b7d(x),
                             self.bp(self.pool(x))], axis=1)


class _IncD(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b3 = Sequential(_ConvBN(cin, 192, 1), _ConvBN(192, 320, 3, stride=2))
        self.b7 = Sequential(
            _ConvBN(cin, 192, 1),
            _ConvBN(192, 192, (1, 7), padding=(0, 3)),
            _ConvBN(192, 192, (7, 1), padding=(3, 0)),
            _ConvBN(192, 192, 3, stride=2))
        self.pool = MaxPool2D(3, stride=2)

    def forward(self, x):
        return manip.concat([self.b3(x), self.b7(x), self.pool(x)], axis=1)


class _IncE(Layer):
    def __init__(self, cin):
        super().__init__()
        self.b1 = _ConvBN(cin, 320, 1)
        self.b3_1 = _ConvBN(cin, 384, 1)
        self.b3_2a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3_2b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.b3d_1 = Sequential(_ConvBN(cin, 448, 1),
                                _ConvBN(448, 384, 3, padding=1))
        self.b3d_3a = _ConvBN(384, 384, (1, 3), padding=(0, 1))
        self.b3d_3b = _ConvBN(384, 384, (3, 1), padding=(1, 0))
        self.pool = AvgPool2D(3, stride=1, padding=1, exclusive=False)
        self.bp = _ConvBN(cin, 192, 1)

    def forward(self, x):
        b3 = self.b3_1(x)
        b3 = manip.concat([self.b3_2a(b3), self.b3_2b(b3)], axis=1)
        b3d = self.b3d_1(x)
        b3d = manip.concat([self.b3d_3a(b3d), self.b3d_3b(b3d)], axis=1)
        return manip.concat([self.b1(x), b3, b3d,
                             self.bp(self.pool(x))], axis=1)


class InceptionV3(Layer):
    def __init__(self, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        self.stem = Sequential(
            _ConvBN(3, 32, 3, stride=2), _ConvBN(32, 32, 3),
            _ConvBN(32, 64, 3, padding=1), MaxPool2D(3, stride=2),
            _ConvBN(64, 80, 1), _ConvBN(80, 192, 3), MaxPool2D(3, stride=2))
        blocks = []
        for cin, pf in zip([192, 256, 288], [32, 64, 64]):
            blocks.append(_IncA(cin, pf))
        blocks.append(_IncB(288))
        for cin, c7 in zip([768] * 4, [128, 160, 160, 192]):
            blocks.append(_IncC(cin, c7))
        blocks.append(_IncD(768))
        blocks.extend([_IncE(1280), _IncE(2048)])
        self.blocks = LayerList(blocks)
        if with_pool:
            self.gap = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.dropout = Dropout(0.2)
            self.fc = Linear(2048, num_classes)

    def forward(self, x):
        x = self.stem(x)
        for b in self.blocks:
            x = b(x)
        if self.with_pool:
            x = self.gap(x)
        if self.num_classes > 0:
            x = self.fc(self.dropout(manip.reshape(x, [-1, 2048])))
        return x


def inception_v3(pretrained=False, **kwargs):
    if pretrained:
        raise NotImplementedError(
            "pretrained weights need network download (zero-egress build)")
    return InceptionV3(**kwargs)
