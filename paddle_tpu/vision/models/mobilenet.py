"""MobileNet V1/V2/V3 (reference: python/paddle/vision/models/
{mobilenetv1,mobilenetv2,mobilenetv3}.py — standard architectures; bodies
are original jax-backed Layer code)."""
from __future__ import annotations

from ...nn.layer import Layer
from ...nn import (Conv2D, BatchNorm2D, ReLU, ReLU6, Hardswish, Hardsigmoid,
                   Linear, Sequential, AdaptiveAvgPool2D, Dropout, Flatten)
from ...tensor import manipulation as manip

__all__ = ["MobileNetV1", "MobileNetV2", "MobileNetV3Small",
           "MobileNetV3Large", "mobilenet_v1", "mobilenet_v2",
           "mobilenet_v3_small", "mobilenet_v3_large"]


def _make_divisible(v, divisor=8, min_value=None):
    min_value = min_value or divisor
    new_v = max(min_value, int(v + divisor / 2) // divisor * divisor)
    if new_v < 0.9 * v:
        new_v += divisor
    return new_v


def _conv_bn(cin, cout, k, stride=1, groups=1, act=ReLU):
    pad = (k - 1) // 2
    layers = [Conv2D(cin, cout, k, stride=stride, padding=pad, groups=groups,
                     bias_attr=False), BatchNorm2D(cout)]
    if act is not None:
        layers.append(act())
    return Sequential(*layers)


class MobileNetV1(Layer):
    """reference mobilenetv1.py: depthwise-separable stacks."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool

        def dw_sep(cin, cout, stride):
            return Sequential(
                _conv_bn(cin, cin, 3, stride=stride, groups=cin),
                _conv_bn(cin, cout, 1))
        s = lambda c: int(c * scale)
        cfg = [(s(32), s(64), 1), (s(64), s(128), 2), (s(128), s(128), 1),
               (s(128), s(256), 2), (s(256), s(256), 1), (s(256), s(512), 2)] \
            + [(s(512), s(512), 1)] * 5 + [(s(512), s(1024), 2),
                                           (s(1024), s(1024), 1)]
        blocks = [_conv_bn(3, s(32), 3, stride=2)]
        blocks += [dw_sep(a, b, st) for a, b, st in cfg]
        self.features = Sequential(*blocks)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.fc = Linear(s(1024), num_classes)

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = manip.reshape(x, [x.shape[0], -1])
            x = self.fc(x)
        return x


class InvertedResidual(Layer):
    """V2 block (reference mobilenetv2.py:30)."""

    def __init__(self, cin, cout, stride, expand_ratio):
        super().__init__()
        hidden = int(round(cin * expand_ratio))
        self.use_res = stride == 1 and cin == cout
        layers = []
        if expand_ratio != 1:
            layers.append(_conv_bn(cin, hidden, 1, act=ReLU6))
        layers += [_conv_bn(hidden, hidden, 3, stride=stride, groups=hidden,
                            act=ReLU6),
                   _conv_bn(hidden, cout, 1, act=None)]
        self.conv = Sequential(*layers)

    def forward(self, x):
        out = self.conv(x)
        return x + out if self.use_res else out


class MobileNetV2(Layer):
    """reference mobilenetv2.py:84."""

    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cfg = [(1, 16, 1, 1), (6, 24, 2, 2), (6, 32, 3, 2), (6, 64, 4, 2),
               (6, 96, 3, 1), (6, 160, 3, 2), (6, 320, 1, 1)]
        cin = _make_divisible(32 * scale)
        last = _make_divisible(1280 * max(1.0, scale))
        feats = [_conv_bn(3, cin, 3, stride=2, act=ReLU6)]
        for t, c, n, s in cfg:
            cout = _make_divisible(c * scale)
            for i in range(n):
                feats.append(InvertedResidual(cin, cout,
                                              s if i == 0 else 1, t))
                cin = cout
        feats.append(_conv_bn(cin, last, 1, act=ReLU6))
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            self.classifier = Sequential(Dropout(0.2), Linear(last, num_classes))
        self._last = last

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = manip.reshape(x, [x.shape[0], -1])
            x = self.classifier(x)
        return x


class SqueezeExcitation(Layer):
    def __init__(self, channels, squeeze):
        super().__init__()
        self.avg = AdaptiveAvgPool2D(1)
        self.fc1 = Conv2D(channels, squeeze, 1)
        self.relu = ReLU()
        self.fc2 = Conv2D(squeeze, channels, 1)
        self.hsig = Hardsigmoid()

    def forward(self, x):
        s = self.hsig(self.fc2(self.relu(self.fc1(self.avg(x)))))
        return x * s


class _V3Block(Layer):
    def __init__(self, cin, hidden, cout, k, stride, use_se, act):
        super().__init__()
        self.use_res = stride == 1 and cin == cout
        layers = []
        if hidden != cin:
            layers.append(_conv_bn(cin, hidden, 1, act=act))
        layers.append(_conv_bn(hidden, hidden, k, stride=stride,
                               groups=hidden, act=act))
        if use_se:
            layers.append(SqueezeExcitation(hidden,
                                            _make_divisible(hidden // 4)))
        layers.append(_conv_bn(hidden, cout, 1, act=None))
        self.block = Sequential(*layers)

    def forward(self, x):
        out = self.block(x)
        return x + out if self.use_res else out


_V3_LARGE = [
    # k, exp, out, se, act, stride
    (3, 16, 16, False, ReLU, 1), (3, 64, 24, False, ReLU, 2),
    (3, 72, 24, False, ReLU, 1), (5, 72, 40, True, ReLU, 2),
    (5, 120, 40, True, ReLU, 1), (5, 120, 40, True, ReLU, 1),
    (3, 240, 80, False, Hardswish, 2), (3, 200, 80, False, Hardswish, 1),
    (3, 184, 80, False, Hardswish, 1), (3, 184, 80, False, Hardswish, 1),
    (3, 480, 112, True, Hardswish, 1), (3, 672, 112, True, Hardswish, 1),
    (5, 672, 160, True, Hardswish, 2), (5, 960, 160, True, Hardswish, 1),
    (5, 960, 160, True, Hardswish, 1),
]
_V3_SMALL = [
    (3, 16, 16, True, ReLU, 2), (3, 72, 24, False, ReLU, 2),
    (3, 88, 24, False, ReLU, 1), (5, 96, 40, True, Hardswish, 2),
    (5, 240, 40, True, Hardswish, 1), (5, 240, 40, True, Hardswish, 1),
    (5, 120, 48, True, Hardswish, 1), (5, 144, 48, True, Hardswish, 1),
    (5, 288, 96, True, Hardswish, 2), (5, 576, 96, True, Hardswish, 1),
    (5, 576, 96, True, Hardswish, 1),
]


class _MobileNetV3(Layer):
    """reference mobilenetv3.py:129 MobileNetV3."""

    def __init__(self, cfg, last_exp, scale=1.0, num_classes=1000,
                 with_pool=True):
        super().__init__()
        self.num_classes = num_classes
        self.with_pool = with_pool
        cin = _make_divisible(16 * scale)
        feats = [_conv_bn(3, cin, 3, stride=2, act=Hardswish)]
        for k, exp, out, se, act, stride in cfg:
            hidden = _make_divisible(exp * scale)
            cout = _make_divisible(out * scale)
            feats.append(_V3Block(cin, hidden, cout, k, stride, se, act))
            cin = cout
        lastconv = _make_divisible(last_exp * scale)
        feats.append(_conv_bn(cin, lastconv, 1, act=Hardswish))
        self.features = Sequential(*feats)
        if with_pool:
            self.pool = AdaptiveAvgPool2D(1)
        if num_classes > 0:
            head = 1280 if last_exp == 960 else 1024
            self.classifier = Sequential(
                Linear(lastconv, head), Hardswish(), Dropout(0.2),
                Linear(head, num_classes))

    def forward(self, x):
        x = self.features(x)
        if self.with_pool:
            x = self.pool(x)
        if self.num_classes > 0:
            x = manip.reshape(x, [x.shape[0], -1])
            x = self.classifier(x)
        return x


class MobileNetV3Large(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_LARGE, 960, scale, num_classes, with_pool)


class MobileNetV3Small(_MobileNetV3):
    def __init__(self, scale=1.0, num_classes=1000, with_pool=True):
        super().__init__(_V3_SMALL, 576, scale, num_classes, with_pool)


def mobilenet_v1(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV1(scale=scale, **kwargs)


def mobilenet_v2(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV2(scale=scale, **kwargs)


def mobilenet_v3_small(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Small(scale=scale, **kwargs)


def mobilenet_v3_large(pretrained=False, scale=1.0, **kwargs):
    if pretrained:
        raise NotImplementedError("pretrained weights are not bundled")
    return MobileNetV3Large(scale=scale, **kwargs)
