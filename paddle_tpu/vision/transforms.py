"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy/host-side preprocessing (HWC uint8 in, CHW float out like the reference).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomHorizontalFlip", "RandomCrop", "RandomResizedCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(arr) if isinstance(img, Tensor) else arr


def _resize_np(arr, size):
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(w * size / h)
        else:
            nh, nw = int(h * size / w), size
    else:
        nh, nw = size
    ys = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
    return arr[ys][:, xs]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else size

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_np(arr[i:i + th, j:j + tw], self.size)
        return _resize_np(arr, self.size)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)
