"""Vision transforms (reference: python/paddle/vision/transforms/) —
numpy/host-side preprocessing (HWC uint8 in, CHW float out like the reference).
"""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Compose", "ToTensor", "Normalize", "Resize", "CenterCrop",
           "RandomHorizontalFlip", "RandomCrop", "RandomResizedCrop", "Transpose"]


class Compose:
    def __init__(self, transforms):
        self.transforms = transforms

    def __call__(self, x):
        for t in self.transforms:
            x = t(x)
        return x


class ToTensor:
    def __init__(self, data_format="CHW"):
        self.data_format = data_format

    def __call__(self, img):
        arr = np.asarray(img, dtype=np.float32) / 255.0
        if arr.ndim == 2:
            arr = arr[:, :, None]
        if self.data_format == "CHW":
            arr = arr.transpose(2, 0, 1)
        return Tensor(arr)


class Normalize:
    def __init__(self, mean=0.0, std=1.0, data_format="CHW", to_rgb=False):
        self.mean = np.asarray(mean, dtype=np.float32)
        self.std = np.asarray(std, dtype=np.float32)
        self.data_format = data_format

    def __call__(self, img):
        arr = img.numpy() if isinstance(img, Tensor) else np.asarray(img, np.float32)
        shape = (-1, 1, 1) if self.data_format == "CHW" else (1, 1, -1)
        arr = (arr - self.mean.reshape(shape)) / self.std.reshape(shape)
        return Tensor(arr) if isinstance(img, Tensor) else arr


def _resize_np(arr, size):
    h, w = arr.shape[:2]
    if isinstance(size, int):
        if h < w:
            nh, nw = size, int(w * size / h)
        else:
            nh, nw = int(h * size / w), size
    else:
        nh, nw = size
    ys = (np.arange(nh) * (h / nh)).astype(np.int64).clip(0, h - 1)
    xs = (np.arange(nw) * (w / nw)).astype(np.int64).clip(0, w - 1)
    return arr[ys][:, xs]


class Resize:
    def __init__(self, size, interpolation="bilinear"):
        self.size = size

    def __call__(self, img):
        return _resize_np(np.asarray(img), self.size)


class CenterCrop:
    def __init__(self, size):
        self.size = (size, size) if isinstance(size, int) else size

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        th, tw = self.size
        i = max((h - th) // 2, 0)
        j = max((w - tw) // 2, 0)
        return arr[i:i + th, j:j + tw]


class RandomHorizontalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[:, ::-1].copy()
        return np.asarray(img)


class RandomCrop:
    def __init__(self, size, padding=None):
        self.size = (size, size) if isinstance(size, int) else size
        self.padding = padding

    def __call__(self, img):
        arr = np.asarray(img)
        if self.padding:
            p = self.padding
            arr = np.pad(arr, ((p, p), (p, p)) + ((0, 0),) * (arr.ndim - 2))
        h, w = arr.shape[:2]
        th, tw = self.size
        i = np.random.randint(0, max(h - th, 0) + 1)
        j = np.random.randint(0, max(w - tw, 0) + 1)
        return arr[i:i + th, j:j + tw]


class RandomResizedCrop:
    def __init__(self, size, scale=(0.08, 1.0), ratio=(3 / 4, 4 / 3)):
        self.size = (size, size) if isinstance(size, int) else size
        self.scale = scale
        self.ratio = ratio

    def __call__(self, img):
        arr = np.asarray(img)
        h, w = arr.shape[:2]
        area = h * w
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            tw = int(round(np.sqrt(target * ar)))
            th = int(round(np.sqrt(target / ar)))
            if 0 < tw <= w and 0 < th <= h:
                i = np.random.randint(0, h - th + 1)
                j = np.random.randint(0, w - tw + 1)
                return _resize_np(arr[i:i + th, j:j + tw], self.size)
        return _resize_np(arr, self.size)


class Transpose:
    def __init__(self, order=(2, 0, 1)):
        self.order = order

    def __call__(self, img):
        return np.asarray(img).transpose(self.order)


# ---------------------------------------------------------------------------
# Round-4 breadth (reference transforms.py: color jitter family, rotation,
# pad, grayscale, vertical flip, erasing)
# ---------------------------------------------------------------------------
def _as_float_hwc(img):
    arr = np.asarray(img)
    was_uint8 = arr.dtype == np.uint8
    a = arr.astype(np.float32)
    return a, was_uint8


def _restore(a, was_uint8):
    if was_uint8:
        return np.clip(a, 0, 255).astype(np.uint8)
    return a


def adjust_brightness(img, factor):
    """reference functional.adjust_brightness: pixel * factor."""
    a, u8 = _as_float_hwc(img)
    return _restore(a * float(factor), u8)


def adjust_contrast(img, factor):
    """Blend with the mean GRAYSCALE level (reference functional
    adjust_contrast uses the luma mean, not the raw RGB mean)."""
    a, u8 = _as_float_hwc(img)
    if a.ndim == 3 and a.shape[-1] == 3:
        mean = (a @ np.asarray([0.299, 0.587, 0.114], np.float32)).mean()
    else:
        mean = a.mean()
    return _restore(mean + (a - mean) * float(factor), u8)


def adjust_saturation(img, factor):
    """Blend with the per-pixel grayscale."""
    a, u8 = _as_float_hwc(img)
    gray = (a @ np.asarray([0.299, 0.587, 0.114], np.float32))[..., None]
    return _restore(gray + (a - gray) * float(factor), u8)


def adjust_hue(img, factor):
    """Shift hue by factor (in [-0.5, 0.5] turns) via HSV round-trip."""
    if not -0.5 <= factor <= 0.5:
        raise ValueError("hue factor must be in [-0.5, 0.5]")
    a, u8 = _as_float_hwc(img)
    scale = 255.0 if u8 else 1.0
    x = a / scale
    mx = x.max(-1)
    mn = x.min(-1)
    diff = mx - mn + 1e-12
    r, g, b = x[..., 0], x[..., 1], x[..., 2]
    h = np.where(mx == r, (g - b) / diff % 6,
                 np.where(mx == g, (b - r) / diff + 2, (r - g) / diff + 4))
    h = (h / 6.0 + factor) % 1.0
    s = np.where(mx > 0, diff / (mx + 1e-12), 0.0)
    v = mx
    i = np.floor(h * 6.0)
    f = h * 6.0 - i
    p = v * (1 - s)
    q = v * (1 - f * s)
    t = v * (1 - (1 - f) * s)
    i = i.astype(np.int32) % 6
    out = np.stack([
        np.choose(i, [v, q, p, p, t, v]),
        np.choose(i, [t, v, v, q, p, p]),
        np.choose(i, [p, p, t, v, v, q]),
    ], axis=-1)
    return _restore(out * scale, u8)


def to_grayscale(img, num_output_channels=1):
    a, u8 = _as_float_hwc(img)
    gray = a @ np.asarray([0.299, 0.587, 0.114], np.float32)
    out = np.repeat(gray[..., None], num_output_channels, -1)
    return _restore(out, u8)


class BrightnessTransform:
    """reference transforms.py BrightnessTransform: factor ~ U[max(0,1-v), 1+v]."""

    def __init__(self, value):
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_brightness(img, f)


class ContrastTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_contrast(img, f)


class SaturationTransform(BrightnessTransform):
    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        f = np.random.uniform(max(0.0, 1 - self.value), 1 + self.value)
        return adjust_saturation(img, f)


class HueTransform:
    def __init__(self, value):
        if not 0 <= value <= 0.5:
            raise ValueError("hue value must be in [0, 0.5]")
        self.value = float(value)

    def __call__(self, img):
        if self.value == 0:
            return np.asarray(img)
        return adjust_hue(img, np.random.uniform(-self.value, self.value))


class ColorJitter:
    """reference transforms.py ColorJitter: random-order composition of
    brightness/contrast/saturation/hue jitter."""

    def __init__(self, brightness=0, contrast=0, saturation=0, hue=0):
        self._ts = [BrightnessTransform(brightness),
                    ContrastTransform(contrast),
                    SaturationTransform(saturation), HueTransform(hue)]

    def __call__(self, img):
        order = np.random.permutation(len(self._ts))
        arr = np.asarray(img)
        for i in order:
            arr = self._ts[i](arr)
        return arr


class RandomVerticalFlip:
    def __init__(self, prob=0.5):
        self.prob = prob

    def __call__(self, img):
        if np.random.rand() < self.prob:
            return np.asarray(img)[::-1].copy()
        return np.asarray(img)


class Grayscale:
    def __init__(self, num_output_channels=1):
        self.num_output_channels = num_output_channels

    def __call__(self, img):
        return to_grayscale(img, self.num_output_channels)


class Pad:
    """reference transforms.py Pad (constant/edge/reflect)."""

    def __init__(self, padding, fill=0, padding_mode="constant"):
        if isinstance(padding, int):
            padding = (padding,) * 4           # l, t, r, b
        elif len(padding) == 2:
            padding = (padding[0], padding[1], padding[0], padding[1])
        self.padding = padding
        self.fill = fill
        self.padding_mode = padding_mode

    def __call__(self, img):
        arr = np.asarray(img)
        l, t, r, b = self.padding
        spec = ((t, b), (l, r)) + ((0, 0),) * (arr.ndim - 2)
        if self.padding_mode == "constant":
            return np.pad(arr, spec, constant_values=self.fill)
        return np.pad(arr, spec, mode=self.padding_mode)


class RandomRotation:
    """reference transforms.py RandomRotation: nearest-sample rotation about
    the image center."""

    def __init__(self, degrees, interpolation="nearest", expand=False,
                 center=None, fill=0):
        if isinstance(degrees, (int, float)):
            degrees = (-abs(degrees), abs(degrees))
        self.degrees = degrees
        self.fill = fill
        # only the default sampling mode is implemented — raise instead of
        # silently diverging from the reference for non-default arguments
        if interpolation != "nearest":
            raise NotImplementedError(
                f"RandomRotation: interpolation={interpolation!r} is not "
                "implemented (only 'nearest')")
        if expand:
            raise NotImplementedError(
                "RandomRotation: expand=True is not implemented")
        if center is not None:
            raise NotImplementedError(
                "RandomRotation: a custom center is not implemented "
                "(rotation is about the image center)")

    def __call__(self, img):
        arr = np.asarray(img)
        angle = np.radians(np.random.uniform(*self.degrees))
        h, w = arr.shape[:2]
        cy, cx = (h - 1) / 2.0, (w - 1) / 2.0
        yy, xx = np.meshgrid(np.arange(h), np.arange(w), indexing="ij")
        # inverse map: output pixel -> source pixel
        ys = cy + (yy - cy) * np.cos(angle) - (xx - cx) * np.sin(angle)
        xs = cx + (yy - cy) * np.sin(angle) + (xx - cx) * np.cos(angle)
        yi = np.round(ys).astype(np.int64)
        xi = np.round(xs).astype(np.int64)
        valid = (yi >= 0) & (yi < h) & (xi >= 0) & (xi < w)
        out = np.full_like(arr, self.fill)
        out[valid] = arr[yi[valid], xi[valid]]
        return out


class RandomErasing:
    """reference transforms.py RandomErasing on HWC/CHW arrays."""

    def __init__(self, prob=0.5, scale=(0.02, 0.33), ratio=(0.3, 3.3),
                 value=0, inplace=False):
        self.prob = prob
        self.scale = scale
        self.ratio = ratio
        self.value = value

    def __call__(self, img):
        if np.random.rand() >= self.prob:
            return img
        is_tensor = isinstance(img, Tensor)
        arr = img.numpy() if is_tensor else np.asarray(img)
        chw = arr.ndim == 3 and arr.shape[0] in (1, 3) and \
            arr.shape[0] < arr.shape[-1]
        h, w = (arr.shape[1], arr.shape[2]) if chw else arr.shape[:2]
        area = h * w
        out = arr.copy()
        for _ in range(10):
            target = area * np.random.uniform(*self.scale)
            ar = np.random.uniform(*self.ratio)
            eh = int(round(np.sqrt(target * ar)))
            ew = int(round(np.sqrt(target / ar)))
            if eh < h and ew < w:
                i = np.random.randint(0, h - eh)
                j = np.random.randint(0, w - ew)
                if chw:
                    out[:, i:i + eh, j:j + ew] = self.value
                else:
                    out[i:i + eh, j:j + ew] = self.value
                break
        return Tensor(out) if is_tensor else out


__all__ += ["BrightnessTransform", "ContrastTransform", "SaturationTransform",
            "HueTransform", "ColorJitter", "RandomVerticalFlip", "Grayscale",
            "Pad", "RandomRotation", "RandomErasing", "adjust_brightness",
            "adjust_contrast", "adjust_saturation", "adjust_hue",
            "to_grayscale"]
