"""Framework-level helpers: save/load, places, mode queries.

Reference: python/paddle/framework/io.py (save :773, load :1020) — nested
state_dict pickling with tensors converted to numpy; python/paddle/base/
framework.py places.
"""
from __future__ import annotations

import io as _io
import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor, Parameter
from .flags import get_flags, set_flags  # re-export

__all__ = ["save", "load", "CPUPlace", "TPUPlace", "CUDAPlace", "XPUPlace",
           "in_dynamic_mode", "set_grad_enabled", "get_flags", "set_flags"]


class _Place:
    def __init__(self, idx=0):
        self._idx = idx

    def __repr__(self):
        return f"{type(self).__name__}({self._idx})"


class CPUPlace(_Place):
    pass


class TPUPlace(_Place):
    pass


class CUDAPlace(TPUPlace):
    """Accepted for reference-script compat; maps to the TPU device."""


class XPUPlace(TPUPlace):
    pass


def in_dynamic_mode():
    return True


def set_grad_enabled(mode):
    from .core.dispatch import set_grad_enabled as f
    return f(mode)


def _to_saveable(obj):
    """Recursively convert Tensors to numpy for pickling (paddle.save
    parity: nested dict/list/tuple of tensors + python objects)."""
    if isinstance(obj, Tensor):
        return {"__paddle_tpu_tensor__": True,
                "data": np.asarray(jax.device_get(obj._value)),
                "stop_gradient": obj.stop_gradient,
                "is_parameter": isinstance(obj, Parameter),
                "name": obj.name}
    if isinstance(obj, dict):
        return {k: _to_saveable(v) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        t = type(obj)
        return t(_to_saveable(v) for v in obj)
    if isinstance(obj, (jnp.ndarray, jax.Array)):
        return {"__paddle_tpu_tensor__": True, "data": np.asarray(jax.device_get(obj)),
                "stop_gradient": True, "is_parameter": False, "name": None}
    return obj


def _from_saveable(obj, return_numpy=False):
    if isinstance(obj, dict):
        if obj.get("__paddle_tpu_tensor__"):
            data = obj["data"]
            if return_numpy:
                return data
            cls = Parameter if obj.get("is_parameter") else Tensor
            if cls is Parameter:
                t = Parameter(jnp.asarray(data), name=obj.get("name"))
                t.stop_gradient = obj.get("stop_gradient", False)
                return t
            return Tensor(jnp.asarray(data), stop_gradient=obj.get("stop_gradient", True),
                          name=obj.get("name"))
        return {k: _from_saveable(v, return_numpy) for k, v in obj.items()}
    if isinstance(obj, (list, tuple)):
        return type(obj)(_from_saveable(v, return_numpy) for v in obj)
    return obj


def save(obj, path, protocol=4, **configs):
    """paddle.save parity (framework/io.py:773)."""
    if hasattr(path, "write"):
        pickle.dump(_to_saveable(obj), path, protocol=protocol)
        return
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(_to_saveable(obj), f, protocol=protocol)


def load(path, return_numpy=False, **configs):
    """paddle.load parity (framework/io.py:1020)."""
    if hasattr(path, "read"):
        obj = pickle.load(path)
    else:
        with open(path, "rb") as f:
            obj = pickle.load(f)
    return _from_saveable(obj, return_numpy=return_numpy)
