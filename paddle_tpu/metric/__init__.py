"""Metrics (reference: python/paddle/metric/metrics.py)."""
from __future__ import annotations

import numpy as np

from ..core.tensor import Tensor

__all__ = ["Metric", "Accuracy", "Precision", "Recall", "Auc", "accuracy"]


class Metric:
    def __init__(self, name=None):
        self._name = name or type(self).__name__.lower()

    def name(self):
        return self._name

    def reset(self):
        raise NotImplementedError

    def update(self, *args):
        raise NotImplementedError

    def accumulate(self):
        raise NotImplementedError

    def compute(self, *args):
        return args


class Accuracy(Metric):
    def __init__(self, topk=(1,), name=None):
        super().__init__(name or "acc")
        self.topk = (topk,) if isinstance(topk, int) else tuple(topk)
        self.maxk = max(self.topk)
        self.reset()

    def reset(self):
        self.total = [0.0] * len(self.topk)
        self.count = [0] * len(self.topk)

    def compute(self, pred, label, *args):
        p = np.asarray(pred._value if isinstance(pred, Tensor) else pred)
        l = np.asarray(label._value if isinstance(label, Tensor) else label)
        if l.ndim == p.ndim and l.shape[-1] == 1:
            l = l[..., 0]
        top = np.argsort(-p, axis=-1)[..., : self.maxk]
        correct = top == l[..., None]
        return Tensor(correct.astype(np.float32))

    def update(self, correct, *args):
        c = np.asarray(correct._value if isinstance(correct, Tensor) else correct)
        n = c.shape[0]
        res = []
        for i, k in enumerate(self.topk):
            hit = float(c[..., :k].sum())
            self.total[i] += hit
            self.count[i] += n
            res.append(hit / max(n, 1))
        return res[0] if len(res) == 1 else res

    def accumulate(self):
        res = [t / max(c, 1) for t, c in zip(self.total, self.count)]
        return res[0] if len(res) == 1 else res


class Precision(Metric):
    def __init__(self, name=None):
        super().__init__(name or "precision")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fp = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fp += int(np.sum(pred_pos & ~lab))

    def accumulate(self):
        d = self.tp + self.fp
        return self.tp / d if d else 0.0


class Recall(Metric):
    def __init__(self, name=None):
        super().__init__(name or "recall")
        self.reset()

    def reset(self):
        self.tp = 0
        self.fn = 0

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels)
        pred_pos = (p > 0.5).reshape(-1)
        lab = l.reshape(-1).astype(bool)
        self.tp += int(np.sum(pred_pos & lab))
        self.fn += int(np.sum(~pred_pos & lab))

    def accumulate(self):
        d = self.tp + self.fn
        return self.tp / d if d else 0.0


class Auc(Metric):
    def __init__(self, curve="ROC", num_thresholds=4095, name=None):
        super().__init__(name or "auc")
        self.num_thresholds = num_thresholds
        self.reset()

    def reset(self):
        self._stat_pos = np.zeros(self.num_thresholds + 1)
        self._stat_neg = np.zeros(self.num_thresholds + 1)

    def update(self, preds, labels):
        p = np.asarray(preds._value if isinstance(preds, Tensor) else preds)
        l = np.asarray(labels._value if isinstance(labels, Tensor) else labels).reshape(-1)
        if p.ndim == 2:
            p = p[:, -1]
        idx = (p * self.num_thresholds).astype(np.int64).clip(0, self.num_thresholds)
        for i, lab in zip(idx, l):
            if lab:
                self._stat_pos[i] += 1
            else:
                self._stat_neg[i] += 1

    def accumulate(self):
        tot_pos = self._stat_pos.sum()
        tot_neg = self._stat_neg.sum()
        if tot_pos == 0 or tot_neg == 0:
            return 0.0
        # trapezoidal over thresholds descending
        pos_cum = np.cumsum(self._stat_pos[::-1])
        neg_cum = np.cumsum(self._stat_neg[::-1])
        tpr = pos_cum / tot_pos
        fpr = neg_cum / tot_neg
        return float(np.trapz(tpr, fpr))


def accuracy(input, label, k=1, correct=None, total=None, name=None):
    import jax.numpy as jnp
    p = np.asarray(input._value)
    l = np.asarray(label._value).reshape(-1)
    top = np.argsort(-p, axis=-1)[:, :k]
    hit = (top == l[:, None]).any(axis=1).mean()
    return Tensor(jnp.asarray(np.float32(hit)))
