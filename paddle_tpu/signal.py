"""Signal processing: frame / overlap_add / stft / istft (reference:
python/paddle/signal.py — frame/overlap_add are backed by CPU/GPU kernels
there; here they are gather / scatter-add index maps that XLA fuses, and the
DFT itself rides the TPU FFT op).

The stft/istft bodies run as cached jitted programs rather than eager op
streams: some TPU transports (the axon tunnel) mis-handle long eager
sequences of complex-dtype ops, while a compiled program is always fine —
and jit is also simply faster for a 10-op DSP pipeline.
"""
from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np

from .core.tensor import Tensor
from .core.dispatch import op_call

__all__ = ["frame", "overlap_add", "stft", "istft"]


def frame(x, frame_length, hop_length, axis=-1, name=None):
    """Slice into overlapping frames: [..., seq] -> [..., frame_length,
    num_frames] (axis=-1) or [seq, ...] -> [num_frames, frame_length, ...]
    (axis=0)."""
    if hop_length <= 0:
        raise ValueError(
            f"hop_length should be > 0, but got {hop_length}")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, but got {axis}")

    def impl(v):
        seq = v.shape[axis]
        if not 0 < frame_length <= seq:
            raise ValueError(
                f"frame_length should be in (0, {seq}], got {frame_length}")
        n_frames = 1 + (seq - frame_length) // hop_length
        offsets = hop_length * jnp.arange(n_frames)
        taps = jnp.arange(frame_length)
        if axis == -1:
            idx = taps[:, None] + offsets[None, :]   # [frame_length, n_frames]
            return v[..., idx]
        idx = offsets[:, None] + taps[None, :]       # [n_frames, frame_length]
        return v[idx]
    return op_call("frame", impl, x)


def overlap_add(x, hop_length, axis=-1, name=None):
    """Inverse of `frame` under summation: frames scatter-add into
    [..., seq_length] (axis=-1) or [seq_length, ...] (axis=0), with
    seq_length = (n_frames - 1) * hop_length + frame_length."""
    if hop_length <= 0:
        raise ValueError(
            f"hop_length should be > 0, but got {hop_length}")
    if axis not in (0, -1):
        raise ValueError(f"axis should be 0 or -1, but got {axis}")

    def impl(v):
        if v.ndim < 2:
            raise ValueError("overlap_add expects rank >= 2 input")
        if axis == -1:
            frame_length, n_frames = v.shape[-2], v.shape[-1]
            seq = (n_frames - 1) * hop_length + frame_length
            pos = (jnp.arange(frame_length)[:, None]
                   + hop_length * jnp.arange(n_frames)[None, :])
            out = jnp.zeros(v.shape[:-2] + (seq,), v.dtype)
            return out.at[..., pos].add(v)
        n_frames, frame_length = v.shape[0], v.shape[1]
        seq = (n_frames - 1) * hop_length + frame_length
        pos = (hop_length * jnp.arange(n_frames)[:, None]
               + jnp.arange(frame_length)[None, :])
        out = jnp.zeros((seq,) + v.shape[2:], v.dtype)
        return out.at[pos].add(v)
    return op_call("overlap_add", impl, x)


@functools.lru_cache(maxsize=64)
def _stft_exec(n_fft, hop_length, center, pad_mode, normalized, onesided):
    @jax.jit
    def run(v, win):
        vv = v if v.ndim == 2 else v[None]
        if win.shape[0] < n_fft:
            pl = (n_fft - win.shape[0]) // 2
            win = jnp.pad(win, (pl, n_fft - win.shape[0] - pl))
        if center:
            p = n_fft // 2
            mode = "reflect" if pad_mode == "reflect" else "constant"
            vv = jnp.pad(vv, ((0, 0), (p, p)), mode=mode)
        n_frames = 1 + (vv.shape[-1] - n_fft) // hop_length
        idx = (jnp.arange(n_fft)[:, None]
               + hop_length * jnp.arange(n_frames)[None, :])
        fr = jnp.transpose(vv[..., idx], (0, 2, 1)) * win
        norm = "ortho" if normalized else "backward"
        if jnp.issubdtype(fr.dtype, jnp.complexfloating):
            out = jnp.fft.fft(fr, axis=-1, norm=norm)
        elif onesided:
            out = jnp.fft.rfft(fr, axis=-1, norm=norm)
        else:
            out = jnp.fft.fft(fr.astype(
                jnp.complex128 if fr.dtype == jnp.float64 else jnp.complex64),
                axis=-1, norm=norm)
        out = jnp.transpose(out, (0, 2, 1))     # [B, freq, n_frames]
        return out[0] if v.ndim == 1 else out
    return run


def stft(x, n_fft, hop_length=None, win_length=None, window=None,
         center=True, pad_mode="reflect", normalized=False, onesided=True,
         name=None):
    """Short-time Fourier transform. Returns [batch, n_fft//2+1 | n_fft,
    num_frames] (batch dim squeezed for 1-D input), complex dtype."""
    x_rank = len(x.shape)
    if x_rank not in (1, 2):
        raise ValueError(
            f"x should be a 1D or 2D real tensor, got rank {x_rank}")
    seq = x.shape[-1]
    if not 0 < n_fft <= seq:
        raise ValueError(f"n_fft should be in (0, {seq}], got {n_fft}")
    if hop_length is None:
        hop_length = n_fft // 4
    if hop_length <= 0:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    if win_length is None:
        win_length = n_fft
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"win_length should be in (0, {n_fft}], got {win_length}")
    if center and pad_mode not in ("constant", "reflect"):
        raise ValueError(
            f'pad_mode should be "reflect" or "constant", got "{pad_mode}"')
    xdt = jnp.result_type(x._value if isinstance(x, Tensor) else x)
    if onesided and jnp.issubdtype(xdt, jnp.complexfloating):
        # reference signal.py: a complex spectrum is not Hermitian — the
        # one-sided half would be unrecoverable
        raise ValueError(
            "onesided should be False when input is a complex Tensor")
    w = window if window is not None else \
        Tensor(jnp.ones((win_length,), jnp.float32))
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    if wv.ndim != 1 or wv.shape[0] != win_length:
        raise ValueError(
            f"expected a 1D window of size win_length({win_length}), "
            f"got shape {tuple(wv.shape)}")
    exec_fn = _stft_exec(n_fft, hop_length, center, pad_mode, normalized,
                         onesided)
    return op_call("stft", exec_fn, x,
                   w if isinstance(w, Tensor) else Tensor(wv))


@functools.lru_cache(maxsize=64)
def _istft_exec(n_fft, hop_length, center, normalized, onesided, length,
                return_complex):
    @jax.jit
    def run(v, win):
        vv = v if v.ndim == 3 else v[None]
        n_frames = vv.shape[-1]
        if win.shape[0] < n_fft:
            pl = (n_fft - win.shape[0]) // 2
            win = jnp.pad(win, (pl, n_fft - win.shape[0] - pl))
        fr = jnp.transpose(vv, (0, 2, 1))        # [B, n_frames, freq]
        norm = "ortho" if normalized else "backward"
        if return_complex:
            out = jnp.fft.ifft(fr, axis=-1, norm=norm)
        else:
            if not onesided:
                fr = fr[..., : n_fft // 2 + 1]
            out = jnp.fft.irfft(fr, n=n_fft, axis=-1, norm=norm)
        out = out * win
        pos = (hop_length * jnp.arange(n_frames)[:, None]
               + jnp.arange(n_fft)[None, :])
        seq = (n_frames - 1) * hop_length + n_fft
        sig = jnp.zeros(out.shape[:1] + (seq,), out.dtype)
        sig = sig.at[:, pos].add(out)
        env = jnp.zeros((seq,), win.dtype).at[pos].add(
            jnp.broadcast_to(win * win, (n_frames, n_fft)))
        if length is None:
            if center:
                sig = sig[:, n_fft // 2: -(n_fft // 2)]
                env = env[n_fft // 2: -(n_fft // 2)]
        else:
            start = n_fft // 2 if center else 0
            sig = sig[:, start: start + length]
            env = env[start: start + length]
        envmin = jnp.min(jnp.abs(env))
        # NOLA-degenerate bins divide by ~0 — clamp so traced callers (where
        # the eager-only hard NOLA error in istft() can't fire) get finite
        # output instead of silent inf/nan; a healthy envelope is untouched.
        env = jnp.where(jnp.abs(env) > 1e-11, env, jnp.ones_like(env))
        sig = sig / env
        return (sig[0] if v.ndim == 2 else sig), envmin
    return run


def istft(x, n_fft, hop_length=None, win_length=None, window=None,
          center=True, normalized=False, onesided=True, length=None,
          return_complex=False, name=None):
    """Inverse STFT with window-envelope normalization and the NOLA check
    (reference signal.py istft)."""
    x_rank = len(x.shape)
    if x_rank not in (2, 3):
        raise ValueError(
            f"x should be a 2D or 3D complex tensor, got rank {x_rank}")
    if hop_length is None:
        hop_length = n_fft // 4
    if win_length is None:
        win_length = n_fft
    if not 0 < hop_length:
        raise ValueError(f"hop_length should be > 0, got {hop_length}")
    if not 0 < win_length <= n_fft:
        raise ValueError(
            f"win_length should be in (0, {n_fft}], got {win_length}")
    if return_complex and onesided:
        raise ValueError("onesided should be False when return_complex=True")
    fft_size = x.shape[-2]
    expected = n_fft // 2 + 1 if onesided else n_fft
    if fft_size != expected:
        raise ValueError(
            f"fft_size (dim -2) should be {expected} for n_fft={n_fft}, "
            f"onesided={onesided}; got {fft_size}")
    w = window if window is not None else \
        Tensor(jnp.ones((win_length,), jnp.float32))
    wv = w._value if isinstance(w, Tensor) else jnp.asarray(w)
    if wv.ndim != 1 or wv.shape[0] != win_length:
        raise ValueError(
            f"expected a 1D window of size win_length({win_length}), "
            f"got shape {tuple(wv.shape)}")
    exec_fn = _istft_exec(n_fft, hop_length, center, normalized, onesided,
                          length, return_complex)
    sig, envmin = op_call("istft", exec_fn, x,
                          w if isinstance(w, Tensor) else Tensor(wv))
    ev = envmin._value if isinstance(envmin, Tensor) else envmin
    # The hard NOLA error is EAGER-ONLY: under jit/compiled pipelines envmin
    # is a tracer, and the jitted body instead clamps degenerate envelope
    # bins to 1 so traced callers degrade gracefully (finite output).
    if not isinstance(ev, jax.core.Tracer):
        if float(ev) < 1e-11:
            raise ValueError(
                "Abort istft: Nonzero Overlap Add (NOLA) condition "
                "failed (see scipy.signal.check_NOLA)")
    return sig
