"""paddle.text parity (reference: python/paddle/text/ — dataset loaders).
Zero-egress environment: synthetic dataset shims; ViterbiDecoder is real."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..nn.layer import Layer

__all__ = ["ViterbiDecoder", "viterbi_decode"]


def viterbi_decode(potentials, transition_params, lengths=None,
                   include_bos_eos_tag=True, name=None):
    """CRF Viterbi decode (reference text/viterbi_decode.py) via lax.scan."""
    def impl(emissions, trans):
        B, T, N = emissions.shape
        start = emissions[:, 0]
        def step(carry, emit_t):
            score = carry  # [B, N]
            cand = score[:, :, None] + trans[None] + emit_t[:, None, :]
            best = jnp.max(cand, axis=1)
            idx = jnp.argmax(cand, axis=1)
            return best, idx
        final, history = jax.lax.scan(step, start,
                                      jnp.moveaxis(emissions[:, 1:], 1, 0))
        best_last = jnp.argmax(final, axis=-1)
        def back(carry, idx_t):
            tag = carry
            prev = jnp.take_along_axis(idx_t, tag[:, None], axis=1)[:, 0]
            return prev, prev
        _, path_rev = jax.lax.scan(back, best_last, history, reverse=True)
        path = jnp.concatenate([path_rev, best_last[None]], axis=0)
        scores = jnp.max(final, axis=-1)
        return scores, jnp.moveaxis(path, 0, 1).astype(jnp.int64)
    return op_call("viterbi_decode", impl, potentials, transition_params)


class ViterbiDecoder(Layer):
    def __init__(self, transitions, include_bos_eos_tag=True, name=None):
        super().__init__()
        self.transitions = transitions
        self.include_bos_eos_tag = include_bos_eos_tag

    def forward(self, potentials, lengths=None):
        return viterbi_decode(potentials, self.transitions, lengths,
                              self.include_bos_eos_tag)
