"""jit.save / jit.load (reference: python/paddle/jit/api.py save :980,
translated_layer.py TranslatedLayer; C++ deploy runtime paddle/fluid/jit/).

Artifact format: `<path>.pdmodel.stablehlo` — serialized jax.export artifact
(StableHLO bytes, the inference-model analog) + `<path>.pdiparams` — pickled
state dict. TranslatedLayer reloads both and is callable like a Layer (the
jit::Layer / PredictorEngine analog, AOT-compiled by XLA on first call).
"""
from __future__ import annotations

import os
import pickle

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..nn.layer import Layer, functional_state
from .. import framework

__all__ = ["save", "load", "TranslatedLayer"]


def _specs_to_sds(input_spec):
    """Map InputSpecs/Tensors to ShapeDtypeStructs.  Dynamic dims (None / -1)
    become jax.export symbolic dimensions — all created in ONE scope so the
    exported artifact is shape-polymorphic across multiple dynamic dims
    (the reference's saved inference models keep the batch dim dynamic)."""
    from ..static.input_spec import InputSpec
    n_dyn = sum(1 for s in input_spec if isinstance(s, InputSpec)
                for d in s.shape if d is None or d == -1)
    syms = iter(jax.export.symbolic_shape(
        ", ".join(f"d{i}" for i in range(n_dyn))) if n_dyn else ())
    out = []
    for spec in input_spec:
        if isinstance(spec, InputSpec):
            dims = tuple(next(syms) if (s is None or s == -1) else int(s)
                         for s in spec.shape)
            out.append(jax.ShapeDtypeStruct(dims, spec.dtype or jnp.float32))
        elif isinstance(spec, Tensor):
            out.append(jax.ShapeDtypeStruct(tuple(spec.shape),
                                            spec._value.dtype))
        elif hasattr(spec, "shape"):
            out.append(jax.ShapeDtypeStruct(
                tuple(spec.shape), getattr(spec, "dtype", jnp.float32)))
        else:
            raise TypeError(f"cannot build input spec from {spec!r}")
    return out


def save(layer, path, input_spec=None, **configs):
    """Export layer as StableHLO + weights."""
    if not isinstance(layer, Layer):
        raise TypeError("jit.save expects a Layer")
    layer.eval()
    state = {name: p._value for name, p in layer.named_parameters()}
    state.update({name: b._value for name, b in layer.named_buffers()})

    def pure_fn(state, *args):
        with functional_state(layer, state):
            out = layer.forward(*[Tensor(a) for a in args])
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    if input_spec is None:
        raise ValueError("jit.save requires input_spec on the TPU backend "
                         "(static shapes are part of the exported artifact)")
    sds = _specs_to_sds(input_spec)
    state_sds = jax.tree_util.tree_map(lambda v: jax.ShapeDtypeStruct(v.shape, v.dtype), state)
    exported = jax.export.export(jax.jit(pure_fn))(state_sds, *sds)
    blob = exported.serialize()
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + ".pdmodel.stablehlo", "wb") as f:
        f.write(blob)
    framework.save({k: np.asarray(v) for k, v in state.items()}, path + ".pdiparams")
    names = [getattr(s, "name", None) for s in (input_spec or [])]
    meta = {"n_inputs": len(sds)}
    if names and all(isinstance(n, str) and n for n in names):
        meta["input_names"] = names
    with open(path + ".pdmodel.meta", "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """Reloaded exported model (reference translated_layer.py:?) — callable,
    eval-only (training=False semantics like the reference's inference
    programs)."""

    def __init__(self, exported, state, meta):
        super().__init__()
        self._exported = exported
        self._state = state
        self._meta = meta
        self.eval()

    def forward(self, *args):
        vals = [a._value if isinstance(a, Tensor) else jnp.asarray(a) for a in args]
        out = self._exported.call(self._state, *vals)
        return jax.tree_util.tree_map(Tensor, out)


def load(path, **configs):
    with open(path + ".pdmodel.stablehlo", "rb") as f:
        exported = jax.export.deserialize(f.read())
    state = framework.load(path + ".pdiparams", return_numpy=True)
    state = {k: jnp.asarray(v) for k, v in state.items()}
    try:
        with open(path + ".pdmodel.meta", "rb") as f:
            meta = pickle.load(f)
    except FileNotFoundError:
        meta = {}
    return TranslatedLayer(exported, state, meta)
