"""to_static: compile a dygraph function/Layer with jax.jit.

Reference: python/paddle/jit/api.py:197 (to_static), dy2static
program_translator.py. Here "program capture" is jax tracing: the wrapped
callable runs once per new input signature; Tensor pytree flattening threads
values in/out; Layer parameters and buffers are lifted to explicit jit inputs
via functional_state so weight updates don't trigger recompilation and buffer
mutations (BN stats) round-trip. RNG inside the trace is keyed by an explicit
key drawn per call (deterministic under paddle.seed).
"""
from __future__ import annotations

import functools
import logging
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as random_mod
from ..nn.layer import Layer, functional_state

__all__ = ["to_static", "not_to_static", "StaticFunction",
           "SymbolicStaticFunction", "ignore_module"]


def _find_layer(fn):
    self_obj = getattr(fn, "__self__", None)
    if isinstance(self_obj, Layer):
        return self_obj
    if isinstance(fn, Layer):
        return fn
    return None


class StaticFunction:
    """Compiled callable with a per-signature cache (the _ExecutorCache /
    guard-cache analog)."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None, build_strategy=None, backend=None,
                 full_graph=True, donate_buffers=False):
        self._raw_fn = function
        self._layer = layer if layer is not None else _find_layer(function)
        self._input_spec = input_spec
        self._donate = donate_buffers
        self._jitted = jax.jit(self._traced_call)
        functools.update_wrapper(self, function if not isinstance(function, Layer)
                                 else function.forward)

    # pure function of (state, rng, args, kwargs)
    def _traced_call(self, state, rng, args, kwargs):
        with random_mod.trace_rng(rng):
            if self._layer is not None:
                with functional_state(self._layer, state) as fs:
                    out = self._call_raw(*args, **kwargs)
                    new_state = fs.collect()
            else:
                out = self._call_raw(*args, **kwargs)
                new_state = {}
        return out, new_state

    def _call_raw(self, *args, **kwargs):
        if isinstance(self._raw_fn, Layer):
            return self._raw_fn.forward(*args, **kwargs)
        return self._raw_fn(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        from . import sot_tape
        # a compiled call inside an active tape recording computes arrays
        # the recorder cannot see: invalidate the outer tape
        sot_tape.taint_recording("nested compiled StaticFunction")
        state = {}
        if self._layer is not None:
            state = {name: p._value for name, p in self._layer.named_parameters()}
            state.update({name: b._value for name, b in self._layer.named_buffers()})
        rng = random_mod.split_key()
        out, new_state = self._jitted(state, rng, args, kwargs)
        if self._layer is not None and new_state:
            # only buffers actually mutate during forward (BN running stats)
            buffer_map = dict(self._layer.named_buffers())
            for name, v in new_state.items():
                t = buffer_map.get(name)
                if t is not None and t._value is not v:
                    t._set_value(v)
        return out

    # -- introspection parity ---------------------------------------------
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._raw_fn if not isinstance(self._raw_fn, Layer)
                                     else self._raw_fn.forward)
        except Exception:
            return "<source unavailable>"

    def concrete_program_specify_input_spec(self, input_spec=None):
        return self

    def get_concrete_program(self, *args, **kwargs):
        return self, None

    @property
    def forward(self):
        return self


class SymbolicStaticFunction(StaticFunction):
    """The SOT analog (reference jit/sot/opcode_translator/: symbolic
    bytecode execution with Guards + graph-break fallback,
    `api.py:302 SymbolicStaticFunction`).

    The reference intercepts CPython bytecode, symbolically executes it into
    a FunctionGraph, caches per-Guard compiled programs, and falls back to
    the original bytecode on a graph break. Under jax the trace IS the
    symbolic executor; what this class adds over plain jit capture:

    * **guards** — the compiled-program cache is keyed on (python-scalar
      argument VALUES, layer training mode, pytree structure) in addition to
      jax's shape/dtype keying: scalars are baked static per variant, so
      `if flag:` branches re-specialize per value exactly like SOT guards;
    * **graph breaks** — a trace failure from data-dependent python control
      flow (`if tensor.sum() > 0:` → TracerBoolConversionError, .numpy() on
      a tracer, dynamic shapes) permanently marks that guard key broken and
      executes eagerly (the pycode_generator fallback), instead of raising;
    * introspection: `compiled_count` / `graph_break_count` /
      `broken_reasons` (the SOT info-collector analog).
    """

    _BREAK_ERRORS = (jax.errors.TracerBoolConversionError,
                     jax.errors.ConcretizationTypeError,
                     jax.errors.TracerArrayConversionError,
                     jax.errors.TracerIntegerConversionError,
                     NotImplementedError)

    #: guard-cache capacity (reference SOT bounds its cache too): a training
    #: loop passing an ever-changing python float would otherwise compile a
    #: new variant per value forever. LRU-evicted beyond this.
    max_variants = 32
    #: tape programs kept per broken guard key (one per value path)
    max_tapes_per_guard = 8

    def __init__(self, *a, **kw):
        super().__init__(*a, **kw)
        from collections import OrderedDict
        self._broken = OrderedDict()    # guard_key -> reason string
        self._variants = OrderedDict()  # guard_key -> jitted fn
        self._tapes = OrderedDict()     # guard_key -> [TapeProgram, ...]
        self.graph_break_count = 0

    @property
    def compiled_count(self):
        return len(self._variants)

    @property
    def partial_graph_count(self):
        """Broken guard keys currently served by compiled tape segments
        (the pycode_generator analog) instead of pure eager."""
        return sum(1 for e in self._tapes.values() if e.get("progs"))

    @property
    def broken_reasons(self):
        return dict(self._broken)

    def _lru_put(self, od, key, value, cap):
        od[key] = value
        od.move_to_end(key)
        while len(od) > cap:
            od.popitem(last=False)

    # -- partial-graph fallback (tape replay; see jit/sot_tape.py) ----------
    def _sot_inputs(self, args, kwargs):
        import numpy as _np
        named = {}
        state_tensors = []
        for i, l in enumerate(jax.tree_util.tree_leaves(
                (args, kwargs), is_leaf=lambda x: isinstance(x, Tensor))):
            if isinstance(l, Tensor):
                named[f"a{i}"] = l._value
            elif isinstance(l, (_np.ndarray, jax.Array)):
                # raw-array args are runtime data too; if the function
                # converts them through an unrecorded path the tape builder
                # refuses (unreferenced-input rule) rather than baking them
                named[f"a{i}"] = l
        if self._layer is not None:
            for n, p in self._layer.named_parameters():
                named[f"s:{n}"] = p._value
                state_tensors.append(p)
            for n, b in self._layer.named_buffers():
                named[f"s:{n}"] = b._value
                state_tensors.append(b)
        return named, state_tensors

    #: consecutive replay misses before a guard goes permanently eager
    max_path_misses = 8

    def _sot_fallback(self, guard, args, kwargs):
        """Broken guard: replay a compiled tape when one matches the
        observed value path; otherwise run eagerly ONCE while recording a
        new tape (compiled prefix -> eager fetch -> compiled rest). Guards
        whose fetched values never stabilise (continuous floats) go
        permanently eager after max_path_misses consecutive misses."""
        from . import sot_tape
        from .sot_tape import record_tape, PathMismatch
        if sot_tape.is_recording():
            # nested broken call during an outer recording: run plain eager
            # so our ops land on the OUTER tape
            return self._call_raw(*args, **kwargs)
        entry = self._tapes.get(guard)
        if entry is None:
            entry = {"progs": [], "misses": 0}
            self._lru_put(self._tapes, guard, entry, self.max_variants)
        if entry["misses"] >= self.max_path_misses:
            return self._call_raw(*args, **kwargs)     # unstable: eager
        named, state_tensors = self._sot_inputs(args, kwargs)
        for prog in list(entry["progs"]):
            try:
                out = prog.replay(named)
                entry["misses"] = 0
                self._tapes.move_to_end(guard)
                return out
            except PathMismatch:
                continue
            except Exception as e:  # noqa: BLE001 — staleness surfaces as
                # KeyError/TypeError/ValueError depending on which segment
                # drifted; dropping the tape and re-recording is the
                # self-healing path. Log it so a genuine replay bug (OOM,
                # compilation failure) is visible instead of silently eaten.
                logging.getLogger(__name__).warning(
                    "sot: dropping tape for %r after replay error %s: %s",
                    guard, type(e).__name__, e)
                entry["progs"].remove(prog)
        entry["misses"] += 1
        if len(entry["progs"]) >= self.max_tapes_per_guard:
            # cache full: recording again would only be thrown away
            return self._call_raw(*args, **kwargs)
        out, prog = record_tape(lambda: self._call_raw(*args, **kwargs),
                                named, state_tensors)
        if prog is not None and prog.n_segments > 0:
            entry["progs"].append(prog)
        elif prog is None:
            entry["misses"] = self.max_path_misses   # untapeable: eager
        return out

    @staticmethod
    def _split_static(tree):
        """Replace python-scalar leaves with placeholders; return
        (traced_tree, static_leaves_tuple) — the guard's value part."""
        leaves, treedef = jax.tree_util.tree_flatten(
            tree, is_leaf=lambda x: isinstance(x, (bool, int, float, str,
                                                   type(None))))
        statics = []
        traced = []
        for i, l in enumerate(leaves):
            if isinstance(l, (bool, int, float, str, type(None))) and \
                    not isinstance(l, Tensor):
                # type is part of the guard: hash(2) == hash(2.0) == hash(True)
                # would otherwise reuse a variant baked with the wrong dtype
                statics.append((i, type(l).__name__, l))
                traced.append(None)
            else:
                traced.append(l)
        return jax.tree_util.tree_unflatten(treedef, traced), \
            tuple(statics), treedef

    def __call__(self, *args, **kwargs):
        traced_args, statics, treedef = self._split_static((args, kwargs))
        training = getattr(self._layer, "training", None)
        # the guard keys on input SIGNATURE too (reference SOT guards per
        # shape/dtype): a graph break on one shape must not de-optimize the
        # compiled variants of other shapes
        avals = tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", "")))
            for l in jax.tree_util.tree_leaves(traced_args))
        guard = (statics, training, str(treedef), avals)
        if guard in self._broken:
            # graph-break path: compiled tape segments around the break
            self._broken.move_to_end(guard)
            return self._sot_fallback(guard, args, kwargs)

        if guard not in self._variants:
            def traced_call(state, rng, traced):
                # re-insert the guarded static values into the pytree
                leaves, td = jax.tree_util.tree_flatten(
                    traced, is_leaf=lambda x: x is None)
                for i, _tname, v in statics:
                    leaves[i] = v
                a, k = jax.tree_util.tree_unflatten(td, leaves)
                return self._traced_call(state, rng, a, k)
            self._lru_put(self._variants, guard, jax.jit(traced_call),
                          self.max_variants)
        else:
            self._variants.move_to_end(guard)

        state = {}
        if self._layer is not None:
            state = {n: p._value for n, p in self._layer.named_parameters()}
            state.update({n: b._value
                          for n, b in self._layer.named_buffers()})
        rng = random_mod.split_key()
        try:
            out, new_state = self._variants[guard](state, rng, traced_args)
        except self._BREAK_ERRORS as e:
            # graph break: serve this guard key via tape-replay partial
            # graphs from now on (compiled prefix/tail, eager break region)
            self._lru_put(self._broken, guard, f"{type(e).__name__}: {e}",
                          self.max_variants)
            self._variants.pop(guard, None)
            self.graph_break_count += 1
            return self._sot_fallback(guard, args, kwargs)
        if self._layer is not None and new_state:
            buffer_map = dict(self._layer.named_buffers())
            for name, v in new_state.items():
                t = buffer_map.get(name)
                if t is not None and t._value is not v:
                    t._set_value(v)
        return out


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """paddle.jit.to_static parity (reference api.py:197).

    full_graph=True → ASTStaticFunction analog: plain jax capture; python
    control flow unrolls at trace time, data-dependent branching must use
    paddle_tpu.static.nn.cond / while_loop.
    full_graph=False → SymbolicStaticFunction (the SOT analog): scalar-value
    guards + graph-break fallback to eager on untraceable control flow.
    """
    cls = StaticFunction if full_graph else SymbolicStaticFunction

    def deco(fn):
        if isinstance(fn, Layer):
            # capture the ORIGINAL forward before rebinding (else sf recurses)
            orig_forward = fn.forward
            sf = cls(orig_forward, layer=fn, input_spec=input_spec,
                     full_graph=full_graph)
            fn.forward = sf
            return fn
        return cls(fn, input_spec=input_spec, full_graph=full_graph)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass
