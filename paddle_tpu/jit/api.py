"""to_static: compile a dygraph function/Layer with jax.jit.

Reference: python/paddle/jit/api.py:197 (to_static), dy2static
program_translator.py. Here "program capture" is jax tracing: the wrapped
callable runs once per new input signature; Tensor pytree flattening threads
values in/out; Layer parameters and buffers are lifted to explicit jit inputs
via functional_state so weight updates don't trigger recompilation and buffer
mutations (BN stats) round-trip. RNG inside the trace is keyed by an explicit
key drawn per call (deterministic under paddle.seed).
"""
from __future__ import annotations

import functools
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import random as random_mod
from ..nn.layer import Layer, functional_state

__all__ = ["to_static", "not_to_static", "StaticFunction", "ignore_module"]


def _find_layer(fn):
    self_obj = getattr(fn, "__self__", None)
    if isinstance(self_obj, Layer):
        return self_obj
    if isinstance(fn, Layer):
        return fn
    return None


class StaticFunction:
    """Compiled callable with a per-signature cache (the _ExecutorCache /
    guard-cache analog)."""

    def __init__(self, function: Callable, layer: Optional[Layer] = None,
                 input_spec=None, build_strategy=None, backend=None,
                 full_graph=True, donate_buffers=False):
        self._raw_fn = function
        self._layer = layer if layer is not None else _find_layer(function)
        self._input_spec = input_spec
        self._donate = donate_buffers
        self._jitted = jax.jit(self._traced_call)
        functools.update_wrapper(self, function if not isinstance(function, Layer)
                                 else function.forward)

    # pure function of (state, rng, args, kwargs)
    def _traced_call(self, state, rng, args, kwargs):
        with random_mod.trace_rng(rng):
            if self._layer is not None:
                with functional_state(self._layer, state) as fs:
                    out = self._call_raw(*args, **kwargs)
                    new_state = fs.collect()
            else:
                out = self._call_raw(*args, **kwargs)
                new_state = {}
        return out, new_state

    def _call_raw(self, *args, **kwargs):
        if isinstance(self._raw_fn, Layer):
            return self._raw_fn.forward(*args, **kwargs)
        return self._raw_fn(*args, **kwargs)

    def __call__(self, *args, **kwargs):
        state = {}
        if self._layer is not None:
            state = {name: p._value for name, p in self._layer.named_parameters()}
            state.update({name: b._value for name, b in self._layer.named_buffers()})
        rng = random_mod.split_key()
        out, new_state = self._jitted(state, rng, args, kwargs)
        if self._layer is not None and new_state:
            # only buffers actually mutate during forward (BN running stats)
            buffer_map = dict(self._layer.named_buffers())
            for name, v in new_state.items():
                t = buffer_map.get(name)
                if t is not None and t._value is not v:
                    t._set_value(v)
        return out

    # -- introspection parity ---------------------------------------------
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._raw_fn if not isinstance(self._raw_fn, Layer)
                                     else self._raw_fn.forward)
        except Exception:
            return "<source unavailable>"

    def concrete_program_specify_input_spec(self, input_spec=None):
        return self

    def get_concrete_program(self, *args, **kwargs):
        return self, None

    @property
    def forward(self):
        return self


def to_static(function=None, input_spec=None, build_strategy=None, backend=None,
              full_graph=True, **kwargs):
    """paddle.jit.to_static parity. Under the TPU design full_graph=True and
    False converge: jax tracing handles arbitrary python control flow by
    unrolling (AST-transpiler analog); data-dependent branching should use
    paddle_tpu.static.nn.cond / while_loop (lax control flow)."""
    def deco(fn):
        if isinstance(fn, Layer):
            # capture the ORIGINAL forward before rebinding (else sf recurses)
            orig_forward = fn.forward
            sf = StaticFunction(orig_forward, layer=fn, input_spec=input_spec,
                                full_graph=full_graph)
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec=input_spec, full_graph=full_graph)
    if function is not None:
        return deco(function)
    return deco


def not_to_static(fn=None):
    if fn is None:
        return lambda f: f
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass
