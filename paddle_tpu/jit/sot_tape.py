"""SOT partial-graph compilation via tape replay (reference:
python/paddle/jit/sot/opcode_translator/executor/pycode_generator.py — on a
graph break the reference regenerates bytecode so the compiled prefix still
runs and only the breaking region is eager).

TPU-native analog: CPython bytecode is out of reach, but the eager dispatch
layer can RECORD the op tape of one eager execution together with every
concretization event (a `bool()`/`item()`/`numpy()` fetch that steered
python control flow). The tape then replays as a chain of jitted SEGMENTS
split at those events:

    compiled segment -> host fetch (the breaking region) -> compiled segment

Each segment's guard is the full fetched ARRAY recorded at tape time:
matching content ⇒ the python control flow between the ops took the same
path ⇒ the recorded op sequence is exactly what the function would do, so
the replay is sound. A mismatch aborts the replay and the caller records a
fresh tape for the new value path (bool branches need at most two tapes).

Soundness guards — the program REFUSES to build (permanent eager fallback)
when replay could silently diverge from eager semantics:
  * differentiable outputs (the eager autograd tape cannot be replayed),
  * layer parameters/buffers mutated during the recorded call (replay has
    no side effects),
  * a declared runtime input never referenced by any recorded op (its data
    reached the ops through an unrecorded transform — AMP casts, numpy
    conversions — and would otherwise be baked stale),
  * a concretize event whose fetched array cannot be resolved to a tape
    value (its guard would be unenforceable), or is too large to guard on.

Layer parameters/buffers are recognised by identity against the state
snapshot taken at record time and become named runtime inputs (re-read each
call, so optimizer updates are visible); remaining arrays are baked
constants (safe by the unused-input refusal above).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, _concretize_hook
from ..core import dispatch as _dispatch

__all__ = ["record_tape", "TapeProgram", "PathMismatch", "is_recording"]

_GUARD_MAX_ELEMS = 65536

# nested broken to_static calls must NOT replay their own tapes while an
# outer recording is active — their eager ops need to land on the outer tape
_recording_depth = [0]
_recording_tainted = [False]


def is_recording():
    return _recording_depth[0] > 0


def taint_recording(reason=""):
    """Called by code that computes arrays OUTSIDE the eager dispatch layer
    while a tape is being recorded (e.g. a nested to_static call that runs
    compiled): its outputs would be baked stale, so the tape must refuse."""
    if _recording_depth[0] > 0:
        _recording_tainted[0] = True


class PathMismatch(Exception):
    """A segment's fetched value diverged from the recorded path."""


class _Untapeable(Exception):
    pass


class _Recording:
    def __init__(self):
        self.ops = []        # dispatch records (name, vals, outs, impl, kw)
        self.events = []     # (op_index_at_fetch, value_obj, np_guard_array)


def record_tape(fn, inputs_named, state_tensors=()):
    """Run `fn()` eagerly while recording the op tape + concretize events.

    inputs_named: {name: jax_array} — runtime inputs (function args
    flattened + layer state). state_tensors: Tensors whose in-place
    mutation during the call makes the tape unsound.
    Returns (fn_output, TapeProgram or None)."""
    rec = _Recording()
    prev_rec = _dispatch._op_recorder[0]
    prev_hook = _concretize_hook[0]
    state_ids = [id(t._value) for t in state_tensors]

    def on_concretize(value, result):
        try:
            arr = np.asarray(jax.device_get(value))
        except Exception:
            arr = None
        # hold the VALUE OBJECT (not just its id): a freed array's id could
        # be recycled by a later op output, mis-wiring the guard
        rec.events.append((len(rec.ops), value, arr))

    _dispatch._op_recorder[0] = rec.ops
    _concretize_hook[0] = on_concretize
    _recording_depth[0] += 1
    prev_taint = _recording_tainted[0]
    _recording_tainted[0] = False
    try:
        out = fn()
    finally:
        tainted = _recording_tainted[0]
        _recording_tainted[0] = prev_taint
        _recording_depth[0] -= 1
        _dispatch._op_recorder[0] = prev_rec
        _concretize_hook[0] = prev_hook
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda x: isinstance(x, Tensor))
    if any(isinstance(l, Tensor) and not l.stop_gradient for l in leaves):
        # differentiable outputs ride the eager autograd tape, which the
        # replay cannot reproduce — keep this path fully eager
        return out, None
    if any(id(t._value) != i for t, i in zip(state_tensors, state_ids)):
        return out, None   # in-place state mutation: replay would skip it
    if tainted:
        return out, None   # nested compiled call: its outputs would bake
    try:
        prog = TapeProgram(rec, inputs_named, out)
    except Exception:
        prog = None  # untapeable structure: permanent-eager fallback
    return out, prog


class TapeProgram:
    """Replayable straight-line program: jitted segments split at
    concretization events, array-guarded."""

    def __init__(self, rec, inputs_named, out):
        self._refs = {}              # id(array) -> ref
        self._consts = []            # baked arrays
        self._input_names = list(inputs_named)
        for i, (name, v) in enumerate(inputs_named.items()):
            self._refs[id(v)] = ("in", i)
        self._records = []           # (impl, kwargs, in_refs, n_out)
        used_inputs = set()
        for op_cursor, (name, vals, outs, impl, kw) in enumerate(rec.ops):
            in_refs = tuple(self._ref_of(v) for v in vals)
            for r in in_refs:
                if r[0] == "in":
                    used_inputs.add(r[1])
            for j, o in enumerate(outs):
                if isinstance(o, (jnp.ndarray, jax.Array)):
                    self._refs.setdefault(id(o), ("op", op_cursor, j))
            self._records.append((impl, kw, in_refs, len(outs)))
        if not self._records:
            # zero recorded ops: the output could only be a baked literal
            raise _Untapeable("no recorded ops")
        if len(used_inputs) < len(self._input_names):
            # some input's data reached the ops through an unrecorded
            # transform (AMP cast, numpy conversion): it would be baked
            # stale — refuse
            raise _Untapeable("unreferenced runtime input")
        # events -> (op_index, ref, np_guard)
        self._events = []
        for op_idx, vobj, guard_arr in rec.events:
            ref = self._refs.get(id(vobj))
            if ref is None or guard_arr is None:
                raise _Untapeable("unguardable concretize event")
            if guard_arr.size > _GUARD_MAX_ELEMS:
                raise _Untapeable("concretize guard too large")
            self._events.append((op_idx, ref, guard_arr))
        # output template
        self._out_leaves, self._out_tree = jax.tree_util.tree_flatten(
            out, is_leaf=lambda x: isinstance(x, Tensor))
        self._out_refs = []
        for leaf in self._out_leaves:
            v = leaf._value if isinstance(leaf, Tensor) else leaf
            if isinstance(v, (jnp.ndarray, jax.Array)):
                r = self._ref_of(v)
                if r[0] == "const":
                    # an array output not derived from any recorded op or
                    # input would replay stale
                    raise _Untapeable("baked array output")
                self._out_refs.append(r)
            else:
                self._out_refs.append(("lit", v))
        # segment boundaries (unique, sorted op indices of events)
        bounds = sorted({e[0] for e in self._events})
        self._segments = []
        start = 0
        for b in bounds + [len(self._records)]:
            if b >= start:
                self._segments.append((start, b))
                start = b
        if start < len(self._records):
            self._segments.append((start, len(self._records)))
        self._jitted = [self._compile_segment(a, b)
                        for a, b in self._segments]

    # -- refs ----------------------------------------------------------------
    def _ref_of(self, v):
        if not isinstance(v, (jnp.ndarray, jax.Array)):
            return ("lit", v)
        r = self._refs.get(id(v))
        if r is not None:
            return r
        self._consts.append(v)
        r = ("const", len(self._consts) - 1)
        self._refs[id(v)] = r
        return r

    def _resolve(self, ref, inputs, env):
        kind = ref[0]
        if kind == "in":
            return inputs[ref[1]]
        if kind == "op":
            return env[ref[1]][ref[2]]
        if kind == "const":
            return self._consts[ref[1]]
        return ref[1]                      # literal

    # -- compilation ---------------------------------------------------------
    def _compile_segment(self, a, b):
        records = self._records[a:b]

        def run(inputs, env_flat):
            env = dict(env_flat)
            for off, (impl, kw, in_refs, _n) in enumerate(records):
                vals = [self._resolve(r, inputs, env) for r in in_refs]
                out = impl(*vals, **kw)
                outs = tuple(out) if isinstance(out, (tuple, list)) else (out,)
                env[a + off] = outs
            return {i: env[i] for i in env if i >= a}
        return jax.jit(run)

    @property
    def n_segments(self):
        return len(self._segments)

    # -- replay --------------------------------------------------------------
    def replay(self, inputs_named):
        """Run the compiled segments; raises PathMismatch when a fetched
        array differs from the recorded guard."""
        inputs = [inputs_named[n] for n in self._input_names]
        env = {}
        ev = list(self._events)
        for (a, b), fn in zip(self._segments, self._jitted):
            new = fn(inputs, env)
            env.update(new)
            while ev and ev[0][0] == b:
                _idx, ref, expect = ev.pop(0)
                got = np.asarray(jax.device_get(
                    self._resolve(ref, inputs, env)))
                if got.shape != expect.shape or not np.array_equal(
                        got, expect, equal_nan=True):
                    raise PathMismatch()
        out_vals = [self._resolve(r, inputs, env) for r in self._out_refs]
        leaves = []
        for tmpl, v in zip(self._out_leaves, out_vals):
            if isinstance(tmpl, Tensor):
                t = Tensor(v)
                t.stop_gradient = tmpl.stop_gradient
                leaves.append(t)
            else:
                leaves.append(v)
        return jax.tree_util.tree_unflatten(self._out_tree, leaves)
