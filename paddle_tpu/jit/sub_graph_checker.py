"""Subgraph accuracy checker (reference: paddle/fluid/sub_graph/
sub_graph_checker.cc — runs a CINN-compiled subgraph against the PHI
reference kernels and compares outputs).

TPU-native analog: "compiled" = XLA (jit), "reference" = the eager
dispatch-committed execution. Two modes:

  * whole-graph: run fn eager and under jax.jit, compare final outputs;
  * op-by-op: record every eager op's (inputs, outputs) through the
    dispatch recorder, then re-execute each op's impl under jit on the
    recorded inputs and report the per-op max |eager − compiled| — the
    divergence localizer the reference's checker provides per subgraph.
"""
from __future__ import annotations

import dataclasses
from typing import Callable, List

import jax
import numpy as np

from ..core import dispatch as _dispatch
from ..core.tensor import Tensor

__all__ = ["OpReport", "CheckResult", "check_accuracy"]


@dataclasses.dataclass
class OpReport:
    name: str
    index: int
    max_abs_err: float
    ok: bool


@dataclasses.dataclass
class CheckResult:
    graph_max_abs_err: float
    graph_ok: bool
    op_reports: List[OpReport]

    def worst(self, k=5):
        return sorted(self.op_reports, key=lambda r: -r.max_abs_err)[:k]


def _to_np(out):
    leaves = jax.tree_util.tree_leaves(
        out, is_leaf=lambda x: isinstance(x, Tensor))
    return [np.asarray(l._value if isinstance(l, Tensor) else l)
            for l in leaves]


def check_accuracy(fn: Callable, *args, rtol=1e-4, atol=1e-5,
                   op_by_op=True) -> CheckResult:
    """fn: Tensor-level callable (Layer.forward, functional op chain).
    args: Tensors/arrays. Returns a CheckResult; graph_ok is the
    whole-graph eager-vs-jit comparison, op_reports localize per-op."""
    t_args = [a if isinstance(a, Tensor) else Tensor(a) for a in args]

    # 1. eager run with the dispatch recorder on
    rec = []
    _dispatch._op_recorder[0] = rec
    try:
        eager_out = fn(*t_args)
    finally:
        _dispatch._op_recorder[0] = None
    eager_np = _to_np(eager_out)

    # 2. whole-graph compiled run
    def pure(*vals):
        out = fn(*[Tensor(v) for v in vals])
        return jax.tree_util.tree_map(
            lambda t: t._value if isinstance(t, Tensor) else t, out,
            is_leaf=lambda x: isinstance(x, Tensor))

    jit_out = jax.jit(pure)(*[t._value for t in t_args])
    jit_np = [np.asarray(l) for l in jax.tree_util.tree_leaves(jit_out)]
    gmax = max((float(np.max(np.abs(a.astype(np.float64)
                                    - b.astype(np.float64))))
                for a, b in zip(eager_np, jit_np)
                if a.dtype.kind in "fc"), default=0.0)
    graph_ok = all(
        np.allclose(a, b, rtol=rtol, atol=atol)
        for a, b in zip(eager_np, jit_np))

    # 3. op-by-op: re-run each recorded op's impl compiled on its inputs
    reports = []
    if op_by_op:
        for idx, (name, vals, outs, impl, skw) in enumerate(rec):
            if impl is None:
                continue
            try:
                jout = jax.jit(lambda *v: impl(*v, **skw))(*vals)
            except Exception:
                continue  # untraceable impl; the whole-graph pass covers it
            jouts = jout if isinstance(jout, (tuple, list)) else (jout,)
            err = 0.0
            for a, b in zip(outs, jouts):
                a = np.asarray(a)
                b = np.asarray(b)
                if a.dtype.kind in "fc":
                    err = max(err, float(np.max(np.abs(
                        a.astype(np.float64) - b.astype(np.float64)))))
            reports.append(OpReport(name, idx, err,
                                    bool(err <= atol + rtol * 1.0)))
    return CheckResult(gmax, graph_ok, reports)
