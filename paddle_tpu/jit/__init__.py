"""paddle.jit parity (reference: python/paddle/jit/ — to_static api.py:197,
jit.save/load translated_layer.py, SOT bytecode capture).

TPU-native design (SURVEY.md §7.4): the AST/SOT transpilers + PIR interpreter
+ CINN collapse into `jax.jit` — dygraph Tensor ops executed under a trace
stage XLA HLO directly; the executor cache is jax's compilation cache keyed by
abstract signature (the _ExecutorCache analog, reference base/executor.py:850).
`jit.save` exports the traced computation as serialized StableHLO plus a
weights archive; `jit.load` restores a callable TranslatedLayer.
"""
from __future__ import annotations

from .api import to_static, not_to_static, ignore_module, StaticFunction
from .save_load import save, load, TranslatedLayer

__all__ = ["to_static", "not_to_static", "save", "load", "TranslatedLayer",
           "StaticFunction", "ignore_module"]
