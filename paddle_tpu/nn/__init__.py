"""paddle.nn parity namespace."""
from __future__ import annotations

from .layer import Layer, functional_state, functional_call
from .common import *  # noqa: F401,F403
from .container import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .activation import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .transformer import *  # noqa: F401,F403
from .rnn import *  # noqa: F401,F403

from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from . import utils  # noqa: F401

from .clip import ClipGradByGlobalNorm, ClipGradByNorm, ClipGradByValue  # noqa: F401
