"""Activation functionals (reference: python/paddle/nn/functional/activation.py).

All map onto jax.nn / jnp primitives; XLA fuses them into adjacent matmuls
(HBM-bandwidth win on TPU).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op_call
from ...core.tensor import Tensor

__all__ = ["relu", "relu_", "relu6", "gelu", "sigmoid", "silu", "swish", "mish",
           "softplus", "softsign", "hardshrink", "softshrink", "tanhshrink",
           "hardsigmoid", "hardswish", "hardtanh", "elu", "elu_", "celu", "selu",
           "leaky_relu", "prelu", "rrelu", "glu", "softmax", "softmax_",
           "log_softmax", "gumbel_softmax", "maxout", "tanh", "tanh_",
           "log_sigmoid", "thresholded_relu", "swiglu"]


def relu(x, name=None):
    return op_call("relu", jax.nn.relu, x)


def relu_(x, name=None):
    return x._set_value(jax.nn.relu(x._value))


def relu6(x, name=None):
    return op_call("relu6", jax.nn.relu6, x)


def gelu(x, approximate=False, name=None):
    return op_call("gelu", lambda v: jax.nn.gelu(v, approximate=approximate), x)


def sigmoid(x, name=None):
    return op_call("sigmoid", jax.nn.sigmoid, x)


def silu(x, name=None):
    return op_call("silu", jax.nn.silu, x)


def swish(x, name=None):
    return op_call("swish", jax.nn.silu, x)


def mish(x, name=None):
    return op_call("mish", lambda v: v * jnp.tanh(jax.nn.softplus(v)), x)


def softplus(x, beta=1.0, threshold=20.0, name=None):
    return op_call("softplus",
                   lambda v: jnp.where(v * beta > threshold, v,
                                       jnp.log1p(jnp.exp(-jnp.abs(beta * v))) / beta
                                       + jnp.maximum(v, 0)), x)


def softsign(x, name=None):
    return op_call("softsign", jax.nn.soft_sign, x)


def hardshrink(x, threshold=0.5, name=None):
    return op_call("hardshrink",
                   lambda v: jnp.where(jnp.abs(v) > threshold, v, 0.0).astype(v.dtype), x)


def softshrink(x, threshold=0.5, name=None):
    return op_call("softshrink",
                   lambda v: jnp.where(v > threshold, v - threshold,
                                       jnp.where(v < -threshold, v + threshold, 0.0)).astype(v.dtype), x)


def tanhshrink(x, name=None):
    return op_call("tanhshrink", lambda v: v - jnp.tanh(v), x)


def hardsigmoid(x, slope=0.1666667, offset=0.5, name=None):
    return op_call("hardsigmoid",
                   lambda v: jnp.clip(v * slope + offset, 0.0, 1.0), x)


def hardswish(x, name=None):
    return op_call("hardswish", lambda v: v * jnp.clip(v + 3.0, 0.0, 6.0) / 6.0, x)


def hardtanh(x, min=-1.0, max=1.0, name=None):
    return op_call("hardtanh", lambda v: jnp.clip(v, min, max), x)


def elu(x, alpha=1.0, name=None):
    return op_call("elu", lambda v: jax.nn.elu(v, alpha=alpha), x)


def elu_(x, alpha=1.0, name=None):
    return x._set_value(jax.nn.elu(x._value, alpha=alpha))


def celu(x, alpha=1.0, name=None):
    return op_call("celu", lambda v: jax.nn.celu(v, alpha=alpha), x)


def selu(x, scale=1.0507009873554805, alpha=1.6732632423543772, name=None):
    return op_call("selu", lambda v: scale * jnp.where(v > 0, v, alpha * jnp.expm1(v)), x)


def leaky_relu(x, negative_slope=0.01, name=None):
    return op_call("leaky_relu", lambda v: jax.nn.leaky_relu(v, negative_slope), x)


def prelu(x, weight, data_format="NCHW", name=None):
    def impl(v, w):
        if w.size == 1:
            ww = w.reshape(())
        else:
            # per-channel: broadcast along channel dim
            ch_dim = 1 if data_format == "NCHW" else v.ndim - 1
            shape = [1] * v.ndim
            shape[ch_dim] = w.size
            ww = w.reshape(shape)
        return jnp.where(v > 0, v, ww * v)
    return op_call("prelu", impl, x, weight)


def rrelu(x, lower=0.125, upper=0.3333333333333333, training=False, name=None):
    from ...core.random import split_key
    def impl(v):
        if training:
            a = jax.random.uniform(split_key(), v.shape, jnp.float32, lower, upper).astype(v.dtype)
        else:
            a = jnp.asarray((lower + upper) / 2.0, v.dtype)
        return jnp.where(v >= 0, v, a * v)
    return op_call("rrelu", impl, x)


def glu(x, axis=-1, name=None):
    return op_call("glu", lambda v: jax.nn.glu(v, axis=axis), x)


def softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def impl(v, axis=axis, cast_dtype=d):
        vv = v.astype(cast_dtype) if cast_dtype is not None else v
        return jax.nn.softmax(vv, axis=axis)
    return op_call("softmax", impl, x, axis=axis, cast_dtype=d)


def softmax_(x, axis=-1, dtype=None, name=None):
    return x._set_value(softmax(x.detach(), axis, dtype)._value)


def log_softmax(x, axis=-1, dtype=None, name=None):
    from ...core.dtype import convert_dtype
    d = convert_dtype(dtype)
    def impl(v):
        vv = v.astype(d) if d is not None else v
        return jax.nn.log_softmax(vv, axis=axis)
    return op_call("log_softmax", impl, x)


def gumbel_softmax(x, temperature=1.0, hard=False, axis=-1, name=None):
    from ...core.random import split_key
    def impl(v):
        g = -jnp.log(-jnp.log(jax.random.uniform(split_key(), v.shape, jnp.float32,
                                                 1e-20, 1.0))).astype(v.dtype)
        y = jax.nn.softmax((v + g) / temperature, axis=axis)
        if hard:
            idx = jnp.argmax(y, axis=axis, keepdims=True)
            onehot = (jnp.arange(y.shape[axis]).reshape(
                [-1 if i == axis % y.ndim else 1 for i in range(y.ndim)]) == idx).astype(y.dtype)
            y = onehot + y - jax.lax.stop_gradient(y)
        return y
    return op_call("gumbel_softmax", impl, x)


def maxout(x, groups, axis=1, name=None):
    def impl(v):
        ax = axis % v.ndim
        c = v.shape[ax]
        new_shape = v.shape[:ax] + (c // groups, groups) + v.shape[ax + 1:]
        return jnp.max(v.reshape(new_shape), axis=ax + 1)
    return op_call("maxout", impl, x)


def tanh(x, name=None):
    return op_call("tanh", jnp.tanh, x)


def tanh_(x, name=None):
    return x._set_value(jnp.tanh(x._value))


def log_sigmoid(x, name=None):
    return op_call("log_sigmoid", jax.nn.log_sigmoid, x)


def thresholded_relu(x, threshold=1.0, value=0.0, name=None):
    return op_call("thresholded_relu",
                   lambda v: jnp.where(v > threshold, v, value).astype(v.dtype), x)


def swiglu(x, y=None, name=None):
    """SwiGLU fused activation (reference incubate fused_swiglu): silu(x) * y;
    when y is None, x is split in half along the last axis."""
    if y is None:
        def impl(v):
            a, b = jnp.split(v, 2, axis=-1)
            return jax.nn.silu(a) * b
        return op_call("swiglu", impl, x)
    return op_call("swiglu", lambda a, b: jax.nn.silu(a) * b, x, y)
