"""paddle.nn.functional parity namespace."""
from __future__ import annotations

from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .conv import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .pooling import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .attention import *  # noqa: F401,F403
from .vision import *  # noqa: F401,F403

from . import flash_attention  # noqa: F401
