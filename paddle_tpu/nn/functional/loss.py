"""Loss functionals (reference: python/paddle/nn/functional/loss.py)."""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import op_call
from ...core.tensor import Tensor

__all__ = ["cross_entropy", "softmax_with_cross_entropy", "mse_loss", "l1_loss",
           "nll_loss", "binary_cross_entropy", "binary_cross_entropy_with_logits",
           "smooth_l1_loss", "kl_div", "margin_ranking_loss", "cosine_embedding_loss",
           "hinge_embedding_loss", "triplet_margin_loss", "log_loss", "square_error_cost",
           "sigmoid_focal_loss", "dice_loss", "ctc_loss", "poisson_nll_loss",
           "gaussian_nll_loss", "multi_label_soft_margin_loss", "soft_margin_loss",
           "margin_cross_entropy"]


def _reduce(v, reduction):
    if reduction == "mean":
        return jnp.mean(v)
    if reduction == "sum":
        return jnp.sum(v)
    return v


def cross_entropy(input, label, weight=None, ignore_index=-100, reduction="mean",
                  soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                  name=None):
    """reference loss.py cross_entropy: hard or soft labels, optional class
    weights, ignore_index, label smoothing."""
    def impl(logits, lab, *rest):
        ax = axis % logits.ndim
        if use_softmax:
            logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=ax)
        else:
            logp = jnp.log(jnp.clip(logits.astype(jnp.float32), 1e-30, None))
        n_classes = logits.shape[ax]
        if soft_label:
            soft = lab.astype(jnp.float32)
            if label_smoothing > 0.0:
                soft = (1 - label_smoothing) * soft + label_smoothing / n_classes
            loss = -jnp.sum(soft * logp, axis=ax)
            if rest:
                w = rest[0]
                wt = jnp.sum(soft * w.reshape([-1 if i == ax else 1 for i in range(logits.ndim)]), axis=ax)
                loss = loss * wt
            return _reduce(loss, reduction)
        ids = lab.astype(jnp.int32)
        if ids.ndim == logits.ndim:
            ids = jnp.squeeze(ids, axis=ax)
        valid = ids != ignore_index
        safe_ids = jnp.where(valid, ids, 0)
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe_ids, ax), axis=ax)
        picked = jnp.squeeze(picked, axis=ax)
        if label_smoothing > 0.0:
            smooth_term = jnp.mean(logp, axis=ax)
            nll = -(1 - label_smoothing) * picked - label_smoothing * smooth_term
        else:
            nll = -picked
        if rest:
            w = rest[0]
            wv = w[safe_ids]
            nll = nll * wv
            nll = jnp.where(valid, nll, 0.0)
            if reduction == "mean":
                denom = jnp.sum(jnp.where(valid, wv, 0.0))
                return jnp.sum(nll) / jnp.maximum(denom, 1e-12)
            return _reduce(nll, reduction)
        nll = jnp.where(valid, nll, 0.0)
        if reduction == "mean":
            denom = jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
            return jnp.sum(nll) / denom
        return _reduce(nll, reduction)
    args = [input, label] if weight is None else [input, label, weight]
    return op_call("cross_entropy", impl, *args)


def softmax_with_cross_entropy(logits, label, soft_label=False, ignore_index=-100,
                               numeric_stable_mode=True, return_softmax=False, axis=-1):
    loss = cross_entropy(logits, label, soft_label=soft_label,
                         ignore_index=ignore_index, reduction="none", axis=axis)
    from .activation import softmax as softmax_fn
    if return_softmax:
        return loss, softmax_fn(logits, axis=axis)
    return loss


def mse_loss(input, label, reduction="mean", name=None):
    return op_call("mse_loss", lambda a, b: _reduce((a - b) ** 2, reduction), input, label)


def l1_loss(input, label, reduction="mean", name=None):
    return op_call("l1_loss", lambda a, b: _reduce(jnp.abs(a - b), reduction), input, label)


def nll_loss(input, label, weight=None, ignore_index=-100, reduction="mean", name=None):
    def impl(logp, lab, *rest):
        ids = lab.astype(jnp.int32)
        valid = ids != ignore_index
        safe = jnp.where(valid, ids, 0)
        # class dim is axis 1 for ndim>1
        picked = jnp.take_along_axis(logp, jnp.expand_dims(safe, 1), axis=1)
        picked = jnp.squeeze(picked, axis=1)
        loss = -picked
        if rest:
            wv = rest[0][safe]
            loss = loss * wv
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(jnp.where(valid, wv, 0.0)), 1e-12)
        else:
            loss = jnp.where(valid, loss, 0.0)
            if reduction == "mean":
                return jnp.sum(loss) / jnp.maximum(jnp.sum(valid.astype(jnp.float32)), 1.0)
        return _reduce(loss, reduction)
    args = [input, label] if weight is None else [input, label, weight]
    return op_call("nll_loss", impl, *args)


def binary_cross_entropy(input, label, weight=None, reduction="mean", name=None):
    def impl(p, y, *rest):
        p = jnp.clip(p, 1e-12, 1.0 - 1e-12)
        loss = -(y * jnp.log(p) + (1 - y) * jnp.log(1 - p))
        if rest:
            loss = loss * rest[0]
        return _reduce(loss, reduction)
    args = [input, label] if weight is None else [input, label, weight]
    return op_call("bce", impl, *args)


def binary_cross_entropy_with_logits(logit, label, weight=None, reduction="mean",
                                     pos_weight=None, name=None):
    def impl(z, y, *rest):
        i = 0
        w = pw = None
        if weight is not None:
            w = rest[i]; i += 1
        if pos_weight is not None:
            pw = rest[i]; i += 1
        max_val = jnp.clip(-z, 0, None)
        if pw is not None:
            log_w = (pw - 1) * y + 1
            loss = (1 - y) * z + log_w * (jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val)
        else:
            loss = (1 - y) * z + jnp.log1p(jnp.exp(-jnp.abs(z))) + max_val
        if w is not None:
            loss = loss * w
        return _reduce(loss, reduction)
    args = [logit, label]
    if weight is not None:
        args.append(weight)
    if pos_weight is not None:
        args.append(pos_weight)
    return op_call("bce_logits", impl, *args)


def smooth_l1_loss(input, label, reduction="mean", delta=1.0, name=None):
    def impl(a, b):
        d = jnp.abs(a - b)
        loss = jnp.where(d < delta, 0.5 * d * d, delta * (d - 0.5 * delta))
        return _reduce(loss, reduction)
    return op_call("smooth_l1", impl, input, label)


def kl_div(input, label, reduction="mean", log_target=False, name=None):
    def impl(logp, t):
        if log_target:
            loss = jnp.exp(t) * (t - logp)
        else:
            loss = t * (jnp.log(jnp.clip(t, 1e-12, None)) - logp)
        if reduction == "batchmean":
            return jnp.sum(loss) / logp.shape[0]
        return _reduce(loss, reduction)
    return op_call("kl_div", impl, input, label)


def margin_ranking_loss(input, other, label, margin=0.0, reduction="mean", name=None):
    def impl(a, b, y):
        return _reduce(jnp.clip(-y * (a - b) + margin, 0, None), reduction)
    return op_call("margin_ranking", impl, input, other, label)


def cosine_embedding_loss(input1, input2, label, margin=0.0, reduction="mean", name=None):
    def impl(a, b, y):
        cos = jnp.sum(a * b, -1) / (jnp.linalg.norm(a, axis=-1) *
                                    jnp.linalg.norm(b, axis=-1) + 1e-12)
        loss = jnp.where(y == 1, 1 - cos, jnp.clip(cos - margin, 0, None))
        return _reduce(loss, reduction)
    return op_call("cosine_embedding", impl, input1, input2, label)


def hinge_embedding_loss(input, label, margin=1.0, reduction="mean", name=None):
    def impl(x, y):
        loss = jnp.where(y == 1, x, jnp.clip(margin - x, 0, None))
        return _reduce(loss, reduction)
    return op_call("hinge_embedding", impl, input, label)


def triplet_margin_loss(input, positive, negative, margin=1.0, p=2.0, epsilon=1e-6,
                        swap=False, reduction="mean", name=None):
    def impl(a, pos, neg):
        dp = jnp.sum(jnp.abs(a - pos) ** p + epsilon, axis=-1) ** (1 / p)
        dn = jnp.sum(jnp.abs(a - neg) ** p + epsilon, axis=-1) ** (1 / p)
        if swap:
            dn2 = jnp.sum(jnp.abs(pos - neg) ** p + epsilon, axis=-1) ** (1 / p)
            dn = jnp.minimum(dn, dn2)
        return _reduce(jnp.clip(dp - dn + margin, 0, None), reduction)
    return op_call("triplet_margin", impl, input, positive, negative)


def log_loss(input, label, epsilon=1e-4, name=None):
    def impl(p, y):
        return -y * jnp.log(p + epsilon) - (1 - y) * jnp.log(1 - p + epsilon)
    return op_call("log_loss", impl, input, label)


def square_error_cost(input, label):
    return op_call("square_error_cost", lambda a, b: (a - b) ** 2, input, label)


def sigmoid_focal_loss(logit, label, normalizer=None, alpha=0.25, gamma=2.0,
                       reduction="sum", name=None):
    def impl(z, y, *rest):
        p = jax.nn.sigmoid(z)
        ce = jnp.log1p(jnp.exp(-jnp.abs(z))) + jnp.clip(-z, 0, None)
        ce = (1 - y) * z + ce
        p_t = p * y + (1 - p) * (1 - y)
        a_t = alpha * y + (1 - alpha) * (1 - y)
        loss = a_t * ((1 - p_t) ** gamma) * ce
        if rest:
            loss = loss / rest[0]
        return _reduce(loss, reduction)
    args = [logit, label] if normalizer is None else [logit, label, normalizer]
    return op_call("sigmoid_focal", impl, *args)


def dice_loss(input, label, epsilon=1e-5, name=None):
    def impl(p, y):
        yf = jax.nn.one_hot(y.squeeze(-1).astype(jnp.int32), p.shape[-1], dtype=p.dtype)
        red = tuple(range(1, p.ndim))
        inter = jnp.sum(p * yf, axis=red)
        union = jnp.sum(p, axis=red) + jnp.sum(yf, axis=red)
        return jnp.mean(1 - (2 * inter + epsilon) / (union + epsilon))
    return op_call("dice", impl, input, label)


def ctc_loss(log_probs, labels, input_lengths, label_lengths, blank=0,
             reduction="mean", norm_by_times=False):
    """CTC via optax (jax-native forward-backward)."""
    import optax
    def impl(lp, lab, il, ll):
        # paddle: lp is [T, B, C] logits; optax wants [B, T, C] log-probs
        logits = jnp.transpose(lp, (1, 0, 2))
        B, T, C = logits.shape
        labmax = lab.shape[1]
        logitpad = jnp.arange(T)[None, :] >= il[:, None]
        labpad = jnp.arange(labmax)[None, :] >= ll[:, None]
        per_seq = optax.ctc_loss(logits, logitpad.astype(jnp.float32),
                                 lab.astype(jnp.int32), labpad.astype(jnp.float32),
                                 blank_id=blank)
        if reduction == "mean":
            return jnp.mean(per_seq / jnp.maximum(ll.astype(jnp.float32), 1.0))
        return _reduce(per_seq, reduction)
    return op_call("ctc_loss", impl, log_probs, labels, input_lengths, label_lengths)


def poisson_nll_loss(input, label, log_input=True, full=False, epsilon=1e-8,
                     reduction="mean", name=None):
    def impl(x, y):
        if log_input:
            loss = jnp.exp(x) - y * x
        else:
            loss = x - y * jnp.log(x + epsilon)
        if full:
            stirling = y * jnp.log(y + epsilon) - y + 0.5 * jnp.log(2 * jnp.pi * (y + epsilon))
            loss = loss + jnp.where(y > 1, stirling, 0.0)
        return _reduce(loss, reduction)
    return op_call("poisson_nll", impl, input, label)


def gaussian_nll_loss(input, label, variance, full=False, epsilon=1e-6,
                      reduction="mean", name=None):
    def impl(mu, y, var):
        var = jnp.clip(var, epsilon, None)
        loss = 0.5 * (jnp.log(var) + (y - mu) ** 2 / var)
        if full:
            loss = loss + 0.5 * jnp.log(2 * jnp.asarray(jnp.pi))
        return _reduce(loss, reduction)
    return op_call("gaussian_nll", impl, input, label, variance)


def multi_label_soft_margin_loss(input, label, weight=None, reduction="mean", name=None):
    def impl(x, y, *rest):
        loss = -(y * jax.nn.log_sigmoid(x) + (1 - y) * jax.nn.log_sigmoid(-x))
        if rest:
            loss = loss * rest[0]
        return _reduce(jnp.mean(loss, axis=-1), reduction)
    args = [input, label] if weight is None else [input, label, weight]
    return op_call("ml_soft_margin", impl, *args)


def soft_margin_loss(input, label, reduction="mean", name=None):
    def impl(x, y):
        return _reduce(jnp.log1p(jnp.exp(-y * x)), reduction)
    return op_call("soft_margin", impl, input, label)


def margin_cross_entropy(logits, label, margin1=1.0, margin2=0.5, margin3=0.0,
                         scale=64.0, group=None, return_softmax=False,
                         reduction="mean"):
    """ArcFace-style margin softmax (reference loss.py margin_cross_entropy),
    single-group variant."""
    def impl(z, lab):
        ids = lab.astype(jnp.int32).reshape(-1)
        onehot = jax.nn.one_hot(ids, z.shape[-1], dtype=z.dtype)
        theta = jnp.arccos(jnp.clip(z, -1.0, 1.0))
        target = jnp.cos(margin1 * theta + margin2) - margin3
        zz = jnp.where(onehot > 0, target, z) * scale
        logp = jax.nn.log_softmax(zz, axis=-1)
        loss = -jnp.sum(onehot * logp, axis=-1)
        return _reduce(loss, reduction)
    loss = op_call("margin_ce", impl, logits, label)
    if return_softmax:
        from .activation import softmax as softmax_fn
        return loss, softmax_fn(logits, axis=-1)
    return loss
