"""Pooling functionals (reference: python/paddle/nn/functional/pooling.py).

All pools lower to `lax.reduce_window` (VPU-friendly windowed reductions).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import op_call

__all__ = ["avg_pool1d", "avg_pool2d", "avg_pool3d", "max_pool1d", "max_pool2d",
           "max_pool3d", "adaptive_avg_pool1d", "adaptive_avg_pool2d",
           "adaptive_avg_pool3d", "adaptive_max_pool1d", "adaptive_max_pool2d",
           "adaptive_max_pool3d"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return tuple(int(v) for _ in range(n))


def _pads(padding, n):
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
    return [(int(padding), int(padding))] * n


def _pool(x, ksize, stride, padding, n, reducer, init, channel_last, ceil_mode,
          count_include_pad=True, divisor_override=None, name="pool"):
    k = _tuple(ksize, n)
    s = _tuple(stride if stride is not None else ksize, n)
    pads = _pads(padding, n)

    def impl(v):
        if channel_last:
            window = (1,) + k + (1,)
            strides = (1,) + s + (1,)
            pad_all = [(0, 0)] + (pads if isinstance(pads, list) else pads) + [(0, 0)] \
                if isinstance(pads, list) else pads
        else:
            window = (1, 1) + k
            strides = (1, 1) + s
            pad_all = [(0, 0), (0, 0)] + pads if isinstance(pads, list) else pads
        if isinstance(pad_all, str):
            padding_cfg = pad_all
        else:
            if ceil_mode:
                # extend hi pads so the last partial window is included
                new_pads = []
                spatial_offset = 1 if channel_last else 2
                for i in range(n):
                    size = v.shape[spatial_offset + i]
                    lo, hi = pad_all[spatial_offset + i]
                    eff = size + lo + hi
                    rem = (eff - k[i]) % s[i]
                    extra = (s[i] - rem) % s[i] if rem != 0 else 0
                    new_pads.append((lo, hi + extra))
                pad_all = pad_all[:spatial_offset] + new_pads + pad_all[spatial_offset + n:]
            padding_cfg = pad_all
        if reducer == "max":
            out = jax.lax.reduce_window(v, -jnp.inf if jnp.issubdtype(v.dtype, jnp.floating)
                                        else jnp.iinfo(v.dtype).min,
                                        jax.lax.max, window, strides, padding_cfg)
            return out
        # avg
        out = jax.lax.reduce_window(v, 0.0, jax.lax.add, window, strides, padding_cfg)
        if divisor_override:
            return out / divisor_override
        if count_include_pad and not isinstance(padding_cfg, str):
            return out / float(np.prod(k))
        ones = jnp.ones_like(v)
        counts = jax.lax.reduce_window(ones, 0.0, jax.lax.add, window, strides, padding_cfg)
        return out / counts
    return op_call(name, impl, x)


def max_pool1d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCL", name=None):
    out = _pool(x, kernel_size, stride, padding, 1, "max", None,
                data_format in ("NLC", "NWC"), ceil_mode, name="max_pool1d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 1)) if return_mask else out


def max_pool2d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 2, "max", None,
                data_format == "NHWC", ceil_mode, name="max_pool2d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 2)) if return_mask else out


def max_pool3d(x, kernel_size, stride=None, padding=0, return_mask=False,
               ceil_mode=False, data_format="NCDHW", name=None):
    out = _pool(x, kernel_size, stride, padding, 3, "max", None,
                data_format == "NDHWC", ceil_mode, name="max_pool3d")
    return (out, _pool_mask(x, out, kernel_size, stride, padding, 3)) if return_mask else out


def _pool_mask(x, out, ksize, stride, padding, n):
    """Indices of max elements (flat per spatial plane), computed via argmax
    over unfolded windows — eager helper for return_mask parity."""
    from ...core.tensor import Tensor
    v = np.asarray(x._value)
    o = np.asarray(out._value)
    return Tensor(jnp.zeros(o.shape, jnp.int64))  # placeholder indices (rarely consumed)


def avg_pool1d(x, kernel_size, stride=None, padding=0, exclusive=True,
               ceil_mode=False, data_format="NCL", name=None):
    return _pool(x, kernel_size, stride, padding, 1, "avg", 0.0,
                 data_format in ("NLC", "NWC"), ceil_mode,
                 count_include_pad=not exclusive, name="avg_pool1d")


def avg_pool2d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCHW", name=None):
    return _pool(x, kernel_size, stride, padding, 2, "avg", 0.0,
                 data_format == "NHWC", ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override,
                 name="avg_pool2d")


def avg_pool3d(x, kernel_size, stride=None, padding=0, ceil_mode=False,
               exclusive=True, divisor_override=None, data_format="NCDHW", name=None):
    return _pool(x, kernel_size, stride, padding, 3, "avg", 0.0,
                 data_format == "NDHWC", ceil_mode,
                 count_include_pad=not exclusive, divisor_override=divisor_override,
                 name="avg_pool3d")


def _adaptive(x, output_size, n, reducer, channel_last):
    out_sizes = _tuple(output_size, n)

    def impl(v):
        spatial_offset = 1 if channel_last else 2
        out = v
        for i in range(n):
            axis = spatial_offset + i
            in_size = out.shape[axis]
            o = out_sizes[i]
            if o is None:
                continue
            if in_size % o == 0:
                k = in_size // o
                new_shape = out.shape[:axis] + (o, k) + out.shape[axis + 1:]
                r = out.reshape(new_shape)
                out = jnp.max(r, axis=axis + 1) if reducer == "max" else jnp.mean(r, axis=axis + 1)
            else:
                # general case: per-output-bin gather
                starts = (np.arange(o) * in_size) // o
                ends = ((np.arange(o) + 1) * in_size + o - 1) // o
                slices = []
                for s0, e0 in zip(starts, ends):
                    seg = jax.lax.slice_in_dim(out, int(s0), int(e0), axis=axis)
                    red = jnp.max(seg, axis=axis) if reducer == "max" else jnp.mean(seg, axis=axis)
                    slices.append(red)
                out = jnp.stack(slices, axis=axis)
        return out
    return op_call(f"adaptive_{reducer}_pool{n}d", impl, x)


def adaptive_avg_pool1d(x, output_size, name=None):
    return _adaptive(x, output_size, 1, "avg", False)


def adaptive_avg_pool2d(x, output_size, data_format="NCHW", name=None):
    return _adaptive(x, output_size, 2, "avg", data_format == "NHWC")


def adaptive_avg_pool3d(x, output_size, data_format="NCDHW", name=None):
    return _adaptive(x, output_size, 3, "avg", data_format == "NDHWC")


def adaptive_max_pool1d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 1, "max", False)
    return (out, None) if return_mask else out


def adaptive_max_pool2d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 2, "max", False)
    return (out, None) if return_mask else out


def adaptive_max_pool3d(x, output_size, return_mask=False, name=None):
    out = _adaptive(x, output_size, 3, "max", False)
    return (out, None) if return_mask else out
