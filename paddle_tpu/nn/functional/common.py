"""Common functionals: linear, dropout, embedding, one_hot, interpolate, ...
(reference: python/paddle/nn/functional/common.py, input.py)
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import op_call
from ...core.tensor import Tensor
from ...core.random import split_key
from ...core import dtype as dtype_mod

__all__ = ["linear", "dropout", "dropout2d", "dropout3d", "alpha_dropout",
           "embedding", "one_hot", "label_smooth", "pad", "interpolate",
           "upsample", "bilinear", "cosine_similarity", "pixel_shuffle",
           "pixel_unshuffle", "channel_shuffle", "unfold", "fold", "normalize",
           "zeropad2d", "class_center_sample"]

from ...tensor.manipulation import pad  # padding shared with tensor namespace


def linear(x, weight, bias=None, name=None):
    """y = x @ W (+ b); paddle weight layout [in_features, out_features]
    (reference common.py linear). Lowers to a single MXU matmul."""
    if bias is None:
        return op_call("linear", lambda v, w: v @ w, x, weight)
    return op_call("linear", lambda v, w, b: v @ w + b, x, weight, bias)


def dropout(x, p=0.5, axis=None, training=True, mode="upscale_in_train", name=None):
    if not training or p == 0.0:
        return x if isinstance(x, Tensor) else Tensor(jnp.asarray(x))
    key = split_key()
    def impl(v):
        if axis is None:
            mask_shape = v.shape
        else:
            axes = axis if isinstance(axis, (list, tuple)) else [axis]
            mask_shape = tuple(v.shape[i] if i in [a % v.ndim for a in axes] else 1
                               for i in range(v.ndim))
        keep = jax.random.bernoulli(key, 1.0 - p, mask_shape)
        if mode == "upscale_in_train":
            return jnp.where(keep, v / (1.0 - p), 0.0).astype(v.dtype)
        return jnp.where(keep, v, 0.0).astype(v.dtype)
    return op_call("dropout", impl, x)


def dropout2d(x, p=0.5, training=True, data_format="NCHW", name=None):
    ch_axis = 1 if data_format == "NCHW" else 3
    return dropout(x, p, axis=[0, ch_axis], training=training)


def dropout3d(x, p=0.5, training=True, data_format="NCDHW", name=None):
    ch_axis = 1 if data_format == "NCDHW" else 4
    return dropout(x, p, axis=[0, ch_axis], training=training)


def alpha_dropout(x, p=0.5, training=True, name=None):
    if not training or p == 0.0:
        return x
    key = split_key()
    def impl(v):
        alpha = 1.6732632423543772
        scale = 1.0507009873554805
        alpha_p = -alpha * scale
        keep = jax.random.bernoulli(key, 1.0 - p, v.shape)
        a = (1.0 / np.sqrt((1.0 - p) * (1.0 + p * alpha_p ** 2))).astype(np.float32)
        b = -a * alpha_p * p
        return (a * jnp.where(keep, v, alpha_p) + b).astype(v.dtype)
    return op_call("alpha_dropout", impl, x)


def _embedding_impl(padding_idx):
    @jax.custom_vjp
    def emb(ids, w):
        return w[ids]

    def fwd(ids, w):
        # residual holds w itself (no copy — it's the live parameter buffer),
        # giving bwd its shape/dtype without non-array residuals
        return w[ids], (ids, w)

    def bwd(res, g):
        ids, w = res
        gw = jnp.zeros(w.shape, g.dtype).at[ids].add(g)
        if padding_idx is not None:
            gw = gw.at[padding_idx].set(0.0)
        return None, gw.astype(w.dtype)

    emb.defvjp(fwd, bwd)
    return emb


def embedding(x, weight, padding_idx=None, max_norm=None, norm_type=2.0,
              sparse=False, scale_grad_by_freq=False, name=None):
    """Lookup with padding_idx grad masking (reference functional/input.py
    embedding; grad-scatter kernel embedding_grad_kernel.cu analog is the
    XLA scatter-add in the custom vjp)."""
    emb = _embedding_impl(padding_idx)
    def impl(w, ids_v):
        ids_i = ids_v.astype(jnp.int32)
        ww = w
        if max_norm is not None:
            norms = jnp.linalg.norm(ww, ord=norm_type, axis=-1, keepdims=True)
            ww = ww * jnp.minimum(1.0, max_norm / (norms + 1e-12))
        return emb(ids_i, ww)
    # note: ids passed as second positional but non-differentiable (int dtype)
    return op_call("embedding", impl, weight, x)


def one_hot(x, num_classes, name=None):
    n = int(num_classes._value) if isinstance(num_classes, Tensor) else int(num_classes)
    return op_call("one_hot",
                   lambda v: jax.nn.one_hot(v.astype(jnp.int32), n, dtype=jnp.float32),
                   x, nondiff=True)


def label_smooth(label, prior_dist=None, epsilon=0.1, name=None):
    def impl(v, *rest):
        n = v.shape[-1]
        if rest:
            return (1 - epsilon) * v + epsilon * rest[0]
        return (1 - epsilon) * v + epsilon / n
    if prior_dist is not None:
        return op_call("label_smooth", impl, label, prior_dist)
    return op_call("label_smooth", impl, label)


def _resize_1d(v, out_size, axis, mode, align_corners, align_mode=0):
    """Differentiable 1-D resize along `axis` via gather-based interpolation."""
    in_size = v.shape[axis]
    if mode == "nearest":
        if align_corners:
            idx = jnp.round(jnp.linspace(0, in_size - 1, out_size)).astype(jnp.int32)
        else:
            scale = in_size / out_size
            idx = jnp.floor(jnp.arange(out_size) * scale).astype(jnp.int32)
        return jnp.take(v, jnp.clip(idx, 0, in_size - 1), axis=axis)
    # linear family
    if align_corners:
        pos = jnp.linspace(0.0, in_size - 1.0, out_size)
    elif align_mode == 1:
        pos = jnp.arange(out_size) * (in_size / out_size)
    else:
        scale = in_size / out_size
        pos = (jnp.arange(out_size) + 0.5) * scale - 0.5
    pos = jnp.clip(pos, 0.0, in_size - 1.0)
    lo = jnp.floor(pos).astype(jnp.int32)
    hi = jnp.clip(lo + 1, 0, in_size - 1)
    w = (pos - lo).astype(v.dtype)
    shape = [1] * v.ndim
    shape[axis] = out_size
    w = w.reshape(shape)
    lo_v = jnp.take(v, lo, axis=axis)
    hi_v = jnp.take(v, hi, axis=axis)
    return lo_v * (1 - w) + hi_v * w


def interpolate(x, size=None, scale_factor=None, mode="nearest",
                align_corners=False, align_mode=0, data_format=None, name=None):
    """reference functional/common.py interpolate: nearest/bilinear/trilinear/
    bicubic/linear/area over NCHW (default) or channel-last layouts."""
    mode = mode.lower()
    def impl(v):
        nd = v.ndim
        df = data_format or {3: "NCW", 4: "NCHW", 5: "NCDHW"}[nd]
        channel_last = df in ("NWC", "NHWC", "NDHWC")
        spatial_axes = list(range(1, nd - 1)) if channel_last else list(range(2, nd))
        in_sizes = [v.shape[a] for a in spatial_axes]
        if size is not None:
            sz = size
            if isinstance(sz, Tensor):
                sz = sz.numpy().tolist()
            sz = [int(s._value) if isinstance(s, Tensor) else int(s) for s in
                  (sz if isinstance(sz, (list, tuple)) else [sz] * len(spatial_axes))]
        else:
            sf = scale_factor if isinstance(scale_factor, (list, tuple)) \
                else [scale_factor] * len(spatial_axes)
            sz = [int(np.floor(i * float(s))) for i, s in zip(in_sizes, sf)]
        if mode == "area":
            # adaptive average pooling semantics
            out = v
            for a, s in zip(spatial_axes, sz):
                n = out.shape[a]
                if n % s == 0:
                    k = n // s
                    new_shape = out.shape[:a] + (s, k) + out.shape[a + 1:]
                    out = jnp.mean(out.reshape(new_shape), axis=a + 1)
                else:
                    out = _resize_1d(out, s, a, "linear", False)
            return out
        m = {"nearest": "nearest", "bilinear": "linear", "trilinear": "linear",
             "linear": "linear", "bicubic": "cubic"}[mode]
        if m == "cubic":
            # route through jax.image for cubic
            full = list(v.shape)
            for a, s in zip(spatial_axes, sz):
                full[a] = s
            return jax.image.resize(v, tuple(full), method="cubic").astype(v.dtype)
        out = v
        for a, s in zip(spatial_axes, sz):
            out = _resize_1d(out, s, a, m, align_corners, align_mode)
        return out
    return op_call("interpolate", impl, x)


def upsample(x, size=None, scale_factor=None, mode="nearest", align_corners=False,
             align_mode=0, data_format=None, name=None):
    return interpolate(x, size, scale_factor, mode, align_corners, align_mode, data_format)


def bilinear(x1, x2, weight, bias=None, name=None):
    def impl(a, b, w, *rest):
        out = jnp.einsum("bi,oij,bj->bo", a, w, b)
        if rest:
            out = out + rest[0]
        return out
    if bias is not None:
        return op_call("bilinear", impl, x1, x2, weight, bias)
    return op_call("bilinear", impl, x1, x2, weight)


def cosine_similarity(x1, x2, axis=1, eps=1e-8, name=None):
    def impl(a, b):
        num = jnp.sum(a * b, axis=axis)
        d1 = jnp.sqrt(jnp.sum(a * a, axis=axis))
        d2 = jnp.sqrt(jnp.sum(b * b, axis=axis))
        return num / jnp.maximum(d1 * d2, eps)
    return op_call("cosine_similarity", impl, x1, x2)


def pixel_shuffle(x, upscale_factor, data_format="NCHW", name=None):
    r = upscale_factor
    def impl(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v2 = v.reshape(b, c // (r * r), r, r, h, w)
            v2 = jnp.transpose(v2, (0, 1, 4, 2, 5, 3))
            return v2.reshape(b, c // (r * r), h * r, w * r)
        b, h, w, c = v.shape
        v2 = v.reshape(b, h, w, r, r, c // (r * r))
        v2 = jnp.transpose(v2, (0, 1, 3, 2, 4, 5))
        return v2.reshape(b, h * r, w * r, c // (r * r))
    return op_call("pixel_shuffle", impl, x)


def pixel_unshuffle(x, downscale_factor, data_format="NCHW", name=None):
    r = downscale_factor
    def impl(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v2 = v.reshape(b, c, h // r, r, w // r, r)
            v2 = jnp.transpose(v2, (0, 1, 3, 5, 2, 4))
            return v2.reshape(b, c * r * r, h // r, w // r)
        b, h, w, c = v.shape
        v2 = v.reshape(b, h // r, r, w // r, r, c)
        v2 = jnp.transpose(v2, (0, 2, 4, 1, 3, 5))
        return v2.reshape(b, h // r, w // r, c * r * r)
    return op_call("pixel_unshuffle", impl, x)


def channel_shuffle(x, groups, data_format="NCHW", name=None):
    def impl(v):
        if data_format == "NCHW":
            b, c, h, w = v.shape
            v2 = v.reshape(b, groups, c // groups, h, w)
            return jnp.swapaxes(v2, 1, 2).reshape(b, c, h, w)
        b, h, w, c = v.shape
        v2 = v.reshape(b, h, w, groups, c // groups)
        return jnp.swapaxes(v2, 3, 4).reshape(b, h, w, c)
    return op_call("channel_shuffle", impl, x)


def unfold(x, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    """im2col (reference unfold): NCHW -> [N, C*kh*kw, L]."""
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings
    def impl(v):
        b, c, h, w = v.shape
        vp = jnp.pad(v, ((0, 0), (0, 0), (pt, pb), (pl, pr)))
        hp, wp = vp.shape[2], vp.shape[3]
        oh = (hp - (dh * (kh - 1) + 1)) // sh + 1
        ow = (wp - (dw * (kw - 1) + 1)) // sw + 1
        patches = jax.lax.conv_general_dilated_patches(
            vp, (kh, kw), (sh, sw), "VALID", rhs_dilation=(dh, dw),
            dimension_numbers=("NCHW", "OIHW", "NCHW"))
        # patches: [N, C*kh*kw, oh, ow]
        return patches.reshape(b, c * kh * kw, oh * ow)
    return op_call("unfold", impl, x)


def fold(x, output_sizes, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
    oh, ow = (output_sizes, output_sizes) if isinstance(output_sizes, int) else output_sizes
    kh, kw = (kernel_sizes, kernel_sizes) if isinstance(kernel_sizes, int) else kernel_sizes
    sh, sw = (strides, strides) if isinstance(strides, int) else strides
    dh, dw = (dilations, dilations) if isinstance(dilations, int) else dilations
    if isinstance(paddings, int):
        pt = pb = pl = pr = paddings
    elif len(paddings) == 2:
        pt = pb = paddings[0]
        pl = pr = paddings[1]
    else:
        pt, pl, pb, pr = paddings
    def impl(v):
        b, ckk, L = v.shape
        c = ckk // (kh * kw)
        hp, wp = oh + pt + pb, ow + pl + pr
        nh = (hp - (dh * (kh - 1) + 1)) // sh + 1
        nw = (wp - (dw * (kw - 1) + 1)) // sw + 1
        out = jnp.zeros((b, c, hp, wp), v.dtype)
        vv = v.reshape(b, c, kh, kw, nh, nw)
        for i in range(kh):
            for j in range(kw):
                hs = i * dh
                ws = j * dw
                out = out.at[:, :, hs:hs + nh * sh:sh, ws:ws + nw * sw:sw].add(vv[:, :, i, j])
        return out[:, :, pt:pt + oh, pl:pl + ow]
    return op_call("fold", impl, x)


def normalize(x, p=2, axis=1, epsilon=1e-12, name=None):
    def impl(v):
        norm = jnp.sum(jnp.abs(v) ** p, axis=axis, keepdims=True) ** (1.0 / p)
        return v / jnp.maximum(norm, epsilon)
    return op_call("normalize", impl, x)


def zeropad2d(x, padding, data_format="NCHW", name=None):
    return pad(x, padding, mode="constant", value=0.0, data_format=data_format)


def class_center_sample(label, num_classes, num_samples, group=None):
    """Sampled-class subset for large-softmax training (reference
    functional/common.py class_center_sample); single-device variant."""
    lv = np.asarray(label._value)
    pos = np.unique(lv)
    if len(pos) >= num_samples:
        sampled = pos
    else:
        rest = np.setdiff1d(np.arange(num_classes), pos)
        extra = np.random.default_rng(0).choice(rest, num_samples - len(pos), replace=False)
        sampled = np.sort(np.concatenate([pos, extra]))
    remap = -np.ones(num_classes, dtype=np.int64)
    remap[sampled] = np.arange(len(sampled))
    return Tensor(jnp.asarray(remap[lv])), Tensor(jnp.asarray(sampled))
