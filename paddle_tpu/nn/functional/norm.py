"""Normalization functionals (reference: python/paddle/nn/functional/norm.py).

batch_norm returns updated running stats through the buffer tensors passed in
(eager: in-place update; under functional_call tracing the updates are
harvested into new_state).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op_call
from ...core.tensor import Tensor

__all__ = ["batch_norm", "layer_norm", "instance_norm", "group_norm",
           "local_response_norm", "rms_norm"]


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")
    if use_global_stats is None:
        use_global_stats = not training

    def stats_axes(v):
        ch = v.ndim - 1 if channel_last else (1 if v.ndim > 1 else 0)
        return tuple(i for i in range(v.ndim) if i != ch), ch

    if use_global_stats:
        def impl(v, m, var, *rest):
            axes, ch = stats_axes(v)
            shape = [1] * v.ndim
            shape[ch] = v.shape[ch]
            out = (v - m.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
            if rest:
                out = out * rest[0].reshape(shape)
                if len(rest) > 1:
                    out = out + rest[1].reshape(shape)
            return out
        args = [x, running_mean, running_var]
        if weight is not None:
            args.append(weight)
            if bias is not None:
                args.append(bias)
        return op_call("batch_norm_infer", impl, *args)

    # training: compute batch stats, update running buffers
    def impl(v, *rest):
        axes, ch = stats_axes(v)
        shape = [1] * v.ndim
        shape[ch] = v.shape[ch]
        mean = jnp.mean(v, axis=axes)
        var = jnp.var(v, axis=axes)
        out = (v - mean.reshape(shape)) * jax.lax.rsqrt(var.reshape(shape) + epsilon)
        if rest:
            out = out * rest[0].reshape(shape)
            if len(rest) > 1:
                out = out + rest[1].reshape(shape)
        return out, mean, var
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    out, bmean, bvar = op_call("batch_norm_train", impl, *args)
    if running_mean is not None:
        # unbiased variance for running stats (paddle semantics)
        n = x.size // bmean.size
        unbias = bvar._value * (n / max(n - 1, 1))
        running_mean._set_value(momentum * running_mean._value +
                                (1 - momentum) * bmean._value)
        running_var._set_value(momentum * running_var._value + (1 - momentum) * unbias)
    return out


def layer_norm_ref(v, w=None, b=None, n_axes=1, epsilon=1e-5):
    """The single jnp-level LayerNorm fallback (fp32 stats). Shared by the
    functional dispatch default and the Pallas untileable fallback."""
    axes = tuple(range(v.ndim - n_axes, v.ndim))
    mean = jnp.mean(v.astype(jnp.float32), axis=axes, keepdims=True)
    var = jnp.var(v.astype(jnp.float32), axis=axes, keepdims=True)
    out = ((v - mean) * jax.lax.rsqrt(var + epsilon)).astype(v.dtype)
    if w is not None:
        out = out * w
    if b is not None:
        out = out + b
    return out


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    if isinstance(normalized_shape, int):
        normalized_shape = (normalized_shape,)
    n_axes = len(tuple(normalized_shape))
    if weight is None and bias is not None:
        # bias must apply independently of weight (paddle semantics)
        import paddle_tpu
        weight = paddle_tpu.ones(list(bias.shape), dtype=str(bias.dtype))

    def impl(v, *rest, n_axes=n_axes, epsilon=epsilon):
        w = rest[0] if rest else None
        b = rest[1] if len(rest) > 1 else None
        return layer_norm_ref(v, w, b, n_axes, epsilon)
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return op_call("layer_norm", impl, *args, n_axes=n_axes, epsilon=epsilon)


def rms_norm_ref(v, w=None, epsilon=1e-6):
    """The single jnp-level RMSNorm fallback (fp32 stats; weight applied in
    fp32 then cast, matching the Pallas kernel's convention). Shared by the
    functional dispatch default, the Pallas untileable fallback, and the
    functional LLaMA block."""
    ms = jnp.mean(jnp.square(v.astype(jnp.float32)), axis=-1, keepdims=True)
    out = v.astype(jnp.float32) * jax.lax.rsqrt(ms + epsilon)
    if w is not None:
        out = out * w.astype(jnp.float32)
    return out.astype(v.dtype)


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (reference incubate fused_rms_norm) — LLaMA's norm; Pallas
    override registers under op name 'rms_norm'."""
    def impl(v, *rest, epsilon=epsilon):
        return rms_norm_ref(v, rest[0] if rest else None, epsilon)
    args = [x] if weight is None else [x, weight]
    return op_call("rms_norm", impl, *args, epsilon=epsilon)


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def impl(v, *rest):
        if channel_last:
            ch = v.ndim - 1
            axes = tuple(range(1, v.ndim - 1))
        else:
            ch = 1
            axes = tuple(range(2, v.ndim))
        mean = jnp.mean(v, axis=axes, keepdims=True)
        var = jnp.var(v, axis=axes, keepdims=True)
        out = (v - mean) * jax.lax.rsqrt(var + eps)
        if rest:
            shape = [1] * v.ndim
            shape[ch] = v.shape[ch]
            out = out * rest[0].reshape(shape)
            if len(rest) > 1:
                out = out + rest[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return op_call("instance_norm", impl, *args)


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channel_last = data_format in ("NHWC", "NLC", "NDHWC")

    def impl(v, *rest):
        if channel_last:
            ch = v.ndim - 1
        else:
            ch = 1
        c = v.shape[ch]
        g = num_groups
        if channel_last:
            new_shape = v.shape[:-1] + (g, c // g)
            vv = v.reshape(new_shape)
            axes = tuple(range(1, v.ndim - 1)) + (v.ndim,)
            mean = jnp.mean(vv, axis=axes, keepdims=True)
            var = jnp.var(vv, axis=axes, keepdims=True)
            out = ((vv - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        else:
            new_shape = (v.shape[0], g, c // g) + v.shape[2:]
            vv = v.reshape(new_shape)
            axes = tuple(range(2, vv.ndim))
            mean = jnp.mean(vv, axis=axes, keepdims=True)
            var = jnp.var(vv, axis=axes, keepdims=True)
            out = ((vv - mean) * jax.lax.rsqrt(var + epsilon)).reshape(v.shape)
        if rest:
            shape = [1] * v.ndim
            shape[ch] = c
            out = out * rest[0].reshape(shape)
            if len(rest) > 1:
                out = out + rest[1].reshape(shape)
        return out
    args = [x]
    if weight is not None:
        args.append(weight)
        if bias is not None:
            args.append(bias)
    return op_call("group_norm", impl, *args)


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def impl(v):
        ch = 1 if data_format.startswith("NC") else v.ndim - 1
        sq = jnp.square(v)
        moved = jnp.moveaxis(sq, ch, -1)
        pad_lo = (size - 1) // 2
        pad_hi = size - 1 - pad_lo
        padded = jnp.pad(moved, [(0, 0)] * (moved.ndim - 1) + [(pad_lo, pad_hi)])
        win = jnp.stack([padded[..., i:i + moved.shape[-1]] for i in range(size)], axis=0)
        s = jnp.sum(win, axis=0)
        s = jnp.moveaxis(s, -1, ch)
        div = (k + alpha * s) ** beta
        return v / div
    return op_call("local_response_norm", impl, x)
