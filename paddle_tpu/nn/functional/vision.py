"""Vision functionals: grid_sample + affine_grid (reference:
python/paddle/nn/functional/vision.py:80 affine_grid, :139 grid_sample).

TPU-first design: the sampler is pure gather + elementwise arithmetic —
one fused XLA program, fully differentiable w.r.t. both the input and the
grid (the reference ships dedicated CUDA fwd/bwd kernels; here jax.vjp
derives the backward through the same gathers).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op_call

__all__ = ["grid_sample", "affine_grid"]


def _unnormalize(coord, size, align_corners):
    """[-1, 1] grid coordinate -> pixel coordinate."""
    if align_corners:
        return (coord + 1.0) * 0.5 * (size - 1)
    return ((coord + 1.0) * size - 1.0) * 0.5


def _reflect(x, lo, hi):
    """Reflect x into [lo, hi] (inclusive), the 'reflection' padding rule."""
    rng = hi - lo
    if rng <= 0:
        return jnp.zeros_like(x)
    dbl = 2 * rng
    x = jnp.mod(jnp.abs(x - lo), dbl)
    return lo + jnp.minimum(x, dbl - x)


def _resolve(coord, size, padding_mode, align_corners):
    """Apply the padding rule to an (unnormalized, float) coordinate.
    Returns (coord, in_bounds_weight_mask_needed)."""
    if padding_mode == "border":
        return jnp.clip(coord, 0, size - 1)
    if padding_mode == "reflection":
        if align_corners:
            coord = _reflect(coord, 0.0, float(size - 1))
        else:
            coord = _reflect(coord, -0.5, size - 0.5)
        return jnp.clip(coord, 0, size - 1)
    return coord  # zeros: handled by masking the gathered values


def grid_sample(x, grid, mode="bilinear", padding_mode="zeros",
                align_corners=True, name=None):
    """Sample `x` at `grid` locations (reference vision.py:139).

    x: [N, C, H, W] (4-D) or [N, C, D, H, W] (5-D)
    grid: [N, Ho, Wo, 2] ((x, y) in [-1, 1]) or [N, Do, Ho, Wo, 3]
    mode: 'bilinear' | 'nearest'; padding_mode: 'zeros'|'border'|'reflection'
    """
    if mode not in ("bilinear", "nearest"):
        raise ValueError(f"unsupported mode {mode!r}")
    if padding_mode not in ("zeros", "border", "reflection"):
        raise ValueError(f"unsupported padding_mode {padding_mode!r}")
    nd = len(x.shape) - 2
    if nd not in (2, 3):
        raise ValueError(f"x must be 4-D or 5-D, got rank {len(x.shape)}")
    if len(grid.shape) != nd + 2 or grid.shape[-1] != nd:
        raise ValueError(
            f"grid rank/last-dim must match x: expected [N, ...spatial, {nd}]"
            f", got {tuple(grid.shape)}")

    def impl(xv, gv):
        sizes = xv.shape[2:]                     # (H, W) or (D, H, W)
        # grid's last dim orders coords fastest-varying first: (x, y[, z])
        # i.e. gv[..., 0] indexes W, gv[..., 1] indexes H, gv[..., 2] D
        coords = []
        for i in range(nd):
            size = sizes[nd - 1 - i]
            c = _unnormalize(gv[..., i].astype(jnp.float32), size,
                             align_corners)
            coords.append(_resolve(c, size, padding_mode, align_corners))
        coords = coords[::-1]                    # now ordered like sizes

        def gather(idx_list):
            """idx_list: int coords per spatial dim, each [N, *out_sp].
            Returns [N, C, *out_sp] with zeros-mode OOB masked."""
            valid = None
            gather_idx = []
            for i, idx in enumerate(idx_list):
                size = sizes[i]
                ok = (idx >= 0) & (idx <= size - 1)
                valid = ok if valid is None else (valid & ok)
                gather_idx.append(jnp.clip(idx, 0, size - 1))
            n = xv.shape[0]
            bidx = jnp.arange(n).reshape((n,) + (1,) * (gv.ndim - 2))
            bidx = jnp.broadcast_to(bidx, gather_idx[0].shape)
            # [N, *out_sp, C] -> [N, C, *out_sp]
            vals = xv.transpose((0,) + tuple(range(2, xv.ndim)) + (1,))[
                (bidx,) + tuple(gather_idx)]
            vals = jnp.moveaxis(vals, -1, 1)
            if padding_mode == "zeros":
                vals = vals * valid[:, None].astype(vals.dtype)
            return vals

        if mode == "nearest":
            idx = [jnp.round(c).astype(jnp.int32) for c in coords]
            return gather(idx).astype(xv.dtype)

        # bilinear / trilinear: corner product over 2^nd corners
        lo = [jnp.floor(c) for c in coords]
        frac = [c - l for c, l in zip(coords, lo)]
        out = None
        for corner in range(2 ** nd):
            idx = []
            w = None
            for i in range(nd):
                hi_side = (corner >> i) & 1
                ci = lo[i] + hi_side
                wi = frac[i] if hi_side else (1.0 - frac[i])
                idx.append(ci.astype(jnp.int32))
                w = wi if w is None else w * wi
            contrib = gather(idx) * w[:, None]
            out = contrib if out is None else out + contrib
        return out.astype(xv.dtype)

    return op_call("grid_sample", impl, x, grid)


def affine_grid(theta, out_shape, align_corners=True, name=None):
    """2-D/3-D sampling grid from batched affine matrices (reference
    vision.py:80).  theta [N, 2, 3] -> grid [N, H, W, 2];
    theta [N, 3, 4] -> grid [N, D, H, W, 3].  out_shape: [N, C, H, W] or
    [N, C, D, H, W]."""
    shape = [int(s) for s in out_shape]
    nd = len(shape) - 2
    if nd not in (2, 3):
        raise ValueError("out_shape must have 4 or 5 entries")

    def impl(tv):
        def base(size):
            if align_corners:
                return jnp.linspace(-1.0, 1.0, size)
            return (jnp.arange(size, dtype=jnp.float32) * 2 + 1) / size - 1.0
        axes = [base(s) for s in shape[2:]]          # D?, H, W
        mesh = jnp.meshgrid(*axes, indexing="ij")
        # homogeneous coords ordered (x, y[, z]) = (W, H[, D])
        ones = jnp.ones_like(mesh[0])
        cols = list(mesh[::-1]) + [ones]
        pts = jnp.stack([c.reshape(-1) for c in cols], -1)  # [P, nd+1]
        grid = jnp.einsum("pk,nik->npi", pts, tv.astype(jnp.float32))
        return grid.reshape((tv.shape[0],) + tuple(shape[2:]) + (nd,))

    return op_call("affine_grid", impl, theta)
