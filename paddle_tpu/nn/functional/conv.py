"""Convolution functionals (reference: python/paddle/nn/functional/conv.py).

All convs lower to a single `lax.conv_general_dilated` HLO — XLA tiles it onto
the MXU. Paddle layouts are kept at the API (NCHW default, weight OIHW); on
TPU XLA canonicalizes layouts internally, so no manual transposes are needed
for performance.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

from ...core.dispatch import op_call

__all__ = ["conv1d", "conv2d", "conv3d", "conv1d_transpose", "conv2d_transpose",
           "conv3d_transpose"]


def _tuple(v, n):
    if isinstance(v, (list, tuple)):
        if len(v) == n:
            return tuple(int(x) for x in v)
        if len(v) == 1:
            return tuple(int(v[0]) for _ in range(n))
    return tuple(int(v) for _ in range(n))


def _padding(padding, n, strides=None):
    """Paddle padding spec -> lax padding list [(lo, hi)] * n or 'SAME'/'VALID'."""
    if isinstance(padding, str):
        return padding.upper()
    if isinstance(padding, (list, tuple)):
        flat = list(padding)
        if len(flat) == n:
            return [(int(p), int(p)) for p in flat]
        if len(flat) == 2 * n:
            return [(int(flat[2 * i]), int(flat[2 * i + 1])) for i in range(n)]
        if len(flat) == 1:
            return [(int(flat[0]), int(flat[0]))] * n
    return [(int(padding), int(padding))] * n


def _dim_numbers(n, channel_last):
    if n == 1:
        return ("NWC", "WIO", "NWC") if channel_last else ("NCW", "OIW", "NCW")
    if n == 2:
        return ("NHWC", "HWIO", "NHWC") if channel_last else ("NCHW", "OIHW", "NCHW")
    return ("NDHWC", "DHWIO", "NDHWC") if channel_last else ("NCDHW", "OIDHW", "NCDHW")


def _conv(x, weight, bias, stride, padding, dilation, groups, n, data_format):
    channel_last = data_format in ("NWC", "NHWC", "NDHWC", "NLC")
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    pad = _padding(padding, n)
    dn = _dim_numbers(n, channel_last)

    def impl(v, w, *rest):
        # paddle weight layout is always [out_c, in_c/groups, *k]
        if channel_last:
            # lax wants e.g. HWIO for NHWC
            perm = tuple(range(2, 2 + n)) + (1, 0)
            w = jnp.transpose(w, perm)
        # native dtype: the MXU accumulates bf16 convs in fp32 already, and
        # preferred_element_type=f32 breaks the conv transpose rule (mixed
        # f32 cotangent × bf16 operand) under autodiff
        out = jax.lax.conv_general_dilated(
            v, w, window_strides=strides, padding=pad,
            rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        out = out.astype(v.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    args = (x, weight) if bias is None else (x, weight, bias)
    return op_call(f"conv{n}d", impl, *args)


def conv1d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv(x, weight, bias, stride, padding, dilation, groups, 1, df)


def conv2d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 2, data_format)


def conv3d(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
           data_format="NCDHW", name=None):
    return _conv(x, weight, bias, stride, padding, dilation, groups, 3, data_format)


def _conv_transpose(x, weight, bias, stride, padding, output_padding, dilation,
                    groups, n, data_format, output_size):
    channel_last = data_format in ("NWC", "NHWC", "NDHWC", "NLC")
    strides = _tuple(stride, n)
    dil = _tuple(dilation, n)
    opad = _tuple(output_padding, n) if output_padding is not None else (0,) * n
    pad_spec = _padding(padding, n)
    dn = _dim_numbers(n, channel_last)

    def impl(v, w, *rest):
        # paddle transpose-conv weight layout: [in_c, out_c/groups, *k]
        # grad-of-conv formulation: lhs_dilation = stride
        k_eff = [dil[i] * (w.shape[2 + i] - 1) + 1 for i in range(n)]
        if isinstance(pad_spec, str):
            if pad_spec == "VALID":
                pads = [(0, 0)] * n
            else:  # SAME
                pads = []
                for i in range(n):
                    size_in = v.shape[1 + i if channel_last else 2 + i]
                    total = max(k_eff[i] - strides[i], 0)
                    pads.append((total // 2, total - total // 2))
        else:
            pads = pad_spec
        conv_pads = []
        for i in range(n):
            lo = k_eff[i] - 1 - pads[i][0]
            hi = k_eff[i] - 1 - pads[i][1] + opad[i]
            conv_pads.append((lo, hi))
        # flip spatial dims & swap in/out channels: OIHW with O=out
        spatial_axes = tuple(range(2, 2 + n))
        wf = jnp.flip(w, spatial_axes)
        # w: [in_c, out_c/groups, *k] -> [out_c, in_c/groups, *k]
        if groups == 1:
            wt = jnp.swapaxes(wf, 0, 1)
        else:
            ic, ocg = wf.shape[0], wf.shape[1]
            wg = wf.reshape((groups, ic // groups, ocg) + wf.shape[2:])
            wg = jnp.swapaxes(wg, 1, 2)  # [g, out/g, in/g, *k]
            wt = wg.reshape((groups * ocg, ic // groups) + wf.shape[2:])
        if channel_last:
            perm = tuple(range(2, 2 + n)) + (1, 0)
            wt = jnp.transpose(wt, perm)
        out = jax.lax.conv_general_dilated(
            v, wt, window_strides=(1,) * n, padding=conv_pads,
            lhs_dilation=strides, rhs_dilation=dil, dimension_numbers=dn,
            feature_group_count=groups)
        out = out.astype(v.dtype)
        if rest:
            b = rest[0]
            shape = [1] * out.ndim
            shape[-1 if channel_last else 1] = b.shape[0]
            out = out + b.reshape(shape)
        return out
    args = (x, weight) if bias is None else (x, weight, bias)
    return op_call(f"conv{n}d_transpose", impl, *args)


def conv1d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, output_size=None, data_format="NCL", name=None):
    df = "NWC" if data_format in ("NLC", "NWC") else "NCW"
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 1, df, output_size)


def conv2d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 2, data_format, output_size)


def conv3d_transpose(x, weight, bias=None, stride=1, padding=0, output_padding=0,
                     groups=1, dilation=1, data_format="NCDHW", output_size=None, name=None):
    return _conv_transpose(x, weight, bias, stride, padding, output_padding,
                           dilation, groups, 3, data_format, output_size)
