"""Attention functionals.

Reference: python/paddle/nn/functional/flash_attention.py (flash_attention
:358, scaled_dot_product_attention :756, flash_attn_unpadded) backed by the
CUDA FA2 kernel (paddle/phi/kernels/gpu/flash_attn_kernel.cu). Here the
default impl is the fused-softmax jnp path (XLA already fuses it well) and the
Pallas flash-attention kernel registers an override under op name
'flash_attention' (paddle_tpu/ops/pallas/flash_attention.py).

Layout convention matches the reference: [batch, seqlen, num_heads, head_dim].
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ...core.dispatch import op_call, get_kernel
from ...core.tensor import Tensor
from ...core.random import split_key

__all__ = ["scaled_dot_product_attention", "flash_attention",
           "flash_attn_unpadded", "sdp_kernel"]


def _sdpa_ref(q, k, v, mask=None, dropout=0.0, causal=False, scale=None,
              dropout_key=None):
    """Reference math: q,k,v [B, S, H, D] -> [B, S, H, D]."""
    d = q.shape[-1]
    if k.shape[2] != q.shape[2]:  # GQA fallback: up-materialize KV heads
        rep = q.shape[2] // k.shape[2]
        k = jnp.repeat(k, rep, axis=2)
        v = jnp.repeat(v, rep, axis=2)
    s = scale if scale is not None else 1.0 / math.sqrt(d)
    # [B, H, Sq, Sk]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) * s
    logits = logits.astype(jnp.float32)
    if causal:
        sq, sk = logits.shape[-2], logits.shape[-1]
        cmask = jnp.tril(jnp.ones((sq, sk), bool), k=sk - sq)
        logits = jnp.where(cmask, logits, -jnp.inf)
    if mask is not None:
        if mask.dtype == jnp.bool_:
            logits = jnp.where(mask, logits, -jnp.inf)
        else:
            logits = logits + mask.astype(jnp.float32)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    if dropout > 0.0 and dropout_key is not None:
        keep = jax.random.bernoulli(dropout_key, 1.0 - dropout, probs.shape)
        probs = jnp.where(keep, probs / (1.0 - dropout), 0.0).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def _dispatch_flash_dropout(query, key, value, rate, causal):
    """Route unmasked dropout attention through the in-kernel-dropout flash
    op when registered (regenerable per-block mask — the [B,H,S,S] probs
    never materialize); returns None when the kernel is unavailable so the
    caller runs its XLA fallback.  Shared by scaled_dot_product_attention
    and flash_attention."""
    if get_kernel("flash_attention_dropout") is None:
        return None
    dk = split_key()
    seed = jax.random.randint(dk, (), 0, 1 << 23).astype(jnp.float32)

    def impl(q, k, v, sd, rate=None, causal=None):
        return _sdpa_ref(q, k, v, dropout=rate, causal=causal,
                         dropout_key=dk)
    return op_call("flash_attention_dropout", impl, query, key, value,
                   seed, rate=float(rate), causal=bool(causal))


def scaled_dot_product_attention(query, key, value, attn_mask=None,
                                 dropout_p=0.0, is_causal=False, training=True,
                                 name=None):
    """reference flash_attention.py:756 — the no-mask/no-dropout fast path
    dispatches through op names the Pallas flash kernel overrides
    ('flash_attention' / 'flash_attention_causal'); masked or dropout
    attention runs the fused-softmax XLA path."""
    use_dropout = dropout_p > 0.0 and training
    if attn_mask is None and not use_dropout:
        def impl(q, k, v):
            return _sdpa_ref(q, k, v, causal=is_causal)
        name_ = "flash_attention_causal" if is_causal else "flash_attention"
        return op_call(name_, impl, query, key, value)
    if attn_mask is None and use_dropout:
        out = _dispatch_flash_dropout(query, key, value, dropout_p, is_causal)
        if out is not None:
            return out
    dk = split_key() if use_dropout else None
    def impl(q, k, v, *rest):
        m = rest[0] if rest else None
        return _sdpa_ref(q, k, v, mask=m, dropout=dropout_p if training else 0.0,
                         causal=is_causal, dropout_key=dk)
    args = [query, key, value] if attn_mask is None else [query, key, value, attn_mask]
    return op_call("sdpa_general", impl, *args)


def flash_attention(query, key, value, dropout=0.0, causal=False,
                    return_softmax=False, fixed_seed_offset=None, rng_name="",
                    training=True, name=None):
    """reference flash_attention.py:358. Returns (out, softmax_lse-like None)."""
    use_dropout = dropout > 0.0 and training
    if use_dropout:
        out = _dispatch_flash_dropout(query, key, value, dropout, causal)
        if out is not None:
            return out, None
    dk = split_key() if use_dropout else None
    def impl(q, k, v):
        return _sdpa_ref(q, k, v, dropout=dropout if training else 0.0,
                         causal=causal, dropout_key=dk)
    name_ = ("flash_attention_causal" if causal else "flash_attention") \
        if not use_dropout else "sdpa_general"
    out = op_call(name_, impl, query, key, value)
    return out, None


def flash_attn_unpadded(query, key, value, cu_seqlens_q, cu_seqlens_k,
                        max_seqlen_q, max_seqlen_k, scale=None, dropout=0.0,
                        causal=False, return_softmax=False,
                        fixed_seed_offset=None, rng_name="", training=True,
                        name=None):
    """Varlen attention over packed sequences (reference flash_attn_unpadded):
    [total_tokens, H, D] + cumulative seqlen boundaries. Implemented by
    building a block-diagonal mask (segment ids) — XLA-friendly static shape."""
    cu_q = cu_seqlens_q._value if isinstance(cu_seqlens_q, Tensor) else cu_seqlens_q
    cu_k = cu_seqlens_k._value if isinstance(cu_seqlens_k, Tensor) else cu_seqlens_k
    def impl(q, k, v):
        tq = q.shape[0]
        tk = k.shape[0]
        seg_q = jnp.cumsum(jnp.zeros(tq, jnp.int32).at[cu_q[1:-1]].add(1))
        seg_k = jnp.cumsum(jnp.zeros(tk, jnp.int32).at[cu_k[1:-1]].add(1))
        use_dropout = dropout > 0.0 and training
        same_boundaries = cu_q is cu_k
        if not same_boundaries:
            try:  # concrete boundary arrays: compare values
                import numpy as _np
                same_boundaries = (cu_q.shape == cu_k.shape
                                   and bool(_np.array_equal(_np.asarray(cu_q),
                                                            _np.asarray(cu_k))))
            except Exception:
                same_boundaries = False  # traced: can't prove equality
        if tq == tk and same_boundaries and scale is None:
            # Pallas varlen kernel: block-diagonal via in-kernel segment
            # ids; dropout (if any) runs in-kernel too
            varlen_k = get_kernel("flash_attention_varlen")
            if varlen_k is not None:
                out = varlen_k(q[None], k[None], v[None], seg_q[None],
                               causal=causal,
                               rate=float(dropout) if use_dropout else 0.0)
                if out is not None:
                    return out[0]
        mask = seg_q[:, None] == seg_k[None, :]
        if causal:
            pos_q = jnp.arange(tq) - cu_q[seg_q]
            pos_k = jnp.arange(tk) - cu_k[seg_k]
            mask = mask & (pos_q[:, None] >= pos_k[None, :])
        out = _sdpa_ref(q[None], k[None], v[None], mask=mask[None, None],
                        dropout=dropout if training else 0.0, causal=False,
                        scale=scale,
                        dropout_key=split_key() if use_dropout else None)
        return out[0]
    out = op_call("flash_attn_unpadded", impl, query, key, value)
    return out, None


class sdp_kernel:
    """Context manager parity for torch-style backend selection; on TPU the
    kernel registry decides (pallas vs xla)."""

    def __init__(self, enable_flash=True, enable_math=True, enable_mem_efficient=True):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
