"""paddle.nn.functional.flash_attention submodule parity — reference keeps
flash attention in its own module (python/paddle/nn/functional/flash_attention.py)."""
from .attention import (  # noqa: F401
    flash_attention, flash_attn_unpadded, scaled_dot_product_attention, sdp_kernel,
)

flash_attn_qkvpacked = None  # provided via flash_attention on unpacked views
