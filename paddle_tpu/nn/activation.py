"""Activation layers (reference: python/paddle/nn/layer/activation.py)."""
from __future__ import annotations

from .layer import Layer
from . import functional as F
from .initializer import Constant

__all__ = ["ReLU", "ReLU6", "GELU", "Sigmoid", "Silu", "Swish", "Mish",
           "Softplus", "Softsign", "Hardshrink", "Softshrink", "Tanhshrink",
           "Hardsigmoid", "Hardswish", "Hardtanh", "ELU", "CELU", "SELU",
           "LeakyReLU", "PReLU", "RReLU", "GLU", "Softmax", "LogSoftmax",
           "Maxout", "Tanh", "LogSigmoid", "ThresholdedReLU"]


def _mk(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, *args, **kw):
            super().__init__()
            merged = dict(defaults)
            names = list(defaults)
            for i, a in enumerate(args):
                merged[names[i]] = a
            merged.update({k: v for k, v in kw.items() if k in merged})
            self._kw = merged

        def forward(self, x):
            return fn(x, **self._kw)
    _Act.__name__ = name
    return _Act


ReLU = _mk("ReLU", F.relu)
ReLU6 = _mk("ReLU6", F.relu6)
GELU = _mk("GELU", F.gelu, approximate=False)
Sigmoid = _mk("Sigmoid", F.sigmoid)
Silu = _mk("Silu", F.silu)
Swish = _mk("Swish", F.swish)
Mish = _mk("Mish", F.mish)
Softplus = _mk("Softplus", F.softplus, beta=1.0, threshold=20.0)
Softsign = _mk("Softsign", F.softsign)
Hardshrink = _mk("Hardshrink", F.hardshrink, threshold=0.5)
Softshrink = _mk("Softshrink", F.softshrink, threshold=0.5)
Tanhshrink = _mk("Tanhshrink", F.tanhshrink)
Hardsigmoid = _mk("Hardsigmoid", F.hardsigmoid)
Hardswish = _mk("Hardswish", F.hardswish)
Hardtanh = _mk("Hardtanh", F.hardtanh, min=-1.0, max=1.0)
ELU = _mk("ELU", F.elu, alpha=1.0)
CELU = _mk("CELU", F.celu, alpha=1.0)
SELU = _mk("SELU", F.selu)
LeakyReLU = _mk("LeakyReLU", F.leaky_relu, negative_slope=0.01)
RReLU = _mk("RReLU", F.rrelu, lower=0.125, upper=1.0 / 3.0)
GLU = _mk("GLU", F.glu, axis=-1)
Softmax = _mk("Softmax", F.softmax, axis=-1)
LogSoftmax = _mk("LogSoftmax", F.log_softmax, axis=-1)
Maxout = _mk("Maxout", F.maxout, groups=2, axis=1)
Tanh = _mk("Tanh", F.tanh)
LogSigmoid = _mk("LogSigmoid", F.log_sigmoid)
ThresholdedReLU = _mk("ThresholdedReLU", F.thresholded_relu, threshold=1.0, value=0.0)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter((num_parameters,), attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)
