"""Transformer layers (reference: python/paddle/nn/layer/transformer.py).

MultiHeadAttention uses the framework's attention dispatch, so the Pallas
flash-attention kernel override applies automatically.
"""
from __future__ import annotations

import jax.numpy as jnp

from .layer import Layer
from .common import Linear, Dropout
from .norm import LayerNorm
from .container import LayerList
from . import functional as F
from ..core.tensor import Tensor
from ..tensor import manipulation as manip

__all__ = ["MultiHeadAttention", "TransformerEncoderLayer", "TransformerEncoder",
           "TransformerDecoderLayer", "TransformerDecoder", "Transformer"]


class MultiHeadAttention(Layer):
    """reference transformer.py MultiHeadAttention: q/k/v/out projections +
    SDPA; supports cross-attention and incremental cache."""

    class Cache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    class StaticCache:
        def __init__(self, k, v):
            self.k, self.v = k, v

    def __init__(self, embed_dim, num_heads, dropout=0.0, kdim=None, vdim=None,
                 need_weights=False, weight_attr=None, bias_attr=None):
        super().__init__()
        self.embed_dim = embed_dim
        self.num_heads = num_heads
        self.head_dim = embed_dim // num_heads
        self.dropout = dropout
        self.need_weights = need_weights
        kdim = kdim or embed_dim
        vdim = vdim or embed_dim
        self.q_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)
        self.k_proj = Linear(kdim, embed_dim, weight_attr, bias_attr)
        self.v_proj = Linear(vdim, embed_dim, weight_attr, bias_attr)
        self.out_proj = Linear(embed_dim, embed_dim, weight_attr, bias_attr)

    def _split(self, t):
        b, s, _ = t.shape
        return manip.reshape(t, [b, s, self.num_heads, self.head_dim])

    def gen_cache(self, key, value=None, type=None):
        if type == MultiHeadAttention.StaticCache:
            k = self._split(self.k_proj(key))
            v = self._split(self.v_proj(value if value is not None else key))
            return MultiHeadAttention.StaticCache(k, v)
        from ..tensor.creation import zeros
        b = key.shape[0]
        k = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        v = zeros([b, 0, self.num_heads, self.head_dim], key.dtype)
        return MultiHeadAttention.Cache(k, v)

    def forward(self, query, key=None, value=None, attn_mask=None, cache=None):
        key = query if key is None else key
        value = key if value is None else value
        q = self._split(self.q_proj(query))
        if isinstance(cache, MultiHeadAttention.StaticCache):
            k, v = cache.k, cache.v
        else:
            k = self._split(self.k_proj(key))
            v = self._split(self.v_proj(value))
            if isinstance(cache, MultiHeadAttention.Cache):
                k = manip.concat([cache.k, k], axis=1)
                v = manip.concat([cache.v, v], axis=1)
                cache = MultiHeadAttention.Cache(k, v)
        out = F.scaled_dot_product_attention(q, k, v, attn_mask=attn_mask,
                                             dropout_p=self.dropout,
                                             training=self.training)
        b, s = out.shape[0], out.shape[1]
        out = manip.reshape(out, [b, s, self.embed_dim])
        out = self.out_proj(out)
        if cache is not None and not isinstance(cache, MultiHeadAttention.StaticCache):
            return out, cache
        return out


class TransformerEncoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, src, src_mask=None, cache=None):
        residual = src
        if self.normalize_before:
            src = self.norm1(src)
        if cache is not None:
            out, cache = self.self_attn(src, src, src, src_mask, cache)
        else:
            out = self.self_attn(src, src, src, src_mask)
        src = residual + self.dropout1(out)
        if not self.normalize_before:
            src = self.norm1(src)
        residual = src
        if self.normalize_before:
            src = self.norm2(src)
        src = self.linear2(self.act_dropout(self.activation(self.linear1(src))))
        src = residual + self.dropout2(src)
        if not self.normalize_before:
            src = self.norm2(src)
        return src if cache is None else (src, cache)

    def gen_cache(self, src):
        return self.self_attn.gen_cache(src)


class TransformerEncoder(Layer):
    def __init__(self, encoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([encoder_layer] +
                                [copy.deepcopy(encoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, src, src_mask=None, cache=None):
        out = src
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is not None:
                out, c = layer(out, src_mask, cache[i])
                new_caches.append(c)
            else:
                out = layer(out, src_mask)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, src):
        return [l.gen_cache(src) for l in self.layers]


class TransformerDecoderLayer(Layer):
    def __init__(self, d_model, nhead, dim_feedforward, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 layer_norm_eps=1e-5):
        super().__init__()
        attn_dropout = dropout if attn_dropout is None else attn_dropout
        act_dropout = dropout if act_dropout is None else act_dropout
        self.normalize_before = normalize_before
        self.self_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                            weight_attr=weight_attr, bias_attr=bias_attr)
        self.cross_attn = MultiHeadAttention(d_model, nhead, attn_dropout,
                                             weight_attr=weight_attr, bias_attr=bias_attr)
        self.linear1 = Linear(d_model, dim_feedforward, weight_attr, bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, weight_attr, bias_attr)
        self.norm1 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm2 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.norm3 = LayerNorm(d_model, epsilon=layer_norm_eps)
        self.dropout1 = Dropout(dropout)
        self.dropout2 = Dropout(dropout)
        self.dropout3 = Dropout(dropout)
        self.act_dropout = Dropout(act_dropout)
        self.activation = getattr(F, activation)

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        residual = tgt
        if self.normalize_before:
            tgt = self.norm1(tgt)
        if cache is None:
            out = self.self_attn(tgt, tgt, tgt, tgt_mask)
        else:
            out, sc = self.self_attn(tgt, tgt, tgt, tgt_mask, cache[0])
        tgt = residual + self.dropout1(out)
        if not self.normalize_before:
            tgt = self.norm1(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm2(tgt)
        if cache is None:
            out = self.cross_attn(tgt, memory, memory, memory_mask)
        else:
            out = self.cross_attn(tgt, memory, memory, memory_mask, cache[1])
            if isinstance(out, tuple):
                out = out[0]
        tgt = residual + self.dropout2(out)
        if not self.normalize_before:
            tgt = self.norm2(tgt)
        residual = tgt
        if self.normalize_before:
            tgt = self.norm3(tgt)
        tgt = self.linear2(self.act_dropout(self.activation(self.linear1(tgt))))
        tgt = residual + self.dropout3(tgt)
        if not self.normalize_before:
            tgt = self.norm3(tgt)
        return tgt if cache is None else (tgt, (sc, cache[1]))

    def gen_cache(self, memory):
        inc = self.self_attn.gen_cache(memory)
        sta = self.cross_attn.gen_cache(memory, memory,
                                        type=MultiHeadAttention.StaticCache)
        return inc, sta


class TransformerDecoder(Layer):
    def __init__(self, decoder_layer, num_layers, norm=None):
        super().__init__()
        import copy
        self.layers = LayerList([decoder_layer] +
                                [copy.deepcopy(decoder_layer) for _ in range(num_layers - 1)])
        self.num_layers = num_layers
        self.norm = norm

    def forward(self, tgt, memory, tgt_mask=None, memory_mask=None, cache=None):
        out = tgt
        new_caches = []
        for i, layer in enumerate(self.layers):
            if cache is None:
                out = layer(out, memory, tgt_mask, memory_mask)
            else:
                out, c = layer(out, memory, tgt_mask, memory_mask, cache[i])
                new_caches.append(c)
        if self.norm is not None:
            out = self.norm(out)
        return out if cache is None else (out, new_caches)

    def gen_cache(self, memory, do_zip=False):
        return [l.gen_cache(memory) for l in self.layers]


class Transformer(Layer):
    def __init__(self, d_model=512, nhead=8, num_encoder_layers=6,
                 num_decoder_layers=6, dim_feedforward=2048, dropout=0.1,
                 activation="relu", attn_dropout=None, act_dropout=None,
                 normalize_before=False, weight_attr=None, bias_attr=None,
                 custom_encoder=None, custom_decoder=None):
        super().__init__()
        self.d_model = d_model
        self.nhead = nhead
        if custom_encoder is not None:
            self.encoder = custom_encoder
        else:
            el = TransformerEncoderLayer(d_model, nhead, dim_feedforward, dropout,
                                         activation, attn_dropout, act_dropout,
                                         normalize_before, weight_attr, bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.encoder = TransformerEncoder(el, num_encoder_layers, norm)
        if custom_decoder is not None:
            self.decoder = custom_decoder
        else:
            dl = TransformerDecoderLayer(d_model, nhead, dim_feedforward, dropout,
                                         activation, attn_dropout, act_dropout,
                                         normalize_before, weight_attr, bias_attr)
            norm = LayerNorm(d_model) if normalize_before else None
            self.decoder = TransformerDecoder(dl, num_decoder_layers, norm)

    def forward(self, src, tgt, src_mask=None, tgt_mask=None, memory_mask=None):
        memory = self.encoder(src, src_mask)
        return self.decoder(tgt, memory, tgt_mask, memory_mask)

    @staticmethod
    def generate_square_subsequent_mask(length):
        from ..core.tensor import Tensor
        m = jnp.where(jnp.tril(jnp.ones((length, length), bool)), 0.0, -jnp.inf)
        return Tensor(m.astype(jnp.float32))
