"""nn.utils parity (reference: python/paddle/nn/utils/)."""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter

__all__ = ["clip_grad_norm_", "clip_grad_value_", "parameters_to_vector",
           "vector_to_parameters", "weight_norm", "remove_weight_norm",
           "spectral_norm"]


def clip_grad_norm_(parameters, max_norm, norm_type=2.0, error_if_nonfinite=False):
    if isinstance(parameters, (Tensor, Parameter)):
        parameters = [parameters]
    grads = [p._grad for p in parameters if p._grad is not None]
    if not grads:
        return Tensor(jnp.zeros(()))
    if norm_type == float("inf"):
        total = jnp.max(jnp.stack([jnp.max(jnp.abs(g._value)) for g in grads]))
    else:
        total = jnp.sum(jnp.stack(
            [jnp.sum(jnp.abs(g._value.astype(jnp.float32)) ** norm_type) for g in grads]
        )) ** (1.0 / norm_type)
    scale = jnp.minimum(max_norm / jnp.maximum(total, 1e-6), 1.0)
    for p in parameters:
        if p._grad is not None:
            p._grad._set_value(p._grad._value * scale)
    return Tensor(total)


def clip_grad_value_(parameters, clip_value):
    if isinstance(parameters, (Tensor, Parameter)):
        parameters = [parameters]
    for p in parameters:
        if p._grad is not None:
            p._grad._set_value(jnp.clip(p._grad._value, -clip_value, clip_value))


def parameters_to_vector(parameters, name=None):
    vals = [p._value.reshape(-1) for p in parameters]
    return Tensor(jnp.concatenate(vals))


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    v = vec._value
    for p in parameters:
        n = p.size
        p._set_value(v[offset:offset + n].reshape(p._value.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparameterize weight = g * v/||v|| (reference nn/utils/weight_norm_hook.py)."""
    import jax
    w = getattr(layer, name)
    dim_ = dim if dim is not None else -1
    axes = tuple(i for i in range(w.ndim) if i != (dim_ % w.ndim)) if dim is not None else None
    norm = jnp.sqrt(jnp.sum(jnp.square(w._value), axis=axes, keepdims=True)) \
        if axes is not None else jnp.sqrt(jnp.sum(jnp.square(w._value)))
    g = Parameter(norm.reshape(-1) if axes is not None else norm.reshape(()))
    v = Parameter(w._value)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def pre_hook(l, inputs):
        vv = l._parameters[name + "_v"]
        gg = l._parameters[name + "_g"]
        if axes is not None:
            nn = jnp.sqrt(jnp.sum(jnp.square(vv._value), axis=axes, keepdims=True))
            shape = [1] * vv._value.ndim
            shape[dim_ % vv._value.ndim] = -1
            wv = vv._value / nn * gg._value.reshape(shape)
        else:
            wv = vv._value / jnp.sqrt(jnp.sum(jnp.square(vv._value))) * gg._value
        object.__setattr__(l, "_wn_cache", Tensor(wv, stop_gradient=False))
        l.__dict__[name] = l._wn_cache
        return None
    layer._weight_norm_hook = layer.register_forward_pre_hook(pre_hook)
    return layer


def remove_weight_norm(layer, name="weight"):
    if hasattr(layer, "_weight_norm_hook"):
        layer._weight_norm_hook.remove()
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    layer.__dict__.pop(name, None)
    layer.add_parameter(name, v)
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from .norm import SpectralNorm
    w = getattr(layer, name)
    sn = SpectralNorm(tuple(w.shape), dim=dim or 0, power_iters=n_power_iterations,
                      epsilon=eps)
    layer.add_sublayer(name + "_sn", sn)

    def pre_hook(l, inputs):
        wn = sn(l._parameters[name])
        l.__dict__[name] = wn
        return None
    layer.register_forward_pre_hook(pre_hook)
    return layer
