"""Norm layers (reference: python/paddle/nn/layer/norm.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .layer import Layer
from . import functional as F
from .initializer import Constant
from ..core.tensor import Tensor
from ..param_attr import ParamAttr

__all__ = ["BatchNorm", "BatchNorm1D", "BatchNorm2D", "BatchNorm3D",
           "SyncBatchNorm", "LayerNorm", "RMSNorm", "GroupNorm", "InstanceNorm1D",
           "InstanceNorm2D", "InstanceNorm3D", "LocalResponseNorm", "SpectralNorm"]


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        self.weight = self.create_parameter(
            (num_features,), attr=weight_attr, default_initializer=Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None
        self.register_buffer("_mean", Tensor(jnp.zeros((num_features,), jnp.float32)))
        self.register_buffer("_variance", Tensor(jnp.ones((num_features,), jnp.float32)))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """Legacy paddle.nn.BatchNorm (accepts act)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, dtype="float32",
                 data_layout="NCHW", in_place=False, moving_mean_name=None,
                 moving_variance_name=None, do_model_average_for_mean_and_var=True,
                 use_global_stats=False, trainable_statistics=False):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act:
            out = getattr(F, self._act)(out)
        return out


class BatchNorm1D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCL", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCL" else "NHWC",
                         use_global_stats)


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCDHW", use_global_stats=None, name=None):
        super().__init__(num_features, momentum, epsilon, weight_attr, bias_attr,
                         "NCHW" if data_format == "NCDHW" else "NHWC", use_global_stats)


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN (reference norm.py SyncBatchNorm backed by
    sync_batch_norm CUDA kernel + NCCL). On TPU, inside pjit the batch stats
    are computed over the global batch automatically when the input is sharded
    over 'dp' (XLA emits the cross-replica reduction); eager single-process
    falls back to local stats."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            new.weight = layer.weight
            new.bias = layer.bias
            new._buffers = layer._buffers
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._normalized_shape = tuple(normalized_shape)
        self._epsilon = epsilon
        self.weight = self.create_parameter(self._normalized_shape, attr=weight_attr,
                                            default_initializer=Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter(self._normalized_shape, attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """LLaMA-style RMSNorm (reference incubate fused_rms_norm API)."""

    def __init__(self, normalized_shape, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        if isinstance(normalized_shape, int):
            normalized_shape = (normalized_shape,)
        self._epsilon = epsilon
        self.weight = self.create_parameter(tuple(normalized_shape), attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = self.create_parameter((num_channels,), attr=weight_attr,
                                            default_initializer=Constant(1.0)) \
            if weight_attr is not False else None
        self.bias = self.create_parameter((num_channels,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        self._data_format = data_format
        if weight_attr is not False:
            self.weight = self.create_parameter((num_features,), attr=weight_attr,
                                                default_initializer=Constant(1.0))
            self.bias = self.create_parameter((num_features,), attr=bias_attr, is_bias=True)
        else:
            self.weight = self.bias = None

    def forward(self, x):
        return F.instance_norm(x, weight=self.weight, bias=self.bias,
                               eps=self._epsilon, data_format=self._data_format)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW", name=None):
        super().__init__()
        self.a = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.a)


class SpectralNorm(Layer):
    """Spectral normalization of a weight (reference norm.py SpectralNorm):
    power iteration on the fly."""

    def __init__(self, weight_shape, dim=0, power_iters=1, epsilon=1e-12,
                 dtype="float32"):
        super().__init__()
        import numpy as np
        self._dim = dim
        self._power_iters = power_iters
        self._epsilon = epsilon
        h = weight_shape[dim]
        w = int(np.prod(weight_shape)) // h
        from ..core.random import split_key
        import jax
        self.register_buffer("weight_u", Tensor(jax.random.normal(split_key(), (h,), jnp.float32)))
        self.register_buffer("weight_v", Tensor(jax.random.normal(split_key(), (w,), jnp.float32)))

    def forward(self, weight):
        from ..core.dispatch import op_call
        u0, v0 = self.weight_u._value, self.weight_v._value
        dim, n_iters, eps = self._dim, self._power_iters, self._epsilon

        def impl(w):
            wm = jnp.moveaxis(w, dim, 0).reshape(w.shape[dim], -1)
            u, v = u0, v0
            for _ in range(n_iters):
                v = wm.T @ u
                v = v / (jnp.linalg.norm(v) + eps)
                u = wm @ v
                u = u / (jnp.linalg.norm(u) + eps)
            sigma = u @ wm @ v
            return w / sigma
        return op_call("spectral_norm", impl, weight)
