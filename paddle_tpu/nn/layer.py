"""Layer base class.

TPU-native analog of the reference nn.Layer (python/paddle/nn/layer/layers.py:353):
parameters/buffers/sublayers registries, hooks, state_dict. Parameters are
pytree-friendly Tensors, so a Layer's state maps directly onto jax transforms
via :func:`functional_state` — the bridge that lets `jit`-compiled train steps
substitute traced values for layer state (the dygraph→static bridge).
"""
from __future__ import annotations

import collections
from typing import Callable, Dict, Iterator, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor, Parameter
from ..core import dtype as dtype_mod
from ..core import random as random_mod

__all__ = ["Layer", "functional_state", "functional_call"]


class _HookHandle:
    _next_id = [0]

    def __init__(self, hooks: dict):
        self._hooks = hooks
        self._id = _HookHandle._next_id[0]
        _HookHandle._next_id[0] += 1

    def remove(self):
        self._hooks.pop(self._id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype_mod.convert_dtype(dtype)
        self._name_scope = name_scope or type(self).__name__.lower()
        self._parameters: Dict[str, Optional[Parameter]] = collections.OrderedDict()
        self._buffers: Dict[str, Optional[Tensor]] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._sub_layers: Dict[str, Optional["Layer"]] = collections.OrderedDict()
        self._forward_pre_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._forward_post_hooks: Dict[int, Callable] = collections.OrderedDict()
        self._casted_by_pure_fp16 = False

    # -- attribute magic ---------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call Layer.__init__ before assigning parameters")
            params[name] = value
            if buffers is not None:
                buffers.pop(name, None)
            if layers is not None:
                layers.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call Layer.__init__ before assigning sublayers")
            layers[name] = value
            if params is not None:
                params.pop(name, None)
            self.__dict__.pop(name, None)
        elif isinstance(value, Tensor) and buffers is not None and name in buffers:
            buffers[name] = value
        elif params is not None and name in params and value is None:
            params[name] = None
        elif layers is not None and name in layers and value is None:
            layers[name] = None
        else:
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        # only called when normal lookup fails
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                return d[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for store in ("_parameters", "_buffers", "_sub_layers"):
            d = self.__dict__.get(store)
            if d is not None and name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def __dir__(self):
        extra = list(self._parameters) + list(self._buffers) + list(self._sub_layers)
        return super().__dir__() + extra

    # -- registration ------------------------------------------------------
    def add_parameter(self, name: str, parameter: Optional[Parameter]):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name: str, sublayer: "Layer"):
        self._sub_layers[name] = sublayer
        return sublayer

    def register_buffer(self, name: str, tensor: Optional[Tensor], persistable=True):
        if tensor is not None and not isinstance(tensor, Tensor):
            tensor = Tensor(jnp.asarray(tensor))
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        """Layer.create_parameter parity: honors ParamAttr initializer /
        trainable / name (reference layers.py create_parameter)."""
        from .initializer import Constant, XavierUniform
        from ..param_attr import ParamAttr
        d = dtype_mod.convert_dtype(dtype) or self._dtype or dtype_mod.default_float_dtype()
        shape = tuple(int(s) for s in shape)
        attr = ParamAttr._to_attr(attr)
        if attr is False:
            return None
        init = default_initializer
        trainable = True
        name = None
        if isinstance(attr, ParamAttr):
            init = attr.initializer or init
            trainable = attr.trainable
            name = attr.name
        if init is None:
            init = Constant(0.0) if is_bias else XavierUniform()
        p = Parameter(jnp.zeros(shape, d), trainable=trainable, name=name)
        init(p)
        if not trainable:
            p.stop_gradient = True
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        d = dtype_mod.convert_dtype(dtype) or self._dtype
        return Tensor(jnp.zeros((), d), name=name)

    # -- iteration ---------------------------------------------------------
    def named_parameters(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Parameter]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for pname, p in layer._parameters.items():
                if p is not None and id(p) not in seen:
                    seen.add(id(p))
                    yield (f"{name}.{pname}" if name else pname), p

    def parameters(self, include_sublayers=True) -> List[Parameter]:
        return [p for _, p in self.named_parameters(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True) -> Iterator[Tuple[str, Tensor]]:
        seen = set()
        for name, layer in self.named_sublayers(prefix=prefix, include_self=True):
            if not include_sublayers and layer is not self:
                continue
            for bname, b in layer._buffers.items():
                if b is not None and id(b) not in seen:
                    seen.add(id(b))
                    yield (f"{name}.{bname}" if name else bname), b

    def buffers(self, include_sublayers=True) -> List[Tensor]:
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_children(self) -> Iterator[Tuple[str, "Layer"]]:
        for name, l in self._sub_layers.items():
            if l is not None:
                yield name, l

    def children(self) -> Iterator["Layer"]:
        for _, l in self.named_children():
            yield l

    def named_sublayers(self, prefix="", include_self=False) -> Iterator[Tuple[str, "Layer"]]:
        if include_self:
            yield prefix, self
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            p = f"{prefix}.{name}" if prefix else name
            yield from l.named_sublayers(prefix=p, include_self=True)

    def sublayers(self, include_self=False) -> List["Layer"]:
        return [l for _, l in self.named_sublayers(include_self=include_self)]

    def apply(self, fn):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    # -- state dict --------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        prefix = structured_name_prefix.rstrip(".")
        for name, p in self.named_parameters(prefix=prefix):
            dest[name] = p
        seen = set()
        for lname, layer in self.named_sublayers(prefix=prefix, include_self=True):
            for bname, b in layer._buffers.items():
                if b is None or id(b) in seen:
                    continue
                seen.add(id(b))
                if bname not in layer._non_persistable_buffer_names:
                    dest[f"{lname}.{bname}" if lname else bname] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        """Assign loaded values into existing Parameter/Tensor objects
        (identity-preserving so optimizer references stay valid — the analog
        of the reference's in-place VarBase copy)."""
        missing, unexpected = [], []
        own = self.state_dict()
        for name, t in own.items():
            if name in state_dict:
                v = state_dict[name]
                arr = v._value if isinstance(v, Tensor) else jnp.asarray(np.asarray(v))
                if tuple(arr.shape) != tuple(t._value.shape):
                    raise ValueError(
                        f"shape mismatch for {name}: got {tuple(arr.shape)}, "
                        f"expected {tuple(t._value.shape)}")
                t._set_value(arr.astype(t._value.dtype))
            else:
                missing.append(name)
        for k in state_dict:
            if k not in own:
                unexpected.append(k)
        return missing, unexpected

    load_dict = set_state_dict

    # -- mode / dtype ------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def astype(self, dtype):
        self._cast_all(dtype_mod.convert_dtype(dtype))
        return self

    def _cast_all(self, d, floating_only=True):
        for t in list(self.parameters()) + list(self.buffers()):
            if floating_only and not jnp.issubdtype(t._value.dtype, jnp.floating):
                continue
            t._set_value(t._value.astype(d))
        for l in self.sublayers(include_self=True):
            l._dtype = d

    def float(self):
        return self.astype(jnp.float32)

    def half(self):
        return self.astype(jnp.float16)

    def bfloat16(self):
        return self.astype(jnp.bfloat16)

    # -- hooks -------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        h = _HookHandle(self._forward_pre_hooks)
        self._forward_pre_hooks[h._id] = hook
        return h

    def register_forward_post_hook(self, hook):
        h = _HookHandle(self._forward_post_hooks)
        self._forward_post_hooks[h._id] = hook
        return h

    # -- call --------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    # -- misc --------------------------------------------------------------
    def full_name(self):
        return self._name_scope

    def extra_repr(self):
        return ""

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, l in self._sub_layers.items():
            if l is None:
                continue
            mod_str = repr(l)
            mod_str = "\n".join("  " + line for line in mod_str.split("\n"))
            lines.append(f"({name}): {mod_str.strip()}")
        main = type(self).__name__ + "(" + extra
        if lines:
            main += "\n  " + "\n  ".join(lines) + "\n"
        return main + ")"

    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()


# ---------------------------------------------------------------------------
# Functionalization bridge (the dygraph→jit state substitution)
# ---------------------------------------------------------------------------
class functional_state:
    """Context manager: substitute a flat {name: value} mapping for the
    layer's parameters/buffers, restoring originals on exit. Values may be
    tracers — this is how jitted train steps thread state through a Layer's
    imperative forward."""

    def __init__(self, layer: Layer, values: Dict[str, object]):
        self.layer = layer
        self.values = values
        self._saved = {}

    def _targets(self):
        d = {}
        for name, p in self.layer.named_parameters():
            d[name] = p
        for name, b in self.layer.named_buffers():
            d[name] = b
        return d

    def __enter__(self):
        targets = self._targets()
        for name, v in self.values.items():
            if name not in targets:
                continue
            t = targets[name]
            self._saved[name] = (t, t._value, t._grad_node, t._out_index, t.stop_gradient)
            val = v._value if isinstance(v, Tensor) else v
            t._value = val
            if isinstance(v, Tensor):
                t._grad_node = v._grad_node
                t._out_index = v._out_index
                t.stop_gradient = v.stop_gradient
        return self

    def __exit__(self, *exc):
        for name, (t, val, node, idx, sg) in self._saved.items():
            t._value = val
            t._grad_node = node
            t._out_index = idx
            t.stop_gradient = sg
        return False

    def collect(self):
        """Current {name: raw value} of the layer state (call inside the
        context to harvest traced buffer updates, e.g. BN running stats)."""
        return {name: t._value for name, t in self._targets().items()}


def functional_call(layer: Layer, state: Dict[str, object], *args, **kwargs):
    """Run layer(*args) with `state` substituted; returns (out, new_state)
    where new_state reflects buffer mutations (running stats etc.)."""
    with functional_state(layer, state) as fs:
        out = layer(*args, **kwargs)
        new_state = fs.collect()
    return out, new_state
