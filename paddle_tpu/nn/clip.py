"""Gradient clipping (reference: python/paddle/nn/clip.py).

Clippers operate on (param, grad) lists and are attached to optimizers via
grad_clip=..., same as the reference. In hybrid-parallel runs the fleet
optimizer wraps ClipGradByGlobalNorm to sum norms across mesh axes
(reference hybrid_parallel_optimizer.py:275 _obtain_optimizer_parameters_list).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["ClipGradByValue", "ClipGradByNorm", "ClipGradByGlobalNorm"]


class ClipGradBase:
    def __call__(self, params_grads):
        return self._clip(params_grads)


class ClipGradByValue(ClipGradBase):
    def __init__(self, max, min=None):
        self.max = float(max)
        self.min = float(min) if min is not None else -self.max

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            out.append((p, Tensor(jnp.clip(g._value, self.min, self.max))))
        return out


class ClipGradByNorm(ClipGradBase):
    def __init__(self, clip_norm):
        self.clip_norm = float(clip_norm)

    def _clip(self, params_grads):
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            norm = jnp.sqrt(jnp.sum(jnp.square(g._value.astype(jnp.float32))))
            scale = jnp.minimum(self.clip_norm / jnp.maximum(norm, 1e-12), 1.0)
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out


class ClipGradByGlobalNorm(ClipGradBase):
    def __init__(self, clip_norm, group_name="default_group", auto_skip_clip=False):
        self.clip_norm = float(clip_norm)

    def _global_norm_sq(self, params_grads):
        total = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            total = total + jnp.sum(jnp.square(g._value.astype(jnp.float32)))
        return total

    def _clip(self, params_grads):
        total = self._global_norm_sq(params_grads)
        global_norm = jnp.sqrt(total)
        scale = self.clip_norm / jnp.maximum(global_norm, self.clip_norm)
        out = []
        for p, g in params_grads:
            if g is None:
                out.append((p, g))
                continue
            if hasattr(p, "need_clip") and not p.need_clip:
                out.append((p, g))
                continue
            out.append((p, Tensor((g._value * scale).astype(g._value.dtype))))
        return out
