"""Common layers (reference: python/paddle/nn/layer/common.py)."""
from __future__ import annotations

import jax.numpy as jnp

from .layer import Layer
from . import functional as F
from ..core import dtype as dtype_mod
from ..param_attr import ParamAttr

__all__ = ["Linear", "Dropout", "Dropout2D", "Dropout3D", "AlphaDropout",
           "Embedding", "Flatten", "Upsample", "UpsamplingBilinear2D",
           "UpsamplingNearest2D", "Pad1D", "Pad2D", "Pad3D", "ZeroPad2D",
           "CosineSimilarity", "Bilinear", "Identity", "Unflatten", "Fold",
           "Unfold", "PixelShuffle", "PixelUnshuffle", "ChannelShuffle",
           "LinearLowPrecision"]


class Identity(Layer):
    def __init__(self, *args, **kwargs):
        super().__init__()

    def forward(self, x):
        return x


class Linear(Layer):
    """y = xW + b, weight [in_features, out_features] (reference common.py:90)."""

    def __init__(self, in_features, out_features, weight_attr=None, bias_attr=None,
                 name=None):
        super().__init__()
        self._in_features = in_features
        self._out_features = out_features
        self.weight = self.create_parameter(
            (in_features, out_features), attr=weight_attr)
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return F.linear(x, self.weight, self.bias)

    def extra_repr(self):
        return f"in_features={self._in_features}, out_features={self._out_features}"


LinearLowPrecision = Linear


class Dropout(Layer):
    def __init__(self, p=0.5, axis=None, mode="upscale_in_train", name=None):
        super().__init__()
        self.p = p
        self.axis = axis
        self.mode = mode

    def forward(self, x):
        return F.dropout(x, self.p, axis=self.axis, training=self.training, mode=self.mode)


class Dropout2D(Layer):
    def __init__(self, p=0.5, data_format="NCHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout2d(x, self.p, training=self.training, data_format=self.data_format)


class Dropout3D(Layer):
    def __init__(self, p=0.5, data_format="NCDHW", name=None):
        super().__init__()
        self.p = p
        self.data_format = data_format

    def forward(self, x):
        return F.dropout3d(x, self.p, training=self.training, data_format=self.data_format)


class AlphaDropout(Layer):
    def __init__(self, p=0.5, name=None):
        super().__init__()
        self.p = p

    def forward(self, x):
        return F.alpha_dropout(x, self.p, training=self.training)


class Embedding(Layer):
    """reference common.py Embedding: [num_embeddings, embedding_dim], with
    optional padding_idx and sparse grads (dense scatter-add on TPU)."""

    def __init__(self, num_embeddings, embedding_dim, padding_idx=None,
                 sparse=False, weight_attr=None, name=None):
        super().__init__()
        self._num_embeddings = num_embeddings
        self._embedding_dim = embedding_dim
        self._padding_idx = (padding_idx if padding_idx is None or padding_idx >= 0
                             else num_embeddings + padding_idx)
        from .initializer import Normal
        attr = ParamAttr._to_attr(weight_attr)
        if isinstance(attr, ParamAttr) and attr.initializer is None:
            attr.initializer = Normal(0.0, 1.0)
        self.weight = self.create_parameter((num_embeddings, embedding_dim), attr=attr)
        if self._padding_idx is not None:
            self.weight._set_value(self.weight._value.at[self._padding_idx].set(0.0))

    def forward(self, x):
        return F.embedding(x, self.weight, padding_idx=self._padding_idx)

    def extra_repr(self):
        return f"{self._num_embeddings}, {self._embedding_dim}"


class Flatten(Layer):
    def __init__(self, start_axis=1, stop_axis=-1):
        super().__init__()
        self.start_axis = start_axis
        self.stop_axis = stop_axis

    def forward(self, x):
        from ..tensor.manipulation import flatten
        return flatten(x, self.start_axis, self.stop_axis)


class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = axis
        self.shape = shape

    def forward(self, x):
        from ..tensor.manipulation import reshape
        s = list(x.shape)
        ax = self.axis % len(s)
        new = s[:ax] + list(self.shape) + s[ax + 1:]
        return reshape(x, new)


class Upsample(Layer):
    def __init__(self, size=None, scale_factor=None, mode="nearest",
                 align_corners=False, align_mode=0, data_format=None, name=None):
        super().__init__()
        self.size, self.scale_factor = size, scale_factor
        self.mode, self.align_corners, self.align_mode = mode, align_corners, align_mode
        self.data_format = data_format

    def forward(self, x):
        return F.interpolate(x, self.size, self.scale_factor, self.mode,
                             self.align_corners, self.align_mode, self.data_format)


class UpsamplingNearest2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "nearest", False, 0, data_format)


class UpsamplingBilinear2D(Upsample):
    def __init__(self, size=None, scale_factor=None, data_format="NCHW", name=None):
        super().__init__(size, scale_factor, "bilinear", True, 0, data_format)


class _PadN(Layer):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCHW", name=None):
        super().__init__()
        self.padding, self.mode, self.value = padding, mode, value
        self.data_format = data_format

    def forward(self, x):
        return F.pad(x, self.padding, mode=self.mode, value=self.value,
                     data_format=self.data_format)


class Pad1D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCL", name=None):
        super().__init__(padding, mode, value, data_format)


class Pad2D(_PadN):
    pass


class Pad3D(_PadN):
    def __init__(self, padding, mode="constant", value=0.0, data_format="NCDHW", name=None):
        super().__init__(padding, mode, value, data_format)


class ZeroPad2D(_PadN):
    def __init__(self, padding, data_format="NCHW", name=None):
        super().__init__(padding, "constant", 0.0, data_format)


class CosineSimilarity(Layer):
    def __init__(self, axis=1, eps=1e-8):
        super().__init__()
        self.axis, self.eps = axis, eps

    def forward(self, x1, x2):
        return F.cosine_similarity(x1, x2, self.axis, self.eps)


class Bilinear(Layer):
    def __init__(self, in1_features, in2_features, out_features, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        self.weight = self.create_parameter((out_features, in1_features, in2_features),
                                            attr=weight_attr)
        self.bias = self.create_parameter((out_features,), attr=bias_attr, is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x1, x2):
        return F.bilinear(x1, x2, self.weight, self.bias)


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.a = (output_sizes, kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.fold(x, *self.a)


class Unfold(Layer):
    def __init__(self, kernel_sizes, strides=1, paddings=0, dilations=1, name=None):
        super().__init__()
        self.a = (kernel_sizes, strides, paddings, dilations)

    def forward(self, x):
        return F.unfold(x, *self.a)


class PixelShuffle(Layer):
    def __init__(self, upscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.df = upscale_factor, data_format

    def forward(self, x):
        return F.pixel_shuffle(x, self.r, self.df)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.r, self.df = downscale_factor, data_format

    def forward(self, x):
        return F.pixel_unshuffle(x, self.r, self.df)


class ChannelShuffle(Layer):
    def __init__(self, groups, data_format="NCHW", name=None):
        super().__init__()
        self.g, self.df = groups, data_format

    def forward(self, x):
        return F.channel_shuffle(x, self.g, self.df)
