"""Recurrent layers (reference: python/paddle/nn/layer/rnn.py).

TPU-native design: the time loop is a `lax.scan`, so the whole RNN compiles to
a single fused XLA while-loop (the reference relies on cuDNN RNN kernels).
Input layout follows paddle: [batch, time, size] by default (time_major=False).
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .layer import Layer
from .container import LayerList
from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..param_attr import ParamAttr
from .initializer import Uniform

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNN", "BiRNN",
           "SimpleRNN", "LSTM", "GRU", "RNNCellBase"]


class RNNCellBase(Layer):
    def get_initial_states(self, batch_ref, shape=None, dtype=None,
                           init_value=0.0, batch_dim_idx=0):
        b = batch_ref.shape[batch_dim_idx]
        hs = self.state_shape
        if isinstance(hs[0], (list, tuple)):
            return tuple(Tensor(jnp.full((b,) + tuple(s[1:]) if s[0] == -1 else (b,) + tuple(s),
                                         init_value, jnp.float32)) for s in hs)
        shape = (b, hs[-1]) if hs[0] == -1 else (b,) + tuple(hs)
        return Tensor(jnp.full(shape, init_value, jnp.float32))


def _cell_params(layer, input_size, hidden_size, gates, weight_ih_attr,
                 weight_hh_attr, bias_ih_attr, bias_hh_attr):
    std = 1.0 / math.sqrt(hidden_size)
    def attr_or(a):
        a = ParamAttr._to_attr(a)
        if isinstance(a, ParamAttr) and a.initializer is None:
            a.initializer = Uniform(-std, std)
        return a
    layer.weight_ih = layer.create_parameter((gates * hidden_size, input_size),
                                             attr=attr_or(weight_ih_attr))
    layer.weight_hh = layer.create_parameter((gates * hidden_size, hidden_size),
                                             attr=attr_or(weight_hh_attr))
    layer.bias_ih = layer.create_parameter((gates * hidden_size,),
                                           attr=attr_or(bias_ih_attr), is_bias=True) \
        if bias_ih_attr is not False else None
    layer.bias_hh = layer.create_parameter((gates * hidden_size,),
                                           attr=attr_or(bias_hh_attr), is_bias=True) \
        if bias_hh_attr is not False else None


class SimpleRNNCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, activation="tanh",
                 weight_ih_attr=None, weight_hh_attr=None, bias_ih_attr=None,
                 bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        self.activation = activation
        _cell_params(self, input_size, hidden_size, 1, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (-1, self.hidden_size)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        act = jnp.tanh if self.activation == "tanh" else jax.nn.relu
        def impl(x, h, wih, whh, *biases):
            z = x @ wih.T + h @ whh.T
            for b in biases:
                z = z + b
            return act(z)
        args = [inputs, states, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args += [self.bias_ih, self.bias_hh]
        h = op_call("simple_rnn_cell", impl, *args)
        return h, h


class LSTMCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 proj_size=0, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 4, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return ((-1, self.hidden_size), (-1, self.hidden_size))

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        h0, c0 = states
        def impl(x, h, c, wih, whh, *biases):
            z = x @ wih.T + h @ whh.T
            for b in biases:
                z = z + b
            i, f, g, o = jnp.split(z, 4, axis=-1)
            i, f, o = jax.nn.sigmoid(i), jax.nn.sigmoid(f), jax.nn.sigmoid(o)
            g = jnp.tanh(g)
            c_new = f * c + i * g
            h_new = o * jnp.tanh(c_new)
            return h_new, c_new
        args = [inputs, h0, c0, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args += [self.bias_ih, self.bias_hh]
        h, c = op_call("lstm_cell", impl, *args)
        return h, (h, c)


class GRUCell(RNNCellBase):
    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size, self.hidden_size = input_size, hidden_size
        _cell_params(self, input_size, hidden_size, 3, weight_ih_attr,
                     weight_hh_attr, bias_ih_attr, bias_hh_attr)

    @property
    def state_shape(self):
        return (-1, self.hidden_size)

    def forward(self, inputs, states=None):
        if states is None:
            states = self.get_initial_states(inputs)
        def impl(x, h, wih, whh, *biases):
            bi = biases[0] if biases else 0
            bh = biases[1] if biases else 0
            gi = x @ wih.T + bi
            gh = h @ whh.T + bh
            ri, zi, ni = jnp.split(gi, 3, axis=-1)
            rh, zh, nh = jnp.split(gh, 3, axis=-1)
            r = jax.nn.sigmoid(ri + rh)
            z = jax.nn.sigmoid(zi + zh)
            n = jnp.tanh(ni + r * nh)
            return (1 - z) * n + z * h
        args = [inputs, states, self.weight_ih, self.weight_hh]
        if self.bias_ih is not None:
            args += [self.bias_ih, self.bias_hh]
        h = op_call("gru_cell", impl, *args)
        return h, h


class RNN(Layer):
    """Generic scan-wrapper around a cell (reference rnn.py RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None, **kwargs):
        from ..tensor import manipulation as manip
        x = inputs if self.time_major else manip.transpose(inputs, [1, 0, 2])
        T = x.shape[0]
        if initial_states is None:
            ref = manip.transpose(inputs, [1, 0, 2]) if self.time_major else inputs
            initial_states = self.cell.get_initial_states(ref)
        steps = range(T - 1, -1, -1) if self.is_reverse else range(T)
        outs = [None] * T
        states = initial_states
        for t in steps:
            o, states = self.cell(x[t], states)
            outs[t] = o
        out = manip.stack(outs, axis=0)
        if not self.time_major:
            out = manip.transpose(out, [1, 0, 2])
        return out, states


class BiRNN(Layer):
    def __init__(self, cell_fw, cell_bw, time_major=False):
        super().__init__()
        self.rnn_fw = RNN(cell_fw, False, time_major)
        self.rnn_bw = RNN(cell_bw, True, time_major)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..tensor import manipulation as manip
        s_fw, s_bw = (initial_states if initial_states is not None else (None, None))
        o_fw, st_fw = self.rnn_fw(inputs, s_fw, sequence_length)
        o_bw, st_bw = self.rnn_bw(inputs, s_bw, sequence_length)
        return manip.concat([o_fw, o_bw], axis=-1), (st_fw, st_bw)


class _RNNBase(Layer):
    """Multi-layer (bi)directional RNN driven by lax.scan per layer."""

    MODE = None

    def __init__(self, input_size, hidden_size, num_layers=1, direction="forward",
                 time_major=False, dropout=0.0, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 activation="tanh", name=None):
        super().__init__()
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndir = 2 if self.bidirect else 1
        cells = []
        Cell = {"rnn": SimpleRNNCell, "lstm": LSTMCell, "gru": GRUCell}[self.MODE]
        for layer_i in range(num_layers):
            isize = input_size if layer_i == 0 else hidden_size * ndir
            for _ in range(ndir):
                if self.MODE == "rnn":
                    cells.append(Cell(isize, hidden_size, activation,
                                      weight_ih_attr, weight_hh_attr,
                                      bias_ih_attr, bias_hh_attr))
                else:
                    cells.append(Cell(isize, hidden_size, weight_ih_attr,
                                      weight_hh_attr, bias_ih_attr, bias_hh_attr))
        self.cells = LayerList(cells)

    def forward(self, inputs, initial_states=None, sequence_length=None):
        from ..tensor import manipulation as manip
        from . import functional as F
        ndir = 2 if self.bidirect else 1
        x = inputs
        final_h, final_c = [], []
        b = x.shape[1 if self.time_major else 0]
        for layer_i in range(self.num_layers):
            outs = []
            for d in range(ndir):
                cell = self.cells[layer_i * ndir + d]
                rnn = RNN(cell, is_reverse=(d == 1), time_major=self.time_major)
                init = None
                if initial_states is not None:
                    idx = layer_i * ndir + d
                    if self.MODE == "lstm":
                        h0, c0 = initial_states
                        init = (h0[idx], c0[idx])
                    else:
                        init = initial_states[idx]
                o, st = rnn(x, init)
                outs.append(o)
                if self.MODE == "lstm":
                    final_h.append(st[0])
                    final_c.append(st[1])
                else:
                    final_h.append(st)
            x = outs[0] if ndir == 1 else manip.concat(outs, axis=-1)
            if self.dropout > 0 and layer_i < self.num_layers - 1:
                x = F.dropout(x, self.dropout, training=self.training)
        h = manip.stack(final_h, axis=0)
        if self.MODE == "lstm":
            c = manip.stack(final_c, axis=0)
            return x, (h, c)
        return x, h


class SimpleRNN(_RNNBase):
    MODE = "rnn"


class LSTM(_RNNBase):
    MODE = "lstm"


class GRU(_RNNBase):
    MODE = "gru"
