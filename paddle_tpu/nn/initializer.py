"""Weight initializers (reference: python/paddle/nn/initializer/).

Each initializer is a callable applied to a Parameter in place, drawing from
the framework's seeded key chain so init is reproducible under paddle.seed.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.random import split_key

__all__ = ["Initializer", "Constant", "Normal", "TruncatedNormal", "Uniform",
           "XavierNormal", "XavierUniform", "KaimingNormal", "KaimingUniform",
           "Assign", "Orthogonal", "Dirac", "calculate_gain"]


def calculate_gain(nonlinearity, param=None):
    gains = {"sigmoid": 1.0, "linear": 1.0, "conv1d": 1.0, "conv2d": 1.0,
             "conv3d": 1.0, "conv1d_transpose": 1.0, "conv2d_transpose": 1.0,
             "conv3d_transpose": 1.0, "tanh": 5.0 / 3.0, "relu": math.sqrt(2.0),
             "leaky_relu": math.sqrt(2.0 / (1 + (param if param is not None else 0.01) ** 2)),
             "selu": 3.0 / 4.0}
    if nonlinearity not in gains:
        raise ValueError(f"unsupported nonlinearity {nonlinearity}")
    return gains[nonlinearity]


def _fans(shape):
    shape = tuple(shape)
    if len(shape) == 0:
        return 1, 1
    if len(shape) == 1:
        return shape[0], shape[0]
    if len(shape) == 2:
        return shape[0], shape[1]
    # conv kernels: paddle layout [out_c, in_c, *spatial]
    receptive = int(np.prod(shape[2:]))
    return shape[1] * receptive, shape[0] * receptive


class Initializer:
    def __call__(self, param, block=None):
        v = self._generate(tuple(param.shape), param._value.dtype)
        param._set_value(v)
        return param

    def _generate(self, shape, dtype):
        raise NotImplementedError


class Constant(Initializer):
    def __init__(self, value=0.0):
        self.value = value

    def _generate(self, shape, dtype):
        return jnp.full(shape, self.value, dtype)


class Normal(Initializer):
    def __init__(self, mean=0.0, std=1.0, name=None):
        self.mean, self.std = mean, std

    def _generate(self, shape, dtype):
        return jax.random.normal(split_key(), shape, jnp.float32).astype(dtype) * self.std + self.mean


class TruncatedNormal(Initializer):
    def __init__(self, mean=0.0, std=1.0, a=-2.0, b=2.0, name=None):
        self.mean, self.std, self.a, self.b = mean, std, a, b

    def _generate(self, shape, dtype):
        lo = (self.a - self.mean) / self.std if self.std else -2.0
        hi = (self.b - self.mean) / self.std if self.std else 2.0
        z = jax.random.truncated_normal(split_key(), lo, hi, shape, jnp.float32)
        return (z * self.std + self.mean).astype(dtype)


class Uniform(Initializer):
    def __init__(self, low=-1.0, high=1.0, name=None):
        self.low, self.high = low, high

    def _generate(self, shape, dtype):
        return jax.random.uniform(split_key(), shape, jnp.float32,
                                  self.low, self.high).astype(dtype)


class XavierNormal(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        std = self.gain * math.sqrt(2.0 / (fi + fo))
        return (jax.random.normal(split_key(), shape, jnp.float32) * std).astype(dtype)


class XavierUniform(Initializer):
    def __init__(self, fan_in=None, fan_out=None, gain=1.0, name=None):
        self.fan_in, self.fan_out, self.gain = fan_in, fan_out, gain

    def _generate(self, shape, dtype):
        fi, fo = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        fo = self.fan_out if self.fan_out is not None else fo
        limit = self.gain * math.sqrt(6.0 / (fi + fo))
        return jax.random.uniform(split_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class KaimingNormal(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        std = gain / math.sqrt(fi)
        return (jax.random.normal(split_key(), shape, jnp.float32) * std).astype(dtype)


class KaimingUniform(Initializer):
    def __init__(self, fan_in=None, negative_slope=0.0, nonlinearity="relu", name=None):
        self.fan_in, self.negative_slope, self.nonlinearity = fan_in, negative_slope, nonlinearity

    def _generate(self, shape, dtype):
        fi, _ = _fans(shape)
        fi = self.fan_in if self.fan_in is not None else fi
        gain = calculate_gain(self.nonlinearity, self.negative_slope)
        limit = gain * math.sqrt(3.0 / fi)
        return jax.random.uniform(split_key(), shape, jnp.float32,
                                  -limit, limit).astype(dtype)


class Assign(Initializer):
    def __init__(self, value, name=None):
        self.value = value

    def _generate(self, shape, dtype):
        v = self.value
        if isinstance(v, Tensor):
            v = v._value
        arr = jnp.asarray(np.asarray(v), dtype=dtype).reshape(shape)
        return arr


class Orthogonal(Initializer):
    def __init__(self, gain=1.0, name=None):
        self.gain = gain

    def _generate(self, shape, dtype):
        rows = shape[0]
        cols = int(np.prod(shape[1:])) if len(shape) > 1 else 1
        flat = (max(rows, cols), min(rows, cols))
        a = jax.random.normal(split_key(), flat, jnp.float32)
        q, r = jnp.linalg.qr(a)
        q = q * jnp.sign(jnp.diagonal(r))
        if rows < cols:
            q = q.T
        return (self.gain * q[:rows, :cols]).reshape(shape).astype(dtype)


class Dirac(Initializer):
    def __init__(self, groups=1, name=None):
        self.groups = groups

    def _generate(self, shape, dtype):
        # conv kernel [out_c, in_c, *spatial]: identity-preserving init
        out_c, in_c = shape[0], shape[1]
        spatial = shape[2:]
        v = np.zeros(shape, dtype=np.float32)
        centers = tuple(s // 2 for s in spatial)
        per_group = out_c // self.groups
        for g in range(self.groups):
            for i in range(min(per_group, in_c)):
                idx = (g * per_group + i, i) + centers
                v[idx] = 1.0
        return jnp.asarray(v, dtype=dtype)
