"""Conv layers (reference: python/paddle/nn/layer/conv.py)."""
from __future__ import annotations

import numpy as np

from .layer import Layer
from . import functional as F

__all__ = ["Conv1D", "Conv2D", "Conv3D", "Conv1DTranspose", "Conv2DTranspose",
           "Conv3DTranspose"]


def _ntuple(v, n):
    if isinstance(v, (list, tuple)):
        return tuple(int(x) for x in (v if len(v) == n else [v[0]] * n))
    return tuple(int(v) for _ in range(n))


class _ConvNd(Layer):
    def __init__(self, n, in_channels, out_channels, kernel_size, stride, padding,
                 dilation, groups, padding_mode, weight_attr, bias_attr,
                 data_format, transpose=False, output_padding=0):
        super().__init__()
        self._n = n
        self._in_channels = in_channels
        self._out_channels = out_channels
        self._kernel_size = _ntuple(kernel_size, n)
        self._stride = stride
        self._padding = padding
        self._dilation = dilation
        self._groups = groups
        self._padding_mode = padding_mode
        self._data_format = data_format
        self._transpose = transpose
        self._output_padding = output_padding
        if transpose:
            wshape = (in_channels, out_channels // groups) + self._kernel_size
        else:
            wshape = (out_channels, in_channels // groups) + self._kernel_size
        from .initializer import KaimingUniform, Uniform
        from ..param_attr import ParamAttr
        attr = ParamAttr._to_attr(weight_attr)
        fan_in = in_channels // groups * int(np.prod(self._kernel_size))
        if isinstance(attr, ParamAttr) and attr.initializer is None:
            # paddle conv default: Uniform(-k, k), k = sqrt(1 / fan_in) via
            # XavierUniform on conv fans; mirror of conv.py _get_default_param_initializer
            import math
            k = math.sqrt(1.0 / max(fan_in, 1))
            attr.initializer = Uniform(-k, k)
        self.weight = self.create_parameter(wshape, attr=attr)
        if bias_attr is not False:
            battr = ParamAttr._to_attr(bias_attr)
            if isinstance(battr, ParamAttr) and battr.initializer is None:
                import math
                k = math.sqrt(1.0 / max(fan_in, 1))
                from .initializer import Uniform as U
                battr.initializer = U(-k, k)
            self.bias = self.create_parameter((out_channels,), attr=battr, is_bias=True)
        else:
            self.bias = None

    def extra_repr(self):
        return (f"{self._in_channels}, {self._out_channels}, "
                f"kernel_size={self._kernel_size}, stride={self._stride}")


class Conv1D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv1d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv2D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv2d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv3D(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 dilation=1, groups=1, padding_mode="zeros", weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, padding_mode, weight_attr, bias_attr, data_format)

    def forward(self, x):
        return F.conv3d(x, self.weight, self.bias, self._stride, self._padding,
                        self._dilation, self._groups, self._data_format)


class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCL"):
        super().__init__(1, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, output_size, self._data_format)


class Conv2DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCHW"):
        super().__init__(2, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv2d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, self._data_format, output_size)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1, padding=0,
                 output_padding=0, groups=1, dilation=1, weight_attr=None,
                 bias_attr=None, data_format="NCDHW"):
        super().__init__(3, in_channels, out_channels, kernel_size, stride, padding,
                         dilation, groups, "zeros", weight_attr, bias_attr,
                         data_format, transpose=True, output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding, self._groups,
                                  self._dilation, self._data_format, output_size)
