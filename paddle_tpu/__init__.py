"""paddle_tpu: a TPU-native deep learning framework.

A from-scratch framework with the capabilities of the reference
(PaddlePaddle ~3.0-rc, mounted at /root/reference) re-designed for TPU:
jax/XLA is the kernel library + compiler + async executor, Pallas provides
hand-tuned kernels for the hot ops, and jax.sharding/shard_map over device
meshes provides the distributed layer (DP/TP/PP/ZeRO/SP/EP) that the
reference implements over NCCL.

Public surface mirrors `paddle.*` so reference users can switch directly.
"""
from __future__ import annotations

__version__ = "0.1.0"

# jax version compat (same spirit as ops/pallas/_compat.py): jax <= 0.4.x
# ships shard_map under jax.experimental only; alias it so the parallel /
# distributed layers' `from jax import shard_map` works on the container's
# jax_graft toolchain.
import jax as _jax_mod
if not hasattr(_jax_mod, "shard_map"):
    try:
        from jax.experimental.shard_map import shard_map as _shard_map_impl
        import functools as _functools_mod

        @_functools_mod.wraps(_shard_map_impl)
        def _shard_map_compat(*args, **kwargs):
            # newer jax renamed check_rep -> check_vma
            if "check_vma" in kwargs:
                kwargs["check_rep"] = kwargs.pop("check_vma")
            return _shard_map_impl(*args, **kwargs)

        _jax_mod.shard_map = _shard_map_compat
    except ImportError:  # very old jax: leave the original ImportError path
        pass
if not hasattr(_jax_mod.lax, "pcast"):
    # pcast is a varying-axis TYPE cast (data identity); pre-varying-types
    # jax (check_rep era) needs no cast at all
    _jax_mod.lax.pcast = lambda x, axes, to="varying": x
if not hasattr(_jax_mod.lax, "axis_size"):
    def _axis_size_compat(axis_name):
        from jax._src import core as _core
        frame = _core.axis_frame(axis_name)  # older jax returns the int
        return getattr(frame, "size", frame)
    _jax_mod.lax.axis_size = _axis_size_compat

from . import flags as _flags_mod
from .flags import get_flags, set_flags

from .core import dtype as _dtype
from .core.dtype import (
    bool_, uint8, int8, int16, int32, int64, float16, bfloat16, float32,
    float64, complex64, complex128, float8_e4m3fn, float8_e5m2,
    set_default_dtype, get_default_dtype,
)
from .core.tensor import Tensor, Parameter, to_tensor, is_tensor
from .core.dispatch import no_grad, enable_grad, is_grad_enabled, set_grad_enabled
from .core import autograd as _autograd_core
from .core.autograd import grad
from .core.random import seed, get_rng_state, set_rng_state
from .core.device import (
    set_device, get_device, device_count, is_compiled_with_cuda,
    is_compiled_with_tpu, is_compiled_with_rocm, is_compiled_with_xpu,
    is_compiled_with_distribute,
)

from .tensor import *  # noqa: F401,F403 — functional op surface
from . import tensor  # noqa: F401

from . import autograd  # noqa: F401
from . import device  # noqa: F401
from .framework import save, load, CPUPlace, TPUPlace, CUDAPlace, in_dynamic_mode  # noqa: F401

# Subsystems (each lands with its build stage; see SURVEY.md §7)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import amp  # noqa: F401
from . import io  # noqa: F401
from . import jit  # noqa: F401
from . import distributed  # noqa: F401
from . import ops  # noqa: F401
from .ops.pallas import register_all as _register_pallas_kernels
# TPU-only; deferred to first kernel lookup because probing jax.devices()
# here would initialise the XLA backend before a multi-process launch can
# call jax.distributed.initialize (distributed/env.py)
from .core import dispatch as _dispatch_mod
_dispatch_mod.add_lazy_initializer(_register_pallas_kernels)
from . import vision  # noqa: F401
from . import incubate  # noqa: F401
from . import metric  # noqa: F401
from . import profiler  # noqa: F401
from . import hapi  # noqa: F401
from . import inference  # noqa: F401
from . import resilience  # noqa: F401
from .hapi import Model, summary, flops  # noqa: F401
from . import static  # noqa: F401
from . import sparse  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import distribution  # noqa: F401
from . import geometric  # noqa: F401
from . import regularizer  # noqa: F401
from . import onnx  # noqa: F401
from . import quantization  # noqa: F401
from . import version  # noqa: F401

from .nn.layer import Layer  # convenience re-export used widely in reference code
from .distributed.parallel import DataParallel  # noqa: F401


def disable_static(place=None):
    """Dygraph is the default (and only) eager mode; accepted for compat."""


def enable_static():
    """Static-graph building is expressed via paddle_tpu.jit/static."""


def disable_signal_handler():
    pass


def in_dynamic_or_pir_mode():
    return True
