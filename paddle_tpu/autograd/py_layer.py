"""PyLayer: user-defined forward/backward (reference:
python/paddle/autograd/py_layer.py, C++ side paddle/fluid/eager/pylayer/).

TPU-native design: the user's static forward/backward become the fwd/bwd of
the recorded GradNode directly — the tape calls `backward` with upstream
grads, so arbitrary Python (including non-jax code) is allowed in eager mode;
under jit tracing both fwd and bwd must be traceable.
"""
from __future__ import annotations

from typing import Any, List

import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.autograd import GradNode
from ..core.dispatch import is_grad_enabled

__all__ = ["PyLayer", "PyLayerContext"]


class PyLayerContext:
    def __init__(self):
        self._saved = ()
        self._materialize_grads = True
        self.not_inplace_tensors = ()

    def save_for_backward(self, *tensors):
        self._saved = tuple(tensors)

    @property
    def saved_tensor(self):
        return self._saved

    def saved_tensors(self):
        return self._saved

    def mark_not_inplace(self, *tensors):
        self.not_inplace_tensors = tuple(tensors)

    def set_materialize_grads(self, value: bool):
        self._materialize_grads = bool(value)


class _PyLayerNodeVjp:
    """Adapter giving a PyLayer's backward the GradNode vjp_fn interface."""

    def __init__(self, cls, ctx, n_diff_inputs):
        self.cls = cls
        self.ctx = ctx
        self.n = n_diff_inputs

    def __call__(self, cotangents):
        cts = cotangents if isinstance(cotangents, tuple) else (cotangents,)
        grads = self.cls.backward(self.ctx, *[Tensor(c) for c in cts])
        if not isinstance(grads, (tuple, list)):
            grads = (grads,)
        out = []
        for g in grads:
            if g is None:
                out.append(jnp.zeros(()))  # dropped below via float0-like skip
            else:
                out.append(g._value if isinstance(g, Tensor) else g)
        return tuple(out[: self.n])


class PyLayer:
    """Subclass with @staticmethod forward(ctx, *args) and backward(ctx, *grads).

    Example (identity with scaled grad):
        class Scale(PyLayer):
            @staticmethod
            def forward(ctx, x):
                return x
            @staticmethod
            def backward(ctx, dy):
                return 2 * dy
    """

    @staticmethod
    def forward(ctx, *args, **kwargs):
        raise NotImplementedError

    @staticmethod
    def backward(ctx, *args):
        raise NotImplementedError

    @classmethod
    def apply(cls, *args, **kwargs):
        ctx = PyLayerContext()
        outs = cls.forward(ctx, *args, **kwargs)
        single = not isinstance(outs, (tuple, list))
        outs_t = (outs,) if single else tuple(outs)

        diff_inputs = [a for a in args if isinstance(a, Tensor)]
        need_grad = is_grad_enabled() and any(not t.stop_gradient for t in diff_inputs)
        if need_grad:
            tensor_outs = [o for o in outs_t if isinstance(o, Tensor)]
            node = GradNode(
                name=f"pylayer_{cls.__name__}",
                vjp_fn=_PyLayerNodeVjp(cls, ctx, len(diff_inputs)),
                inputs=diff_inputs,
                out_avals=[(tuple(o.shape), o._value.dtype) for o in tensor_outs],
                multi=len(tensor_outs) > 1,
            )
            for k, o in enumerate(tensor_outs):
                o.stop_gradient = False
                o._grad_node = node
                o._out_index = k
                node.attach_output(k, o)
        return outs_t[0] if single else outs_t
