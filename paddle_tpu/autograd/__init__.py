"""Autograd public API (reference: python/paddle/autograd/)."""
from __future__ import annotations

from ..core.autograd import backward, grad
from ..core.dispatch import no_grad, enable_grad, is_grad_enabled, set_grad_enabled
from .py_layer import PyLayer, PyLayerContext
from .saved_tensors_hooks import saved_tensors_hooks

__all__ = ["backward", "grad", "no_grad", "enable_grad", "is_grad_enabled",
           "set_grad_enabled", "PyLayer", "PyLayerContext", "saved_tensors_hooks"]
