"""saved_tensors_hooks parity (reference python/paddle/autograd/saved_tensors_hooks.py).

On TPU the residuals live inside jax.vjp closures; the hook pair is applied to
PyLayer ctx.save_for_backward tensors (the user-visible saved-tensor path).
Registered globally; pack runs at save time, unpack at backward time.
"""
from __future__ import annotations

_hooks_stack = []


class saved_tensors_hooks:
    def __init__(self, pack_hook, unpack_hook):
        self.pack_hook = pack_hook
        self.unpack_hook = unpack_hook

    def __enter__(self):
        _hooks_stack.append((self.pack_hook, self.unpack_hook))
        return self

    def __exit__(self, *exc):
        _hooks_stack.pop()
        return False


def current_hooks():
    return _hooks_stack[-1] if _hooks_stack else None
