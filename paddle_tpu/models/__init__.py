"""Model zoo (the PaddleNLP/PaddleMIX-config analog for the benchmark set)."""
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, build_functional_llama  # noqa: F401
