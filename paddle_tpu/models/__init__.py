"""Model zoo (the PaddleNLP/PaddleMIX-config analog for the BASELINE set:
LLaMA #4, ERNIE #3, SD UNet #5; ResNet/ViT live in vision.models)."""
from . import llama  # noqa: F401
from .llama import LlamaConfig, LlamaForCausalLM, build_functional_llama  # noqa: F401
from . import ernie  # noqa: F401
from .ernie import ErnieConfig, ErnieModel, ErnieForMaskedLM  # noqa: F401
from . import unet  # noqa: F401
from .unet import UNetConfig, UNet2DConditionModel  # noqa: F401
