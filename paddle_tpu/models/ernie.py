"""ERNIE model family (BASELINE.json config #3: ERNIE-3.0 base MLM pretrain,
sharding stage-2).

Reference: PaddleNLP's ErnieModel (transformer encoder, learned positions,
token-type embeddings, post-LN) — the reference repo ships the framework it
trains on; the architecture here follows the public ERNIE-3.0-base config.
Built entirely from framework layers (nn.TransformerEncoder path) so it
exercises the encoder stack the way vision/ViT exercises it.
"""
from __future__ import annotations

from dataclasses import dataclass

import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn import Linear, Embedding, LayerNorm, Dropout, LayerList
from ..nn import functional as F
from ..tensor import manipulation as manip

__all__ = ["ErnieConfig", "ErnieModel", "ErnieForMaskedLM",
           "ErnieForSequenceClassification", "ernie_config_base",
           "ernie_config_tiny"]


@dataclass
class ErnieConfig:
    vocab_size: int = 40000
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    hidden_act: str = "gelu"
    hidden_dropout_prob: float = 0.1
    attention_probs_dropout_prob: float = 0.1
    max_position_embeddings: int = 2048
    type_vocab_size: int = 4
    layer_norm_eps: float = 1e-12
    pad_token_id: int = 0


def ernie_config_base():
    return ErnieConfig()


def ernie_config_tiny(vocab=1000, hidden=64, layers=2, heads=4, seq=64):
    return ErnieConfig(vocab_size=vocab, hidden_size=hidden,
                       num_hidden_layers=layers, num_attention_heads=heads,
                       intermediate_size=hidden * 4,
                       max_position_embeddings=seq, hidden_dropout_prob=0.0,
                       attention_probs_dropout_prob=0.0)


class ErnieEmbeddings(Layer):
    def __init__(self, c: ErnieConfig):
        super().__init__()
        self.word_embeddings = Embedding(c.vocab_size, c.hidden_size)
        self.position_embeddings = Embedding(c.max_position_embeddings,
                                             c.hidden_size)
        self.token_type_embeddings = Embedding(c.type_vocab_size, c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        import paddle_tpu as paddle
        B, S = input_ids.shape
        if position_ids is None:
            position_ids = paddle.to_tensor(
                jnp.broadcast_to(jnp.arange(S, dtype=jnp.int32), (B, S)))
        if token_type_ids is None:
            token_type_ids = paddle.to_tensor(
                jnp.zeros((B, S), jnp.int32))
        x = (self.word_embeddings(input_ids)
             + self.position_embeddings(position_ids)
             + self.token_type_embeddings(token_type_ids))
        return self.dropout(self.layer_norm(x))


class ErnieSelfAttention(Layer):
    def __init__(self, c: ErnieConfig):
        super().__init__()
        self.num_heads = c.num_attention_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.q = Linear(c.hidden_size, c.hidden_size)
        self.k = Linear(c.hidden_size, c.hidden_size)
        self.v = Linear(c.hidden_size, c.hidden_size)
        self.out = Linear(c.hidden_size, c.hidden_size)
        self.dropout_p = c.attention_probs_dropout_prob

    def forward(self, x, attn_mask=None):
        b, s, _ = x.shape
        q = manip.reshape(self.q(x), [b, s, self.num_heads, self.head_dim])
        k = manip.reshape(self.k(x), [b, s, self.num_heads, self.head_dim])
        v = manip.reshape(self.v(x), [b, s, self.num_heads, self.head_dim])
        o = F.scaled_dot_product_attention(
            q, k, v, attn_mask=attn_mask, dropout_p=self.dropout_p,
            is_causal=False, training=self.training)
        return self.out(manip.reshape(o, [b, s, -1]))


class ErnieLayer(Layer):
    """Post-LN encoder block (BERT/ERNIE convention)."""

    def __init__(self, c: ErnieConfig):
        super().__init__()
        self.attention = ErnieSelfAttention(c)
        self.norm1 = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.fc1 = Linear(c.hidden_size, c.intermediate_size)
        self.fc2 = Linear(c.intermediate_size, c.hidden_size)
        self.norm2 = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.dropout = Dropout(c.hidden_dropout_prob)
        self.act = getattr(F, c.hidden_act)

    def forward(self, x, attn_mask=None):
        x = self.norm1(x + self.dropout(self.attention(x, attn_mask)))
        h = self.fc2(self.act(self.fc1(x)))
        return self.norm2(x + self.dropout(h))


class ErnieModel(Layer):
    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.config = config
        self.embeddings = ErnieEmbeddings(config)
        self.encoder = LayerList([ErnieLayer(config)
                                  for _ in range(config.num_hidden_layers)])
        self.pooler = Linear(config.hidden_size, config.hidden_size)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and attention_mask.ndim == 2:
            # [B, S] padding mask -> additive [B, 1, 1, S]
            import paddle_tpu as paddle
            m = (1.0 - attention_mask.astype("float32")) * -1e4
            attention_mask = manip.reshape(m, [m.shape[0], 1, 1, m.shape[1]])
        x = self.embeddings(input_ids, token_type_ids, position_ids)
        for layer in self.encoder:
            x = layer(x, attention_mask)
        pooled = F.tanh(self.pooler(x[:, 0]))
        return x, pooled


class ErnieForMaskedLM(Layer):
    """MLM head (the BASELINE pretrain objective)."""

    def __init__(self, config: ErnieConfig):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.config = config
        c = config
        self.transform = Linear(c.hidden_size, c.hidden_size)
        self.layer_norm = LayerNorm(c.hidden_size, epsilon=c.layer_norm_eps)
        self.decoder = Linear(c.hidden_size, c.vocab_size)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None,
                labels=None, ignore_index=-100, return_logits=False):
        """With labels, returns (loss, logits_or_None).

        The training loss runs through the vocab-chunked online-logsumexp
        head (the same chunked-CE design that broke the LLaMA perf plateau,
        PERF.md §3): the [B, S, V] logits tensor never materializes, and the
        second element of the return is **None** — a deliberate departure
        from the reference's (loss, prediction_scores) contract, because
        materializing 40k-vocab logits nobody reads is exactly the HBM
        traffic the head removes.  Callers that need the scores pass
        `return_logits=True` to get the dense head + dense CE (identical
        loss to f32 accumulation, reference-shaped return)."""
        seq, _ = self.ernie(input_ids, token_type_ids,
                            attention_mask=attention_mask)
        h = self.layer_norm(F.gelu(self.transform(seq)))
        if labels is not None:
            if return_logits:
                logits = self.decoder(h)
                loss = F.cross_entropy(
                    manip.reshape(logits, [-1, self.config.vocab_size]),
                    manip.reshape(labels, [-1]), ignore_index=ignore_index)
                return loss, logits
            from ..incubate.nn import functional as IF
            loss = IF.fused_linear_cross_entropy(
                h, self.decoder.weight, labels, n_chunks=8,
                bias=self.decoder.bias, ignore_index=ignore_index)
            return loss, None
        return self.decoder(h)


class ErnieForSequenceClassification(Layer):
    def __init__(self, config: ErnieConfig, num_classes=2):
        super().__init__()
        self.ernie = ErnieModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, num_classes)

    def forward(self, input_ids, token_type_ids=None, attention_mask=None):
        _, pooled = self.ernie(input_ids, token_type_ids,
                               attention_mask=attention_mask)
        return self.classifier(self.dropout(pooled))
