"""LLaMA model family (BASELINE.json config #4: LLaMA-2 7B/13B TP+PP).

Two forms:
 * `LlamaForCausalLM` — eager Layer (dygraph parity; PaddleNLP-style config),
   using the framework attention dispatch (Pallas flash-attn override) and
   optional fleet TP layers when mp_degree > 1.
 * `build_functional_llama` — pure param-pytree + apply fns matching
   paddle_tpu.parallel.PipelineTrainStep's (embed, block, head) contract,
   used by the hybrid dp×pp×mp compiled train step, bench.py, and
   __graft_entry__.dryrun_multichip.
"""
from __future__ import annotations

import functools
import math
from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor, Parameter
from ..nn.layer import Layer
from ..nn import Linear, Embedding, RMSNorm, LayerList
from ..nn import functional as F
from ..tensor import manipulation as manip
from ..incubate.nn.functional import fused_rotary_position_embedding

__all__ = ["LlamaConfig", "LlamaForCausalLM", "LlamaModel", "LlamaDecoderLayer",
           "build_functional_llama", "llama_microbatch_fns", "llama_block_specs",
           "llama_config_7b", "llama_config_tiny", "build_llama_decode",
           "build_llama_paged_decode", "make_paged_decode_horizon",
           "functional_params_from_layer", "llama_generate",
           "gather_kv_pages", "scatter_kv_pages"]


@dataclass
class LlamaConfig:
    vocab_size: int = 32000
    hidden_size: int = 4096
    intermediate_size: int = 11008
    num_hidden_layers: int = 32
    num_attention_heads: int = 32
    num_key_value_heads: int = 32
    max_position_embeddings: int = 4096
    rms_norm_eps: float = 1e-5
    rope_theta: float = 10000.0
    tie_word_embeddings: bool = False
    use_flash_attention: bool = True
    tensor_parallel_degree: int = 1
    dtype: str = "float32"
    # MoE variant (LLaMA-MoE / Mixtral-style): num_experts > 1 swaps the
    # dense MLP for a MoELayer of per-expert SwiGLU FFNs
    num_experts: int = 1
    moe_topk: int = 2
    moe_capacity_factor: float = 1.25
    moe_aux_loss_weight: float = 0.01


def llama_config_7b():
    return LlamaConfig()


def llama_config_tiny(vocab=1024, hidden=128, layers=2, heads=4, seq=128):
    return LlamaConfig(vocab_size=vocab, hidden_size=hidden,
                      intermediate_size=hidden * 3, num_hidden_layers=layers,
                      num_attention_heads=heads, num_key_value_heads=heads,
                      max_position_embeddings=seq)


def _rope_tables(seq_len, head_dim, theta, dtype=jnp.float32):
    inv = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    pos = jnp.arange(seq_len, dtype=jnp.float32)
    freqs = jnp.outer(pos, inv)
    emb = jnp.concatenate([freqs, freqs], axis=-1)
    return jnp.sin(emb).astype(dtype), jnp.cos(emb).astype(dtype)


def _apply_rope(x, sin, cos):
    # x: [B, S, H, D]; sin/cos: [S, D]
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    rot = jnp.concatenate([-x2, x1], axis=-1)
    return x * cos[None, :, None, :] + rot * sin[None, :, None, :]


class LlamaAttention(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        self.num_heads = c.num_attention_heads
        self.num_kv = c.num_key_value_heads
        self.head_dim = c.hidden_size // c.num_attention_heads
        self.config = c
        tp = c.tensor_parallel_degree
        if tp > 1:
            from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                                           RowParallelLinear)
            self.q_proj = ColumnParallelLinear(c.hidden_size,
                                               self.num_heads * self.head_dim,
                                               has_bias=False, gather_output=False)
            self.k_proj = ColumnParallelLinear(c.hidden_size,
                                               self.num_kv * self.head_dim,
                                               has_bias=False, gather_output=False)
            self.v_proj = ColumnParallelLinear(c.hidden_size,
                                               self.num_kv * self.head_dim,
                                               has_bias=False, gather_output=False)
            self.o_proj = RowParallelLinear(self.num_heads * self.head_dim,
                                            c.hidden_size, has_bias=False,
                                            input_is_parallel=True)
        else:
            self.q_proj = Linear(c.hidden_size, self.num_heads * self.head_dim,
                                 bias_attr=False)
            self.k_proj = Linear(c.hidden_size, self.num_kv * self.head_dim,
                                 bias_attr=False)
            self.v_proj = Linear(c.hidden_size, self.num_kv * self.head_dim,
                                 bias_attr=False)
            self.o_proj = Linear(self.num_heads * self.head_dim, c.hidden_size,
                                 bias_attr=False)

    def forward(self, x, sin=None, cos=None):
        b, s, _ = x.shape
        q = manip.reshape(self.q_proj(x), [b, s, -1, self.head_dim])
        k = manip.reshape(self.k_proj(x), [b, s, -1, self.head_dim])
        v = manip.reshape(self.v_proj(x), [b, s, -1, self.head_dim])
        if sin is not None:
            from ..core.dispatch import op_call
            q = op_call("rope", lambda qq: _apply_rope(qq, sin, cos), q)
            k = op_call("rope", lambda kk: _apply_rope(kk, sin, cos), k)
        # GQA KV heads pass through un-repeated: the Pallas kernel indexes
        # them natively; the jnp fallback up-materializes internally
        out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                             training=self.training)
        out = manip.reshape(out, [b, s, -1])
        return self.o_proj(out)


class LlamaMLP(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        c = config
        tp = c.tensor_parallel_degree
        if tp > 1:
            from ..distributed.fleet.meta_parallel import (ColumnParallelLinear,
                                                           RowParallelLinear)
            self.gate_proj = ColumnParallelLinear(c.hidden_size, c.intermediate_size,
                                                  has_bias=False, gather_output=False)
            self.up_proj = ColumnParallelLinear(c.hidden_size, c.intermediate_size,
                                                has_bias=False, gather_output=False)
            self.down_proj = RowParallelLinear(c.intermediate_size, c.hidden_size,
                                               has_bias=False, input_is_parallel=True)
        else:
            self.gate_proj = Linear(c.hidden_size, c.intermediate_size, bias_attr=False)
            self.up_proj = Linear(c.hidden_size, c.intermediate_size, bias_attr=False)
            self.down_proj = Linear(c.intermediate_size, c.hidden_size, bias_attr=False)

    def forward(self, x):
        return self.down_proj(F.swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaMoEBlock(Layer):
    """Mixtral/LLaMA-MoE-style sparse MLP: MoELayer over per-expert SwiGLU
    FFNs (expert-parallel-ready via incubate moe; dense eager here)."""

    def __init__(self, config: LlamaConfig):
        super().__init__()
        if config.tensor_parallel_degree > 1:
            raise NotImplementedError(
                "LlamaMoEBlock: tensor-parallel experts are not implemented "
                "— use expert parallelism (incubate moe ep_axis / moe_ffn "
                "over an 'ep' mesh axis) instead of mp for the MoE variant")
        from ..incubate.distributed.models.moe import MoELayer

        class _Expert(Layer):
            def __init__(self, c):
                super().__init__()
                self.gate_proj = Linear(c.hidden_size, c.intermediate_size,
                                        bias_attr=False)
                self.up_proj = Linear(c.hidden_size, c.intermediate_size,
                                      bias_attr=False)
                self.down_proj = Linear(c.intermediate_size, c.hidden_size,
                                        bias_attr=False)

            def forward(self, x):
                return self.down_proj(
                    F.swiglu(self.gate_proj(x), self.up_proj(x)))

        self.moe = MoELayer(
            d_model=config.hidden_size,
            experts=[_Expert(config) for _ in range(config.num_experts)],
            gate={"type": "gshard", "top_k": config.moe_topk},
            capacity_factor=config.moe_capacity_factor)

    def forward(self, x):
        return self.moe(x)

    def aux_loss(self):
        l = self.moe.gate.get_loss()
        return l


class LlamaDecoderLayer(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.self_attn = LlamaAttention(config)
        self.post_attention_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.mlp = LlamaMoEBlock(config) if config.num_experts > 1 \
            else LlamaMLP(config)

    def forward(self, x, sin=None, cos=None):
        x = x + self.self_attn(self.input_layernorm(x), sin, cos)
        x = x + self.mlp(self.post_attention_layernorm(x))
        return x


class LlamaModel(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        if config.tensor_parallel_degree > 1:
            from ..distributed.fleet.meta_parallel import VocabParallelEmbedding
            self.embed_tokens = VocabParallelEmbedding(config.vocab_size,
                                                       config.hidden_size)
        else:
            self.embed_tokens = Embedding(config.vocab_size, config.hidden_size)
        self.layers = LayerList([LlamaDecoderLayer(config)
                                 for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        head_dim = config.hidden_size // config.num_attention_heads
        sin, cos = _rope_tables(config.max_position_embeddings, head_dim,
                                config.rope_theta)
        self._sin, self._cos = sin, cos

    def forward(self, input_ids):
        x = self.embed_tokens(input_ids)
        s = x.shape[1]
        sin, cos = self._sin[:s], self._cos[:s]
        for layer in self.layers:
            x = layer(x, sin, cos)
        return self.norm(x)


class LlamaForCausalLM(Layer):
    def __init__(self, config: LlamaConfig):
        super().__init__()
        self.config = config
        self.llama = self.model = LlamaModel(config)
        self.lm_head = Linear(config.hidden_size, config.vocab_size, bias_attr=False)
        if config.tie_word_embeddings:
            self.lm_head.weight = self.model.embed_tokens.weight

    def generate(self, input_ids, max_new_tokens=32, temperature=0.0,
                 top_k=0, top_p=1.0, eos_token_id=None, seed=0):
        """Compiled KV-cache generation (PaddleNLP model.generate analog):
        exports this Layer's weights to the functional decode path once and
        decodes with a jitted per-token step."""
        if self.config.tensor_parallel_degree > 1:
            raise NotImplementedError("generate() needs full weights on this "
                                      "host (tensor_parallel_degree == 1)")
        if self.config.num_experts > 1:
            raise NotImplementedError(
                "generate() does not support the MoE variant — the functional "
                "decode path computes the dense FFN")
        # re-export per call: weights may have trained since the last one
        params = functional_params_from_layer(self)
        ids = input_ids._value if isinstance(input_ids, Tensor) \
            else jnp.asarray(input_ids)
        out = llama_generate(params, self.config, ids,
                             max_new_tokens=max_new_tokens,
                             temperature=temperature, top_k=top_k,
                             top_p=top_p, eos_token_id=eos_token_id,
                             seed=seed)
        return Tensor(out)

    def forward(self, input_ids, labels=None):
        h = self.model(input_ids)
        logits = self.lm_head(h)
        if labels is not None:
            loss = F.cross_entropy(
                manip.reshape(logits, [-1, self.config.vocab_size]),
                manip.reshape(labels, [-1]))
            if self.config.num_experts > 1:
                # collect per-layer MoE balance losses (Mixtral aux loss)
                for layer in self.model.layers:
                    aux = layer.mlp.aux_loss()
                    if aux is not None:
                        loss = loss + self.config.moe_aux_loss_weight * aux
            return loss, logits
        return logits


# ---------------------------------------------------------------------------
# Functional form (pipeline/bench path)
# ---------------------------------------------------------------------------
def llama_block_specs(mp_axis: str = "mp", moe: bool = False,
                      ep_axis: str = None):
    """Per-leaf PartitionSpec suffixes (excluding the leading layer dim) for
    Megatron-style tensor parallelism over `mp_axis`:

      wq/wk/wv, wgate/wup: column-parallel (output dim sharded over mp)
      wo, wdown:           row-parallel (input dim sharded, psum after)
      ln1/ln2:             replicated

    With moe=True the FFN leaves are the expert-stacked tensors; ep_axis
    shards their expert dim (expert parallelism — reference moe_layer.py).

    Reference: fleet/layers/mpu/mp_layers.py:336 (ColumnParallelLinear),
    :543 (RowParallelLinear) — here the sharded matmuls live inside the
    pipeline stage function (block_apply) as rank-local dots + lax.psum.
    """
    col = (None, mp_axis)
    row = (mp_axis, None)
    specs = {"ln1": (None,), "wq": col, "wk": col, "wv": col, "wo": row,
             "ln2": (None,)}
    if moe:
        exp = (ep_axis, None, None)
        specs.update({"gate_w": (None, None), "we_gate": exp, "we_up": exp,
                      "we_down": exp})
    else:
        specs.update({"wgate": col, "wup": col, "wdown": row})
    return specs


def llama_microbatch_fns(config: LlamaConfig, mp_axis: str = None, dtype=None,
                         ep_axis: str = None):
    """Per-microbatch (embed, block, head) adapters for the pipeline schedule
    step fns (Pipeline1F1BTrainStep et al.), without initializing a second
    parameter set: embed returns one [mbs, S, H] microbatch, head consumes a
    single microbatch activation."""
    _, _, _, ea1, ba1, hl1 = build_functional_llama(
        config, n_micro=1, mp_axis=mp_axis, ep_axis=ep_axis, dtype=dtype,
        init_params=False)
    embed_mb = lambda p, mb: ea1(p, mb)[0]
    head_mb = lambda p, y, mb: hl1(p, y[None], mb)
    return embed_mb, ba1, head_mb


def build_functional_llama(config: LlamaConfig, key=None, dtype=None,
                           n_micro: int = 1, mp_axis: str = None,
                           ep_axis: str = None, init_params: bool = True,
                           head_chunks: int = 0):
    """Returns (embed_params, block_params_stacked, head_params,
    embed_apply, block_apply, head_loss_apply).

    block_params leaves have leading dim num_hidden_layers (stackable over
    'pp'). batch = (input_ids[B,S], labels[B,S]); embed_apply splits B into
    n_micro microbatches.

    When mp_axis is set, block_apply is tensor-parallel over that mesh axis:
    it must then run inside shard_map with `mp_axis` in scope and with block
    weights sharded per `llama_block_specs(mp_axis)` (column-parallel QKV and
    gate/up, row-parallel wo/wdown followed by lax.psum over mp_axis).  The
    per-rank head counts are derived from the *local* weight shard shapes, so
    the same block_apply works sharded and unsharded.  Requires
    num_attention_heads % mp == 0 and num_key_value_heads % mp == 0.
    """
    c = config
    d = jnp.dtype(dtype) if dtype is not None else jnp.float32
    key = key if key is not None else jax.random.PRNGKey(0)
    head_dim = c.hidden_size // c.num_attention_heads
    ks = jax.random.split(key, 16)

    def init(k, shape, scale=None):
        scale = scale if scale is not None else 1.0 / math.sqrt(shape[0])
        return (jax.random.normal(k, shape, jnp.float32) * scale).astype(d)

    L = c.num_hidden_layers
    kv_dim = c.num_key_value_heads * head_dim
    moe = c.num_experts > 1
    E = c.num_experts
    if not init_params:
        embed_params = block_params = head_params = None
    else:
        embed_params = {"tok": init(ks[0], (c.vocab_size, c.hidden_size), 0.02)}
        block_params = {
            "ln1": jnp.ones((L, c.hidden_size), d),
            "wq": jnp.stack([init(jax.random.fold_in(ks[1], i),
                                  (c.hidden_size, c.hidden_size)) for i in range(L)]),
            "wk": jnp.stack([init(jax.random.fold_in(ks[2], i),
                                  (c.hidden_size, kv_dim)) for i in range(L)]),
            "wv": jnp.stack([init(jax.random.fold_in(ks[3], i),
                                  (c.hidden_size, kv_dim)) for i in range(L)]),
            "wo": jnp.stack([init(jax.random.fold_in(ks[4], i),
                                  (c.hidden_size, c.hidden_size)) for i in range(L)]),
            "ln2": jnp.ones((L, c.hidden_size), d),
        }
        if moe:
            # expert-stacked FFN (LLaMA-MoE / Mixtral; ep-shardable on dim 1)
            block_params.update({
                "gate_w": jnp.stack([init(jax.random.fold_in(ks[9], i),
                                          (c.hidden_size, E), 0.02)
                                     for i in range(L)]),
                "we_gate": jnp.stack([init(jax.random.fold_in(ks[5], i),
                                           (E, c.hidden_size,
                                            c.intermediate_size),
                                           1.0 / math.sqrt(c.hidden_size))
                                      for i in range(L)]),
                "we_up": jnp.stack([init(jax.random.fold_in(ks[6], i),
                                         (E, c.hidden_size,
                                          c.intermediate_size),
                                         1.0 / math.sqrt(c.hidden_size))
                                    for i in range(L)]),
                "we_down": jnp.stack([init(jax.random.fold_in(ks[7], i),
                                           (E, c.intermediate_size,
                                            c.hidden_size),
                                           1.0 / math.sqrt(c.intermediate_size))
                                      for i in range(L)]),
            })
        else:
            block_params.update({
                "wgate": jnp.stack([init(jax.random.fold_in(ks[5], i),
                                         (c.hidden_size, c.intermediate_size)) for i in range(L)]),
                "wup": jnp.stack([init(jax.random.fold_in(ks[6], i),
                                       (c.hidden_size, c.intermediate_size)) for i in range(L)]),
                "wdown": jnp.stack([init(jax.random.fold_in(ks[7], i),
                                         (c.intermediate_size, c.hidden_size)) for i in range(L)]),
            })
        head_params = {"ln_f": jnp.ones((c.hidden_size,), d),
                       "lm": init(ks[8], (c.hidden_size, c.vocab_size), 0.02)}

    sin_t, cos_t = _rope_tables(c.max_position_embeddings, head_dim, c.rope_theta, d)

    def rms(x, w, eps=c.rms_norm_eps):
        from ..core.dispatch import get_kernel
        from ..nn.functional.norm import rms_norm_ref
        impl = get_kernel("rms_norm")
        if impl is not None:
            return impl(x, w, epsilon=eps)
        return rms_norm_ref(x, w, eps)

    def embed_apply(p, batch):
        ids, labels = batch
        # [B, S] -> [n_micro, mbs, S, H]
        x = p["tok"][ids]
        B = x.shape[0]
        mbs = B // n_micro
        return x.reshape((n_micro, mbs) + x.shape[1:])

    def _mp_reduce(y):
        # row-parallel epilogue: sum partials across mp ranks, then restore
        # the manual-varying type (psum strips mp from the vma set, but the
        # residual stream it is added to is varying over mp)
        if mp_axis is None:
            return y
        y = jax.lax.psum(y, mp_axis)
        return jax.lax.pcast(y, (mp_axis,), to="varying")

    def block_apply(lp, x):
        # x: [mbs, S, H] (one microbatch); weight leaves may be mp-local
        # shards (llama_block_specs) — head counts derive from local shapes
        B, S, H = x.shape
        nh_l = lp["wq"].shape[-1] // head_dim
        nkv_l = lp["wk"].shape[-1] // head_dim
        h = rms(x, lp["ln1"])
        q = (h @ lp["wq"]).reshape(B, S, nh_l, head_dim)
        k = (h @ lp["wk"]).reshape(B, S, nkv_l, head_dim)
        v = (h @ lp["wv"]).reshape(B, S, nkv_l, head_dim)
        sin, cos = sin_t[:S], cos_t[:S]
        q = _apply_rope(q, sin, cos)
        k = _apply_rope(k, sin, cos)
        from ..core.dispatch import get_kernel
        attn_impl = get_kernel("flash_attention_causal")
        # GQA: the Pallas kernel indexes KV heads natively; only the jnp
        # fallback up-materializes (reference flash_attn GQA path)
        o = attn_impl(q, k, v) if attn_impl is not None else None
        if o is None:
            if nh_l != nkv_l:
                rep = nh_l // nkv_l
                k = jnp.repeat(k, rep, axis=2)
                v = jnp.repeat(v, rep, axis=2)
            logits = jnp.einsum("bqhd,bkhd->bhqk", q, k) / math.sqrt(head_dim)
            mask = jnp.tril(jnp.ones((S, S), bool))
            logits = jnp.where(mask, logits.astype(jnp.float32), -jnp.inf)
            w = jax.nn.softmax(logits, -1).astype(x.dtype)
            o = jnp.einsum("bhqk,bkhd->bqhd", w, v)
        o = _mp_reduce(o.reshape(B, S, nh_l * head_dim) @ lp["wo"])
        x = x + o
        h = rms(x, lp["ln2"])
        if moe:
            return x + _moe_ffn_block(lp, h, B, S)
        ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
        return x + _mp_reduce(ff @ lp["wdown"])

    def _moe_ffn_block(lp, h, B, S):
        """Sparse SwiGLU FFN over the expert-stacked leaves. Under shard_map
        with `ep_axis` in scope the expert dim of we_* is the LOCAL shard and
        dispatch/combine ride lax.all_to_all (reference MoEScatter/MoEGather);
        without ep_axis it is the dense single-mesh computation."""
        from ..incubate.distributed.models.moe.gate import (top_k_gating,
                                                            compute_capacity)
        from ..incubate.distributed.models.moe.moe_layer import (
            moe_dispatch, moe_combine, ep_all_to_all, ep_all_to_all_back)
        T = B * S
        xf = h.reshape(T, -1)
        E_total = lp["gate_w"].shape[-1]
        logits = (xf @ lp["gate_w"].astype(xf.dtype)).astype(jnp.float32)
        capacity = compute_capacity(T, E_total, c.moe_topk,
                                    c.moe_capacity_factor)
        # balance aux loss is intentionally not routed through the pipeline
        # loss (the per-stage schedules carry only the LM loss); use the
        # eager LlamaMoEBlock path when the aux term must train the gate
        combine, dispatch, _aux, _ = top_k_gating(
            logits, c.moe_topk, capacity, balance_loss_weight=0.0)
        disp = moe_dispatch(xf, dispatch)                 # [E_total, C, H]
        if ep_axis is not None:
            disp = ep_all_to_all(disp, ep_axis)           # [E_local, W*C, H]
        ff = jax.nn.silu(jnp.einsum("ebd,edh->ebh", disp,
                                    lp["we_gate"].astype(disp.dtype))) \
            * jnp.einsum("ebd,edh->ebh", disp, lp["we_up"].astype(disp.dtype))
        y = jnp.einsum("ebh,ehd->ebd", ff, lp["we_down"].astype(ff.dtype))
        if ep_axis is not None:
            y = ep_all_to_all_back(y, ep_axis)            # [E_total, C, H]
        out = moe_combine(y, combine)
        return out.reshape(B, S, -1).astype(h.dtype)

    def head_loss_apply(p, y, batch):
        # y: [n_micro, mbs, S, H]
        ids, labels = batch
        B = labels.shape[0]
        mbs = B // n_micro
        lab = labels.reshape(n_micro, mbs, -1)
        h = rms(y, p["ln_f"])
        if head_chunks:
            # vocab-chunked online-logsumexp head: the [*, V] logits tensor
            # never materializes (round-4 perf work; see
            # incubate.nn.functional.fused_linear_cross_entropy_impl)
            from ..incubate.nn.functional import \
                fused_linear_cross_entropy_impl
            nllv = fused_linear_cross_entropy_impl(
                h.reshape(-1, c.hidden_size), p["lm"], lab.reshape(-1),
                n_chunks=head_chunks)
            return jnp.mean(nllv)
        logits = h @ p["lm"]
        logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
        nll = -jnp.take_along_axis(logp, lab[..., None].astype(jnp.int32), -1)
        return jnp.mean(nll)

    return embed_params, block_params, head_params, embed_apply, block_apply, head_loss_apply


# ---------------------------------------------------------------------------
# Serving decode path (KV cache)
# ---------------------------------------------------------------------------
def build_llama_decode(config: LlamaConfig, max_seq: int = None, dtype=None):
    """Compiled autoregressive serving path (reference: the fused decode
    attention masked_multihead_attention_kernel.cu + Predictor decode loop).

    Returns (init_cache, prefill, decode_step) over the same
    (embed_params, block_params, head_params) pytrees build_functional_llama
    produces:

      cache = init_cache(B)                      # {"k","v" [L,B,S,KV,D], "pos"}
      logits, cache = prefill(params, ids)       # prompt pass, fills cache
      logits, cache = decode_step(params, tok, cache)   # one token, O(S) attn

    All shapes static (max_seq bounds the cache); jit decode_step once and
    every generated token reuses the executable.
    """
    c = config
    d = jnp.dtype(dtype) if dtype is not None else jnp.float32
    S_max = max_seq or c.max_position_embeddings
    head_dim = c.hidden_size // c.num_attention_heads
    L = c.num_hidden_layers
    nkv = c.num_key_value_heads
    sin_t, cos_t = _rope_tables(S_max, head_dim, c.rope_theta, d)

    from ..nn.functional.norm import rms_norm_ref

    def init_cache(batch):
        return {
            "k": jnp.zeros((L, batch, S_max, nkv, head_dim), d),
            "v": jnp.zeros((L, batch, S_max, nkv, head_dim), d),
            "pos": jnp.zeros((), jnp.int32),
        }

    def _rope_at(pos, T):
        """Rope table slice [pos:pos+T] — static fast path for a host-int
        pos (the dense-prefill pos=0 case), dynamic_slice for a traced
        pos.  The isinstance dispatch is static under trace (a tracer is
        an ndarray, a python int is not — no tracer bool conversion), and
        the callers hoist it out of the layer scan: one slice per step,
        not one per layer."""
        if isinstance(pos, jnp.ndarray):
            return (jax.lax.dynamic_slice_in_dim(sin_t, pos, T, 0),
                    jax.lax.dynamic_slice_in_dim(cos_t, pos, T, 0))
        return sin_t[pos:pos + T], cos_t[pos:pos + T]

    def _block_step(lp, x, k_cache, v_cache, pos, n_valid, sin, cos):
        """One decoder block on x [B, T, H] with cache write at pos and
        attention over cache[:, :n_valid]; sin/cos are the caller's rope
        slice for [pos, pos+T). Returns (x_out, k_cache, v_cache)."""
        B, T, H = x.shape
        nh = c.num_attention_heads
        h = rms_norm_ref(x, lp["ln1"], c.rms_norm_eps)
        q = (h @ lp["wq"]).reshape(B, T, nh, head_dim)
        k = (h @ lp["wk"]).reshape(B, T, nkv, head_dim)
        v = (h @ lp["wv"]).reshape(B, T, nkv, head_dim)
        q = _apply_rope(q, sin, cos)
        k = _apply_rope(k, sin, cos)
        k_cache = jax.lax.dynamic_update_slice_in_dim(k_cache, k, pos, 1)
        v_cache = jax.lax.dynamic_update_slice_in_dim(v_cache, v, pos, 1)
        rep = nh // nkv
        kf = jnp.repeat(k_cache, rep, axis=2) if rep > 1 else k_cache
        vf = jnp.repeat(v_cache, rep, axis=2) if rep > 1 else v_cache
        s = jnp.einsum("bthd,bshd->bhts", q.astype(jnp.float32),
                       kf.astype(jnp.float32)) / math.sqrt(head_dim)
        q_pos = pos + jnp.arange(T)[None, :, None]          # [1, T, 1]
        k_pos = jnp.arange(S_max)[None, None, :]            # [1, 1, S]
        mask = (k_pos <= q_pos) & (k_pos < n_valid)
        s = jnp.where(mask[:, None, :, :], s, -jnp.inf)
        p = jax.nn.softmax(s, axis=-1).astype(x.dtype)
        o = jnp.einsum("bhts,bshd->bthd", p, vf).reshape(B, T, nh * head_dim)
        x = x + o @ lp["wo"]
        h = rms_norm_ref(x, lp["ln2"], c.rms_norm_eps)
        ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
        return x + ff @ lp["wdown"], k_cache, v_cache

    def _head(hp, x_last):
        h = rms_norm_ref(x_last, hp["ln_f"], c.rms_norm_eps)
        return (h @ hp["lm"]).astype(jnp.float32)

    def prefill(params, ids):                         # graftlint: jit
        """ids [B, T_prompt] -> (logits [B, vocab] for the last token, cache)."""
        ep, bp, hp = params
        B, T = ids.shape
        cache = init_cache(B)
        x = ep["tok"][ids].astype(d)
        sin, cos = _rope_at(0, T)

        def body(carry, layer_in):
            xc, = carry
            lp, kc, vc = layer_in
            x_out, kc, vc = _block_step(lp, xc, kc, vc, 0, T, sin, cos)
            return (x_out,), (kc, vc)

        (x,), (ks, vs) = jax.lax.scan(
            body, (x,), (bp, cache["k"], cache["v"]))
        cache = {"k": ks, "v": vs, "pos": jnp.asarray(T, jnp.int32)}
        return _head(hp, x[:, -1]), cache

    def decode_step(params, tok, cache):              # graftlint: jit
        """tok [B] int32 -> (logits [B, vocab], cache advanced by one)."""
        ep, bp, hp = params
        B = tok.shape[0]
        pos = cache["pos"]
        x = ep["tok"][tok][:, None, :].astype(d)       # [B, 1, H]
        sin, cos = _rope_at(pos, 1)

        def body(carry, layer_in):
            xc, = carry
            lp, kc, vc = layer_in
            x_out, kc, vc = _block_step(lp, xc, kc, vc, pos, pos + 1,
                                        sin, cos)
            return (x_out,), (kc, vc)

        (x,), (ks, vs) = jax.lax.scan(
            body, (x,), (bp, cache["k"], cache["v"]))
        cache = {"k": ks, "v": vs, "pos": pos + 1}
        return _head(hp, x[:, -1]), cache

    return init_cache, prefill, decode_step


# ---------------------------------------------------------------------------
# Paged-KV serving decode path (ragged paged attention + page-pool cache)
# ---------------------------------------------------------------------------
def llama_paged_param_specs(mp_axis: str = "mp"):
    """Per-leaf PartitionSpec for the paged-decode ``(ep, bp, hp)`` params
    tree under tensor parallelism over ``mp_axis``: column-parallel wq/wk/wv
    and wgate/wup (output dim sharded = heads / FFN columns), ROW-parallel
    wdown (input dim sharded — its matmul produces the partial sums the
    layer's ONE AllReduce combines), and wo REPLICATED: it multiplies the
    all_gathered head outputs, so its matmul is bit-identical to the
    single-chip engine's (the gather is exact; see _gather_heads).  The
    leading dim of every bp leaf is the stacked layer axis (unsharded).
    Returned as a pytree matching (ep, bp, hp) for shard_map in_specs and
    NamedSharding placement alike."""
    from jax.sharding import PartitionSpec as P
    col = P(None, None, mp_axis)
    bp = {"ln1": P(), "wq": col, "wk": col, "wv": col, "wo": P(),
          "ln2": P(), "wgate": col, "wup": col,
          "wdown": P(None, mp_axis, None)}
    return ({"tok": P()}, bp, {"ln_f": P(), "lm": P()})


def llama_paged_page_spec(mp_axis: str = "mp"):
    """PartitionSpec for one side of the paged-KV store: shard the KV-head
    axis (dim 1 of the ``[L, Hkv, NP+1, ps, D]`` data pages and of the
    ``[L, Hkv, NP+1, ps]`` scale pages) over ``mp_axis``.  A single spec
    works as a pytree prefix for both the raw-array and the quantized
    ``{"q","s"}`` page stores — every leaf shards the same axis."""
    from jax.sharding import PartitionSpec as P
    return P(None, mp_axis)


def gather_kv_pages(store, idx):
    """Gather pages ``idx`` from one side of the paged-KV store (raw array
    or quantized ``{"q","s"}`` dict alike).  The page axis is AXIS 2 of the
    ``[L, Hkv, NP+1, ps, D]`` data planes and ``[L, Hkv, NP+1, ps]`` scale
    planes — this function is the one place that contract lives for
    transfers (snapshot, restore, and the disaggregated prefill->decode
    handoff all ride it).  The KV-head axis (dim 1) is what
    ``llama_paged_page_spec`` shards over ``mp``, and a page gather never
    touches it: at equal ``mp`` degree the gathered planes land rank-local
    on the destination submesh with no re-sharding.  Returns planes in
    ``idx`` order."""
    if isinstance(store, dict):
        return {k: v[:, :, idx] for k, v in store.items()}
    return store[:, :, idx]


def scatter_kv_pages(store, ids, planes):
    """Splice ``planes`` (a :func:`gather_kv_pages` result, same page
    order) into the store at page ids ``ids`` — the inverse transfer used
    by full-KV restore and by ``import_kv`` on a foreign engine.  A
    quantized store splices data AND scale planes together: int8/fp8 codes
    without their per-row scales are garbage magnitudes."""
    if isinstance(store, dict):
        return {k: store[k].at[:, :, ids].set(
                    jnp.asarray(planes[k], store[k].dtype))
                for k in store}
    return store.at[:, :, ids].set(jnp.asarray(planes, store.dtype))


def build_llama_paged_decode(config: LlamaConfig, page_size: int = 16,
                             num_pages: int = 64, dtype=None,
                             attention_impl: str = "auto",
                             interpret: bool = False, kv_dtype=None,
                             mesh=None, mp_axis: str = "mp",
                             quantized_allreduce: bool = False):
    """Paged-KV decode path (the `block_multihead_attention` serving analog;
    Ragged Paged Attention arxiv 2604.15464): the KV cache lives in a pool of
    fixed-size pages shared by every in-flight request, so mixed-length
    sequences occupy memory (and attention FLOPs) proportional to their OWN
    length instead of the longest sequence in the batch.

    Returns (init_pages, prefill, prefill_chunk, decode_step, verify_step):

      pages = init_pages()
          {"k","v": [L, Hkv, num_pages + 1, page_size, head_dim]} — the last
          page is the TRASH page inactive slots write into; the page pool
          (inference/paged.py PagePool) hands out ids < num_pages.

      logits, pages_k, pages_v = prefill(params, ids, true_len, page_row,
                                         pages_k, pages_v)
          ids [1, T_pad] right-padded prompt, true_len the real length,
          page_row [P] this request's page table.  Dense causal attention
          over the prompt; post-RoPE K/V scatter into the request's pages;
          logits [vocab] for the LAST real token.

      logits, greedy_tok, pages_k, pages_v = prefill_chunk(
              params, ids, start, chunk_len, page_row, pages_k, pages_v)
          CHUNKED / SUFFIX prefill for the prefix cache + chunked-prefill
          scheduler: ids [1, C_pad] right-padded chunk of the prompt, start
          the number of tokens ALREADY in this request's pages (a cached
          prefix and/or earlier chunks), chunk_len the real chunk length.
          The chunk's K/V scatter into the pages at absolute positions
          start..start+chunk_len-1 (RoPE at those positions), then the
          chunk attends as ONE ragged query segment of the unified kernel
          (causal across cache + chunk).  Returns logits [vocab] for the
          LAST real chunk token plus its fused greedy argmax token (int32
          scalar) — a greedy request's final chunk consumes the token
          directly (no separate sample dispatch); only sampled lanes read
          the logits.  `prefill_chunk(.., start=0, chunk_len=T)` is
          semantically identical to `prefill` (the engine keeps the dense
          path for the no-cache-hit whole-prompt case purely so its
          numerics stay byte-identical with the pre-cache engine).

      logits, pages_k, pages_v = decode_step(params, toks, lengths,
                                             page_tables, pages_k, pages_v,
                                             active)
          One token per slot: toks [S], lengths [S] (tokens already cached —
          the new token lands at position lengths[s]), page_tables [S, P],
          active [S] bool.  Inactive slots write to the trash page and
          produce garbage logits the engine discards.

      Decode, verify, AND chunked prefill all dispatch the ONE ragged
      paged-attention kernel (attention_impl "pallas"/"auto"-on-TPU) or
      its ONE jnp ref ("ref"/"auto"-off-TPU) — decode is the q_len = 1
      segment, verify q_len = K+1, a chunk q_len = chunk_len.  There is
      no per-path attention implementation anywhere in the paged family.

      logits0, greedy, pages_k, pages_v = verify_step(params, toks, lengths,
                                                      page_tables, pages_k,
                                                      pages_v, n_q)
          Speculative-decoding verify: toks [S, K+1] (pending token +
          draft tokens per slot), n_q [S] valid query counts — scores all
          K+1 positions in one dispatch so the engine can accept the
          longest draft prefix whose argmax matches (lossless under
          greedy sampling).  See the fn docstring for the rewind
          contract.

    All shapes static; jit once and every decode step of a whole serving
    run reuses the same executable regardless of which requests occupy
    which slots.

    ``kv_dtype`` ("int8" / "fp8", ROADMAP item 2): the page store holds
    QUANTIZED K/V — each side becomes a ``{"q": [L, Hkv, NP+1, ps, D]
    storage-dtype, "s": [L, Hkv, NP+1, ps] f32}`` dict of data pages plus
    per-(page, head, token-row) absmax scales.  Every scatter path
    (prefill / prefill_chunk / decode_step / verify_step) quantizes
    through ``serving.quant.quantize_kv`` before writing, and every
    attention path dequantizes through the ONE ``dequantize_kv``
    expression — fused inside the unified ragged kernel on TPU (decode,
    verify, and chunked prefill alike), applied to the gathered rows in
    its jnp ref off-TPU.  Per-row scales make quantization
    write-order independent, so the engine's whole bit-exactness matrix
    (cache on/off, chunked, preemption re-prefill, COW, snapshot, spec
    decode) holds for the quantized engine against itself.  The dense
    ``prefill`` additionally fake-quants its LOCAL K/V before attending
    (quantize -> dequantize round trip), so its numerics equal a chunked
    prefill of the same prompt reading the rows back from the pages.

    ``mesh`` (ROADMAP item 1, TP serving): when a Mesh binding ``mp_axis``
    with size > 1 is given, the four jitted fns come back wrapped in
    ``shard_map`` over that axis — Q/KV heads and KV pages sharded over
    ``mp`` (specs: llama_paged_param_specs / llama_paged_page_spec), every
    scalar/logits input and output replicated.  Per layer the sharded body
    pays exactly ONE AllReduce (the row-parallel wdown partial reduction;
    f32 psum by default, the EQuARX int8 grid with
    ``quantized_allreduce=True`` — distributed/quant_collectives) plus one
    exact all_gather of the per-rank attention head outputs, after which wo
    applies replicated — so with f32 collectives every matmul is
    bit-identical to the single-chip engine and the only divergence source
    is the psum's fixed summation order.  Requires mp | num_key_value_heads
    (hence mp | num_attention_heads); MoE blocks are not supported under
    TP serving.
    """
    from ..ops.pallas.paged_attention import (ragged_paged_attention,
                                              ragged_paged_attention_ref)
    c = config
    d = jnp.dtype(dtype) if dtype is not None else jnp.float32
    head_dim = c.hidden_size // c.num_attention_heads
    L = c.num_hidden_layers
    nkv = c.num_key_value_heads
    nh = c.num_attention_heads
    TRASH = num_pages
    tp = 1 if mesh is None else int(mesh.shape[mp_axis])
    if tp > 1:
        if c.num_experts > 1:
            raise NotImplementedError(
                "tensor-parallel paged decode does not support MoE blocks")
        if nkv % tp or nh % tp:
            raise ValueError(
                f"mp={tp} must divide num_key_value_heads={nkv} (and "
                f"num_attention_heads={nh}) to head-shard paged decode")
        from ..distributed.quant_collectives import allreduce as _allreduce

    def _gather_heads(o):  # graftlint: spmd=mp
        """Head-sharded attention epilogue: each rank pushed its LOCAL
        heads through the one ragged dispatch; the tiled all_gather over
        the head axis (second-to-last) restores the full [..., nh, D] in
        global head order — NamedSharding hands rank r the contiguous head
        block r*nh_l..(r+1)*nh_l-1, which is exactly the r-th tile of the
        gather.  The gather moves bits unchanged, so the replicated wo
        matmul that follows is bit-identical to single-chip.  NOT an
        AllReduce: the layer's one psum stays the wdown reduction."""
        if tp == 1:
            return o
        return jax.lax.all_gather(o, mp_axis, axis=o.ndim - 2, tiled=True)

    def _mp_reduce(y):  # graftlint: spmd=mp
        """THE one AllReduce per transformer layer: sum the row-parallel
        wdown partials over mp — plain f32 psum by default (the bit-exact
        escape hatch), the EQuARX int8 per-chunk grid when the engine asks
        for quantized collectives."""
        if tp == 1:
            return y
        return _allreduce(y, mp_axis, quantized=quantized_allreduce)
    if kv_dtype is not None:
        from ..serving.quant import dequantize_kv, kv_spec, quantize_kv
        kv_storage, kv_qmax = kv_spec(kv_dtype)
    sin_t, cos_t = _rope_tables(c.max_position_embeddings, head_dim,
                                c.rope_theta, d)
    if attention_impl == "auto":
        try:
            use_kernel = any(dev.platform == "tpu" for dev in jax.devices())
        except Exception:
            use_kernel = False
    else:
        use_kernel = attention_impl == "pallas"

    from ..nn.functional.norm import rms_norm_ref

    def init_pages():
        shape = (L, nkv, num_pages + 1, page_size, head_dim)
        if kv_dtype is None:
            return {"k": jnp.zeros(shape, d), "v": jnp.zeros(shape, d)}
        sshape = (L, nkv, num_pages + 1, page_size)

        def side():
            return {"q": jnp.zeros(shape, kv_storage),
                    "s": jnp.zeros(sshape, jnp.float32)}
        return {"k": side(), "v": side()}

    def _scatter(store, vals, page, off):
        """Write per-token K or V rows (``vals [..., nkv, D]``) into the
        (per-layer) page store at ``[:, page, off]``; returns the updated
        store plus the LOCAL view of what was written — ``vals`` itself
        on the f32/bf16 path, the dequantized round trip on a quantized
        store (so a caller attending over its own fresh rows sees exactly
        what any later gather of the pages will see)."""
        if kv_dtype is None:
            return store.at[:, page, off].set(
                jnp.moveaxis(vals.astype(d), -2, 0)), vals
        qv, sv = quantize_kv(vals, qmax=kv_qmax, dtype=kv_storage)
        new = {"q": store["q"].at[:, page, off].set(jnp.moveaxis(qv, -2, 0)),
               "s": store["s"].at[:, page, off].set(jnp.moveaxis(sv, -1, 0))}
        # .astype(d): the jnp paths consume dequantized rows in the
        # COMPUTE dtype, exactly like the f32/bf16 store — activations
        # keep their dtype (no silent f32 promotion) and decode/chunk/
        # verify/dense all see the same rounded values on a bf16 engine
        return new, dequantize_kv(qv, sv).astype(d)

    def _attn(q, kc_l, vc_l, page_tables, q_start, q_len, kv_len):
        """THE attention dispatch: every paged path (decode, speculative
        verify, chunked prefill) routes its ragged query segments
        ``q [S, Qmax, nh, D]`` through the ONE ragged paged-attention
        kernel (or, off-TPU, its ONE jnp ref) — impl-uniformity is what
        makes speculative verify lossless by construction rather than by
        bench assert.  On a quantized store the int8/fp8 pages and their
        per-row scales pass straight through; dequant fuses inside the
        kernel (and inside the ref's gather) for every path."""
        if kv_dtype is not None:
            kq, vq = kc_l["q"], vc_l["q"]
            scale_kw = dict(k_scales=kc_l["s"], v_scales=vc_l["s"])
        else:
            kq, vq = kc_l, vc_l
            scale_kw = {}
        if use_kernel:
            return ragged_paged_attention(q, kq, vq, page_tables, q_start,
                                          q_len, kv_len, interpret=interpret,
                                          **scale_kw)
        return ragged_paged_attention_ref(q, kq, vq, page_tables, q_start,
                                          q_len, kv_len, **scale_kw)

    def _rope_at(x, sin_p, cos_p):
        # x: [..., H, D]; sin_p/cos_p: [..., D] (per-row positions — the
        # leading dims are [S] for decode, [C] for chunks, [S, Q] for the
        # multi-token verify step)
        half = x.shape[-1] // 2
        x1, x2 = x[..., :half], x[..., half:]
        rot = jnp.concatenate([-x2, x1], axis=-1)
        return x * cos_p[..., None, :] + rot * sin_p[..., None, :]

    def _head(hp, h_last):
        h = rms_norm_ref(h_last, hp["ln_f"], c.rms_norm_eps)
        return (h @ hp["lm"]).astype(jnp.float32)

    def prefill(params, ids, true_len, page_row, pages_k, pages_v):  # graftlint: jit
        ep, bp, hp = params
        T = ids.shape[1]
        x = ep["tok"][ids[0]].astype(d)               # [T, H]
        t_idx = jnp.arange(T)
        valid = t_idx < true_len
        page = jnp.where(valid, page_row[t_idx // page_size], TRASH)
        off = t_idx % page_size
        sin, cos = sin_t[:T], cos_t[:T]

        def body(carry, layer_in):
            xc, = carry
            lp, kc_l, vc_l = layer_in
            # head counts from the LOCAL weight shards: under shard_map
            # each rank holds nh/tp q heads and nkv/tp kv heads
            nh_l = lp["wq"].shape[-1] // head_dim
            nkv_l = lp["wk"].shape[-1] // head_dim
            h = rms_norm_ref(xc, lp["ln1"], c.rms_norm_eps)
            q = (h @ lp["wq"]).reshape(T, nh_l, head_dim)
            k = (h @ lp["wk"]).reshape(T, nkv_l, head_dim)
            v = (h @ lp["wv"]).reshape(T, nkv_l, head_dim)
            q = _rope_at(q, sin, cos)
            k = _rope_at(k, sin, cos)
            kc_l, k_loc = _scatter(kc_l, k, page, off)
            vc_l, v_loc = _scatter(vc_l, v, page, off)
            rep = nh_l // nkv_l
            kf = jnp.repeat(k_loc, rep, axis=1) if rep > 1 else k_loc
            vf = jnp.repeat(v_loc, rep, axis=1) if rep > 1 else v_loc
            s = jnp.einsum("qhd,khd->hqk", q.astype(jnp.float32),
                           kf.astype(jnp.float32)) / math.sqrt(head_dim)
            mask = (t_idx[None, :] <= t_idx[:, None]) & valid[None, :]
            s = jnp.where(mask[None, :, :], s, -jnp.inf)
            p = jax.nn.softmax(s, axis=-1).astype(xc.dtype)
            o = jnp.einsum("hqk,khd->qhd", p, vf)
            xc = xc + _gather_heads(o).reshape(T, nh * head_dim) @ lp["wo"]
            h = rms_norm_ref(xc, lp["ln2"], c.rms_norm_eps)
            ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
            return (xc + _mp_reduce(ff @ lp["wdown"]),), (kc_l, vc_l)

        (x,), (ks, vs) = jax.lax.scan(body, (x,), (bp, pages_k, pages_v))
        h_last = jax.lax.dynamic_index_in_dim(x, true_len - 1, 0,
                                              keepdims=False)
        return _head(hp, h_last), ks, vs

    def prefill_chunk(params, ids, start, chunk_len, page_row, pages_k,
                      pages_v):                       # graftlint: jit
        ep, bp, hp = params
        C = ids.shape[1]
        x = ep["tok"][ids[0]].astype(d)               # [C, H]
        i_idx = jnp.arange(C)
        valid = i_idx < chunk_len
        pos = start + i_idx                           # absolute positions
        page = jnp.where(valid, page_row[pos // page_size], TRASH)
        off = pos % page_size
        sin, cos = jnp.take(sin_t, pos, axis=0), jnp.take(cos_t, pos, axis=0)
        # the whole chunk is ONE ragged query segment of the unified
        # kernel: queries at absolute positions start..start+chunk_len-1
        # attend every page-table position <= their own (causal across the
        # cached prefix + earlier chunk tokens).  Positions past the
        # written region (or recycled-page garbage) can never be <= a
        # query position, so the segment mask alone keeps them out.
        start_r = jnp.reshape(start, (1,)).astype(jnp.int32)
        clen_r = jnp.reshape(chunk_len, (1,)).astype(jnp.int32)
        kvlen_r = start_r + clen_r
        page_tab = page_row[None]                     # [1, P]

        def body(carry, layer_in):
            xc, = carry
            lp, kc_l, vc_l = layer_in
            nh_l = lp["wq"].shape[-1] // head_dim
            nkv_l = lp["wk"].shape[-1] // head_dim
            h = rms_norm_ref(xc, lp["ln1"], c.rms_norm_eps)
            q = (h @ lp["wq"]).reshape(C, nh_l, head_dim)
            k = (h @ lp["wk"]).reshape(C, nkv_l, head_dim)
            v = (h @ lp["wv"]).reshape(C, nkv_l, head_dim)
            q = _rope_at(q, sin, cos)
            k = _rope_at(k, sin, cos)
            kc_l, _ = _scatter(kc_l, k, page, off)
            vc_l, _ = _scatter(vc_l, v, page, off)
            o = _attn(q[None], kc_l, vc_l, page_tab,
                      start_r, clen_r, kvlen_r)[0]
            xc = xc + _gather_heads(o).reshape(C, nh * head_dim) @ lp["wo"]
            h = rms_norm_ref(xc, lp["ln2"], c.rms_norm_eps)
            ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
            return (xc + _mp_reduce(ff @ lp["wdown"]),), (kc_l, vc_l)

        (x,), (ks, vs) = jax.lax.scan(body, (x,), (bp, pages_k, pages_v))
        h_last = jax.lax.dynamic_index_in_dim(x, chunk_len - 1, 0,
                                              keepdims=False)
        logits = _head(hp, h_last)
        # fused greedy sampling: the chunk dispatch also emits the argmax
        # token, so a greedy request's FINAL chunk needs no separate
        # sample executable — the engine consumes this token directly and
        # the logits feed only sampled-temperature lanes
        return logits, jnp.argmax(logits).astype(jnp.int32), ks, vs

    def decode_step(params, toks, lengths, page_tables, pages_k, pages_v,
                    active):                          # graftlint: jit
        ep, bp, hp = params
        S = toks.shape[0]
        x = ep["tok"][toks].astype(d)                 # [S, H]
        pos = jnp.where(active, lengths, 0)
        page = jnp.where(active, jnp.take_along_axis(
            page_tables, (pos // page_size)[:, None], 1)[:, 0], TRASH)
        off = pos % page_size
        eff_len = jnp.where(active, lengths + 1, 0)
        n_q = active.astype(jnp.int32)                # q_len: 1 live, 0 idle
        sin_p, cos_p = sin_t[pos], cos_t[pos]         # [S, D]

        def body(carry, layer_in):
            xc, = carry
            lp, kc_l, vc_l = layer_in
            nh_l = lp["wq"].shape[-1] // head_dim
            nkv_l = lp["wk"].shape[-1] // head_dim
            h = rms_norm_ref(xc, lp["ln1"], c.rms_norm_eps)
            q = (h @ lp["wq"]).reshape(S, nh_l, head_dim)
            k = (h @ lp["wk"]).reshape(S, nkv_l, head_dim)
            v = (h @ lp["wv"]).reshape(S, nkv_l, head_dim)
            q = _rope_at(q, sin_p, cos_p)
            k = _rope_at(k, sin_p, cos_p)
            kc_l, _ = _scatter(kc_l, k, page, off)
            vc_l, _ = _scatter(vc_l, v, page, off)
            # decode is the q_len = 1 segment of the unified ragged kernel
            o = _attn(q[:, None], kc_l, vc_l, page_tables,
                      pos, n_q, eff_len)[:, 0]
            xc = xc + _gather_heads(o).reshape(S, nh * head_dim) @ lp["wo"]
            h = rms_norm_ref(xc, lp["ln2"], c.rms_norm_eps)
            ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
            return (xc + _mp_reduce(ff @ lp["wdown"]),), (kc_l, vc_l)

        (x,), (ks, vs) = jax.lax.scan(body, (x,), (bp, pages_k, pages_v))
        return _head(hp, x), ks, vs

    def verify_step(params, toks, lengths, page_tables, pages_k, pages_v,
                    n_q):                             # graftlint: jit
        """Multi-token speculative VERIFY (self-speculative decoding):
        score Q = K+1 query positions per slot in ONE dispatch.  Per slot,
        toks[s, 0] is the pending token (the last sampled token, not yet
        in the cache) and toks[s, 1:] its draft tokens; n_q[s] counts the
        VALID queries (1 + drafts; 0 marks an inactive slot — padding
        lanes write to the trash page and return garbage the engine
        ignores).  Every valid query's K/V scatters into the slot's pages
        at absolute positions lengths[s]..lengths[s]+n_q[s]-1 (RoPE at
        those positions), then each slot attends as one ragged segment of
        the UNIFIED paged-attention kernel — the very callable decode and
        chunked prefill dispatch, so verify-vs-decode losslessness is
        impl-uniform by construction.  Returns (logits0 [S, vocab] f32 —
        position-0 logits for sampled slots; greedy [S, Q] int32 — argmax
        per position, the engine's acceptance test; pages_k; pages_v).

        Rewind contract: K/V written for drafts the engine then REJECTS
        sits at positions >= the rewound `lengths` — every attention path
        masks by `lengths`, so stale entries are overwritten by later
        writes before any query can ever attend to them."""
        ep, bp, hp = params
        S, Q = toks.shape
        x = ep["tok"][toks].astype(d)                 # [S, Q, H]
        q_idx = jnp.arange(Q)
        valid = q_idx[None, :] < n_q[:, None]         # [S, Q]
        pos = lengths[:, None] + q_idx[None, :]       # [S, Q] absolute
        # out-of-range indices on the padding lanes clip (jax gather
        # semantics) and are routed to TRASH by the `valid` mask anyway
        page = jnp.where(valid, jnp.take_along_axis(
            page_tables, pos // page_size, axis=1), TRASH)
        off = pos % page_size
        sin, cos = sin_t[pos], cos_t[pos]             # [S, Q, D]
        # each slot is one ragged segment of the unified kernel: n_q
        # queries starting at absolute position lengths[s], causal among
        # themselves and over the cached context — the SAME kernel (and
        # off-TPU the same ref) decode dispatches with q_len = 1
        kv_len = lengths + n_q

        def body(carry, layer_in):
            xc, = carry
            lp, kc_l, vc_l = layer_in
            nh_l = lp["wq"].shape[-1] // head_dim
            nkv_l = lp["wk"].shape[-1] // head_dim
            h = rms_norm_ref(xc, lp["ln1"], c.rms_norm_eps)
            q = (h @ lp["wq"]).reshape(S, Q, nh_l, head_dim)
            k = (h @ lp["wk"]).reshape(S, Q, nkv_l, head_dim)
            v = (h @ lp["wv"]).reshape(S, Q, nkv_l, head_dim)
            q = _rope_at(q, sin, cos)
            k = _rope_at(k, sin, cos)
            kc_l, _ = _scatter(kc_l, k, page, off)
            vc_l, _ = _scatter(vc_l, v, page, off)
            o = _gather_heads(
                _attn(q, kc_l, vc_l, page_tables, lengths, n_q, kv_len)) \
                .reshape(S, Q, nh * head_dim)
            xc = xc + o @ lp["wo"]
            h = rms_norm_ref(xc, lp["ln2"], c.rms_norm_eps)
            ff = jax.nn.silu(h @ lp["wgate"]) * (h @ lp["wup"])
            return (xc + _mp_reduce(ff @ lp["wdown"]),), (kc_l, vc_l)

        (x,), (ks, vs) = jax.lax.scan(body, (x,), (bp, pages_k, pages_v))
        logits = _head(hp, x)                         # [S, Q, V] f32
        greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return logits[:, 0], greedy, ks, vs

    if tp > 1:
        # TP serving region: the four paged fns run under shard_map over
        # mp — params/pages per the spec helpers, every scalar + logits
        # input/output replicated.  All replicated outputs are computed
        # identically on every rank (the last op touching the residual is
        # the psum), so check_vma=False only skips re-proving what the
        # per-layer collective structure already guarantees.
        from jax.sharding import PartitionSpec
        p_specs = llama_paged_param_specs(mp_axis)
        pg = llama_paged_page_spec(mp_axis)
        r = PartitionSpec()

        def _smap(fn, in_specs, out_specs):
            return jax.shard_map(fn, mesh=mesh, in_specs=in_specs,
                                 out_specs=out_specs, check_vma=False)

        prefill = _smap(prefill, (p_specs, r, r, r, pg, pg), (r, pg, pg))
        prefill_chunk = _smap(prefill_chunk, (p_specs, r, r, r, r, pg, pg),
                              (r, r, pg, pg))
        decode_step = _smap(decode_step, (p_specs, r, r, r, pg, pg, r),
                            (r, pg, pg))
        verify_step = _smap(verify_step, (p_specs, r, r, r, pg, pg, r),
                            (r, r, pg, pg))

    return init_pages, prefill, prefill_chunk, decode_step, verify_step


def _sample_per_request(logits, key, temps, top_ps):
    """Per-request sampling for the serving engine: logits [S, V], temps /
    top_ps [S] -> token ids [S] int32.  temp <= 0 rows decode greedily; the
    rest draw from the per-row nucleus (`tensor/search._top_p_mask` — the
    same mask `top_p_sampling` applies)."""
    from ..tensor.search import _top_p_mask
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.maximum(temps, 1e-6)[:, None]
    masked = _top_p_mask(scaled, top_ps)
    sampled = jax.random.categorical(key, masked, axis=-1).astype(jnp.int32)
    return jnp.where(temps <= 0.0, greedy, sampled)


def make_paged_decode_horizon(decode_step, sample_fn=None):
    """Build the K-step decode-horizon loop with ON-DEVICE token feedback
    (the serving engine's one decode executable; ROADMAP item 5).

    K decode+sample steps fuse into one ``fori_loop`` dispatch, and the
    loop state that used to round-trip through the host between dispatches
    — the last sampled token per slot, the cache lengths, the remaining
    generation budget, and the per-slot done flags — is both ACCEPTED and
    RETURNED as device values.  A double-buffered engine feeds dispatch
    N+1 directly from dispatch N's ``(toks, lengths, remaining, done)``
    outputs, so the decode feedback token never touches the host and the
    host-side drain of dispatch N's emitted tokens moves off the critical
    path.  A synchronous engine passes host values and ``done0=False``
    everywhere; the math (and therefore greedy output) is bit-identical
    either way.

    Per-slot freeze semantics inside the loop (mirrors
    ``llama_generate_fused``'s masking, so greedy outputs are step-exact
    at any K): a slot freezes once it emits ``eos_ids[s]`` (where >= 0)
    or its ``remaining`` budget hits zero; frozen slots echo ``eos_ids``
    into ``out``, stop advancing ``lengths``/``remaining``, and carry
    their state through unchanged — including slots frozen at ENTRY via
    ``done0`` (a lane whose EOS the overlapped host has not yet drained)
    and inactive slots (``active=False``), whose returned ``done`` is the
    ``done0`` passthrough so a momentarily stalled lane is never
    permanently frozen by one inactive dispatch.

    ``decode_step`` is the paged single-token executable from
    :func:`build_llama_paged_decode`; ``sample_fn`` defaults to
    :func:`_sample_per_request` (only consulted when ``greedy=False``).

    Returns ``horizon(params, toks, lengths, page_tables, pk, pv, active,
    key, temps, top_ps, remaining, eos_ids, done0, *, K, greedy) ->
    (out [S, K], toks, lengths, remaining, done, pk, pv)`` — the page
    buffers stay the LAST two outputs (the engine's ``_call_paged``
    rebind convention)."""
    if sample_fn is None:
        sample_fn = _sample_per_request

    def horizon(params, toks, lengths, page_tables, pk, pv, active, key,
                temps, top_ps, remaining, eos_ids, done0, *, K, greedy):  # graftlint: jit
        S = toks.shape[0]
        out = jnp.zeros((S, K), jnp.int32)

        def body(t, carry):
            toks, lengths, rem, pk, pv, done, key, out = carry
            live = ~done
            logits, pk, pv = decode_step(params, toks, lengths,
                                         page_tables, pk, pv, live)
            if greedy:
                # static fast path when every running request decodes
                # greedily (the common serving default): skips the
                # sort/cumsum of the nucleus mask — the same shortcut
                # _sample_token takes for temperature == 0.0
                tok = jnp.argmax(logits, axis=-1).astype(jnp.int32)
            else:
                key, sub = jax.random.split(key)
                tok = sample_fn(logits, sub, temps, top_ps)
            tok = jnp.where(done, eos_ids, tok)
            out = out.at[:, t].set(tok)
            lengths = lengths + live.astype(lengths.dtype)
            rem = rem - live.astype(rem.dtype)
            done = done | ((eos_ids >= 0) & (tok == eos_ids)) | (rem <= 0)
            return (tok, lengths, rem, pk, pv, done, key, out)

        carry = (toks, lengths, remaining, pk, pv, ~active | done0, key, out)
        toks, lengths, rem, pk, pv, done, key, out = jax.lax.fori_loop(
            0, K, body, carry)
        # inactive lanes pass done0 through untouched: ~active folded into
        # the in-loop freeze must not leak into the carried done state
        done = jnp.where(active, done, done0)
        return out, toks, lengths, rem, done, pk, pv

    return horizon


def functional_params_from_layer(model: "LlamaForCausalLM"):
    """Stack an eager LlamaForCausalLM's per-layer weights into the
    (embed, block, head) pytrees the functional/decode paths consume.
    Requires tensor_parallel_degree == 1 (full weights on this host) and
    the dense (non-MoE) variant."""
    if getattr(model.config, "num_experts", 1) > 1:
        raise NotImplementedError(
            "functional_params_from_layer: MoE experts do not map onto the "
            "dense wgate/wup/wdown leaves")
    m = model.model
    def val(p):
        return p._value
    bp = {
        "ln1": jnp.stack([val(l.input_layernorm.weight) for l in m.layers]),
        "wq": jnp.stack([val(l.self_attn.q_proj.weight) for l in m.layers]),
        "wk": jnp.stack([val(l.self_attn.k_proj.weight) for l in m.layers]),
        "wv": jnp.stack([val(l.self_attn.v_proj.weight) for l in m.layers]),
        "wo": jnp.stack([val(l.self_attn.o_proj.weight) for l in m.layers]),
        "ln2": jnp.stack([val(l.post_attention_layernorm.weight) for l in m.layers]),
        "wgate": jnp.stack([val(l.mlp.gate_proj.weight) for l in m.layers]),
        "wup": jnp.stack([val(l.mlp.up_proj.weight) for l in m.layers]),
        "wdown": jnp.stack([val(l.mlp.down_proj.weight) for l in m.layers]),
    }
    ep = {"tok": val(m.embed_tokens.weight)}
    hp = {"ln_f": val(m.norm.weight), "lm": val(model.lm_head.weight)}
    return ep, bp, hp


def _sample_token(logits, key, *, temperature=1.0, top_k=0, top_p=1.0):
    """logits [B, V] -> token ids [B] (greedy when temperature == 0).

    The sampling knobs are KEYWORD-ONLY statics (python `if`s below branch
    on them): callers bind them via functools.partial before jitting, so
    each (temperature, top_k, top_p) combination is its own executable —
    graftlint TRACE001 enforces that they can never arrive traced."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_k and top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; keep at least 1
        cutoff_idx = jnp.sum(cum < top_p, axis=-1)
        cutoff = jnp.take_along_axis(sorted_logits, cutoff_idx[:, None], -1)
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def llama_generate(params, config: LlamaConfig, input_ids, max_new_tokens=32,
                   temperature=0.0, top_k=0, top_p=1.0, eos_token_id=None,
                   seed=0, max_seq=None, dtype=None):
    """Compiled autoregressive generation over the KV-cache decode path
    (the PaddleNLP `model.generate` analog for the functional params).

    input_ids: int [B, T_prompt] (numpy/jax). Returns int32 of FIXED shape
    [B, T_prompt + max_new_tokens]; once a sequence emits eos_token_id its
    tail is padded with eos. Raises when the total length exceeds the cache
    (max_seq / max_position_embeddings). The jitted prefill/decode/sample
    executables are cached per (config, lengths, sampling knobs) so serving
    loops compile once.
    """
    c = config
    ids = jnp.asarray(input_ids, jnp.int32)
    B, T = ids.shape
    S_max = _resolve_cache_len(c, T, max_new_tokens, max_seq)
    prefill, decode, sample = _generate_executables(
        c, S_max, temperature, top_k, top_p, dtype=dtype)
    key = jax.random.PRNGKey(seed)

    logits, cache = prefill(params, ids)
    out = [ids]
    done = jnp.zeros((B,), bool)
    for i in range(max_new_tokens):
        key, sub = jax.random.split(key)
        tok = sample(logits, sub)
        if eos_token_id is not None:
            tok = jnp.where(done, eos_token_id, tok)
            done = done | (tok == eos_token_id)
        out.append(tok[:, None])
        if i == max_new_tokens - 1:
            break                        # the next logits would be discarded
        if eos_token_id is not None and bool(done.all()):
            # every sequence finished: pad the tail to the fixed shape
            pad = jnp.full((B, max_new_tokens - 1 - i), eos_token_id,
                           jnp.int32)
            out.append(pad)
            break
        logits, cache = decode(params, tok, cache)
    return jnp.concatenate(out, axis=1)


_GENERATE_CACHE = {}


def _resolve_cache_len(config, T, max_new_tokens, max_seq):
    """Shared llama_generate/_fused prologue: bucket the KV-cache length
    (multiple of 256, capped by the model context) so requests in the same
    bucket share an executable, and validate the fit."""
    if config.num_experts > 1:
        raise NotImplementedError(
            "llama generation: the MoE decode path is not implemented — "
            "build_llama_decode computes the dense FFN")
    required = T + max_new_tokens
    bucket = min(config.max_position_embeddings,
                 ((required + 255) // 256) * 256)
    S_max = max_seq or bucket
    if required > S_max:
        raise ValueError(
            f"prompt ({T}) + max_new_tokens ({max_new_tokens}) = {required} "
            f"exceeds the KV cache length {S_max}; raise max_seq / "
            "max_position_embeddings or generate fewer tokens")
    return S_max


def _cache_put(cache, key, val, cap=16):
    """FIFO-evict ONE entry at capacity; clearing all would thrash hot
    executables."""
    if len(cache) > cap:
        cache.pop(next(iter(cache)))
    cache[key] = val
    return val


def llama_generate_fused(params, config: LlamaConfig, input_ids,
                         max_new_tokens=32, temperature=0.0, top_k=0,
                         top_p=1.0, eos_token_id=None, seed=0, max_seq=None,
                         dtype=None):
    """Whole-generation-in-one-graph variant of llama_generate: prefill +
    a `lax.fori_loop` over decode steps (sampling inside the loop) compile
    into ONE executable, so serving pays a single dispatch per request
    instead of one per token.

    Measured r5 (271M, B=1, v5e over the remote transport): the per-token
    python loop runs ~48 tok/s — ~20 ms/token of dispatch round-trips
    against ~2 ms of model math; the fused loop removes that overhead
    entirely.  Trade-off vs llama_generate: always runs max_new_tokens
    steps (no early exit when every sequence hits EOS — EOS tails are
    masked to eos_token_id, same output contract)."""
    c = config
    ids = jnp.asarray(input_ids, jnp.int32)
    if max_new_tokens <= 0:
        # parity with llama_generate: the prompt comes back unchanged
        # (ADVICE r5 #3 — the fused loop's pre-loop sample would otherwise
        # clobber the last prompt token via the clamped update at column T)
        return ids
    B, T = ids.shape
    S_max = _resolve_cache_len(c, T, max_new_tokens, max_seq)
    fused = _generate_fused_executable(
        c, S_max, int(max_new_tokens), float(temperature), int(top_k),
        float(top_p), -1 if eos_token_id is None else int(eos_token_id),
        None if dtype is None else jnp.dtype(dtype).name)
    return fused(params, ids, jax.random.PRNGKey(seed))


_FUSED_CACHE = {}


def _generate_fused_executable(config, S_max, max_new, temperature, top_k,
                               top_p, eos_id, dtype_name):
    ckey = (tuple(sorted(config.__dict__.items())), S_max, max_new,
            temperature, top_k, top_p, eos_id, dtype_name)
    hit = _FUSED_CACHE.get(ckey)
    if hit is not None:
        return hit
    dtype = None if dtype_name is None else jnp.dtype(dtype_name)
    _, prefill, decode_step = build_llama_decode(config, max_seq=S_max,
                                                 dtype=dtype)
    sample = functools.partial(_sample_token, temperature=temperature,
                               top_k=top_k, top_p=top_p)

    def gen(params, ids, key):
        B, T = ids.shape
        logits, cache = prefill(params, ids)
        out = jnp.zeros((B, T + max_new), jnp.int32)
        out = jax.lax.dynamic_update_slice(out, ids, (0, 0))
        done = jnp.zeros((B,), bool)

        def emit(logits, out, done, key, t):
            key, sub = jax.random.split(key)
            tok = sample(logits, sub)
            if eos_id >= 0:
                tok = jnp.where(done, eos_id, tok)
                done = done | (tok == eos_id)
            out = jax.lax.dynamic_update_slice(out, tok[:, None], (0, T + t))
            return tok, out, done, key

        # decode-then-sample ordering: exactly max_new - 1 decode steps (the
        # logits after the LAST sampled token are never computed — the same
        # dead step llama_generate's loop breaks out of)
        tok, out, done, key = emit(logits, out, done, key, 0)

        def body(t, carry):
            tok, cache, out, done, key = carry
            logits, cache = decode_step(params, tok, cache)
            tok, out, done, key = emit(logits, out, done, key, t)
            return (tok, cache, out, done, key)

        tok, cache, out, done, key = jax.lax.fori_loop(
            1, max_new, body, (tok, cache, out, done, key))
        return out

    return _cache_put(_FUSED_CACHE, ckey, jax.jit(gen))


def _generate_executables(config, S_max, temperature, top_k, top_p,
                          dtype=None):
    """(prefill, decode, sample) jitted once per key — new closures per call
    would defeat jax.jit's cache entirely. `dtype` is the activation/KV-cache
    compute dtype (None = f32; serve bf16 params with dtype=bf16)."""
    ckey = (tuple(sorted(config.__dict__.items())), S_max,
            float(temperature), int(top_k), float(top_p),
            None if dtype is None else jnp.dtype(dtype).name)
    hit = _GENERATE_CACHE.get(ckey)
    if hit is not None:
        return hit
    _, prefill, decode_step = build_llama_decode(config, max_seq=S_max,
                                                 dtype=dtype)
    entry = (jax.jit(prefill), jax.jit(decode_step),
             jax.jit(functools.partial(_sample_token, temperature=temperature,
                                       top_k=top_k, top_p=top_p)))
    return _cache_put(_GENERATE_CACHE, ckey, entry)
