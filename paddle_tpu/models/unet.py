"""Stable-Diffusion-style UNet (BASELINE.json config #5: SD 1.5 UNet —
conv + attention mixed workload for the Pallas/conv kernels).

Compact latent-diffusion UNet following the SD 1.5 topology: sinusoidal
timestep embedding → MLP; down path of ResBlocks with self+cross attention
at the lower resolutions; middle ResBlock-attn-ResBlock; up path with skip
concatenation; GroupNorm(32)+SiLU throughout. Built from framework layers
only (Conv2D/GroupNorm/Linear/SDPA dispatch)."""
from __future__ import annotations

import math
from dataclasses import dataclass, field
from typing import List, Tuple

import jax.numpy as jnp

from ..nn.layer import Layer
from ..nn import Linear, Conv2D, GroupNorm, LayerNorm, LayerList
from ..nn import functional as F
from ..tensor import manipulation as manip

__all__ = ["UNetConfig", "UNet2DConditionModel", "unet_config_sd15",
           "unet_config_tiny", "timestep_embedding"]


@dataclass
class UNetConfig:
    in_channels: int = 4
    out_channels: int = 4
    block_channels: Tuple[int, ...] = (320, 640, 1280, 1280)
    layers_per_block: int = 2
    attn_levels: Tuple[int, ...] = (0, 1, 2)    # levels with attention
    num_heads: int = 8
    cross_attention_dim: int = 768
    norm_groups: int = 32
    time_embed_mult: int = 4


def unet_config_sd15():
    return UNetConfig()


def unet_config_tiny():
    return UNetConfig(in_channels=4, out_channels=4,
                      block_channels=(32, 64), layers_per_block=1,
                      attn_levels=(1,), num_heads=4, cross_attention_dim=32,
                      norm_groups=8)


def timestep_embedding(t, dim, max_period=10000.0):
    """Sinusoidal embedding [B] -> [B, dim] (SD convention)."""
    half = dim // 2
    freqs = jnp.exp(-math.log(max_period)
                    * jnp.arange(half, dtype=jnp.float32) / half)
    v = t._value if hasattr(t, "_value") else jnp.asarray(t)
    args = v.astype(jnp.float32)[:, None] * freqs[None]
    emb = jnp.concatenate([jnp.cos(args), jnp.sin(args)], axis=-1)
    import paddle_tpu as paddle
    return paddle.Tensor(emb)


class ResBlock(Layer):
    def __init__(self, c_in, c_out, t_dim, groups):
        super().__init__()
        self.norm1 = GroupNorm(min(groups, c_in), c_in)
        self.conv1 = Conv2D(c_in, c_out, 3, padding=1)
        self.time_proj = Linear(t_dim, c_out)
        self.norm2 = GroupNorm(min(groups, c_out), c_out)
        self.conv2 = Conv2D(c_out, c_out, 3, padding=1)
        self.skip = Conv2D(c_in, c_out, 1) if c_in != c_out else None

    def forward(self, x, temb):
        h = self.conv1(F.silu(self.norm1(x)))
        h = h + manip.reshape(self.time_proj(F.silu(temb)),
                              [temb.shape[0], -1, 1, 1])
        h = self.conv2(F.silu(self.norm2(h)))
        return h + (self.skip(x) if self.skip is not None else x)


class CrossAttention(Layer):
    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.heads = heads
        self.to_q = Linear(dim, dim, bias_attr=False)
        self.to_k = Linear(ctx_dim, dim, bias_attr=False)
        self.to_v = Linear(ctx_dim, dim, bias_attr=False)
        self.to_out = Linear(dim, dim)

    def forward(self, x, ctx=None):
        ctx = x if ctx is None else ctx
        b, n, _ = x.shape
        hd = x.shape[-1] // self.heads
        q = manip.reshape(self.to_q(x), [b, n, self.heads, hd])
        k = manip.reshape(self.to_k(ctx), [b, ctx.shape[1], self.heads, hd])
        v = manip.reshape(self.to_v(ctx), [b, ctx.shape[1], self.heads, hd])
        o = F.scaled_dot_product_attention(q, k, v, is_causal=False,
                                           training=self.training)
        return self.to_out(manip.reshape(o, [b, n, -1]))


class TransformerBlock(Layer):
    """Self-attn → cross-attn → geglu-ff over flattened spatial tokens."""

    def __init__(self, dim, ctx_dim, heads):
        super().__init__()
        self.norm1 = LayerNorm(dim)
        self.attn1 = CrossAttention(dim, dim, heads)
        self.norm2 = LayerNorm(dim)
        self.attn2 = CrossAttention(dim, ctx_dim, heads)
        self.norm3 = LayerNorm(dim)
        self.ff1 = Linear(dim, dim * 8)
        self.ff2 = Linear(dim * 4, dim)
        self.proj_in = Conv2D(dim, dim, 1)
        self.proj_out = Conv2D(dim, dim, 1)
        self.norm_in = GroupNorm(min(32, dim), dim)

    def forward(self, x, ctx):
        b, c, h, w = x.shape
        res = x
        t = self.proj_in(self.norm_in(x))
        t = manip.transpose(manip.reshape(t, [b, c, h * w]), [0, 2, 1])
        t = t + self.attn1(self.norm1(t))
        t = t + self.attn2(self.norm2(t), ctx)
        ff = self.ff1(self.norm3(t))
        gate = ff[:, :, ff.shape[-1] // 2:]
        ff = ff[:, :, : ff.shape[-1] // 2] * F.gelu(gate)
        t = t + self.ff2(ff)
        t = manip.reshape(manip.transpose(t, [0, 2, 1]), [b, c, h, w])
        return self.proj_out(t) + res


class UNet2DConditionModel(Layer):
    """The SD UNet: (latents [B,4,H,W], t [B], context [B,L,ctx]) -> eps."""

    def __init__(self, config: UNetConfig = None):
        super().__init__()
        c = config or unet_config_sd15()
        self.config = c
        ch = c.block_channels
        t_dim = ch[0] * c.time_embed_mult
        self.t_dim0 = ch[0]
        self.time_fc1 = Linear(ch[0], t_dim)
        self.time_fc2 = Linear(t_dim, t_dim)
        self.conv_in = Conv2D(c.in_channels, ch[0], 3, padding=1)

        self.down_res = LayerList()
        self.down_attn = LayerList()
        self.downsamplers = LayerList()
        cur = ch[0]
        self._skips_per_level = c.layers_per_block
        for lvl, cout in enumerate(ch):
            for i in range(c.layers_per_block):
                self.down_res.append(ResBlock(cur, cout, t_dim, c.norm_groups))
                self.down_attn.append(
                    TransformerBlock(cout, c.cross_attention_dim, c.num_heads)
                    if lvl in c.attn_levels else None)
                cur = cout
            if lvl < len(ch) - 1:
                self.downsamplers.append(Conv2D(cur, cur, 3, stride=2, padding=1))

        self.mid_res1 = ResBlock(cur, cur, t_dim, c.norm_groups)
        self.mid_attn = TransformerBlock(cur, c.cross_attention_dim, c.num_heads)
        self.mid_res2 = ResBlock(cur, cur, t_dim, c.norm_groups)

        self.up_res = LayerList()
        self.up_attn = LayerList()
        self.upsamplers = LayerList()
        skip_ch = []
        cc = ch[0]
        for lvl, cout in enumerate(ch):
            for _ in range(c.layers_per_block):
                skip_ch.append(cout)
        for lvl in reversed(range(len(ch))):
            cout = ch[lvl]
            for i in range(c.layers_per_block):
                s = skip_ch.pop()
                self.up_res.append(ResBlock(cur + s, cout, t_dim, c.norm_groups))
                self.up_attn.append(
                    TransformerBlock(cout, c.cross_attention_dim, c.num_heads)
                    if lvl in c.attn_levels else None)
                cur = cout
            if lvl > 0:
                self.upsamplers.append(Conv2D(cur, cur, 3, padding=1))

        self.norm_out = GroupNorm(min(c.norm_groups, cur), cur)
        self.conv_out = Conv2D(cur, c.out_channels, 3, padding=1)

    def forward(self, latents, timesteps, context):
        c = self.config
        # sinusoidal table is f32; follow the latents' compute dtype so the
        # time-projection adds don't promote the conv stream back to f32
        temb = timestep_embedding(timesteps, self.t_dim0).astype(latents.dtype)
        temb = self.time_fc2(F.silu(self.time_fc1(temb)))

        x = self.conv_in(latents)
        skips = []
        idx = 0
        ds = 0
        for lvl in range(len(c.block_channels)):
            for i in range(c.layers_per_block):
                x = self.down_res[idx](x, temb)
                if self.down_attn[idx] is not None:
                    x = self.down_attn[idx](x, context)
                skips.append(x)
                idx += 1
            if lvl < len(c.block_channels) - 1:
                x = self.downsamplers[ds](x)
                ds += 1

        x = self.mid_res1(x, temb)
        x = self.mid_attn(x, context)
        x = self.mid_res2(x, temb)

        idx = 0
        us = 0
        for lvl in reversed(range(len(c.block_channels))):
            for i in range(c.layers_per_block):
                skip = skips.pop()
                x = manip.concat([x, skip], axis=1)
                x = self.up_res[idx](x, temb)
                if self.up_attn[idx] is not None:
                    x = self.up_attn[idx](x, context)
                idx += 1
            if lvl > 0:
                x = F.interpolate(x, scale_factor=2, mode="nearest")
                x = self.upsamplers[us](x)
                us += 1

        return self.conv_out(F.silu(self.norm_out(x)))
