"""Regularizers (reference: python/paddle/regularizer.py — L1Decay/L2Decay
objects consumed per-parameter via ParamAttr.regularizer or globally via
Optimizer(weight_decay=...)); applied in Optimizer.step as a gradient
augmentation, exactly the reference's append_regularization_ops semantics."""
from __future__ import annotations

import jax.numpy as jnp

__all__ = ["WeightDecayRegularizer", "L1Decay", "L2Decay"]


class WeightDecayRegularizer:
    """Base class (reference regularizer.py:25)."""

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)

    @property
    def coeff(self):
        return self._coeff

    def __call__(self, param_value):
        """Return d(penalty)/d(param) to add to the gradient."""
        raise NotImplementedError

    def __repr__(self):
        return f"{type(self).__name__}(coeff={self._coeff})"


class L1Decay(WeightDecayRegularizer):
    """penalty = coeff * sum |w|  ->  grad += coeff * sign(w)
    (reference regularizer.py:60)."""

    def __call__(self, param_value):
        return self._coeff * jnp.sign(param_value)


class L2Decay(WeightDecayRegularizer):
    """penalty = 0.5 * coeff * sum w^2  ->  grad += coeff * w
    (reference regularizer.py:141)."""

    def __call__(self, param_value):
        return self._coeff * param_value
