"""paddle.version parity (reference: python/paddle/version/__init__.py —
generated at build time with commit/cuda/cudnn info; here: jax/libtpu)."""
from __future__ import annotations

full_version = "0.1.0"
major, minor, patch = "0", "1", "0"
rc = "0"
commit = "unknown"


def _backend_versions():
    import jax
    import jaxlib
    return {"jax": jax.__version__, "jaxlib": jaxlib.__version__}


def cuda():
    return False  # TPU build


def cudnn():
    return False


def xpu():
    return False


def tpu() -> str:
    try:
        import jax
        kinds = {d.device_kind for d in jax.devices() if d.platform == "tpu"}
        return ",".join(sorted(kinds)) if kinds else "none"
    except Exception:
        return "unknown"


def show():
    print(f"paddle_tpu {full_version}")
    for k, v in _backend_versions().items():
        print(f"{k}: {v}")
    print(f"commit: {commit}")
