"""Sparse tensors (reference: python/paddle/sparse/ — COO/CSR API over
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_*_tensor.h).

TPU-native: backed by jax.experimental.sparse.BCOO (XLA-lowered sparse ops).
CSR round-trips through BCOO (TPU kernels are COO-oriented).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "matmul", "masked_matmul", "nn"]


class SparseCooTensor(Tensor):
    """Tensor whose value is a BCOO; dense ops densify on demand."""

    def __init__(self, bcoo, stop_gradient=True):
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)
        self._bcoo = bcoo

    def indices(self):
        return Tensor(self._bcoo.indices.T)

    def values(self):
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    @property
    def nnz(self):
        return self._bcoo.nse


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)),
                        shape=tuple(shape) if shape else None)
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_v = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cols_v = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    vals_v = np.asarray(values._value if isinstance(values, Tensor) else values)
    rows = np.repeat(np.arange(len(crows_v) - 1), np.diff(crows_v))
    idx = np.stack([rows, cols_v])
    return sparse_coo_tensor(idx, vals_v, shape, dtype, place, stop_gradient)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add_batch_dim if False else None
        s = jsparse.BCOO.sum_duplicates(
            jsparse.BCOO((jnp.concatenate([x._bcoo.data, y._bcoo.data]),
                          jnp.concatenate([x._bcoo.indices, y._bcoo.indices])),
                         shape=x._bcoo.shape))
        return SparseCooTensor(s)
    from ..tensor.math import add as dense_add
    return dense_add(x if not isinstance(x, SparseCooTensor) else x.to_dense(),
                     y if not isinstance(y, SparseCooTensor) else y.to_dense())


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x._bcoo @ yv)
    from ..tensor.linalg import matmul as dense_mm
    return dense_mm(x, y)


def masked_matmul(x, y, mask, name=None):
    from ..tensor.linalg import matmul as dense_mm
    dense = dense_mm(x, y)
    m = mask
    if isinstance(m, SparseCooTensor):
        out = jsparse.BCOO.fromdense(dense._value * (m._bcoo.todense() != 0))
        return SparseCooTensor(out)
    return dense


class _SparseNN:
    """paddle.sparse.nn subset (ReLU on sparse values)."""

    @staticmethod
    def relu(x):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO(
                (jax.nn.relu(x._bcoo.data), x._bcoo.indices), shape=x._bcoo.shape))
        from ..nn.functional import relu as dense_relu
        return dense_relu(x)


nn = _SparseNN()
