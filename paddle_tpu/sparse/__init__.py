"""Sparse tensors (reference: python/paddle/sparse/ — COO/CSR API over
SparseCooTensor/SparseCsrTensor, paddle/phi/core/sparse_*_tensor.h).

TPU-native: backed by jax.experimental.sparse.BCOO (XLA-lowered sparse ops).
CSR round-trips through BCOO (TPU kernels are COO-oriented).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import sparse as jsparse

from ..core.tensor import Tensor

__all__ = ["sparse_coo_tensor", "sparse_csr_tensor", "SparseCooTensor",
           "is_same_shape", "add", "matmul", "masked_matmul", "nn"]


class SparseCooTensor(Tensor):
    """Tensor whose value is a BCOO; dense ops densify on demand."""

    def __init__(self, bcoo, stop_gradient=True):
        super().__init__(bcoo.todense(), stop_gradient=stop_gradient)
        self._bcoo = bcoo

    def indices(self):
        return Tensor(self._bcoo.indices.T)

    def values(self):
        # keep the autograd tape when the producing op attached its
        # value Tensor (sparse nn layers do)
        vt = getattr(self, "_values_t", None)
        if vt is not None:
            return vt
        return Tensor(self._bcoo.data)

    def to_dense(self):
        return Tensor(self._bcoo.todense(), stop_gradient=self.stop_gradient)

    def is_sparse(self):
        return True

    def is_sparse_coo(self):
        return True

    @property
    def nnz(self):
        return self._bcoo.nse


def sparse_coo_tensor(indices, values, shape=None, dtype=None, place=None,
                      stop_gradient=True):
    idx = indices._value if isinstance(indices, Tensor) else jnp.asarray(indices)
    val = values._value if isinstance(values, Tensor) else jnp.asarray(values)
    bcoo = jsparse.BCOO((val, idx.T.astype(jnp.int32)),
                        shape=tuple(shape) if shape else None)
    return SparseCooTensor(bcoo, stop_gradient=stop_gradient)


def sparse_csr_tensor(crows, cols, values, shape, dtype=None, place=None,
                      stop_gradient=True):
    crows_v = np.asarray(crows._value if isinstance(crows, Tensor) else crows)
    cols_v = np.asarray(cols._value if isinstance(cols, Tensor) else cols)
    vals_v = np.asarray(values._value if isinstance(values, Tensor) else values)
    rows = np.repeat(np.arange(len(crows_v) - 1), np.diff(crows_v))
    idx = np.stack([rows, cols_v])
    return sparse_coo_tensor(idx, vals_v, shape, dtype, place, stop_gradient)


def is_same_shape(x, y):
    return tuple(x.shape) == tuple(y.shape)


def add(x, y, name=None):
    if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
        out = jsparse.bcoo_add_batch_dim if False else None
        s = jsparse.BCOO.sum_duplicates(
            jsparse.BCOO((jnp.concatenate([x._bcoo.data, y._bcoo.data]),
                          jnp.concatenate([x._bcoo.indices, y._bcoo.indices])),
                         shape=x._bcoo.shape))
        return SparseCooTensor(s)
    from ..tensor.math import add as dense_add
    return dense_add(x if not isinstance(x, SparseCooTensor) else x.to_dense(),
                     y if not isinstance(y, SparseCooTensor) else y.to_dense())


def matmul(x, y, name=None):
    if isinstance(x, SparseCooTensor):
        yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
        return Tensor(x._bcoo @ yv)
    from ..tensor.linalg import matmul as dense_mm
    return dense_mm(x, y)


def masked_matmul(x, y, mask, name=None):
    from ..tensor.linalg import matmul as dense_mm
    dense = dense_mm(x, y)
    m = mask
    if isinstance(m, SparseCooTensor):
        out = jsparse.BCOO.fromdense(dense._value * (m._bcoo.todense() != 0))
        return SparseCooTensor(out)
    return dense


class _SparseNN:
    """paddle.sparse.nn namespace: layer classes (lazily bound from
    nn_layers to avoid an import cycle with the Layer base) plus the
    functional relu shim kept from round 3."""

    @staticmethod
    def relu(x):
        if isinstance(x, SparseCooTensor):
            return SparseCooTensor(jsparse.BCOO(
                (jax.nn.relu(x._bcoo.data), x._bcoo.indices), shape=x._bcoo.shape))
        from ..nn.functional import relu as dense_relu
        return dense_relu(x)

    def __getattr__(self, name):
        from . import nn_layers
        try:
            return getattr(nn_layers, name)
        except AttributeError:
            raise AttributeError(f"paddle.sparse.nn has no attribute {name!r}")


nn = _SparseNN()


# ---------------------------------------------------------------------------
# Round-3 surface expansion (reference python/paddle/sparse/unary.py,
# binary.py, multiary.py, creation CSR)
# ---------------------------------------------------------------------------
class SparseCsrTensor(SparseCooTensor):
    """CSR view (reference SparseCsrTensor): stored as BCOO (TPU kernels are
    COO-oriented), CSR accessors derived on demand."""

    def __init__(self, bcoo, stop_gradient=True):
        super().__init__(bcoo, stop_gradient=stop_gradient)

    def _csr(self):
        cached = getattr(self, "_csr_cache", None)
        if cached is not None:
            return cached
        idx = np.asarray(self._bcoo.indices)
        rows, cols = idx[:, 0], idx[:, 1]
        order = np.lexsort((cols, rows))
        crows = np.zeros(self.shape[0] + 1, np.int64)
        np.add.at(crows[1:], rows, 1)
        self._csr_cache = (np.cumsum(crows), cols[order],
                           np.asarray(self._bcoo.data)[order])
        return self._csr_cache

    def crows(self):
        return Tensor(jnp.asarray(self._csr()[0]))

    def cols(self):
        return Tensor(jnp.asarray(self._csr()[1]))

    def values(self):
        return Tensor(jnp.asarray(self._csr()[2]))

    def is_sparse_coo(self):
        return False

    def is_sparse_csr(self):
        return True


def from_dense(x, sparse_dim=None):
    """Dense Tensor/array -> SparseCooTensor (reference Tensor.to_sparse_coo).
    sparse_dim: leading dims that are sparse; the rest stay dense (hybrid
    layout — BCOO n_dense)."""
    v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
    n_dense = 0 if sparse_dim is None else v.ndim - int(sparse_dim)
    if sparse_dim is not None and not 1 <= int(sparse_dim) <= v.ndim:
        raise ValueError(f"sparse_dim {sparse_dim} out of range for "
                         f"{v.ndim}-D tensor")
    return SparseCooTensor(jsparse.BCOO.fromdense(v, n_dense=n_dense))


def to_sparse_csr(x):
    """COO/dense -> SparseCsrTensor (2-D only, reference to_sparse_csr)."""
    if isinstance(x, SparseCooTensor):
        bcoo = x._bcoo
    else:
        v = x._value if isinstance(x, Tensor) else jnp.asarray(x)
        bcoo = jsparse.BCOO.fromdense(v)
    if len(bcoo.shape) != 2:
        raise ValueError("to_sparse_csr supports 2-D tensors")
    return SparseCsrTensor(bcoo)


def coalesce(x, name=None):
    """Merge duplicate indices (reference sparse.coalesce)."""
    return SparseCooTensor(jsparse.BCOO.sum_duplicates(x._bcoo))


def transpose(x, perm, name=None):
    """Sparse transpose (reference sparse.transpose)."""
    idx = x._bcoo.indices[:, jnp.asarray(perm)]
    shape = tuple(x._bcoo.shape[p] for p in perm)
    return SparseCooTensor(jsparse.BCOO((x._bcoo.data, idx), shape=shape))


def mv(x, vec, name=None):
    """Sparse matrix × dense vector (reference sparse.mv) — delegates to
    matmul's sparse dispatch."""
    return matmul(x, vec)


def addmm(input, x, y, beta=1.0, alpha=1.0, name=None):
    """beta*input + alpha*(x @ y) with sparse x (reference sparse.addmm)."""
    base = input._value if isinstance(input, Tensor) else jnp.asarray(input)
    yv = y._value if isinstance(y, Tensor) else jnp.asarray(y)
    prod = x._bcoo @ yv if isinstance(x, SparseCooTensor) else \
        (x._value if isinstance(x, Tensor) else jnp.asarray(x)) @ yv
    return Tensor(beta * base + alpha * prod)


def _value_unary(fn_jax, name):
    """Value-wise unary op preserving the sparsity pattern (the reference
    unary.py contract: applied to stored values only — valid for f(0)=0)."""
    def op(x, *a, **kw):
        if isinstance(x, SparseCooTensor):
            return type(x)(jsparse.BCOO(
                (fn_jax(x._bcoo.data, *a, **kw), x._bcoo.indices),
                shape=x._bcoo.shape))
        import paddle_tpu
        return getattr(paddle_tpu, name)(x, *a, **kw)
    op.__name__ = name
    return op


sin = _value_unary(jnp.sin, "sin")
tan = _value_unary(jnp.tan, "tan")
asin = _value_unary(jnp.arcsin, "asin")
atan = _value_unary(jnp.arctan, "atan")
sinh = _value_unary(jnp.sinh, "sinh")
tanh = _value_unary(jnp.tanh, "tanh")
asinh = _value_unary(jnp.arcsinh, "asinh")
atanh = _value_unary(jnp.arctanh, "atanh")
sqrt = _value_unary(jnp.sqrt, "sqrt")
square = _value_unary(jnp.square, "square")
log1p = _value_unary(jnp.log1p, "log1p")
abs = _value_unary(jnp.abs, "abs")
expm1 = _value_unary(jnp.expm1, "expm1")
neg = _value_unary(jnp.negative, "neg")


def pow(x, factor, name=None):
    if isinstance(x, SparseCooTensor):
        return _value_unary(lambda v: jnp.power(v, factor), "pow")(x)
    import paddle_tpu
    return paddle_tpu.pow(x, factor)


def cast(x, index_dtype=None, value_dtype=None, name=None):
    data = x._bcoo.data
    idx = x._bcoo.indices
    if value_dtype is not None:
        data = data.astype(value_dtype)
    if index_dtype is not None:
        idx = idx.astype(index_dtype)
    return type(x)(jsparse.BCOO((data, idx), shape=x._bcoo.shape))


def _sparse_binary(merge, name):
    def op(x, y, name=None):
        if isinstance(x, SparseCooTensor) and isinstance(y, SparseCooTensor):
            # implemented over dense for correctness (XLA fuses; the
            # reference's CSR kernels are a CUDA specialization)
            return from_dense(merge(x._bcoo.todense(), y._bcoo.todense()))
        xd = x.to_dense() if isinstance(x, SparseCooTensor) else x
        yd = y.to_dense() if isinstance(y, SparseCooTensor) else y
        xv = xd._value if isinstance(xd, Tensor) else jnp.asarray(xd)
        yv = yd._value if isinstance(yd, Tensor) else jnp.asarray(yd)
        return Tensor(merge(xv, yv))
    op.__name__ = name
    return op


subtract = _sparse_binary(jnp.subtract, "subtract")
multiply = _sparse_binary(jnp.multiply, "multiply")


def _safe_divide(xv, yv):
    # divide only on the support: implicit zeros stay implicit (0/0 must
    # not become NaN and densify the result)
    support = (xv != 0) & (yv != 0)
    return jnp.where(support, xv / jnp.where(support, yv, 1.0), 0.0)


divide = _sparse_binary(_safe_divide, "divide")

__all__ += ["SparseCsrTensor", "from_dense", "to_sparse_csr", "coalesce",
            "transpose", "mv", "addmm", "sin", "tan", "asin", "atan", "sinh",
            "tanh", "asinh", "atanh", "sqrt", "square", "log1p", "abs",
            "expm1", "neg", "pow", "cast", "subtract", "multiply", "divide"]
