"""Sparse NN layers (reference: python/paddle/sparse/nn/layer/{conv,pooling,
norm,activation}.py — SubmConv3D/Conv3D over SparseCooTensor voxels).

TPU-native design: the reference's gather-GEMM-scatter CUDA kernels
(paddle/phi/kernels/sparse/gpu/conv_kernel.cu) become a rulebook built on
the host (per kernel offset: which active input site feeds which output
site) plus jnp GEMM + segment-sum scatter over those static index maps —
the per-offset GEMMs land on the MXU and the scatter is one XLA
segment_sum. Coordinates are host bookkeeping exactly like the reference's
rulebook construction; the value path is pure jax (differentiable through
op_call's tape w.r.t. values / weight / bias).
"""
from __future__ import annotations

import itertools

import jax
import jax.numpy as jnp
import numpy as np

from ..core.tensor import Tensor
from ..core.dispatch import op_call
from ..nn.layer import Layer
from . import SparseCooTensor, sparse_coo_tensor

__all__ = ["Conv3D", "SubmConv3D", "Conv2D", "SubmConv2D", "MaxPool3D",
           "BatchNorm", "ReLU", "ReLU6", "LeakyReLU", "Softmax"]


def _attach_values(st, vals):
    """Keep the tape-connected value Tensor on the sparse output so
    .values() backward reaches weights (SparseCooTensor.values)."""
    st._values_t = vals
    return st


def _to_list(v, dims, name):
    if isinstance(v, (int, np.integer)):
        return [int(v)] * dims
    out = [int(a) for a in v]
    if len(out) != dims:
        raise ValueError(f"{name} must have {dims} entries, got {out}")
    return out


def _coords_values(x):
    if not isinstance(x, SparseCooTensor):
        raise TypeError("sparse nn layers expect a SparseCooTensor input")
    coords = np.asarray(x._bcoo.indices)        # [nnz, 1+dims] (N + spatial)
    vals = Tensor(x._bcoo.data)                 # [nnz, Cin] dense channels
    vals.stop_gradient = x.stop_gradient
    return coords, vals


def _build_rulebook(coords, spatial_shape, kernel, stride, padding, dilation,
                    subm):
    """Host-side rulebook: for every kernel offset, (in_rows, out_rows)
    index pairs, plus the output coordinate table. subm=True keeps the
    output site set identical to the input's (stride must be 1)."""
    dims = len(kernel)
    n_sp = np.asarray(spatial_shape)
    in_sp = coords[:, 1:1 + dims]
    batch = coords[:, 0]
    if subm:
        if any(s != 1 for s in stride):
            raise ValueError("SubmConv requires stride 1")
        out_coords = coords
        site_ids = {tuple(c): i for i, c in enumerate(coords.tolist())}
        out_sp_shape = list(spatial_shape)
    else:
        out_sp_shape = [(spatial_shape[d] + 2 * padding[d]
                         - dilation[d] * (kernel[d] - 1) - 1) // stride[d] + 1
                        for d in range(dims)]
        site_ids = {}
        out_list = []
    rules = []
    for off in itertools.product(*[range(k) for k in kernel]):
        # output site o satisfies: in = o*stride - pad + off*dilation
        target = in_sp - np.asarray([off[d] * dilation[d]
                                     for d in range(dims)]) \
            + np.asarray(padding)
        ok = np.ones(len(coords), bool)
        for d in range(dims):
            ok &= (target[:, d] % stride[d] == 0)
        out_sp = np.where(ok[:, None], target // np.asarray(stride), -1)
        for d in range(dims):
            ok &= (out_sp[:, d] >= 0) & (out_sp[:, d] < out_sp_shape[d])
        in_rows, out_rows = [], []
        idx_ok = np.nonzero(ok)[0]
        for i in idx_ok:
            key = (int(batch[i]),) + tuple(int(v) for v in out_sp[i])
            if subm:
                j = site_ids.get(key)
                if j is None:
                    continue
            else:
                j = site_ids.get(key)
                if j is None:
                    j = len(out_list)
                    site_ids[key] = j
                    out_list.append(key)
            in_rows.append(int(i))
            out_rows.append(j)
        if in_rows:
            rules.append((off, np.asarray(in_rows), np.asarray(out_rows)))
    if subm:
        out_coords_arr = coords
    else:
        out_coords_arr = np.asarray(out_list, coords.dtype).reshape(
            -1, 1 + dims)
    return rules, out_coords_arr, out_sp_shape


def _sparse_conv(x, weight, bias, kernel, stride, padding, dilation, subm):
    coords, vals = _coords_values(x)
    dims = len(kernel)
    spatial = [int(s) for s in x.shape[1:1 + dims]]
    rules, out_coords, out_sp = _build_rulebook(
        coords, spatial, kernel, stride, padding, dilation, subm)
    n_out = len(out_coords)
    cout = int(weight.shape[-1])

    def impl(v, w, *rest):
        acc = jnp.zeros((n_out, cout), v.dtype)
        for off, in_rows, out_rows in rules:
            contrib = v[in_rows] @ w[off].astype(v.dtype)
            acc = acc + jax.ops.segment_sum(contrib, out_rows, n_out)
        if rest:
            acc = acc + rest[0].astype(acc.dtype)
        return acc

    args = (vals, weight) + ((bias,) if bias is not None else ())
    out_vals = op_call("sparse_conv3d" if dims == 3 else "sparse_conv2d",
                       impl, *args)
    shape = [int(x.shape[0])] + out_sp + [cout]
    return _attach_values(sparse_coo_tensor(
        out_coords.T, out_vals, shape,
        stop_gradient=out_vals.stop_gradient), out_vals)


class _SparseConv(Layer):
    _dims = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, subm=False, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format=None, backend=None):
        super().__init__()
        if groups != 1:
            raise ValueError("sparse conv supports groups=1 only")
        if padding_mode != "zeros":
            raise ValueError("sparse conv supports padding_mode='zeros'")
        d = self._dims
        self._kernel_size = _to_list(kernel_size, d, "kernel_size")
        self._stride = _to_list(stride, d, "stride")
        self._padding = _to_list(padding, d, "padding")
        self._dilation = _to_list(dilation, d, "dilation")
        self._subm = subm
        if subm and any(s != 1 for s in self._stride):
            raise ValueError("SubmConv requires stride 1")
        self._in_channels = in_channels
        self._out_channels = out_channels
        # reference conv.py:108 — weight is [*kernel, in, out]
        self.weight = self.create_parameter(
            tuple(self._kernel_size) + (in_channels, out_channels),
            attr=weight_attr)
        self.bias = self.create_parameter((out_channels,), attr=bias_attr,
                                          is_bias=True) \
            if bias_attr is not False else None

    def forward(self, x):
        return _sparse_conv(x, self.weight, self.bias, self._kernel_size,
                            self._stride, self._padding, self._dilation,
                            self._subm)

    def extra_repr(self):
        return (f"in={self._in_channels}, out={self._out_channels}, "
                f"kernel={self._kernel_size}, subm={self._subm}")


class Conv3D(_SparseConv):
    """Sparse NDHWC Conv3D (reference sparse/nn/layer/conv.py:308)."""
    _dims = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 backend=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format,
                         backend=backend)


class SubmConv3D(_SparseConv):
    """Submanifold sparse Conv3D — output sites == input sites (reference
    conv.py:578; the SECOND Mineko-style conv that keeps sparsity)."""
    _dims = 3

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NDHWC", backend=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, key=key,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format,
                         backend=backend)


class Conv2D(_SparseConv):
    """Sparse NHWC Conv2D (reference conv.py:443)."""
    _dims = 2

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, padding_mode="zeros",
                 weight_attr=None, bias_attr=None, data_format="NHWC",
                 backend=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=False,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format,
                         backend=backend)


class SubmConv2D(_SparseConv):
    """Submanifold sparse Conv2D (reference conv.py:720)."""
    _dims = 2

    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, dilation=1, groups=1, key=None,
                 padding_mode="zeros", weight_attr=None, bias_attr=None,
                 data_format="NHWC", backend=None):
        super().__init__(in_channels, out_channels, kernel_size, stride,
                         padding, dilation, groups, subm=True, key=key,
                         padding_mode=padding_mode, weight_attr=weight_attr,
                         bias_attr=bias_attr, data_format=data_format,
                         backend=backend)


class MaxPool3D(Layer):
    """Sparse NDHWC max pooling (reference sparse/nn/layer/pooling.py:33):
    same rulebook as conv, segment-max reduce."""

    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NDHWC", name=None):
        super().__init__()
        self._kernel = _to_list(kernel_size, 3, "kernel_size")
        self._stride = _to_list(stride if stride is not None else kernel_size,
                                3, "stride")
        self._padding = _to_list(padding, 3, "padding")

    def forward(self, x):
        coords, vals = _coords_values(x)
        spatial = [int(s) for s in x.shape[1:4]]
        rules, out_coords, out_sp = _build_rulebook(
            coords, spatial, self._kernel, self._stride, self._padding,
            [1, 1, 1], subm=False)
        n_out = len(out_coords)
        c = int(x.shape[-1])

        def impl(v):
            acc = jnp.full((n_out, c), -jnp.inf, v.dtype)
            for _off, in_rows, out_rows in rules:
                upd = jax.ops.segment_max(v[in_rows], out_rows, n_out)
                has = jax.ops.segment_sum(
                    jnp.ones(len(in_rows), jnp.float32), out_rows, n_out) > 0
                acc = jnp.where(has[:, None], jnp.maximum(acc, upd), acc)
            return acc

        out_vals = op_call("sparse_maxpool3d", impl, vals)
        shape = [int(x.shape[0])] + out_sp + [c]
        return _attach_values(sparse_coo_tensor(
            out_coords.T, out_vals, shape,
            stop_gradient=out_vals.stop_gradient), out_vals)


class BatchNorm(Layer):
    """BatchNorm over the dense channel of active sites (reference
    sparse/nn/layer/norm.py:35 — applies 1-D BN to the values)."""

    def __init__(self, num_features, momentum=0.9, epsilon=1e-5,
                 weight_attr=None, bias_attr=None, data_format="NDHWC",
                 use_global_stats=None, name=None):
        super().__init__()
        from ..nn.norm import BatchNorm1D
        self._bn = BatchNorm1D(num_features, momentum=momentum,
                               epsilon=epsilon, weight_attr=weight_attr,
                               bias_attr=bias_attr)

    def forward(self, x):
        coords, vals = _coords_values(x)
        out_vals = self._bn(vals)
        return _attach_values(sparse_coo_tensor(
            coords.T, out_vals, [int(s) for s in x.shape],
            stop_gradient=out_vals.stop_gradient), out_vals)

    def train(self):
        super().train()
        self._bn.train()
        return self

    def eval(self):
        super().eval()
        self._bn.eval()
        return self


class _ValueActivation(Layer):
    _fn = None
    _name = "act"

    def forward(self, x):
        coords, vals = _coords_values(x)
        out = op_call(f"sparse_{self._name}", type(self)._fn, vals)
        return _attach_values(sparse_coo_tensor(
            coords.T, out, [int(s) for s in x.shape],
            stop_gradient=out.stop_gradient), out)


class ReLU(_ValueActivation):
    """reference sparse/nn/layer/activation.py:29."""
    _fn = staticmethod(jax.nn.relu)
    _name = "relu"


class ReLU6(_ValueActivation):
    _fn = staticmethod(jax.nn.relu6)
    _name = "relu6"


class LeakyReLU(_ValueActivation):
    _name = "leaky_relu"

    def __init__(self, negative_slope=0.01, name=None):
        super().__init__()
        self._slope = negative_slope

    def forward(self, x):
        coords, vals = _coords_values(x)
        slope = self._slope
        out = op_call("sparse_leaky_relu",
                      lambda v: jax.nn.leaky_relu(v, slope), vals)
        return _attach_values(sparse_coo_tensor(
            coords.T, out, [int(s) for s in x.shape],
            stop_gradient=out.stop_gradient), out)


class Softmax(Layer):
    """Softmax over the dense channel axis of the values (reference
    activation.py:73 — only the last-axis case is supported there too)."""

    def __init__(self, axis=-1, name=None):
        super().__init__()
        if axis != -1:
            raise ValueError("sparse Softmax supports axis=-1 only")

    def forward(self, x):
        coords, vals = _coords_values(x)
        out = op_call("sparse_softmax", lambda v: jax.nn.softmax(v, -1), vals)
        return _attach_values(sparse_coo_tensor(
            coords.T, out, [int(s) for s in x.shape],
            stop_gradient=out.stop_gradient), out)
