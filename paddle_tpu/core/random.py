"""Seeded RNG state.

TPU-native analog of the reference's Generator (paddle/phi/core/generator.h:32)
and the fleet RNGStatesTracker for parallel-deterministic dropout
(python/paddle/distributed/fleet/layers/mpu/random.py). jax's counter-based
``jax.random`` keys replace stateful Philox offsets: a global default
generator holds a key and splits on every draw; named trackers derive
per-mesh-axis keys so TP/PP ranks get deterministic, distinct dropout masks.
"""
from __future__ import annotations

import threading
from typing import Dict

import jax
import jax.numpy as jnp

__all__ = ["seed", "get_rng_state", "set_rng_state", "default_generator",
           "Generator", "RNGStatesTracker", "get_rng_state_tracker", "split_key"]

_DEFAULT_SEED = 0


class Generator:
    """Stateful key holder; ``next_key()`` splits (the seed/offset analog)."""

    def __init__(self, seed: int = 0):
        self._lock = threading.Lock()
        self.manual_seed(seed)

    def manual_seed(self, s: int):
        self._seed = int(s)
        # lazy: PRNGKey initialises the XLA backend, which must not happen
        # at import time (jax.distributed.initialize must run first in
        # multi-process launch — see distributed/env.py)
        self._key = None
        return self

    def initial_seed(self) -> int:
        return self._seed

    def _ensure_key(self):
        if self._key is None:
            self._key = jax.random.PRNGKey(self._seed)

    def next_key(self):
        with self._lock:
            self._ensure_key()
            self._key, sub = jax.random.split(self._key)
            return sub

    def get_state(self):
        with self._lock:
            self._ensure_key()
            return self._key

    def set_state(self, state):
        self._key = jnp.asarray(state, dtype=jnp.uint32)
        return self


default_generator = Generator(_DEFAULT_SEED)


def seed(s: int):
    """paddle.seed parity: reseed the default generator (and all trackers)."""
    default_generator.manual_seed(s)
    _TRACKER.reset(s)
    return default_generator


_TRACE_KEYS = []


class trace_rng:
    """Route RNG draws to a traced key while compiling (used by jit.to_static
    and compiled train steps): inside the context, split_key() derives from
    the supplied (possibly tracer) key so dropout masks are part of the traced
    computation instead of baked-in constants."""

    def __init__(self, key):
        self._key = key

    def __enter__(self):
        _TRACE_KEYS.append(self._key)
        return self

    def __exit__(self, *exc):
        _TRACE_KEYS.pop()
        return False


def split_key():
    """Draw a fresh subkey from the active RNG source (eager: the default
    generator; traced: the trace key stack)."""
    if _TRACE_KEYS:
        key, sub = jax.random.split(_TRACE_KEYS[-1])
        _TRACE_KEYS[-1] = key
        return sub
    return default_generator.next_key()


def get_rng_state():
    return [default_generator.get_state()]


def set_rng_state(state):
    if isinstance(state, (list, tuple)):
        state = state[0]
    default_generator.set_state(state)


class RNGStatesTracker:
    """Named RNG states for hybrid parallel determinism
    (mpu/random.py RNGStatesTracker analog): e.g. 'global_seed' shared across
    the TP group vs 'local_seed' distinct per TP rank, so dropout inside
    column-parallel regions is per-rank while elsewhere replicated."""

    def __init__(self):
        self._states: Dict[str, Generator] = {}

    def reset(self, base_seed: int = 0):
        for name, gen in self._states.items():
            gen.manual_seed(_mix(base_seed, name))

    def add(self, name: str, seed: int):
        if name in self._states:
            raise ValueError(f"rng state {name} already exists")
        self._states[name] = Generator(seed)

    def states(self):
        return {k: g.get_state() for k, g in self._states.items()}

    def set_states(self, states):
        for k, s in states.items():
            self._states.setdefault(k, Generator(0)).set_state(s)

    def key(self, name: str):
        if name not in self._states:
            self.add(name, _mix(default_generator.initial_seed(), name))
        return self._states[name].next_key()

    def rng_state(self, name: str = "global_seed"):
        """Context manager: routes default-generator draws to a named state
        (mpu/random.py get_rng_state_tracker().rng_state() parity)."""
        tracker = self

        class _Ctx:
            def __enter__(self_ctx):
                global default_generator
                if name not in tracker._states:
                    tracker.add(name, _mix(default_generator.initial_seed(), name))
                self_ctx._saved = default_generator
                _swap(tracker._states[name])
                return self_ctx

            def __exit__(self_ctx, *exc):
                _swap(self_ctx._saved)
                return False
        return _Ctx()


def _mix(seed: int, name: str) -> int:
    return (hash((int(seed), name)) & 0x7FFFFFFF)


def _swap(gen: Generator):
    global default_generator
    default_generator = gen


_TRACKER = RNGStatesTracker()


def get_rng_state_tracker() -> RNGStatesTracker:
    return _TRACKER
