"""Device management.

TPU-native analog of paddle/phi/backends/ DeviceManager + python
paddle.device (python/paddle/device/__init__.py). There are no streams —
XLA owns async execution — so stream/event APIs are compatibility shims with
synchronization mapped to ``jax.block_until_ready``.
"""
from __future__ import annotations

import jax

__all__ = ["set_device", "get_device", "device_count", "get_all_device_type",
           "is_compiled_with_cuda", "is_compiled_with_tpu", "synchronize",
           "Stream", "Event", "current_stream"]

_current = ["tpu:0"]


def _platform():
    try:
        return jax.default_backend()
    except Exception:
        return "cpu"


def set_device(device: str):
    """paddle.set_device parity. Accepts 'tpu', 'tpu:0', 'cpu', 'gpu:0'
    (gpu mapped to the default backend for reference-script compat)."""
    dev = device.lower()
    if dev.startswith("gpu") or dev.startswith("cuda") or dev.startswith("xpu"):
        dev = dev.replace("gpu", "tpu").replace("cuda", "tpu").replace("xpu", "tpu")
    _current[0] = dev if ":" in dev else f"{dev}:0"
    return _current[0]


def get_device() -> str:
    plat = _platform()
    if plat == "cpu":
        return "cpu"
    idx = _current[0].split(":")[1] if ":" in _current[0] else "0"
    return f"{plat}:{idx}"


def device_count() -> int:
    return jax.device_count()


def local_device_count() -> int:
    return jax.local_device_count()


def get_all_device_type():
    return sorted({d.platform for d in jax.devices()})


def is_compiled_with_cuda() -> bool:
    return False


def is_compiled_with_rocm() -> bool:
    return False


def is_compiled_with_xpu() -> bool:
    return False


def is_compiled_with_tpu() -> bool:
    return any(d.platform == "tpu" for d in jax.devices())


def is_compiled_with_distribute() -> bool:
    return True


def synchronize(device=None):
    """Block until all dispatched work completes (device.synchronize parity).
    XLA has no user-visible streams; sync via a trivial barrier value."""
    import jax.numpy as jnp
    jnp.zeros(()).block_until_ready()


class Stream:
    """Compatibility shim: XLA schedules asynchronously; wait == barrier."""

    def __init__(self, device=None, priority=2):
        self.device = device

    def synchronize(self):
        synchronize()

    def wait_stream(self, other):
        synchronize()

    def record_event(self, event=None):
        event = event or Event()
        return event

    def wait_event(self, event):
        pass


class Event:
    def __init__(self, enable_timing=False, blocking=False, interprocess=False):
        pass

    def record(self, stream=None):
        pass

    def query(self):
        return True

    def synchronize(self):
        synchronize()


_default_stream = Stream()


def current_stream(device=None):
    return _default_stream
