"""Tensor façade over ``jax.Array``.

TPU-native replacement for the reference's DenseTensor + public Tensor
(paddle/phi/core/dense_tensor.h:37, paddle/phi/api/include/tensor.h:82) and the
eager AutogradMeta (paddle/fluid/eager/autograd_meta.h:61): a lightweight
Python wrapper holding a jax value plus autograd metadata. The jax value may be
a concrete ``jax.Array`` (eager mode — dispatch-committed async, the analog of
Paddle's stream-async kernels) or a tracer (inside ``jit``/``grad``
transforms), so the same Tensor code works in both execution modes.

Autograd: ``stop_gradient`` has Paddle semantics (default True; Parameters
default False). ``backward()`` walks the tape built by
:mod:`paddle_tpu.core.autograd`.
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from . import dtype as dtype_mod

__all__ = ["Tensor", "Parameter", "to_tensor", "is_tensor"]

_tensor_method_registry = {}


def monkey_patch_method(name):
    """Register a function as a Tensor method (the analog of the generated
    pybind Tensor methods, paddle/fluid/pybind/eager_method.cc)."""
    def deco(fn):
        setattr(Tensor, name, fn)
        _tensor_method_registry[name] = fn
        return fn
    return deco


@jax.jit
def _split_complex(a):
    return jnp.real(a), jnp.imag(a)


# concretization listener (jit SOT tape recorder): when set, every
# device->host fetch that can steer python control flow reports
# (jax_value, python_result) — the reference SOT's "graph break on
# data-dependent control flow" observation points.
_concretize_hook = [None]


def _notify_concretize(value, result):
    hook = _concretize_hook[0]
    if hook is not None:
        hook(value, result)
    return result


class Tensor:
    __slots__ = ("_value", "stop_gradient", "_grad", "_grad_node", "_out_index",
                 "name", "persistable", "_backward_hooks", "trainable",
                 "_dist_mesh", "_placements", "sequence_parallel",
                 "__weakref__")

    def __init__(self, value, stop_gradient=True, name=None):
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        self.stop_gradient = stop_gradient
        self._grad = None           # Tensor | None
        self._grad_node = None      # autograd.GradNode | None
        self._out_index = 0
        self.name = name
        self.persistable = False
        self.trainable = True
        self._backward_hooks = None

    # -- value access -----------------------------------------------------
    @property
    def value(self):
        return self._value

    @property
    def shape(self):
        return list(self._value.shape)

    @property
    def ndim(self):
        return self._value.ndim

    @property
    def size(self):
        return int(np.prod(self._value.shape)) if self._value.shape else 1

    @property
    def dtype(self):
        return np.dtype(self._value.dtype)

    @property
    def place(self):
        try:
            devs = list(self._value.devices())
            return str(devs[0]) if devs else "tpu"
        except Exception:
            return "traced"

    def numel(self):
        return self.size

    def dim(self):
        return self._value.ndim

    @property
    def is_leaf(self):
        return self._grad_node is None

    def numpy(self):
        v = self._value
        if _concretize_hook[0] is not None:
            # host fetches can steer python control flow: report to the
            # SOT tape recorder (guarded on the full array)
            return _notify_concretize(v, self._numpy_raw())
        return self._numpy_raw()

    def _numpy_raw(self):
        v = self._value
        # some TPU transports (axon tunnel) cannot fetch complex arrays, and
        # a failed attempt poisons the stream — split complex into two real
        # transfers up front (as a compiled program; eager complex ops are
        # equally unreliable there) and recombine on host
        if (isinstance(v, jax.Array) and not isinstance(v, jax.core.Tracer)
                and jnp.issubdtype(v.dtype, jnp.complexfloating)
                and any(d.platform not in ("cpu", "gpu")
                        for d in v.devices())):
            re, im = _split_complex(v)
            return (np.asarray(jax.device_get(re))
                    + 1j * np.asarray(jax.device_get(im))
                    ).astype(np.dtype(v.dtype))
        return np.asarray(jax.device_get(v))

    def item(self, *args):
        # _numpy_raw: exactly one concretize notification per fetch
        if args:
            return _notify_concretize(self._value,
                                      self._numpy_raw().item(*args))
        return _notify_concretize(self._value, self._numpy_raw().item())

    def tolist(self):
        return self.numpy().tolist()

    def __array__(self, dtype=None):
        a = self.numpy()
        return a.astype(dtype) if dtype is not None else a

    def __dlpack__(self, *a, **kw):
        return self._value.__dlpack__(*a, **kw)

    # -- autograd ----------------------------------------------------------
    @property
    def grad(self):
        return self._grad

    @grad.setter
    def grad(self, g):
        self._grad = g

    def clear_grad(self):
        self._grad = None

    def clear_gradient(self, set_to_zero=False):
        if set_to_zero and self._grad is not None:
            self._grad = Tensor(jnp.zeros_like(self._grad._value))
        else:
            self._grad = None

    def backward(self, grad_tensor=None, retain_graph=False):
        from . import autograd
        autograd.backward([self], [grad_tensor] if grad_tensor is not None else None,
                          retain_graph=retain_graph)

    def register_hook(self, hook):
        """Register a grad hook (reference: paddle/fluid/eager/hooks.h).
        Returns a removable handle."""
        if self._backward_hooks is None:
            self._backward_hooks = []
        self._backward_hooks.append(hook)
        hooks = self._backward_hooks
        class _Handle:
            def remove(self):
                if hook in hooks:
                    hooks.remove(hook)
        return _Handle()

    def detach(self):
        t = Tensor(self._value, stop_gradient=True, name=self.name)
        return t

    def detach_(self):
        self._grad_node = None
        self.stop_gradient = True
        return self

    def clone(self):
        from .dispatch import op_call
        return op_call("clone", lambda x: x + jnp.zeros((), dtype=x.dtype) if jnp.issubdtype(x.dtype, jnp.inexact) else x, self)

    # -- in-place-ish helpers ---------------------------------------------
    def _set_value(self, value):
        """Replace the underlying buffer (used by optimizers / set_state_dict).
        Detaches from any recorded graph."""
        if isinstance(value, Tensor):
            value = value._value
        self._value = value
        return self

    def set_value(self, value):
        if isinstance(value, (np.ndarray, list, tuple, int, float)):
            value = jnp.asarray(value, dtype=self._value.dtype)
        return self._set_value(value)

    def copy_(self, other, blocking=True):
        return self._set_value(other)

    def fill_(self, v):
        return self._set_value(jnp.full_like(self._value, v))

    def zero_(self):
        return self._set_value(jnp.zeros_like(self._value))

    # -- misc --------------------------------------------------------------
    def astype(self, dtype):
        from .dispatch import op_call
        d = dtype_mod.convert_dtype(dtype)
        return op_call("cast", lambda x: x.astype(d), self)

    def cast(self, dtype):
        return self.astype(dtype)

    def to(self, *args, **kwargs):
        # Accepts dtype and/or device strings; device moves are XLA-managed.
        out = self
        for a in list(args) + list(kwargs.values()):
            if isinstance(a, str) and a in ("cpu", "tpu", "gpu") or ":" in str(a):
                continue
            try:
                out = out.astype(a)
            except ValueError:
                continue
        return out

    def cpu(self):
        return Tensor(jax.device_get(self._value), stop_gradient=self.stop_gradient)

    def pin_memory(self):
        return self

    def contiguous(self):
        return self

    def is_contiguous(self):
        return True

    def __len__(self):
        if self._value.ndim == 0:
            raise TypeError("len() of a 0-d tensor")
        return self._value.shape[0]

    def __repr__(self):
        sg = self.stop_gradient
        try:
            val = np.asarray(self._value)
            body = np.array2string(val, precision=6, separator=", ")
        except Exception:
            body = f"<traced {self._value}>"
        return (f"Tensor(shape={self.shape}, dtype={self.dtype.name}, "
                f"stop_gradient={sg},\n       {body})")

    def __hash__(self):
        return id(self)

    def __bool__(self):
        return _notify_concretize(self._value, bool(self._value))

    def __int__(self):
        return _notify_concretize(self._value, int(self._value))

    def __float__(self):
        return _notify_concretize(self._value, float(self._value))

    def __index__(self):
        return _notify_concretize(self._value, int(self._value))

    def __iter__(self):
        for i in range(len(self)):
            yield self[i]

    def __format__(self, spec):
        if self._value.ndim == 0:
            return format(self.item(), spec)
        return repr(self)

    # Arithmetic dunders are attached in paddle_tpu/tensor/__init__.py via
    # monkey_patch_method, mirroring how the reference patches math methods
    # onto Tensor (python/paddle/tensor/tensor.prototype.pyi pattern).


class Parameter(Tensor):
    """Trainable tensor (reference: python/paddle/base/framework.py
    EagerParamBase). stop_gradient defaults to False."""
    __slots__ = ("optimize_attr", "regularizer", "do_model_average", "need_clip",
                 "is_distributed")

    def __init__(self, value, trainable=True, name=None):
        super().__init__(value, stop_gradient=not trainable, name=name)
        self.trainable = trainable
        self.persistable = True
        self.optimize_attr = {"learning_rate": 1.0}
        self.regularizer = None
        self.do_model_average = None
        self.need_clip = True
        self.is_distributed = False

    @property
    def trainable_(self):
        return self.trainable


def is_tensor(x):
    return isinstance(x, Tensor)


def to_tensor(data, dtype=None, place=None, stop_gradient=True):
    """paddle.to_tensor parity (reference python/paddle/tensor/creation.py)."""
    d = dtype_mod.convert_dtype(dtype)
    if isinstance(data, Tensor):
        v = data._value
        if d is not None and v.dtype != d:
            v = v.astype(d)
        return Tensor(v, stop_gradient=stop_gradient)
    if isinstance(data, (list, tuple)) and any(isinstance(x, Tensor) for x in data):
        data = [x._value if isinstance(x, Tensor) else x for x in data]
        v = jnp.stack([jnp.asarray(x) for x in data])
    else:
        if isinstance(data, (float, int, bool, complex)) or (
                isinstance(data, np.ndarray) and d is None):
            # match paddle: python floats default to the default float dtype
            if isinstance(data, bool):
                v = jnp.asarray(data)
            elif isinstance(data, float):
                v = jnp.asarray(data, dtype=dtype_mod.default_float_dtype())
            elif isinstance(data, int):
                v = jnp.asarray(data, dtype=jnp.int64 if jax.config.jax_enable_x64 else jnp.int32)
            else:
                # numpy array: preserve its dtype (downcast 64-bit under x32)
                v = jnp.asarray(data)
        else:
            v = jnp.asarray(data, dtype=d)
    if d is not None and v.dtype != d:
        v = v.astype(d)
    return Tensor(v, stop_gradient=stop_gradient)


# -- pytree registration ---------------------------------------------------
def _tensor_flatten(t: Tensor):
    return (t._value,), (t.stop_gradient, t.name)


def _tensor_unflatten(aux, children):
    t = Tensor(children[0], stop_gradient=aux[0], name=aux[1])
    return t


jax.tree_util.register_pytree_node(Tensor, _tensor_flatten, _tensor_unflatten)
jax.tree_util.register_pytree_node(
    Parameter,
    lambda p: ((p._value,), (p.trainable, p.name)),
    lambda aux, ch: Parameter(ch[0], trainable=aux[0], name=aux[1]),
)
