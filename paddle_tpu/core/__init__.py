from . import dtype  # noqa: F401
from . import tensor  # noqa: F401
from . import dispatch  # noqa: F401
from . import autograd  # noqa: F401
from . import random  # noqa: F401
from . import device  # noqa: F401
