"""Dtype registry for paddle_tpu.

TPU-native dtype system: thin aliases over numpy/jax dtypes with the same
surface the reference exposes through ``paddle.dtype`` (reference:
paddle/phi/common/data_type.h, python/paddle/framework/dtype.py). On TPU,
bfloat16 is the preferred compute dtype (MXU-native); float32 is the default
parameter dtype.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

# Canonical dtypes (jnp dtype objects double as the public `paddle_tpu.float32`
# style aliases).
bool_ = jnp.bool_
uint8 = jnp.uint8
int8 = jnp.int8
int16 = jnp.int16
int32 = jnp.int32
int64 = jnp.int64
float16 = jnp.float16
bfloat16 = jnp.bfloat16
float32 = jnp.float32
float64 = jnp.float64
complex64 = jnp.complex64
complex128 = jnp.complex128
float8_e4m3fn = jnp.float8_e4m3fn
float8_e5m2 = jnp.float8_e5m2

_STR_TO_DTYPE = {
    "bool": bool_,
    "uint8": uint8,
    "int8": int8,
    "int16": int16,
    "int32": int32,
    "int64": int64,
    "float16": float16,
    "bfloat16": bfloat16,
    "bf16": bfloat16,
    "float32": float32,
    "float64": float64,
    "complex64": complex64,
    "complex128": complex128,
    "float8_e4m3fn": float8_e4m3fn,
    "float8_e5m2": float8_e5m2,
    "fp16": float16,
    "fp32": float32,
    "fp64": float64,
}

_DEFAULT_DTYPE = [jnp.float32]


def convert_dtype(dtype):
    """Normalize a dtype spec (str / np dtype / jnp dtype / paddle-style) to a
    numpy dtype object usable by jax."""
    if dtype is None:
        return None
    if isinstance(dtype, str):
        key = dtype.lower().replace("paddle.", "")
        if key not in _STR_TO_DTYPE:
            raise ValueError(f"Unknown dtype string: {dtype!r}")
        return np.dtype(_STR_TO_DTYPE[key])
    try:
        return np.dtype(dtype)
    except TypeError:
        raise ValueError(f"Cannot interpret {dtype!r} as a dtype")


def dtype_name(dtype) -> str:
    return np.dtype(dtype).name


def set_default_dtype(dtype):
    """paddle.set_default_dtype equivalent (reference:
    python/paddle/framework/framework.py)."""
    d = convert_dtype(dtype)
    if not np.issubdtype(d, np.floating) and d != np.dtype(jnp.bfloat16):
        raise TypeError(f"default dtype must be floating, got {dtype}")
    _DEFAULT_DTYPE[0] = d
    return d


def get_default_dtype():
    return np.dtype(_DEFAULT_DTYPE[0]).name


def default_float_dtype():
    return _DEFAULT_DTYPE[0]


def is_floating_point_dtype(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.floating)


def is_integer_dtype(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.integer)


def is_inexact_dtype(dtype) -> bool:
    return jnp.issubdtype(np.dtype(dtype), jnp.inexact)
