"""Tape-based eager autograd engine.

TPU-native analog of the reference's eager backward engine
(paddle/fluid/eager/backward.cc:106 RunBackward — in-degree map + ready-queue
BFS; grad_node_info.h:197 GradNodeBase; accumulation/ leaf AccumulationNode).

Design: each recorded op holds a ``jax.vjp`` residual closure (the
TensorWrapper analog — residuals live on-device inside the closure). Backward
walks the node graph in reverse with dependency counting exactly like the
reference's ready-queue loop, accumulating output-grad contributions per node
and depositing leaf grads into ``Tensor.grad``. Node bodies are jax functions,
so the whole backward can also run under ``jit`` tracing.
"""
from __future__ import annotations

from collections import deque
from typing import List, Optional, Sequence

import jax
import jax.numpy as jnp

from .tensor import Tensor

__all__ = ["GradNode", "backward", "grad"]


class GradNode:
    """One recorded op on the tape (GradNodeBase analog)."""
    __slots__ = ("name", "vjp_fn", "inputs", "out_avals", "multi",
                 "out_grads", "out_tensors")

    def __init__(self, name, vjp_fn, inputs: List[Tensor], out_avals, multi):
        self.name = name
        self.vjp_fn = vjp_fn
        self.inputs = inputs            # Tensors we differentiate w.r.t.
        self.out_avals = out_avals      # [(shape, dtype), ...]
        self.multi = multi
        self.out_grads: List = [None] * len(out_avals)
        self.out_tensors: List = [None] * len(out_avals)  # weakrefs (hooks)

    def attach_output(self, index, tensor):
        import weakref
        self.out_tensors[index] = weakref.ref(tensor)

    def release(self):
        self.vjp_fn = None
        self.out_grads = [None] * len(self.out_avals)

    def accumulate_out_grad(self, index, g):
        if self.out_grads[index] is None:
            self.out_grads[index] = g
        else:
            self.out_grads[index] = self.out_grads[index] + g


def _is_float0(g):
    return hasattr(g, "dtype") and g.dtype == jax.dtypes.float0


def _run_hooks(t: Tensor, g):
    if t._backward_hooks:
        for hook in list(t._backward_hooks):
            out = hook(Tensor(g))
            if out is not None:
                g = out._value if isinstance(out, Tensor) else out
    return g


def _topo_collect(roots: Sequence[GradNode]):
    """BFS the reachable node graph; count consumer references per node
    (the in-degree map of backward.cc:36)."""
    indeg = {}
    seen = set()
    q = deque()
    for n in roots:
        if id(n) not in seen:
            seen.add(id(n))
            indeg[id(n)] = indeg.get(id(n), 0)
            q.append(n)
    nodes = {id(n): n for n in roots}
    while q:
        n = q.popleft()
        for t in n.inputs:
            p = t._grad_node
            if p is None or t.stop_gradient:
                continue
            indeg[id(p)] = indeg.get(id(p), 0) + 1
            if id(p) not in seen:
                seen.add(id(p))
                nodes[id(p)] = p
                q.append(p)
    return nodes, indeg


def _engine(out_tensors: Sequence[Tensor], out_grads: Sequence,
            retain_graph: bool,
            capture: Optional[dict] = None,
            accumulate_leaf: bool = True):
    """Core ready-queue loop (backward.cc:255 analog).

    capture: optional {id(tensor): slot} — when a grad flows into one of these
    tensors, store it there (used by paddle_tpu.grad partial grads).

    Hooks fire ONCE per tensor on the fully-accumulated gradient (reference
    GradientAccumulator semantics): leaf grads buffer locally until the walk
    finishes; intermediate-tensor hooks run when their node becomes ready.
    """
    leaf_acc = {}  # id(t) -> [tensor, value]

    def deposit_leaf(t, g):
        slot = leaf_acc.get(id(t))
        if slot is None:
            leaf_acc[id(t)] = [t, g]
        else:
            slot[1] = slot[1] + g

    roots = []
    for t, g in zip(out_tensors, out_grads):
        node = t._grad_node
        if node is None:
            # output is a leaf: its grad is just g
            if capture is not None and id(t) in capture:
                capture[id(t)].append(g)
            elif accumulate_leaf and not t.stop_gradient:
                deposit_leaf(t, g)
            continue
        node.accumulate_out_grad(t._out_index, g)
        roots.append(node)

    if not roots and not leaf_acc:
        return
    nodes, indeg = _topo_collect(roots)
    ready = deque(n for n in nodes.values() if indeg[id(n)] == 0)

    while ready:
        node = ready.popleft()
        if node.vjp_fn is None:
            raise RuntimeError(
                f"Trying to backward through node '{node.name}' a second time "
                "(set retain_graph=True to allow).")
        # zero-fill missing output grads; cast to the primal-output dtype
        # (AMP: upstream fp32 grads meet bf16 outputs); run output-tensor
        # hooks once on the accumulated grad
        cts = []
        for k, ((shape, dt), g) in enumerate(zip(node.out_avals, node.out_grads)):
            if g is None:
                g = jnp.zeros(shape, dt)
            else:
                if hasattr(g, "dtype") and g.dtype != dt:
                    g = g.astype(dt)
                ref = node.out_tensors[k]
                t_out = ref() if ref is not None else None
                if t_out is not None:
                    g = _run_hooks(t_out, g)
            cts.append(g)
        ct = tuple(cts) if node.multi else cts[0]
        in_grads = node.vjp_fn(ct)
        if not retain_graph:
            node.release()
        else:
            node.out_grads = [None] * len(node.out_avals)
        for t, g in zip(node.inputs, in_grads):
            if _is_float0(g):
                continue
            parent = t._grad_node
            if capture is not None and id(t) in capture:
                capture[id(t)].append(g)
                # still propagate further (tensor may also be upstream of others)
            if parent is None or t.stop_gradient:
                if t.stop_gradient:
                    continue
                if accumulate_leaf and (capture is None or id(t) not in capture):
                    deposit_leaf(t, g)
                continue
            parent.accumulate_out_grad(t._out_index, g)
            indeg[id(parent)] -= 1
            if indeg[id(parent)] == 0:
                ready.append(parent)

    # finalize leaves: hooks once on the total, then accumulate into .grad
    for t, g in leaf_acc.values():
        g = _run_hooks(t, g)
        t._grad = Tensor(g) if t._grad is None else Tensor(t._grad._value + g)


def _default_grad(t: Tensor):
    if not jnp.issubdtype(t._value.dtype, jnp.inexact):
        raise RuntimeError("backward() root must be floating point")
    return jnp.ones(t._value.shape, t._value.dtype)


def backward(tensors, grad_tensors=None, retain_graph=False):
    """paddle.autograd.backward parity."""
    if isinstance(tensors, Tensor):
        tensors = [tensors]
    if grad_tensors is None:
        grad_tensors = [None] * len(tensors)
    outs, gs = [], []
    for t, g in zip(tensors, grad_tensors):
        if g is None:
            g = _default_grad(t)
        elif isinstance(g, Tensor):
            g = g._value
        outs.append(t)
        gs.append(g)
    _engine(outs, gs, retain_graph=retain_graph)


def grad(outputs, inputs, grad_outputs=None, retain_graph=None,
         create_graph=False, only_inputs=True, allow_unused=False,
         no_grad_vars=None):
    """paddle.grad parity (reference general_grad.h partial-graph grads).

    Note: create_graph (higher-order through the tape) is supported by
    functional re-derivation: use paddle_tpu.incubate.autograd or nest
    jax-level transforms for higher-order; the tape itself records first-order.
    """
    single = isinstance(outputs, Tensor)
    outputs = [outputs] if single else list(outputs)
    single_in = isinstance(inputs, Tensor)
    inputs = [inputs] if single_in else list(inputs)
    if grad_outputs is None:
        grad_outputs = [None] * len(outputs)
    grad_outputs = [(_default_grad(o) if g is None else
                     (g._value if isinstance(g, Tensor) else g))
                    for o, g in zip(outputs, grad_outputs)]

    capture = {id(t): [] for t in inputs}
    retain = bool(retain_graph) if retain_graph is not None else bool(create_graph)
    _engine(outputs, grad_outputs, retain_graph=retain, capture=capture,
            accumulate_leaf=False)

    results = []
    for t in inputs:
        contribs = capture[id(t)]
        if not contribs:
            if not allow_unused:
                raise RuntimeError(
                    "One of the differentiated tensors appears unused in the "
                    "graph (pass allow_unused=True to return None for it).")
            results.append(None)
        else:
            acc = contribs[0]
            for c in contribs[1:]:
                acc = acc + c
            results.append(Tensor(acc))
    return results[0] if single_in else results
