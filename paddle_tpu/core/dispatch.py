"""Eager op dispatch.

TPU-native analog of the reference's kernel dispatch + generated dygraph
forward functions (paddle/phi/core/kernel_factory.h:316 KernelFactory,
eager_gen.py generated ``*_ad_func``): every functional op funnels through
:func:`op_call`, which

1. resolves the kernel implementation from the registry (default = jax/XLA;
   Pallas overrides register under the same op name — the
   ``PD_REGISTER_KERNEL`` analog, paddle/phi/core/kernel_registry.h:196),
2. applies AMP auto-cast when an amp context is active (eager_gen.py:645),
3. unwraps Tensor arguments to jax values,
4. when grad is required, runs the op under ``jax.vjp`` and records a GradNode
   on the tape (eager_gen.py:1175 GenerateNodeCreationCodes analog),
5. wraps outputs back into Tensors,
6. optionally NaN/Inf-checks outputs (FLAGS_check_nan_inf, eager_gen.py:749).

Because jax values may be tracers, the same dispatch path works inside
``jit``-traced step functions; in that case the "eager" ops stage XLA HLO
instead of executing immediately — the executor role collapses into XLA.
"""
from __future__ import annotations

import functools
import threading
import time
from typing import Any, Callable, Dict, Optional

import jax
import jax.numpy as jnp

from .tensor import Tensor
from .. import flags

__all__ = ["op_call", "register_kernel", "get_kernel", "no_grad",
           "enable_grad", "is_grad_enabled", "set_grad_enabled", "defop"]

# --------------------------------------------------------------------------
# Kernel registry: op name -> {impl_name: fn}. "default" = jax/XLA impl;
# "pallas" overrides win when FLAGS_use_pallas_kernels is on.
# --------------------------------------------------------------------------
_KERNELS: Dict[str, Dict[str, Callable]] = {}

# Deferred registration hooks (e.g. Pallas overrides, which must probe the
# device platform — an XLA-backend-initialising call that cannot happen at
# import time in multi-process launches). Run once, on first kernel lookup.
_lazy_initializers = []
_lazy_lock = threading.Lock()


def add_lazy_initializer(fn: Callable):
    _lazy_initializers.append(fn)


def _run_lazy_initializers():
    if not _lazy_initializers:
        return
    with _lazy_lock:
        while _lazy_initializers:
            fn = _lazy_initializers.pop(0)
            fn()


def register_kernel(name: str, impl: str = "default"):
    """PD_REGISTER_KERNEL analog (kernel_registry.h:196)."""
    def deco(fn):
        _KERNELS.setdefault(name, {})[impl] = fn
        return fn
    return deco


def get_kernel(name: str, default: Optional[Callable] = None) -> Optional[Callable]:
    _run_lazy_initializers()
    impls = _KERNELS.get(name)
    if not impls:
        return default
    if flags.get_flag("use_pallas_kernels") and "pallas" in impls:
        return impls["pallas"]
    return impls.get("default", default)


# --------------------------------------------------------------------------
# Grad mode (reference: python/paddle/base/dygraph/base.py no_grad_,
# egr::Controller::HasGrad)
# --------------------------------------------------------------------------
class _GradMode:
    enabled = True


class _GradGuard:
    def __init__(self, mode: bool):
        self._mode = mode
        self._prev = None

    def __enter__(self):
        self._prev = _GradMode.enabled
        _GradMode.enabled = self._mode
        return self

    def __exit__(self, *exc):
        _GradMode.enabled = self._prev
        return False

    def __call__(self, fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            with _GradGuard(self._mode):
                return fn(*args, **kwargs)
        return wrapper


def no_grad(func=None):
    """Usable as context manager or decorator (paddle.no_grad parity)."""
    g = _GradGuard(False)
    if func is not None:
        return g(func)
    return g


def enable_grad(func=None):
    g = _GradGuard(True)
    if func is not None:
        return g(func)
    return g


def is_grad_enabled() -> bool:
    return _GradMode.enabled


class _SetGradEnabled:
    """paddle.set_grad_enabled parity: takes effect immediately AND works as
    a context manager that restores the previous mode on exit."""

    def __init__(self, mode: bool):
        self._prev = _GradMode.enabled
        _GradMode.enabled = bool(mode)

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        _GradMode.enabled = self._prev
        return False


def set_grad_enabled(mode: bool):
    return _SetGradEnabled(mode)


# --------------------------------------------------------------------------
# AMP hook (filled in by paddle_tpu.amp to avoid an import cycle).
# --------------------------------------------------------------------------
_amp_cast_hook = [None]  # fn(op_name, tensor_values:list, tensor_idx) -> values


def _set_amp_hook(fn):
    _amp_cast_hook[0] = fn


# Debug-mode op recorder (subgraph accuracy checker, reference
# sub_graph_checker.cc): when set, every eager op appends
# (name, input_values, output_values) — concrete values only.
_op_recorder = [None]


def _record_op(name, vals, outs, impl=None, static_kwargs=None):
    rec = _op_recorder[0]
    if rec is None:
        return
    if any(isinstance(v, jax.core.Tracer) for v in vals) or \
       any(isinstance(o, jax.core.Tracer) for o in outs):
        return  # tracing (inside jit): not an eager execution
    rec.append((name, tuple(vals), tuple(outs), impl, dict(static_kwargs or {})))


# profiler op-timing hook (reference profiler_statistic.py's host-op events):
# when set (by profiler.Profiler.start), every eager op_call appends
# (name, t_start_s, dur_s, out_shapes). Timing is blocking — the profiler
# trades throughput for per-op attribution, like the reference's tracer.
_op_timer = [None]


def _timed_exec(name, fn):
    timer = _op_timer[0]
    if timer is None:
        return fn()
    t0 = time.perf_counter()
    out = fn()
    try:
        arrs = [x for x in jax.tree_util.tree_leaves(out)
                if isinstance(x, jax.Array)]
        jax.block_until_ready(arrs)
    except Exception:
        pass
    dur = time.perf_counter() - t0
    outs = out if isinstance(out, (tuple, list)) else (out,)
    shapes = tuple(tuple(getattr(o, "shape", ())) for o in outs
                   if hasattr(o, "shape"))
    timer.append((name, t0, dur, shapes))
    return out


def _check_numerics(name, vals):
    import numpy as np
    for v in vals:
        if hasattr(v, "dtype") and jnp.issubdtype(v.dtype, jnp.inexact):
            try:
                arr = np.asarray(v)
            except Exception:
                return  # tracer: skip (use jax.debug_nans under jit)
            if not np.all(np.isfinite(arr)):
                msg = f"NaN/Inf detected in output of op '{name}'"
                if flags.get_flag("check_nan_inf_level") >= 1:
                    import warnings
                    warnings.warn(msg)
                else:
                    raise FloatingPointError(msg)


# --------------------------------------------------------------------------
# The dispatch entry.
# --------------------------------------------------------------------------
def op_call(name: str, fn: Callable, *args, nondiff: bool = False, **static_kwargs):
    """Execute op `name` with jax-level impl `fn`.

    Positional args may be Tensors (differentiable inputs) or raw values;
    static_kwargs are non-differentiable config. Returns Tensor or tuple of
    Tensors mirroring fn's output structure.
    """
    impl = get_kernel(name, fn)

    tensor_idx = []
    vals = []
    for i, a in enumerate(args):
        if isinstance(a, Tensor):
            vals.append(a._value)
            tensor_idx.append(i)
        else:
            vals.append(a)

    if _amp_cast_hook[0] is not None:
        vals = _amp_cast_hook[0](name, vals, tensor_idx)

    need_grad = (not nondiff) and _GradMode.enabled and any(
        not args[i].stop_gradient for i in tensor_idx)

    # Under an outer jit/grad trace the tape is NOT the autodiff engine —
    # the outer jax transform differentiates the staged ops directly.
    # Recording the inner jax.vjp there is wasted work AND breaks
    # custom_vjp kernel impls (the outer grad would have to differentiate
    # through the inner linearization: "Linearization failed to produce
    # known values"). Stage the op plainly and let outer autodiff own it.
    tracing = any(isinstance(v, jax.core.Tracer) for v in vals)

    if need_grad:
        # differentiate only w.r.t. inexact-dtype tensor inputs (refined in
        # BOTH modes so traced/eager stop_gradient semantics agree)
        diff_idx = [i for i in tensor_idx
                    if jnp.issubdtype(jnp.result_type(vals[i]), jnp.inexact)]
        need_grad = bool(diff_idx)

    if not need_grad or tracing:
        out = _timed_exec(name, lambda: impl(*vals, **static_kwargs))
        multi = isinstance(out, (tuple, list))
        outs = tuple(out) if multi else (out,)
        if flags.get_flag("check_nan_inf"):
            _check_numerics(name, outs)
        _record_op(name, vals, outs, impl, static_kwargs)
        # keep differentiability visible to downstream eager semantics
        sg = (not need_grad) if tracing else True
        wrapped = tuple(Tensor(o, stop_gradient=sg) if not isinstance(o, Tensor) else o
                        for o in outs)
        return wrapped if multi else wrapped[0]

    def f(*diff_vals):
        vv = list(vals)
        for i, dv in zip(diff_idx, diff_vals):
            vv[i] = dv
        return impl(*vv, **static_kwargs)

    primals = [vals[i] for i in diff_idx]
    out, vjp_fn = _timed_exec(name, lambda: jax.vjp(f, *primals))
    multi = isinstance(out, (tuple, list))
    outs = tuple(out) if multi else (out,)
    if flags.get_flag("check_nan_inf"):
        _check_numerics(name, outs)
    _record_op(name, vals, outs, impl, static_kwargs)

    from .autograd import GradNode
    in_tensors = [args[i] for i in diff_idx]
    node = GradNode(name=name, vjp_fn=vjp_fn, inputs=in_tensors,
                    out_avals=[(o.shape, o.dtype) for o in outs], multi=multi)
    wrapped = []
    for k, o in enumerate(outs):
        t = Tensor(o, stop_gradient=False)
        t._grad_node = node
        t._out_index = k
        node.attach_output(k, t)
        wrapped.append(t)
    wrapped = tuple(wrapped)
    return wrapped if multi else wrapped[0]


def defop(name: str, nondiff: bool = False):
    """Decorator: lift a jax-level function into a Tensor-level op going
    through dispatch. The single-source op-spec analog of ops.yaml."""
    def deco(fn):
        @functools.wraps(fn)
        def wrapper(*args, **kwargs):
            return op_call(name, fn, *args, nondiff=nondiff, **kwargs)
        wrapper.__wrapped_jax_impl__ = fn
        return wrapper
    return deco
