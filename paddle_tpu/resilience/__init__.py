"""Resilience layer: deterministic fault injection, crash-consistent
checkpoint management, and the typed failures the self-healing serving
engine surfaces.  See README.md §Resilience for the degradation ladder and
the fault-point catalog (resilience/faults.py docstring)."""
from .faults import (FaultPlan, FaultSpec, InjectedFault, inject,  # noqa: F401
                     fault_point, active_plan)
from .checkpoint import CheckpointManager  # noqa: F401

__all__ = ["FaultPlan", "FaultSpec", "InjectedFault", "inject", "fault_point",
           "active_plan", "CheckpointManager"]
