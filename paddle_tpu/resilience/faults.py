"""Deterministic, scoped fault injection.

Production TPU jobs treat preemption, torn checkpoint writes, NaN bursts,
pool pressure, and slow collectives as *normal operating conditions*; the
recovery paths that handle them are exactly the code that never runs in a
clean CI environment.  This module makes every one of those paths testable
on CPU: subsystems consult named **fault points** (`fault_point(name,
**ctx)`) at the moments where real hardware would fail, and a seeded
:class:`FaultPlan` — activated for a scope with :func:`inject` — decides
deterministically which consults fire.

Fault-point catalog (the consulting subsystem documents exact ctx keys):

==========================  ====================================================
``ckpt.write``              checkpoint writer, once per WRITE_CHUNK bytes per
                            staged file (ctx: ``file``, ``offset``) — ``raise``
                            kills the write mid-file, leaving a torn staging dir
``ckpt.commit``             just before the atomic staging->final rename
                            (ctx: ``path``) — ``raise`` simulates preemption
                            after a complete write but before the commit point
``ckpt.dirsync``            just before the parent-directory-entry fsync that
                            precedes the rename (ctx: ``path``, ``phase``) —
                            ``raise`` kills the commit in the window where the
                            staging dir's NAME is not yet durable
``train.nonfinite``         once per TrainStep call (ctx: ``step``) —
                            ``trigger`` poisons that step's loss+grads with NaN
``pagepool.alloc``          PagePool.alloc (ctx: ``n``, ``free``) — ``raise``
                            injects InjectedFault, ``trigger`` the standard
                            pool-exhausted RuntimeError
``serve.pool_pressure``     once per ServingEngine.step (ctx: ``step``) —
                            ``trigger`` makes the engine see zero free pages
                            that step (exhaustion without shrinking the pool)
``serve.crash``             twice per ServingEngine.step (ctx: ``engine``,
                            ``step``, ``phase`` in {"sched", "record"}) —
                            ``raise`` kills the replica mid-step (after
                            admissions / after token record), stranding its
                            in-flight requests for a fleet to migrate
``serve.wedge``             once per ServingEngine.step (ctx: ``engine``,
                            ``step``) — ``trigger`` makes the step return
                            without doing ANY work (an unresponsive replica;
                            fleet watchdogs see consecutive no-progress
                            heartbeats)
``serve.snapshot``          once per EngineSnapshotManager.save_engine (ctx:
                            ``engine``, ``step``, ``mode``) — ``raise`` dies
                            before anything stages; ``trigger`` TEARS the
                            committed snapshot after the fact (bit-rot),
                            which manifest verification must reject
``spmd.collective``         once per recorded collective in a spmd_sanitize
                            scope (ctx: ``rank``, ``index``, ``kind``) —
                            ``trigger`` drops that rank's event (the
                            skipped-branch divergence drill)
``comm.ready``              wait_with_timeout readiness check (ctx: ``op``) —
                            ``trigger`` simulates a collective that never
                            becomes ready (CommTimeoutError)
``rpc.drop_frame``          RpcClient, once per send attempt (ctx: ``method``,
                            ``attempt``) — ``trigger`` loses the request frame
                            before the wire; the client burns the attempt
                            timeout waiting, then backs off and retries
``rpc.delay_frame``         RpcClient (ctx: ``method``, ``attempt``) —
                            ``trigger`` sends the frame ``fault_delay_s`` late
``rpc.truncate_frame``      RpcClient (ctx: ``method``, ``attempt``) —
                            ``trigger`` sends half the body then kills the
                            connection; the server must drop the torn frame
                            WITHOUT invoking the handler
``rpc.half_open``           RpcClient (ctx: ``method``, ``attempt``) —
                            ``trigger`` delivers the request fully but dies
                            before the reply: the handler runs exactly once
                            and the retry must hit the idempotency cache (the
                            no-double-submit drill)
``thread.interleave``       ThreadSanitizer, once per instrumented lock
                            acquire/release (ctx: ``op``, ``lock``,
                            ``thread``) — ``trigger`` injects a short
                            sleep-yield at that point, steering the OS
                            scheduler into rare interleavings; with a seeded
                            plan the yield schedule is reproducible, turning
                            flaky race reports into deterministic drills
==========================  ====================================================

Firing rules per spec: ``at=k`` fires exactly on the k-th matching consult
(0-based); otherwise consults ``after`` <= hit fire until ``count`` fires
have happened (``count=None`` -> forever).  ``prob`` gates each eligible
fire through the plan-seeded RNG (chaos sweeps).  ``match`` filters consults
by ctx equality, e.g. ``match={"file": "rank0.data"}``.
"""
from __future__ import annotations

import threading
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

__all__ = ["InjectedFault", "FaultSpec", "FaultPlan", "inject", "fault_point",
           "active_plan"]


class InjectedFault(RuntimeError):
    """Raised at a fault point by a firing spec with ``action='raise'``."""


@dataclass
class FaultSpec:
    """One fault rule: where (``point`` + ``match``), when (``at`` /
    ``after`` / ``count`` / ``prob``), and how (``action``)."""
    point: str
    action: str = "raise"          # "raise" -> InjectedFault; "trigger" ->
    at: int | None = None          #   point-specific degraded behavior
    after: int = 0
    count: int | None = 1
    prob: float = 1.0
    match: dict = field(default_factory=dict)
    hits: int = 0                  # matching consults so far (telemetry)
    fired: int = 0

    def __post_init__(self):
        if self.action not in ("raise", "trigger"):
            raise ValueError(f"unknown fault action {self.action!r}")

    def _matches(self, ctx: dict) -> bool:
        return all(ctx.get(k) == v for k, v in self.match.items())


class FaultPlan:
    """A seeded set of :class:`FaultSpec` rules, consulted via
    :func:`fault_point` while active (see :func:`inject`)."""

    def __init__(self, specs=(), seed: int = 0):
        self.specs: list[FaultSpec] = []
        if isinstance(specs, dict):
            specs = [FaultSpec(point=p, **kw) for p, kw in specs.items()]
        for s in specs:
            self.specs.append(s if isinstance(s, FaultSpec)
                              else FaultSpec(**s) if isinstance(s, dict)
                              else FaultSpec(point=s))
        self.seed = int(seed)
        self._rng = np.random.default_rng(self.seed)
        self._lock = threading.Lock()

    def consult(self, point: str, ctx: dict) -> FaultSpec | None:
        """Count a hit on every matching spec; return the first that fires."""
        firing = None
        with self._lock:
            for spec in self.specs:
                if spec.point != point or not spec._matches(ctx):
                    continue
                h = spec.hits
                spec.hits += 1
                if firing is not None:
                    continue  # one action per consult: later specs keep
                              # their hit count but spend no fire budget
                if spec.at is not None:
                    eligible = h == spec.at
                else:
                    eligible = h >= spec.after and (
                        spec.count is None or spec.fired < spec.count)
                if eligible and (spec.prob >= 1.0
                                 or self._rng.random() < spec.prob):
                    spec.fired += 1
                    firing = spec
        return firing

    def fired(self, point: str | None = None) -> int:
        return sum(s.fired for s in self.specs
                   if point is None or s.point == point)

    def hits(self, point: str | None = None) -> int:
        return sum(s.hits for s in self.specs
                   if point is None or s.point == point)


# Active-plan stack. Module-level (not thread-local) on purpose: faults must
# be visible to worker threads the scope spawns (async checkpoint writers,
# watchdog waiters). tests/conftest.py asserts it is empty between tests.
_ACTIVE: list[FaultPlan] = []
_STACK_LOCK = threading.Lock()


def active_plan() -> FaultPlan | None:
    return _ACTIVE[-1] if _ACTIVE else None


@contextmanager
def inject(plan=None, *, seed: int = 0, **kw):
    """Activate a fault plan for the enclosed scope (re-entrant; the innermost
    plan wins). Accepts a :class:`FaultPlan`, or anything
    ``FaultPlan(specs, seed=seed)`` accepts — e.g. a ``{point: rule-kwargs}``
    dict or a list of :class:`FaultSpec`."""
    if not isinstance(plan, FaultPlan):
        plan = FaultPlan(plan or (), seed=seed, **kw)
    with _STACK_LOCK:
        _ACTIVE.append(plan)
    try:
        yield plan
    finally:
        with _STACK_LOCK:
            _ACTIVE.remove(plan)


def fault_point(name: str, **ctx) -> FaultSpec | None:
    """Consult the active plan at a named fault point.

    Returns None (the overwhelmingly common no-plan / no-fire case), raises
    :class:`InjectedFault` for a firing ``action='raise'`` spec, or returns
    the firing spec for ``action='trigger'`` (the call site degrades
    accordingly)."""
    plan = active_plan()
    if plan is None:
        return None
    spec = plan.consult(name, ctx)
    if spec is not None and spec.action == "raise":
        raise InjectedFault(
            f"injected fault at '{name}' (hit {spec.hits - 1}, ctx={ctx})")
    return spec
