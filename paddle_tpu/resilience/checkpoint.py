"""CheckpointManager: crash-consistent save/rotate/resume for train loops.

Sits on top of the staged, manifest-verified ``distributed.checkpoint``
writer and adds the job-level discipline preempted TPU jobs need:

  * ``maybe_save(step)`` — save every ``save_interval`` steps into
    ``root/step_XXXXXXXX`` (each an atomic rename-committed snapshot);
  * keep-last-N rotation (older snapshots deleted only after the new one is
    durable, so a crash mid-save always leaves an intact predecessor);
  * ``find_latest_complete()`` — newest snapshot that passes manifest
    verification; torn/corrupt snapshots from mid-write preemptions are
    skipped, never loaded;
  * ``restore()`` — exact resume of model params/buffers, optimizer
    accumulators (positionally keyed, so a rebuilt process with different
    auto-generated parameter names still maps correctly), LR-schedule state,
    GradScaler state, the global RNG key, and the step counter.  Resuming
    from a snapshot reproduces the uninterrupted run's loss trajectory
    bit-for-bit (tests/test_resilience.py asserts exact equality).
"""
from __future__ import annotations

import os
import re
import shutil

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["CheckpointManager"]

_STEP_RE = re.compile(r"^step_(\d+)$")


def _nest(flat: dict) -> dict:
    """Rebuild a nested dict from dotted flat keys (py-value metadata)."""
    out: dict = {}
    for k, v in flat.items():
        parts = k.split(".")
        d = out
        for p in parts[:-1]:
            d = d.setdefault(p, {})
        if isinstance(d, dict):
            d[parts[-1]] = v
    return out


def _read_py_values(path) -> dict:
    """Flat {dotted-name: value} for the non-tensor leaves a save recorded in
    metadata.json (step counters, LR-schedule scalars, scaler state)."""
    import json
    with open(os.path.join(path, "metadata.json")) as f:
        meta = json.load(f)
    return {name: e.get("value") for name, e in meta["tensors"].items()
            if e.get("py")}


class CheckpointManager:
    """Drives periodic crash-consistent checkpoints for one training job.

    Any of ``model`` / ``optimizer`` / ``lr_scheduler`` / ``scaler`` may be
    None; only the supplied pieces are saved and restored.  ``extra_state``
    passed to :meth:`save` rides along as py metadata and comes back from
    :meth:`restore` via ``last_extra``.
    """

    def __init__(self, root, model=None, optimizer=None, lr_scheduler=None,
                 scaler=None, save_interval: int = 1, keep_last: int | None = 3,
                 telemetry=None):
        if save_interval < 1:
            raise ValueError("save_interval must be >= 1")
        if keep_last is not None and keep_last < 1:
            raise ValueError("keep_last must be >= 1 (or None to keep all)")
        self.root = os.fspath(root)
        self.model = model
        self.optimizer = optimizer
        self.lr_scheduler = lr_scheduler
        self.scaler = scaler
        self.save_interval = int(save_interval)
        self.keep_last = keep_last
        self.last_extra = None
        # observability.TrainTelemetry (or None = off): ckpt.save /
        # ckpt.stage / ckpt.commit / ckpt.restore spans + flight events,
        # and torn-snapshot rejections recorded with the active FaultPlan
        # context (the chaos-sweep postmortem trail)
        self.telemetry = telemetry
        os.makedirs(self.root, exist_ok=True)

    # -- discovery ---------------------------------------------------------
    def _step_dirs(self):
        """[(step, absolute path)] ascending; final (committed) dirs only.
        A snapshot stranded at ``step_N.old`` by a crash in the commit's
        swap window is healed back to ``step_N`` first, so discovery never
        silently skips the newest intact checkpoint."""
        from ..distributed.checkpoint.save_state_dict import (
            recover_interrupted_commit)
        names = os.listdir(self.root)
        for d in names:
            if d.endswith(".old") and _STEP_RE.match(d[:-4]):
                recover_interrupted_commit(os.path.join(self.root, d[:-4]))
        out = []
        for d in os.listdir(self.root):
            m = _STEP_RE.match(d)
            full = os.path.join(self.root, d)
            if m and os.path.isdir(full):
                out.append((int(m.group(1)), full))
        return sorted(out)

    def find_latest_complete(self):
        """Newest snapshot passing manifest verification, or None.  Torn or
        corrupt snapshots (killed mid-write, bit-flipped files) are skipped —
        resume always lands on the previous intact checkpoint.  Each
        rejection is a telemetry flight event (with the active fault-plan
        context), so a resume that silently skipped a snapshot leaves a
        postmortem trail saying which one and why."""
        from ..distributed.checkpoint import (verify_checkpoint,
                                              CheckpointCorruptError)
        for _, path in reversed(self._step_dirs()):
            try:
                verify_checkpoint(path)
                return path
            except CheckpointCorruptError as e:
                if self.telemetry is not None:
                    self.telemetry.torn_snapshot(path, e)
                continue
        return None

    @staticmethod
    def step_of(path) -> int | None:
        m = _STEP_RE.match(os.path.basename(os.fspath(path).rstrip("/")))
        return int(m.group(1)) if m else None

    # -- save --------------------------------------------------------------
    def should_save(self, step: int) -> bool:
        return step % self.save_interval == 0

    def maybe_save(self, step: int, extra_state=None, async_save=False):
        if self.should_save(step):
            return self.save(step, extra_state=extra_state,
                             async_save=async_save)
        return None

    def _opt_tensor_state(self):
        """Optimizer accumulators keyed positionally (``p{i}.{name}``):
        auto-generated parameter names restart from zero in a fresh process,
        so positional keys are the only stable identity across a resume."""
        opt = self.optimizer
        sd = {}
        for i, p in enumerate(opt._parameter_list):
            st = opt._accumulators.get(id(p))
            if st is None:
                st = opt._init_state(p._value)
            for k, v in st.items():
                sd[f"p{i}.{k}"] = Tensor(v)
        return sd

    def wait(self):
        """Drain pending async saves, re-raising the first writer/commit
        failure — call at job milestones and before relying on a snapshot.
        A surfaced background failure is recorded to telemetry first (the
        launching ``ckpt.save`` span already closed ok=True — async spans
        measure launch, durability is confirmed here)."""
        from ..distributed.checkpoint import wait_async_save
        try:
            wait_async_save()
        except BaseException as e:
            if self.telemetry is not None:
                self.telemetry.async_save_failed(e)
            raise

    def save(self, step: int, extra_state=None, async_save=False):
        """Write one crash-consistent snapshot for ``step`` and rotate.

        Entry first drains any pending async save (pipelined: at most one in
        flight), so a failed background write surfaces HERE instead of
        rotting silently in a thread — training must not believe a
        checkpoint exists when its writer died.

        With telemetry attached, the whole save gets a ``ckpt.save`` span
        and the writer reports its stage/commit sub-phase durations
        (``ckpt.stage_s`` / ``ckpt.commit_s``) via
        ``save_state_dict(on_phase=...)``.  Async caveat: with
        ``async_save=True`` the span (and the ``ckpt.saves`` count) covers
        launch + snapshot capture only — durability is confirmed at the
        next :meth:`wait`/:meth:`save` entry, where a background failure
        records a ``ckpt.async_save_failed`` flight event before
        re-raising."""
        tel = self.telemetry
        if tel is None:
            return self._save_impl(step, extra_state, async_save, None)
        with tel.span("ckpt.save", step=int(step), async_save=async_save):
            path = self._save_impl(step, extra_state, async_save,
                                   tel.phase_event)
        tel.saved(int(step), path)
        return path

    def _save_impl(self, step, extra_state, async_save, on_phase):
        from ..distributed.checkpoint import save_state_dict
        from ..core.random import get_rng_state
        from ..optimizer.lr import LRScheduler
        self.wait()
        state = {"step": int(step),
                 "rng": np.asarray(jax.device_get(get_rng_state()[0]))}
        if self.model is not None:
            state["model"] = dict(self.model.state_dict())
        if self.optimizer is not None:
            state["opt"] = self._opt_tensor_state()
            opt_meta = {"global_step": self.optimizer._global_step}
            if isinstance(self.optimizer._learning_rate, LRScheduler):
                opt_meta["lr_sched"] = \
                    self.optimizer._learning_rate.state_dict()
            state["opt_meta"] = opt_meta
        if self.lr_scheduler is not None:
            state["lr_sched"] = self.lr_scheduler.state_dict()
        if self.scaler is not None:
            state["scaler"] = self.scaler.state_dict()
        if extra_state is not None:
            state["extra"] = extra_state
        path = os.path.join(self.root, f"step_{step:08d}")
        save_state_dict(state, path, async_save=async_save,
                        on_phase=on_phase)
        self._rotate()
        return path

    def _rotate(self):
        if self.keep_last is None:
            return
        dirs = self._step_dirs()
        for step, path in dirs[:-self.keep_last]:
            shutil.rmtree(path, ignore_errors=True)
            shutil.rmtree(path + ".tmp", ignore_errors=True)
            # .old debris too, or _step_dirs' healing would resurrect the
            # rotated-away snapshot from it
            shutil.rmtree(path + ".old", ignore_errors=True)
        # sweep torn staging debris from crashed saves: any step_N.tmp with
        # N strictly below the newest COMMITTED step cannot be in flight
        # (saves are monotonic and pipelined via wait()), so it is an orphan
        if dirs:
            newest = dirs[-1][0]
            for d in os.listdir(self.root):
                if d.endswith(".tmp"):
                    m = _STEP_RE.match(d[:-4])
                    if m and int(m.group(1)) < newest:
                        shutil.rmtree(os.path.join(self.root, d),
                                      ignore_errors=True)

    # -- restore -----------------------------------------------------------
    def restore(self, path=None) -> int | None:
        """Load ``path`` (default: :meth:`find_latest_complete`) back into the
        attached objects; returns the restored step, or None when no intact
        snapshot exists (fresh start)."""
        tel = self.telemetry
        if tel is None:
            return self._restore_impl(path)
        with tel.span("ckpt.restore"):
            step = self._restore_impl(path)
        if step is not None:
            tel.restored(step, str(path) if path is not None else "")
        return step

    def _restore_impl(self, path=None) -> int | None:
        from ..distributed.checkpoint import load_state_dict, verify_checkpoint
        from ..core.random import get_rng_state, set_rng_state
        self.wait()  # never restore around an in-flight async save
        if path is None:
            path = self.find_latest_complete()  # already fully verified
            if path is None:
                return None
        else:
            verify_checkpoint(path)
        template: dict = {}
        if self.model is not None:
            # live Tensors: load_state_dict writes params/buffers in place
            template["model"] = dict(self.model.state_dict())
        opt_tensors = None
        if self.optimizer is not None:
            opt_tensors = {}
            for i, p in enumerate(self.optimizer._parameter_list):
                for k, v in self.optimizer._init_state(p._value).items():
                    opt_tensors[f"p{i}.{k}"] = Tensor(jnp.zeros_like(v))
            template["opt"] = opt_tensors
        rng_t = Tensor(jnp.zeros_like(
            jnp.asarray(get_rng_state()[0], jnp.uint32)))
        template["rng"] = rng_t
        load_state_dict(template, path)
        py = _nest(_read_py_values(path))
        if self.optimizer is not None:
            for i, p in enumerate(self.optimizer._parameter_list):
                st = {k: opt_tensors[f"p{i}.{k}"]._value
                      for k in self.optimizer._init_state(p._value)}
                self.optimizer._accumulators[id(p)] = st
            meta = py.get("opt_meta", {})
            if "global_step" in meta:
                self.optimizer._global_step = int(meta["global_step"])
            from ..optimizer.lr import LRScheduler
            if isinstance(self.optimizer._learning_rate, LRScheduler) \
                    and isinstance(meta.get("lr_sched"), dict):
                self.optimizer._learning_rate.set_state_dict(meta["lr_sched"])
        if self.lr_scheduler is not None and isinstance(py.get("lr_sched"),
                                                        dict):
            self.lr_scheduler.set_state_dict(py["lr_sched"])
        if self.scaler is not None and isinstance(py.get("scaler"), dict):
            self.scaler.load_state_dict(py["scaler"])
        set_rng_state(rng_t._value)
        self.last_extra = py.get("extra")
        step = py.get("step")
        return int(step) if step is not None else self.step_of(path)
