"""Global flag registry.

TPU-native analog of the reference's gflags-style registry
(paddle/common/flags.h:38 PD_DEFINE_*, flags.cc 183 exported FLAGS_*,
paddle/common/flags.h:336 ExportedFlagInfoMap). Flags are plain Python state:
registered with a type + default + help string, overridable from the
environment (``FLAGS_check_nan_inf=1``) exactly like the reference, and
settable at runtime via :func:`set_flags` (``paddle.set_flags`` parity).
"""
from __future__ import annotations

import builtins
import os
import threading
from dataclasses import dataclass
from typing import Any, Callable, Dict, Optional

_LOCK = threading.RLock()


@dataclass
class FlagInfo:
    name: str
    type: type
    default: Any
    value: Any
    help: str = ""
    is_writable: bool = True
    on_change: Optional[Callable[[Any], None]] = None


_REGISTRY: Dict[str, FlagInfo] = {}


def _coerce(ftype: type, value: Any) -> Any:
    if ftype is bool:
        if isinstance(value, str):
            return value.strip().lower() in ("1", "true", "yes", "on")
        return bool(value)
    return ftype(value)


def define_flag(name: str, default: Any, help: str = "", type: Optional[type] = None,
                on_change: Optional[Callable[[Any], None]] = None) -> None:
    """Register a flag. Environment variable ``FLAGS_<name>`` overrides the
    default at registration time (env parity with the reference)."""
    ftype = type if type is not None else builtins.type(default)
    with _LOCK:
        env = os.environ.get(f"FLAGS_{name}")
        value = _coerce(ftype, env) if env is not None else default
        _REGISTRY[name] = FlagInfo(name=name, type=ftype, default=default,
                                   value=value, help=help, on_change=on_change)
        if env is not None and on_change is not None:
            on_change(value)


def get_flags(flags=None) -> Dict[str, Any]:
    """paddle.get_flags parity: return {name: value} for the requested flags
    (all flags when None)."""
    with _LOCK:
        if flags is None:
            return {k: v.value for k, v in _REGISTRY.items()}
        if isinstance(flags, str):
            flags = [flags]
        out = {}
        for name in flags:
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _REGISTRY:
                raise ValueError(f"Flag {name} not registered")
            out[name] = _REGISTRY[key].value
        return out


def set_flags(flags: Dict[str, Any]) -> None:
    """paddle.set_flags parity."""
    with _LOCK:
        for name, value in flags.items():
            key = name[6:] if name.startswith("FLAGS_") else name
            if key not in _REGISTRY:
                raise ValueError(f"Flag {name} not registered")
            info = _REGISTRY[key]
            if not info.is_writable:
                raise ValueError(f"Flag {name} is not writable at runtime")
            info.value = _coerce(info.type, value)
            if info.on_change is not None:
                info.on_change(info.value)


def get_flag(name: str) -> Any:
    key = name[6:] if name.startswith("FLAGS_") else name
    return _REGISTRY[key].value


def exported_flags_info() -> Dict[str, FlagInfo]:
    """ExportedFlagInfoMap analog (paddle/common/flags.h:336)."""
    with _LOCK:
        return dict(_REGISTRY)


# ---------------------------------------------------------------------------
# Core flags (subset of the reference's 183 with TPU-meaningful semantics).
# ---------------------------------------------------------------------------
define_flag("check_nan_inf", False, "Scan op outputs for NaN/Inf after every eager op "
            "(reference FLAGS_check_nan_inf; generated sites eager_gen.py:749).", type=bool)
define_flag("check_nan_inf_level", 0, "0: abort on NaN/Inf; >=1: warn only.", type=int)
define_flag("benchmark", False, "Block on every op for accurate timing.", type=bool)
define_flag("paddle_tpu_deterministic", False, "Force deterministic kernels.", type=bool)
define_flag("use_pallas_kernels", True, "Enable Pallas kernel overrides for hot ops.", type=bool)
define_flag("use_pallas_norm_kernels", False, "Also override softmax/layer_norm with the "
            "Pallas kernels (measured slower than XLA's own fusion inside full models "
            "on v5e — opt-in; the kernels themselves are tested and correct).", type=bool)
define_flag("use_pallas_adamw", False, "Use the fused Pallas AdamW update kernel "
            "(measured ~2% slower than XLA's own fused elementwise chain on the 271M "
            "llama train step, v5e, round 4 — opt-in; tested and correct).", type=bool)
define_flag("log_level", 0, "VLOG-style verbosity.", type=int)
define_flag("amp_dtype", "bfloat16", "Default AMP low-precision dtype on TPU.", type=str)
define_flag("allocator_strategy", "xla", "Informational: HBM is managed by XLA.", type=str,
            )
define_flag("embedding_deterministic", False, "Deterministic embedding grad scatter.", type=bool)
define_flag("check_comm_nan", False, "NaN/Inf-scan finished collective results "
            "(reference phi/core/distributed/check/).", type=bool)
define_flag("comm_timeout_seconds", 1800.0, "Watchdog deadline for eager collective "
            "readiness (reference comm_task_manager.h:57 IsTimeout).", type=float)
define_flag("cudnn_deterministic", False, "Accepted for reference compat; no-op on TPU.", type=bool)
