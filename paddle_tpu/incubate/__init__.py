"""paddle.incubate parity (reference: python/paddle/incubate/ — fused ops,
MoE models, asp). The fused functional surface maps to framework ops whose
Pallas overrides provide the fusion on TPU."""
from . import nn
from . import autograd
from . import distributed
from . import asp

__all__ = ["nn", "autograd", "distributed", "asp"]
