"""ASP — automatic structured (n:m) sparsity (reference:
python/paddle/incubate/asp/ — ASPHelper, calculate_density,
create_mask/check_mask 2:4 patterns, decorate() masked optimizer).

TPU-native note: XLA has no sparse-MXU path, so n:m sparsity here delivers
the reference's TRAINING workflow (prune → masked fine-tune → export masks)
rather than a speedup; the masks ride along for deployment stacks that can
exploit them.
"""
from __future__ import annotations

import weakref

import numpy as np
import jax
import jax.numpy as jnp

from ...nn.layer import Layer

__all__ = ["calculate_density", "create_mask", "check_sparsity",
           "prune_model", "decorate", "reset_excluded_layers",
           "set_excluded_layers"]

_EXCLUDED = set()


def set_excluded_layers(param_names, main_program=None):
    _EXCLUDED.update(param_names)


def reset_excluded_layers(main_program=None):
    _EXCLUDED.clear()


def calculate_density(x) -> float:
    v = np.asarray(x._value if hasattr(x, "_value") else x)
    return float((v != 0).sum() / v.size)


def create_mask(w, n: int = 2, m: int = 4):
    """n:m mask along the LAST dim: in every group of m consecutive values
    keep the n largest magnitudes (reference create_mask / get_mask_2d
    best-effort for non-divisible tails)."""
    v = jnp.asarray(w._value if hasattr(w, "_value") else w)
    shape = v.shape
    last = shape[-1]
    pad = (-last) % m
    flat = v.reshape(-1, last)
    if pad:
        flat = jnp.concatenate(
            [flat, jnp.zeros((flat.shape[0], pad), flat.dtype)], axis=1)
    groups = flat.reshape(flat.shape[0], -1, m)
    # rank within each group; keep the n largest |values|
    order = jnp.argsort(jnp.abs(groups), axis=-1)
    ranks = jnp.argsort(order, axis=-1)        # rank of each element
    mask = (ranks >= m - n).astype(v.dtype)
    mask = mask.reshape(flat.shape[0], -1)[:, :last].reshape(shape)
    return mask


def check_sparsity(w, n: int = 2, m: int = 4) -> bool:
    """True when every complete m-group has at most n nonzeros (convs are
    checked over the in*kh*kw GEMM view, matching prune_model)."""
    v = np.asarray(w._value if hasattr(w, "_value") else w)
    if v.ndim > 2:
        v = v.reshape(v.shape[0], -1)
    last = v.shape[-1]
    usable = last - last % m
    if usable == 0:
        return True
    g = v.reshape(-1, last)[:, :usable].reshape(-1, m)
    return bool(((g != 0).sum(axis=-1) <= n).all())


def _prunable(model: Layer):
    from ...nn import Linear, Conv2D
    for name, sub in model.named_sublayers(include_self=True):
        if isinstance(sub, (Linear, Conv2D)):
            pname = f"{name}.weight" if name else "weight"
            if pname in _EXCLUDED or name in _EXCLUDED:
                continue
            yield pname, sub


# module-level mask registry (the reference ASPHelper keeps one too):
# prune_model registers layers here so decorate() works regardless of
# call order and with the reference's decorate(optimizer) signature.
# WEAK references: discarded models must be garbage-collectable.
_MASKED_LAYERS = []


def _live_masked_layers():
    out = []
    alive = []
    for ref in _MASKED_LAYERS:
        sub = ref()
        if sub is not None:
            alive.append(ref)
            out.append(sub)
    _MASKED_LAYERS[:] = alive
    return out


def prune_model(model: Layer, n: int = 2, m: int = 4, mask_algo="mask_1d",
                with_mask=True):
    """Apply n:m masks to every Linear/Conv2D weight (reference
    ASPHelper.prune_model). Masks are recorded on the layer
    (`sub.asp_mask`), in the module registry, and in the returned dict."""
    masks = {}
    for pname, sub in _prunable(model):
        w = sub.weight._value
        if w.ndim > 2:
            # conv OIHW: mask over the GEMM reduction view in*kh*kw (the
            # reference prunes the im2col matrix, not the kw axis alone)
            m2d = create_mask(w.reshape(w.shape[0], -1), n, m)
            mask = m2d.reshape(w.shape)
        else:
            mask = create_mask(w, n, m)
        sub.weight._set_value(w * mask)
        sub.asp_mask = mask
        masks[pname] = mask
        if all(ref() is not sub for ref in _MASKED_LAYERS):
            _MASKED_LAYERS.append(weakref.ref(sub))
    model._asp_masks = masks
    return masks


def decorate(optimizer, model: Layer = None):
    """Wrap optimizer.step to re-apply the pruning masks after every update
    (reference OptimizerWithSparsityGuarantee): gradients may point off the
    sparse support, the mask projection puts the weights back on it.

    Masks are looked up AT STEP TIME (model sublayers when given, else the
    module registry prune_model fills), so decorate-before-prune — the
    reference's documented order — works."""
    orig_step = optimizer.step

    def step(*a, **kw):
        out = orig_step(*a, **kw)
        if model is not None:
            layers = (sub for _, sub in _prunable(model))
        else:
            layers = iter(_live_masked_layers())
        for sub in layers:
            mask = getattr(sub, "asp_mask", None)
            if mask is not None:
                sub.weight._set_value(sub.weight._value * mask)
        return out

    optimizer.step = step
    optimizer._asp_decorated = True
    return optimizer
