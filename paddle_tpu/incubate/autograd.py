"""incubate.autograd: functional transforms (reference:
python/paddle/incubate/autograd/ — jvp/vjp/Jacobian/Hessian primitives).
These expose jax's transform stack directly over Tensor pytrees."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor

__all__ = ["jvp", "vjp", "Jacobian", "Hessian", "forward_grad", "grad"]


def _uw(tree):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def _w(tree):
    return jax.tree_util.tree_map(Tensor, tree)


def _lift(func):
    def fn(*vals):
        args = [Tensor(v) for v in vals]
        out = func(*args)
        return _uw(out)
    return fn


def vjp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    out, vjp_fn = jax.vjp(_lift(func), *_uw(list(xs)))
    if v is None:
        v = jnp.ones_like(out)
    else:
        v = _uw(v)
    grads = vjp_fn(v)
    return _w(out), _w(list(grads))


def jvp(func, xs, v=None):
    xs = xs if isinstance(xs, (list, tuple)) else [xs]
    primals = _uw(list(xs))
    tangents = _uw(v) if v is not None else [jnp.ones_like(p) for p in primals]
    if not isinstance(tangents, (list, tuple)):
        tangents = [tangents]
    out, jv = jax.jvp(_lift(func), tuple(primals), tuple(tangents))
    return _w(out), _w(jv)


class Jacobian:
    def __init__(self, func, xs, is_batched=False):
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        self._J = jax.jacobian(_lift(func), argnums=tuple(range(len(self._xs))))(
            *_uw(list(self._xs)))

    def __getitem__(self, idx):
        J = self._J[0] if isinstance(self._J, tuple) and len(self._J) == 1 else self._J
        return Tensor(jnp.asarray(J)[idx])

    @property
    def shape(self):
        J = self._J[0] if isinstance(self._J, tuple) and len(self._J) == 1 else self._J
        return list(jnp.asarray(J).shape)


class Hessian:
    def __init__(self, func, xs, is_batched=False):
        self._xs = xs if isinstance(xs, (list, tuple)) else [xs]
        self._H = jax.hessian(_lift(func))(*_uw(list(self._xs)))

    def __getitem__(self, idx):
        return Tensor(jnp.asarray(self._H)[idx])


def forward_grad(func, xs, v=None):
    return jvp(func, xs, v)[1]


def grad(func, xs, v=None):
    return vjp(func, xs, v)[1]
