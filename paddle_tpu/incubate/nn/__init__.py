from . import functional  # noqa: F401
from .layers import FusedMultiHeadAttention, FusedFeedForward, FusedTransformerEncoderLayer  # noqa: F401
