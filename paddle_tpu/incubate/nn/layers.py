"""Fused transformer layers (reference: python/paddle/incubate/nn/layer/
fused_transformer.py). On TPU these alias the standard layers — XLA + Pallas
deliver the fusion the reference's fused CUDA kernels provide."""
from __future__ import annotations

from ...nn.transformer import MultiHeadAttention, TransformerEncoderLayer
from ...nn.layer import Layer
from ...nn import Linear, Dropout, LayerNorm
from ...nn import functional as F

__all__ = ["FusedMultiHeadAttention", "FusedFeedForward",
           "FusedTransformerEncoderLayer"]


class FusedMultiHeadAttention(MultiHeadAttention):
    def __init__(self, embed_dim, num_heads, dropout_rate=0.5, attn_dropout_rate=0.5,
                 kdim=None, vdim=None, normalize_before=False, need_weights=False,
                 qkv_weight_attr=None, qkv_bias_attr=None, linear_weight_attr=None,
                 linear_bias_attr=None, pre_ln_scale_attr=None, pre_ln_bias_attr=None,
                 ln_scale_attr=None, ln_bias_attr=None, epsilon=1e-5,
                 nranks=1, ring_id=-1, transpose_qkv_wb=False, name=None):
        super().__init__(embed_dim, num_heads, attn_dropout_rate, kdim, vdim,
                         need_weights)


class FusedFeedForward(Layer):
    def __init__(self, d_model, dim_feedforward, dropout_rate=0.1, epsilon=1e-5,
                 activation="relu", act_dropout_rate=None, normalize_before=False,
                 linear1_weight_attr=None, linear1_bias_attr=None,
                 linear2_weight_attr=None, linear2_bias_attr=None,
                 ln1_scale_attr=None, ln1_bias_attr=None, ln2_scale_attr=None,
                 ln2_bias_attr=None, nranks=1, ring_id=-1, name=None):
        super().__init__()
        self.linear1 = Linear(d_model, dim_feedforward, linear1_weight_attr,
                              linear1_bias_attr)
        self.linear2 = Linear(dim_feedforward, d_model, linear2_weight_attr,
                              linear2_bias_attr)
        self.norm = LayerNorm(d_model, epsilon=epsilon)
        self.dropout = Dropout(dropout_rate)
        self.act = getattr(F, activation)
        self.normalize_before = normalize_before

    def forward(self, x):
        residual = x
        if self.normalize_before:
            x = self.norm(x)
        x = residual + self.dropout(self.linear2(self.act(self.linear1(x))))
        if not self.normalize_before:
            x = self.norm(x)
        return x


class FusedTransformerEncoderLayer(TransformerEncoderLayer):
    pass
