"""Fused functional ops (reference: python/paddle/incubate/nn/functional/ —
fused_rms_norm, fused_rotary_position_embedding, fused_swiglu, fused_moe,
masked_multihead_attention, variable_length_memory_efficient_attention).

On TPU the "fusion" is delivered by the kernel registry: these entry points
call the same op names the Pallas kernels override; without overrides XLA's
fusion already merges the elementwise chains.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...core.dispatch import op_call
from ...core.tensor import Tensor
from ...nn.functional.norm import rms_norm as _rms_norm
from ...nn.functional.norm import layer_norm as _layer_norm
from ...nn.functional.activation import swiglu as _swiglu

__all__ = ["fused_rms_norm", "fused_layer_norm", "fused_rotary_position_embedding",
           "swiglu", "fused_swiglu", "fused_linear", "fused_bias_act",
           "fused_dropout_add", "masked_multihead_attention",
           "variable_length_memory_efficient_attention", "fused_moe",
           "fused_linear_cross_entropy"]


def fused_linear_cross_entropy_impl(x, weight, labels, n_chunks=8, bias=None):
    """jax-level core: per-token NLL of softmax(x @ weight [+ bias]) WITHOUT
    ever materializing the [T, V] logits (reference intent: the CUDA
    c_softmax_with_cross_entropy / flash-like head kernels — here an
    online-logsumexp lax.scan over vocab chunks with a rematted body, so
    backward recomputes each chunk's logits and peak memory is O(T·V/n)).

    Measured round 4 (271M llama head, 32k vocab, v5e): the ~3 GB of f32
    logits traffic this removes is what lets the no-remat train step fit in
    HBM (+41% tokens/s end-to-end vs the materialized head + full remat).

    x: [T, H] (any float dtype; logits accumulate in f32)
    weight: [H, V]; labels: int [T]; bias: optional [V].
    Returns per-token NLL [T] (f32).
    """
    T, H = x.shape
    V = weight.shape[1]
    if V % n_chunks:
        # keep chunking (the whole point is never materializing [T, V]):
        # largest divisor of V not exceeding the requested chunk count
        n_chunks = next(d for d in range(n_chunks, 0, -1) if V % d == 0)
    C = V // n_chunks
    Wc = jnp.swapaxes(weight.reshape(H, n_chunks, C), 0, 1)  # [n, H, C]
    # bias-free callers (the LLaMA head — the benched hot path) must not pay
    # a scanned zeros add, so the xs tuple only carries a bias when one exists
    Bc = (None if bias is None
          else bias.astype(jnp.float32).reshape(n_chunks, C))
    lab = labels.reshape(-1).astype(jnp.int32)

    @jax.checkpoint
    def body(carry, xs):
        m, s, ll = carry
        if Bc is None:
            w, base = xs
        else:
            w, b, base = xs
        logits = jax.lax.dot_general(x, w, (((1,), (0,)), ((), ())),
                                     preferred_element_type=jnp.float32)
        if Bc is not None:
            logits = logits + b[None, :]
        m_new = jnp.maximum(m, logits.max(-1))
        s = s * jnp.exp(m - m_new) + jnp.exp(logits - m_new[:, None]).sum(-1)
        rel = lab - base
        inside = (rel >= 0) & (rel < C)
        picked = jnp.take_along_axis(
            logits, jnp.clip(rel, 0, C - 1)[:, None], -1)[:, 0]
        ll = jnp.where(inside, picked, ll)
        return (m_new, s, ll), None

    carry = (jnp.full((T,), -jnp.inf, jnp.float32),
             jnp.zeros((T,), jnp.float32), jnp.zeros((T,), jnp.float32))
    bases = jnp.arange(n_chunks, dtype=jnp.int32) * C
    xs = (Wc, bases) if Bc is None else (Wc, Bc, bases)
    (m, s, ll), _ = jax.lax.scan(body, carry, xs)
    return m + jnp.log(s) - ll


def fused_linear_cross_entropy(x, weight, labels, n_chunks=8, bias=None,
                               ignore_index=None, name=None):
    """Mean NLL of a linear head + softmax cross-entropy, vocab-chunked so
    the full logits tensor never exists (see fused_linear_cross_entropy_impl).
    x: [..., H] is flattened over leading dims; labels matches them.
    With `ignore_index`, the mean runs over the non-ignored tokens only
    (F.cross_entropy parity)."""
    def impl(xv, wv, lv, *rest):
        bv = rest[0] if rest else None
        x2 = xv.reshape(-1, xv.shape[-1])
        nll = fused_linear_cross_entropy_impl(
            x2, wv, lv.reshape(-1), n_chunks=n_chunks, bias=bv)
        if ignore_index is None:
            return jnp.mean(nll)
        valid = (lv.reshape(-1) != ignore_index).astype(jnp.float32)
        return jnp.sum(nll * valid) / jnp.maximum(jnp.sum(valid), 1.0)
    args = (x, weight, labels) if bias is None else (x, weight, labels, bias)
    return op_call("fused_linear_cross_entropy", impl, *args)


def fused_rms_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-6,
                   begin_norm_axis=-1, **kw):
    out = _rms_norm(x, norm_weight, epsilon)
    if norm_bias is not None:
        out = out + norm_bias
    return out, None


def fused_layer_norm(x, norm_weight=None, norm_bias=None, epsilon=1e-5, **kw):
    shape = (x.shape[-1],)
    return _layer_norm(x, shape, norm_weight, norm_bias, epsilon), None


def fused_rotary_position_embedding(q, k=None, v=None, sin=None, cos=None,
                                    position_ids=None, use_neox_rotary_style=True,
                                    time_major=False, rotary_emb_base=10000.0):
    """RoPE (reference fused_rotary_position_embedding). Layout [B, S, H, D]."""
    def rope_one(t, sin_v, cos_v):
        def impl(x, s, c):
            if use_neox_rotary_style:
                half = x.shape[-1] // 2
                x1, x2 = x[..., :half], x[..., half:]
                rot = jnp.concatenate([-x2, x1], axis=-1)
            else:
                x1 = x[..., 0::2]
                x2 = x[..., 1::2]
                rot = jnp.stack([-x2, x1], axis=-1).reshape(x.shape)
            return x * c + rot * s
        return op_call("rope", impl, t, sin_v, cos_v)

    if sin is None or cos is None:
        S = q.shape[1]
        D = q.shape[-1]
        inv = 1.0 / (rotary_emb_base ** (jnp.arange(0, D, 2, dtype=jnp.float32) / D))
        pos = jnp.arange(S, dtype=jnp.float32)
        freqs = jnp.outer(pos, inv)
        emb = jnp.concatenate([freqs, freqs], axis=-1) if use_neox_rotary_style \
            else jnp.repeat(freqs, 2, axis=-1)
        sin = Tensor(jnp.sin(emb)[None, :, None, :])
        cos = Tensor(jnp.cos(emb)[None, :, None, :])
    outs = [rope_one(t, sin, cos) if t is not None else None for t in (q, k, v)]
    return tuple(outs)


swiglu = _swiglu


def fused_swiglu(x, y=None):
    return _swiglu(x, y)


def fused_linear(x, weight, bias=None, transpose_weight=False, name=None):
    from ...nn.functional.common import linear
    if transpose_weight:
        from ...tensor.manipulation import t as transpose_t
        weight = transpose_t(weight)
    return linear(x, weight, bias)


def fused_bias_act(x, bias=None, act_method="gelu", **kw):
    from ...nn import functional as F
    if bias is not None:
        x = x + bias
    return getattr(F, act_method)(x)


def fused_dropout_add(x, y, p=0.5, training=True, mode="upscale_in_train", name=None):
    from ...nn.functional.common import dropout
    return dropout(x, p, training=training, mode=mode) + y


def masked_multihead_attention(x, cache_kv=None, src_mask=None, bias=None,
                               sequence_lengths=None, rotary_tensor=None,
                               beam_cache_offset=None, seq_len=1,
                               rotary_emb_dims=0, use_neox_rotary_style=False,
                               compute_dtype="default", out_scale=-1,
                               quant_round_type=1, quant_max_bound=127.0,
                               quant_min_bound=-127.0):
    """Decode-phase attention with KV cache (reference
    incubate/nn/functional/masked_multihead_attention.py backed by
    masked_multihead_attention_kernel.cu).

    x: [B, 3*H*D] fused qkv for ONE new token per sequence.
    cache_kv: [2, B, H, max_seq, D]; sequence_lengths: int32 [B, 1] — the
    number of cached tokens per sequence (the new token is written there).
    bias: optional fused qkv bias [3*H*D]. Returns (out [B, H*D], updated
    cache_kv) like the reference.
    """
    import math as _math

    if rotary_tensor is not None or rotary_emb_dims:
        raise NotImplementedError(
            "masked_multihead_attention: in-kernel rotary embedding is not "
            "implemented — apply RoPE to q/k before the call (see "
            "models/llama.py build_llama_decode) or use "
            "fused_rotary_position_embedding")
    if beam_cache_offset is not None:
        raise NotImplementedError(
            "masked_multihead_attention: beam search cache offsets are not "
            "implemented")
    if bias is not None:
        x = x + bias
    if sequence_lengths is None:
        # The reference CUDA kernel derives the write position from cache
        # metadata; our cache is a bare array, so without sequence_lengths
        # every call would silently write (and attend to) position 0 only.
        raise ValueError(
            "masked_multihead_attention requires sequence_lengths (int32 "
            "[B, 1], the number of cached tokens per sequence) — without it "
            "repeated decode calls would overwrite cache position 0. Track "
            "the position explicitly like models/llama.py "
            "build_llama_decode's cache['pos'].")

    def impl(xv, cache, seq_lens, *rest):
        mask = rest[0] if src_mask is not None else None
        two, B, H, S_max, D = cache.shape
        qkv = xv.reshape(B, 3, H, D)
        q, k, v = qkv[:, 0], qkv[:, 1], qkv[:, 2]       # [B, H, D]
        pos = seq_lens.reshape(B).astype(jnp.int32)
        # write k/v at each sequence's position
        bidx = jnp.arange(B)
        cache = cache.at[0, bidx, :, pos, :].set(k)
        cache = cache.at[1, bidx, :, pos, :].set(v)
        kc, vc = cache[0], cache[1]                      # [B, H, S, D]
        s = jnp.einsum("bhd,bhsd->bhs", q.astype(jnp.float32),
                       kc.astype(jnp.float32)) / _math.sqrt(D)
        valid = jnp.arange(S_max)[None, :] <= pos[:, None]   # [B, S]
        s = jnp.where(valid[:, None, :], s, -jnp.inf)
        if mask is not None:
            s = s + mask.reshape(B, 1, -1)[..., :S_max].astype(jnp.float32)
        p = jax.nn.softmax(s, axis=-1)
        o = jnp.einsum("bhs,bhsd->bhd", p.astype(vc.dtype), vc)
        return o.reshape(B, H * D), cache

    args = [x, cache_kv, sequence_lengths]
    if src_mask is not None:
        args.append(src_mask)
    return op_call("masked_multihead_attention", impl, *args)


def variable_length_memory_efficient_attention(query, key, value, seq_lens=None,
                                               kv_seq_lens=None, mask=None,
                                               scale=None, causal=False):
    from ...nn.functional.attention import scaled_dot_product_attention
    return scaled_dot_product_attention(query, key, value, attn_mask=mask,
                                        is_causal=causal)


def fused_moe(x, gate_weight, expert_weights1, expert_bias1, expert_weights2,
              expert_bias2, quant_method="None", moe_topk=2, norm_topk_prob=True):
    """Dense-compute MoE (reference incubate/nn/functional/fused_moe.py):
    every token × every expert with a top-k mask — the XLA-friendly
    formulation; the EP all-to-all variant lives in
    paddle_tpu.incubate.distributed.models.moe."""
    def impl(xv, gw, w1, b1, w2, b2):
        B = xv.shape[:-1]
        d = xv.shape[-1]
        logits = xv @ gw  # [..., E]
        E = logits.shape[-1]
        probs = jax.nn.softmax(logits, axis=-1)
        topv, topi = jax.lax.top_k(probs, moe_topk)
        if norm_topk_prob:
            topv = topv / jnp.sum(topv, -1, keepdims=True)
        # dense: compute all experts, weight by routing mask
        h = jnp.einsum("...d,edh->...eh", xv, w1) + b1
        h = jax.nn.silu(h[..., : h.shape[-1] // 2]) * h[..., h.shape[-1] // 2:] \
            if w2.shape[-2] * 2 == h.shape[-1] else jax.nn.gelu(h)
        out_e = jnp.einsum("...eh,ehd->...ed", h, w2) + b2
        mask = jnp.zeros(B + (E,), xv.dtype)
        mask = jnp.sum(jax.nn.one_hot(topi, E, dtype=xv.dtype) * topv[..., None], axis=-2)
        return jnp.einsum("...ed,...e->...d", out_e, mask)
    return op_call("fused_moe", impl, x, gate_weight, expert_weights1,
                   expert_bias1, expert_weights2, expert_bias2)
