"""paddle.incubate.distributed parity (reference: python/paddle/incubate/distributed/)."""
from . import models

__all__ = ["models"]
