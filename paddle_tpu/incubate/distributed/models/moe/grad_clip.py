"""MoE-aware global-norm gradient clipping (reference: python/paddle/
incubate/distributed/models/moe/grad_clip.py:63 ClipGradForMOEByGlobalNorm).

Expert parameters are sharded over the expert-parallel group, so the global
norm must sum the *local* expert-grad norms across that group once, while
shared (non-expert) parameter norms are already replicated and must not be
re-summed. The reference psums the expert partial norm over the moe group;
here the same psum runs over the `ep` mesh axis when one is in scope, and
is a no-op otherwise (single-process semantics match).
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....nn.clip import ClipGradByGlobalNorm

__all__ = ["ClipGradForMOEByGlobalNorm"]


def _is_expert_param(p):
    return getattr(p, "is_expert", False) or getattr(p, "no_sync", False)


class ClipGradForMOEByGlobalNorm(ClipGradByGlobalNorm):
    def __init__(self, clip_norm, is_expert_param_func=None, moe_group=None,
                 group_name="default_moe_group", ep_axis="ep"):
        super().__init__(clip_norm, group_name)
        self.is_expert = is_expert_param_func or _is_expert_param
        self.ep_axis = ep_axis

    def _global_norm_sq(self, params_grads):
        normal_sq = jnp.zeros((), jnp.float32)
        expert_sq = jnp.zeros((), jnp.float32)
        for p, g in params_grads:
            if g is None or (hasattr(p, "need_clip") and not p.need_clip):
                continue
            v = g._value if hasattr(g, "_value") else g
            sq = jnp.sum(jnp.square(v.astype(jnp.float32)))
            if self.is_expert(p):
                expert_sq = expert_sq + sq
            else:
                normal_sq = normal_sq + sq
        from .....distributed.fleet.meta_parallel.mp_layers import mp_axis_in_scope
        if mp_axis_in_scope(self.ep_axis):
            expert_sq = jax.lax.psum(expert_sq, self.ep_axis)
        return normal_sq + expert_sq
