"""Expert-parallel MoE (reference: python/paddle/incubate/distributed/models/moe/)."""
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate, top_k_gating, compute_capacity
from .moe_layer import (MoELayer, moe_dispatch, moe_combine, moe_ffn,
                        ep_all_to_all, ep_all_to_all_back)
from .grad_clip import ClipGradForMOEByGlobalNorm
from . import utils

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate", "top_k_gating",
           "compute_capacity", "MoELayer", "moe_dispatch", "moe_combine",
           "moe_ffn", "ep_all_to_all", "ep_all_to_all_back",
           "ClipGradForMOEByGlobalNorm", "utils"]
