"""MoE gates (reference: python/paddle/incubate/distributed/models/moe/gate/
— naive_gate.py NaiveGate, gshard_gate.py GShardGate, switch_gate.py
SwitchGate over base_gate.py BaseGate).

TPU-native design: a gate is a small Layer producing, from token features
[T, d], the *static-shape* routing tensors the dispatcher consumes:

    combine_weights f32[T, E, C]   (token t's weight in expert e's slot c)
    dispatch_mask  bool[T, E, C]   (combine_weights != 0)
    aux_loss       f32[]           (load-balance loss, 0 for NaiveGate)

Capacity overflow is masking (tokens beyond an expert's C slots get zero
weight — "dropped" exactly like the reference's prune_gate_by_capacity),
so every shape is known to XLA and the dispatch/combine are einsums that
tile onto the MXU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from .....nn.layer import Layer
from .....core.dispatch import op_call

__all__ = ["BaseGate", "NaiveGate", "GShardGate", "SwitchGate",
           "top_k_gating", "compute_capacity"]


def compute_capacity(num_tokens, num_experts, top_k, capacity_factor):
    """C = ceil(k*T/E * factor), min 1 (reference gshard_gate.py capacity=(1.2, 2.4))."""
    return max(1, int(math.ceil(top_k * num_tokens / num_experts * capacity_factor)))


def top_k_gating(logits, top_k, capacity, *, normalize=True,
                 balance_loss_weight=1.0, prng=None, random_routing_prob=False):
    """Core static-shape top-k capacity gating (GShard algorithm).

    logits: f32[T, E]. Returns (combine_weights[T,E,C], dispatch_mask[T,E,C],
    aux_loss[], info dict). Slot assignment is k-major (all 1st choices
    queue before any 2nd choice, reference gshard order via fmoe-style
    per-k cumsum).
    """
    T, E = logits.shape
    C = capacity
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)       # [T, E]
    topv, topi = jax.lax.top_k(probs, top_k)                          # [T, k]

    if random_routing_prob and top_k == 2 and prng is not None:
        from .utils import random_routing
        r = jax.random.uniform(prng, (T,))
        topi = random_routing(topi, topv, r, topk=2)

    # masks per k-slot: [k, T, E]; dropped (-1) slots one_hot to all-zero
    kmask = jax.nn.one_hot(topi.T, E, dtype=jnp.float32)
    # queue position: 1st-choice tokens claim slots before 2nd-choice ones
    flat = kmask.reshape(top_k * T, E)                                 # k-major
    pos = jnp.cumsum(flat, axis=0) - flat                              # [k*T, E]
    pos = pos.reshape(top_k, T, E)
    within = (pos < C) & (kmask > 0)                                   # [k, T, E]

    # load-balance aux loss (switch/gshard): E * sum_e mean_frac_e * mean_prob_e
    me = jnp.mean(probs, axis=0)                                       # [E]
    ce = jnp.mean(kmask[0], axis=0)                                    # 1st-choice frac
    aux = jnp.sum(me * ce) * E * balance_loss_weight

    gate_w = topv.T[..., None] * within                                # [k, T, E]
    if normalize:
        denom = jnp.sum(gate_w, axis=(0, 2), keepdims=True)            # per token
        gate_w = gate_w / jnp.maximum(denom, 1e-9)

    slot = jnp.minimum(pos, C - 1).astype(jnp.int32)                   # [k, T, E]
    slot_oh = jax.nn.one_hot(slot, C, dtype=jnp.float32) * within[..., None]
    combine = jnp.sum(gate_w[..., None] * slot_oh, axis=0)             # [T, E, C]
    dispatch = combine > 0
    info = {"probs": probs, "top_idx": topi, "within_capacity": within}
    return combine, dispatch, aux, info


class BaseGate(Layer):
    """reference gate/base_gate.py: holds expert counts + loss slot."""

    def __init__(self, num_expert, n_worker=1):
        super().__init__()
        self.num_expert = num_expert
        self.n_worker = n_worker
        self.tot_expert = num_expert * n_worker
        self.loss = None

    def get_loss(self, clear=True):
        loss = self.loss
        if clear:
            self.loss = None
        return loss


class NaiveGate(BaseGate):
    """Top-k softmax gate without capacity (reference naive_gate.py)."""

    random_routing = False

    def __init__(self, d_model, num_expert, n_worker=1, topk=2,
                 capacity_factor=None, eval_capacity_factor=None,
                 balance_loss_weight=1.0):
        super().__init__(num_expert, n_worker)
        self.top_k = topk
        self.capacity_factor = capacity_factor
        self.eval_capacity_factor = eval_capacity_factor
        self.balance_loss_weight = balance_loss_weight
        self.gate_weight = self.create_parameter((d_model, self.tot_expert))

    def capacity_for(self, num_tokens, training=True):
        f = self.capacity_factor if training else \
            (self.eval_capacity_factor or self.capacity_factor)
        if f is None:
            # no drops: every token can land in any expert
            return num_tokens
        return compute_capacity(num_tokens, self.tot_expert, self.top_k, f)

    def forward(self, x):
        def impl(xv, w):
            return xv @ w.astype(xv.dtype)
        return op_call("moe_gate", impl, x, self.gate_weight)


def _split_capacity(capacity):
    if isinstance(capacity, (tuple, list)):
        train = capacity[0]
        ev = capacity[1] if len(capacity) > 1 else capacity[0]
        return train, ev
    return capacity, capacity


class GShardGate(NaiveGate):
    """Top-2 gate with capacity + balance loss + random routing
    (reference gshard_gate.py; capacity=(1.2, 2.4) = train/eval factors)."""

    def __init__(self, d_model, num_expert, n_worker=1, topk=2,
                 capacity=(1.2, 2.4), random_routing=True,
                 balance_loss_weight=1.0, group=None, gate_weight=None):
        cf, ef = _split_capacity(capacity)
        super().__init__(d_model, num_expert, n_worker, topk=topk,
                         capacity_factor=cf, eval_capacity_factor=ef,
                         balance_loss_weight=balance_loss_weight)
        self.random_routing = random_routing


class SwitchGate(NaiveGate):
    """Top-1 switch gate with capacity (reference switch_gate.py)."""

    def __init__(self, d_model, num_expert, n_worker=1, topk=1, capacity=(1.2, 2.4),
                 balance_loss_weight=1.0, group=None, gate_weight=None):
        cf, ef = _split_capacity(capacity)
        super().__init__(d_model, num_expert, n_worker, topk=1,
                         capacity_factor=cf, eval_capacity_factor=ef,
                         balance_loss_weight=balance_loss_weight)
