"""MoE layer with expert parallelism (reference: python/paddle/incubate/
distributed/models/moe/moe_layer.py — MoEScatter :97, MoEGather :147, and
the global_scatter/global_gather NCCL all-to-all underneath).

TPU-native design
-----------------
The reference scatters tokens into dynamically-sized per-expert buffers and
moves them with `global_scatter` (NCCL alltoallv). XLA needs static shapes,
so the dispatch is the GShard formulation instead:

  gate → combine_weights[T,E,C] → dispatch einsum → [E, C, d]
       → `lax.all_to_all` over the `ep` mesh axis → [E_local, W*C, d]
       → local experts → reverse all_to_all → combine einsum → [T, d]

Both data movements are single XLA collectives riding ICI; the einsums tile
onto the MXU. Capacity overflow is masking (zero combine weight), which is
exactly the reference's prune_gate_by_capacity semantics without dynamic
shapes.

Two entry points:
  * `moe_dispatch` / `moe_combine` / `moe_ffn` — the functional core, usable
    directly inside `shard_map` (pass `ep_axis="ep"`) or under GSPMD.
  * `MoELayer` — reference-parity Layer wrapping gate + expert Layers.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from .....core.dispatch import op_call
from .....nn.layer import Layer
from .....nn.container import LayerList
from .gate import BaseGate, NaiveGate, GShardGate, SwitchGate, top_k_gating

__all__ = ["MoELayer", "moe_dispatch", "moe_combine", "moe_ffn",
           "ep_all_to_all", "ep_all_to_all_back"]


def ep_all_to_all(disp, ep_axis):
    """[E, C, d] per-rank dispatch buffer → [E_local, W*C, d] expert inbox.

    W = size of `ep_axis`; requires E % W == 0. The leading W chunk of the
    second dim indexes the source rank (reference MoEScatter/global_scatter).
    """
    W = jax.lax.psum(1, ep_axis)
    E, C, d = disp.shape
    x = disp.reshape(W, E // W, C, d)
    x = jax.lax.all_to_all(x, ep_axis, split_axis=0, concat_axis=0, tiled=False)
    # x: [W(source rank), E_local, C, d]
    x = jnp.moveaxis(x, 0, 1)                       # [E_local, W, C, d]
    return x.reshape(E // W, W * C, d)


def ep_all_to_all_back(y, ep_axis):
    """Inverse of `ep_all_to_all`: [E_local, W*C, d] → [E, C, d]
    (reference MoEGather/global_gather)."""
    W = jax.lax.psum(1, ep_axis)
    El, WC, d = y.shape
    C = WC // W
    x = y.reshape(El, W, C, d)
    x = jnp.moveaxis(x, 1, 0)                        # [W, E_local, C, d]
    x = jax.lax.all_to_all(x, ep_axis, split_axis=0, concat_axis=0, tiled=False)
    return x.reshape(W * El, C, d)


def moe_dispatch(x, dispatch_mask, dtype=None):
    """x[T, d] × dispatch[T, E, C] → [E, C, d] (slot-addressed token copy)."""
    m = dispatch_mask.astype(dtype or x.dtype)
    return jnp.einsum("td,tec->ecd", x, m)


def moe_combine(y, combine_weights):
    """y[E, C, d] × combine[T, E, C] → [T, d] (weighted sum of expert outs)."""
    return jnp.einsum("ecd,tec->td", y, combine_weights.astype(y.dtype))


def moe_ffn(x, gate_weight, w1, b1, w2, b2, *, top_k=2, capacity_factor=1.25,
            ep_axis=None, activation="gelu", normalize=True,
            balance_loss_weight=1.0, capacity=None):
    """Functional MoE-FFN block: gate + dispatch + expert FFN + combine.

    x: [T, d]. gate_weight: [d, E_total]. w1/b1/w2/b2 carry a leading expert
    dim — E_total outside shard_map, E_local = E_total/W inside shard_map
    over `ep_axis`. Returns (out[T, d], aux_loss).
    """
    T, dm = x.shape
    E = gate_weight.shape[-1]
    logits = (x @ gate_weight.astype(x.dtype)).astype(jnp.float32)
    if capacity is None:
        from .gate import compute_capacity
        capacity = compute_capacity(T, E, top_k, capacity_factor)
    combine, dispatch, aux, _ = top_k_gating(
        logits, top_k, capacity, normalize=normalize,
        balance_loss_weight=balance_loss_weight)

    disp = moe_dispatch(x, dispatch)                        # [E, C, d]
    if ep_axis is not None:
        disp = ep_all_to_all(disp, ep_axis)                 # [E_l, W*C, d]

    act = getattr(jax.nn, activation)
    h = jnp.einsum("ebd,edh->ebh", disp, w1.astype(disp.dtype))
    if b1 is not None:
        h = h + b1[:, None, :].astype(h.dtype)
    h = act(h)
    y = jnp.einsum("ebh,ehd->ebd", h, w2.astype(h.dtype))
    if b2 is not None:
        y = y + b2[:, None, :].astype(y.dtype)

    if ep_axis is not None:
        y = ep_all_to_all_back(y, ep_axis)                  # [E, C, d]
    out = moe_combine(y, combine)
    return out, aux


def _make_gate(gate, d_model, num_expert, n_worker, top_k, capacity_factor):
    if isinstance(gate, BaseGate):
        return gate
    cfg = dict(gate) if isinstance(gate, dict) else {"type": gate or "gshard"}
    typ = cfg.pop("type", "gshard")
    k = cfg.pop("top_k", top_k)
    # MoELayer's capacity_factor wins unless the gate config names its own
    cfg.setdefault("capacity", cfg.pop("capacity_factor", capacity_factor))
    if typ == "naive":
        cap = cfg.pop("capacity", None)
        if isinstance(cap, (tuple, list)):
            cap = cap[0]
        return NaiveGate(d_model, num_expert, n_worker, topk=k,
                         capacity_factor=cap, **cfg)
    if typ == "switch":
        return SwitchGate(d_model, num_expert, n_worker, **cfg)
    return GShardGate(d_model, num_expert, n_worker, topk=k, **cfg)


class MoELayer(Layer):
    """Reference-parity MoE layer (moe_layer.py:MoELayer).

    experts: LayerList of expert Layers (this rank's experts when running
    under expert parallelism; all experts otherwise). gate: BaseGate | dict
    config {"type": "gshard"|"switch"|"naive", "top_k": k}. The balance loss
    is exposed via `gate.get_loss()` after forward, as in the reference.
    """

    def __init__(self, d_model, experts, gate=None, moe_group=None,
                 mp_group=None, recompute_interval=0, top_k=2,
                 capacity_factor=1.25, ep_axis=None, ep_world_size=1, **kw):
        super().__init__()
        self.d_model = d_model
        self.experts = experts if isinstance(experts, LayerList) else LayerList(experts)
        self.ep_axis = ep_axis
        # n_worker scales the gate to the GLOBAL expert count: under expert
        # parallelism this Layer holds only the local experts, but routing
        # must cover all ep_world_size * len(experts) of them
        if ep_axis is not None:
            n_worker = int(ep_world_size)
            if n_worker < 1:
                raise ValueError("ep_world_size must be >= 1 when ep_axis is set")
        else:
            n_worker = getattr(moe_group, "nranks", 1) or 1 if moe_group is not None else 1
        self.world_size = n_worker
        self.top_k = top_k
        self.capacity_factor = capacity_factor
        self.gate = _make_gate(gate, d_model, len(self.experts), n_worker,
                               top_k, capacity_factor)
        if getattr(self.gate, "capacity_factor", None) is None:
            self.gate.capacity_factor = capacity_factor

    def forward(self, x):
        shape = x.shape
        d = shape[-1]
        from .....tensor import manipulation as manip
        xf = manip.reshape(x, [-1, d])
        T = xf.shape[0]
        logits = self.gate(xf)
        capacity = self.gate.capacity_for(T, training=self.training)
        top_k = self.gate.top_k
        prng = None
        if self.training and getattr(self.gate, "random_routing", False):
            from .....core import random as _rnd
            prng = _rnd.default_generator.next_key()

        def route(lg):
            combine, dispatch, aux, _ = top_k_gating(
                lg.astype(jnp.float32), top_k, capacity,
                balance_loss_weight=self.gate.balance_loss_weight,
                prng=prng, random_routing_prob=prng is not None)
            return combine, dispatch.astype(jnp.float32), aux

        combine, dispatch, aux = op_call("moe_gating", route, logits)
        self.gate.loss = aux

        def disp_impl(xv, dsp):
            out = moe_dispatch(xv, dsp)                       # [E, C, d]
            if self.ep_axis is not None:
                out = ep_all_to_all(out, self.ep_axis)        # [E_l, W*C, d]
            return out

        disp = op_call("moe_dispatch", disp_impl, xf, dispatch)
        outs = [self.experts[i](disp[i]) for i in range(len(self.experts))]
        y = manip.stack(outs)

        def comb_impl(yv, cmb, xv):
            if self.ep_axis is not None:
                yv = ep_all_to_all_back(yv, self.ep_axis)     # [E, C, d]
            return moe_combine(yv, cmb).astype(xv.dtype)

        out = op_call("moe_combine", comb_impl, y, combine, xf)
        return manip.reshape(out, list(shape[:-1]) + [d])
