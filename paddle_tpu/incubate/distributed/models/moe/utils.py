"""Static-shape TPU analogs of the reference's MoE capacity kernels
(paddle/phi/kernels/number_count_kernel.h, assign_pos_kernel.h,
limit_by_capacity_kernel.h, prune_gate_by_capacity_kernel.h,
random_routing_kernel.h).

The CUDA kernels scatter tokens with atomics into dynamically-sized
buffers; on TPU every shape must be static, so the same facts are
computed with one-hot + cumsum (an O(T*E) formulation XLA tiles onto
the VPU) and capacity overflow is expressed as masking, not pruning.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

__all__ = ["number_count", "assign_pos", "limit_by_capacity",
           "prune_gate_by_capacity", "random_routing", "count_by_gate"]


def number_count(gate_idx, upper_range):
    """Tokens routed to each expert. gate_idx: int[...] in [0, upper_range).
    Returns int32[upper_range] (reference number_count_kernel.h)."""
    oh = jax.nn.one_hot(gate_idx.reshape(-1), upper_range, dtype=jnp.int32)
    return jnp.sum(oh, axis=0)


def assign_pos(gate_idx, num_expert):
    """Position of each token within its expert's queue, in flat order.
    Returns int32 with gate_idx's shape (reference assign_pos_kernel.h,
    minus the CUDA atomics: cumsum over one-hot gives the same order)."""
    flat = gate_idx.reshape(-1)
    oh = jax.nn.one_hot(flat, num_expert, dtype=jnp.int32)
    pos = jnp.cumsum(oh, axis=0) - 1          # [T, E]
    return jnp.take_along_axis(pos, flat[:, None], axis=1)[:, 0].reshape(gate_idx.shape)


def limit_by_capacity(expert_count, capacity, n_worker=1):
    """Clamp per-expert counts to capacity*n_worker (reference
    limit_by_capacity_kernel.h)."""
    cap = jnp.asarray(capacity)
    return jnp.minimum(expert_count, cap * n_worker)


def prune_gate_by_capacity(gate_idx, expert_count, n_expert, n_worker=1):
    """Set gate_idx of overflowing tokens to -1 (reference
    prune_gate_by_capacity_kernel.h). Static-shape: recompute each
    token's queue position and compare with its expert's capacity."""
    pos = assign_pos(gate_idx, n_expert)
    cap = expert_count[gate_idx.reshape(-1)].reshape(gate_idx.shape)
    return jnp.where(pos < cap, gate_idx, -1)


def random_routing(topk_idx, topk_value, prob, topk=2):
    """Reference random_routing_kernel.h: for k=2, drop the 2nd expert
    with probability prob < value*2 (keeps high-confidence 2nd choices)."""
    if topk != 2:
        return topk_idx
    keep = prob < topk_value[..., 1] * 2.0
    second = jnp.where(keep, topk_idx[..., 1], -1)
    return jnp.stack([topk_idx[..., 0], second], axis=-1)


def count_by_gate(gate_idx, num_expert, n_worker=1):
    """(expert_count, per-token position) pair used by the dispatcher."""
    return number_count(gate_idx, num_expert), assign_pos(gate_idx, num_expert)
