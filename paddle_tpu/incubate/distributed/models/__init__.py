from . import moe

__all__ = ["moe"]
