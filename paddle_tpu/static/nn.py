"""Structured control flow (reference: paddle.static.nn.cond/while_loop backed
by paddle/fluid/operators/controlflow/). TPU-native: lax.cond / lax.while_loop
/ lax.scan — jit-compatible data-dependent control flow.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core.dispatch import op_call

__all__ = ["cond", "while_loop", "case", "switch_case", "scan"]


def _wrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda v: Tensor(v) if not isinstance(v, Tensor) else v, tree)


def _unwrap_tree(tree):
    return jax.tree_util.tree_map(
        lambda t: t._value if isinstance(t, Tensor) else t, tree,
        is_leaf=lambda x: isinstance(x, Tensor))


def cond(pred, true_fn, false_fn, name=None):
    p = pred._value if isinstance(pred, Tensor) else pred
    def impl(pv):
        def tf(_):
            return _unwrap_tree(true_fn())
        def ff(_):
            return _unwrap_tree(false_fn())
        return jax.lax.cond(jnp.asarray(pv).astype(bool).reshape(()), tf, ff, 0)
    out = impl(p)
    return _wrap_tree(out)


def while_loop(cond_fn, body_fn, loop_vars, is_test=False, name=None):
    init = _unwrap_tree(list(loop_vars))
    def c(vals):
        out = cond_fn(*_wrap_tree(vals))
        return (out._value if isinstance(out, Tensor) else out).reshape(()).astype(bool)
    def b(vals):
        out = body_fn(*_wrap_tree(vals))
        return _unwrap_tree(list(out))
    final = jax.lax.while_loop(c, b, init)
    return _wrap_tree(final)


def case(pred_fn_pairs, default=None, name=None):
    for pred, fn in pred_fn_pairs:
        p = bool(pred._value) if isinstance(pred, Tensor) and not isinstance(
            pred._value, jax.core.Tracer) else None
        if p is True:
            return fn()
        if p is None:
            # traced: chain lax.cond
            rest = pred_fn_pairs[pred_fn_pairs.index((pred, fn)) + 1:]
            nxt = (lambda: case(rest, default)) if (rest or default) else fn
            return cond(pred, fn, nxt if rest or default else fn)
    if default is not None:
        return default()
    raise ValueError("no branch taken and no default")


def switch_case(branch_index, branch_fns, default=None, name=None):
    idx = branch_index._value if isinstance(branch_index, Tensor) else branch_index
    fns = dict(branch_fns) if not isinstance(branch_fns, dict) else branch_fns
    keys = sorted(fns)
    def impl(iv):
        branches = [lambda _, f=fns[k]: _unwrap_tree(f()) for k in keys]
        if default is not None:
            branches.append(lambda _, f=default: _unwrap_tree(f()))
        sel = jnp.searchsorted(jnp.asarray(keys), iv.reshape(()).astype(jnp.int32))
        ok = jnp.isin(iv.reshape(()).astype(jnp.int32), jnp.asarray(keys))
        which = jnp.where(ok, sel, len(keys) if default is not None else 0)
        return jax.lax.switch(jnp.clip(which, 0, len(branches) - 1), branches, 0)
    return _wrap_tree(impl(jnp.asarray(idx)))


def scan(f, init, xs, length=None, reverse=False, unroll=1):
    """lax.scan exposed at the framework level (the fused-RNN building block)."""
    def body(carry, x):
        c, y = f(_wrap_tree(carry), _wrap_tree(x))
        return _unwrap_tree(c), _unwrap_tree(y)
    carry, ys = jax.lax.scan(body, _unwrap_tree(init), _unwrap_tree(xs),
                             length=length, reverse=reverse, unroll=unroll)
    return _wrap_tree(carry), _wrap_tree(ys)
