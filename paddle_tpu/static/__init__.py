"""paddle.static parity (reference: python/paddle/static/).

TPU-native collapse: the static graph IS the jaxpr/StableHLO that jax.jit
traces (SURVEY.md L4b→XLA). This namespace keeps the user-facing pieces that
still matter: InputSpec, structured control flow (lax-backed cond/while_loop —
the controlflow-ops analog), save/load_inference_model delegating to
jit.save/load, and — round 5 — a WORKING Program/program_guard/data/Executor
build-then-run workflow: `program_guard` records the dispatch-level op tape
as ops execute on `data` placeholders, and `Executor.run` replays it with
the fed values (reference static Program.build → Executor.run, collapsed
onto the same op-record machinery the SOT tape uses).
"""
from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from ..core.tensor import Tensor
from ..core import dispatch as _dispatch
from .input_spec import InputSpec
from . import nn

__all__ = ["InputSpec", "nn", "save_inference_model", "load_inference_model",
           "Program", "program_guard", "default_main_program",
           "default_startup_program", "gradients", "data", "Executor"]


class Program:
    """A recorded static graph: the eager op tape captured under
    `program_guard`, replayable by `Executor.run` with fed inputs
    (reference framework.Program; the graph IR itself is the jaxpr XLA
    sees — this object holds the build-time op sequence + placeholders)."""

    def __init__(self):
        self.random_seed = 0
        self._ops = []           # (name, vals, outs, impl, static_kwargs)
        # feed name -> the placeholder ARRAY itself.  A strong reference is
        # load-bearing (ADVICE r5 #5): holding only id(array) let CPython
        # recycle the id after a GC'd / rebound placeholder, silently
        # binding the feed to an unrelated array at replay time.  Replay
        # matches by identity against this held object.
        self._feeds = {}

    def global_block(self):
        return self

    def clone(self, for_test=False):
        p = Program()
        p._ops = list(self._ops)
        p._feeds = dict(self._feeds)
        return p

    # -- replay ------------------------------------------------------------
    def _run(self, feed, fetch_vals):
        env = {}
        for name, placeholder in self._feeds.items():
            if feed and name in feed:
                fv = feed[name]
                env[id(placeholder)] = fv._value if isinstance(fv, Tensor) \
                    else jnp.asarray(fv)
        for op_name, vals, outs, impl, kw in self._ops:
            new_vals = [env.get(id(v), v) if not isinstance(v, (int, float,
                        str, bool, type(None))) else v for v in vals]
            res = impl(*new_vals, **kw)
            res_t = res if isinstance(res, tuple) else (res,)
            for old, new in zip(outs, res_t):
                env[id(old)] = new
        return [env.get(id(v), v) for v in fetch_vals]


_main = Program()
_startup = Program()
_active = [None]


def data(name, shape, dtype="float32", lod_level=0):
    """Placeholder variable (reference static.data): a concrete zeros
    Tensor (None/-1 dims -> 1) whose identity the active Program maps to
    the feed name; Executor.run substitutes the fed array."""
    concrete = tuple(1 if d in (None, -1) else int(d) for d in shape)
    t = Tensor(jnp.zeros(concrete, dtype))
    t.name = name
    prog = _active[0] if _active[0] is not None else _main
    prog._feeds[name] = t._value
    return t


class Executor:
    """reference static.Executor: run(program, feed, fetch_list) replays
    the recorded op tape with the fed values."""

    def __init__(self, place=None):
        self.place = place

    def run(self, program=None, feed=None, fetch_list=None, **kw):
        prog = program if isinstance(program, Program) else _main
        if not prog._ops:        # startup program / empty graph: no-op
            return []
        fetch_list = fetch_list or []
        fetch_vals = [f._value if isinstance(f, Tensor) else f
                      for f in fetch_list]
        outs = prog._run(feed, fetch_vals)
        return [np.asarray(o) for o in outs]


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    """Record every dispatched op inside the block into `main_program`
    (reference program_guard; ops still EXECUTE eagerly on the placeholder
    values, which is what lets plain python build code run unchanged)."""

    def __init__(self, main_program=None, startup_program=None):
        self._prog = main_program if main_program is not None else _main

    def __enter__(self):
        self._prev_active = _active[0]
        self._prev_rec = _dispatch._op_recorder[0]
        _active[0] = self._prog
        _dispatch._op_recorder[0] = self._prog._ops
        return self

    def __exit__(self, *exc):
        _dispatch._op_recorder[0] = self._prev_rec
        _active[0] = self._prev_active
        return False


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """reference static/io.py save_inference_model. On the TPU backend the
    inference artifact is the StableHLO export: pass the model Layer as
    `fetch_vars` (or `program`) and InputSpecs as `feed_vars` and this
    delegates to paddle_tpu.jit.save."""
    from ..nn.layer import Layer
    from ..jit import save as jit_save
    layer = None
    for cand in (fetch_vars, program, kwargs.get("layer")):
        if isinstance(cand, Layer):
            layer = cand
            break
    if layer is None:
        raise TypeError(
            "save_inference_model on the TPU backend exports a Layer: pass "
            "the model as fetch_vars/program (got "
            f"{type(fetch_vars).__name__}); the StableHLO artifact is the "
            "inference model.")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit_save(layer, path_prefix, input_spec=list(specs))
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jit_load
    return jit_load(path_prefix)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)
