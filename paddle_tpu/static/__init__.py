"""paddle.static parity (reference: python/paddle/static/).

TPU-native collapse: the static graph IS the jaxpr/StableHLO that jax.jit
traces (SURVEY.md L4b→XLA). This namespace keeps the user-facing pieces that
still matter: InputSpec, structured control flow (lax-backed cond/while_loop —
the controlflow-ops analog), and save/load_inference_model delegating to
jit.save/load.
"""
from __future__ import annotations

from .input_spec import InputSpec
from . import nn

__all__ = ["InputSpec", "nn", "save_inference_model", "load_inference_model",
           "Program", "program_guard", "default_main_program",
           "default_startup_program", "gradients"]


class Program:
    """Shim: programs are traced jaxprs; kept for scripts that construct
    Program() handles."""

    def __init__(self):
        self.random_seed = 0

    def global_block(self):
        return self

    def clone(self, for_test=False):
        return Program()


_main = Program()
_startup = Program()


def default_main_program():
    return _main


def default_startup_program():
    return _startup


class program_guard:
    def __init__(self, main_program=None, startup_program=None):
        pass

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


def save_inference_model(path_prefix, feed_vars, fetch_vars, executor=None,
                         program=None, **kwargs):
    """reference static/io.py save_inference_model. On the TPU backend the
    inference artifact is the StableHLO export: pass the model Layer as
    `fetch_vars` (or `program`) and InputSpecs as `feed_vars` and this
    delegates to paddle_tpu.jit.save."""
    from ..nn.layer import Layer
    from ..jit import save as jit_save
    layer = None
    for cand in (fetch_vars, program, kwargs.get("layer")):
        if isinstance(cand, Layer):
            layer = cand
            break
    if layer is None:
        raise TypeError(
            "save_inference_model on the TPU backend exports a Layer: pass "
            "the model as fetch_vars/program (got "
            f"{type(fetch_vars).__name__}); the StableHLO artifact is the "
            "inference model.")
    specs = feed_vars if isinstance(feed_vars, (list, tuple)) else [feed_vars]
    jit_save(layer, path_prefix, input_spec=list(specs))
    return path_prefix


def load_inference_model(path_prefix, executor=None, **kwargs):
    from ..jit import load as jit_load
    return jit_load(path_prefix)


def gradients(targets, inputs, target_gradients=None, no_grad_set=None):
    from ..core.autograd import grad
    return grad(targets, inputs, grad_outputs=target_gradients, allow_unused=True)
