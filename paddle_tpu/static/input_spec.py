"""InputSpec (reference: python/paddle/static/input_spec.py)."""
from __future__ import annotations

import numpy as np

from ..core import dtype as dtype_mod

__all__ = ["InputSpec"]


class InputSpec:
    def __init__(self, shape, dtype="float32", name=None, stop_gradient=False):
        self.shape = tuple(shape)
        self.dtype = dtype_mod.convert_dtype(dtype)
        self.name = name
        self.stop_gradient = stop_gradient

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tuple(tensor.shape), tensor.dtype, name or tensor.name)

    @classmethod
    def from_numpy(cls, ndarray, name=None):
        return cls(ndarray.shape, ndarray.dtype, name)

    def batch(self, batch_size):
        return InputSpec((batch_size,) + self.shape, self.dtype, self.name)

    def unbatch(self):
        return InputSpec(self.shape[1:], self.dtype, self.name)

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={np.dtype(self.dtype).name}, name={self.name})"
