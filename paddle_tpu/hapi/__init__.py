"""High-level API (reference: python/paddle/hapi/ — Model.fit model.py:1472,
callbacks, summary, dynamic_flops)."""
from .model import Model
from .summary import summary
from .dynamic_flops import flops
from . import callbacks

__all__ = ["Model", "summary", "flops", "callbacks"]
