"""High-level API (reference: python/paddle/hapi/ — Model.fit model.py:1472,
callbacks, summary)."""
from .model import Model
from .summary import summary
from . import callbacks

__all__ = ["Model", "summary", "callbacks"]
